"""The master's single RPC endpoint.

Parity: reference ``master/servicer.py`` — one generic endpoint dispatching
on message class: rendezvous joins/worlds, device-check reports and
diagnosis queries, kv-store, dynamic data sharding, metrics, sync barriers,
failures, and the runtime-tunable parallel config.
"""

import os
import signal
import time
from typing import Any, Dict

from dlrover_tpu.chaos.injector import fault_hit
from dlrover_tpu.chaos.sites import ChaosSite
from dlrover_tpu.common import env_utils
from dlrover_tpu.common import messages as m
from dlrover_tpu.common.constants import RendezvousName
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.rpc import RpcServer, current_request_id
from dlrover_tpu.master.mutation_locks import MutationLocks
from dlrover_tpu.observability.event_log import is_telemetry
from dlrover_tpu.observability.events import EventKind, emit

#: Messages whose handlers mutate durable master state. With a state
#: store attached, each is journaled WRITE-AHEAD (append, then apply,
#: both under the store's mutation lock) so a crash between the two is
#: recovered by replay and journal order equals apply order.
_JOURNALED = (
    m.DatasetShardParams,
    m.TaskReport,
    m.TaskHoldReport,
    m.KVStoreSet,
    m.KVStoreAdd,
    m.KVStoreDelete,
    m.NodeStatusReport,
    m.NodeFailure,
    # Forwarded event batches are state: the timeline must survive a
    # master failover, and a retried batch must land exactly once.
    m.EventReport,
    # Rescale acks decide plan completion vs abort; the outcome must
    # survive a master failover (replay re-derives it).
    m.RescaleAck,
    # Writer elections are first-claimant races over kv state; journaling
    # them replays the race in the original order, so a recovered master
    # answers with the same owner it already promised.
    m.CkptWriterElect,
    # A preemption notice arms the proactive shrink and hands off writer
    # leases; a master failover mid-notice must replay it exactly once.
    m.PreemptionNotice,
    # Batched lease completions: a retried batch must land exactly once
    # (the dedup cache absorbs it live; replay re-derives the acks).
    m.LeaseReport,
)

#: Mutating messages journaled AFTER their handler runs: the record must
#: carry data the handler chose (e.g. which shard was dispatched), and a
#: record lost to a crash between apply and append is recoverable by the
#: fencing protocol (clients re-report held tasks on incarnation change).
_APPLY_THEN_LOG = (
    m.TaskRequest,
    # Bulk grants: the record must carry the shard ids the service
    # chose; _handle special-cases the journal payload (a "lease"
    # record, not a "dispatch" one).
    m.LeaseRequest,
)


class MasterServicer:
    #: dtlint DT009: the servicer itself keeps almost no state — every
    #: mutation lands in a subsystem behind that subsystem's lock (via
    #: the per-message mutation shard). The three attrs below are
    #: deliberately lock-free: ``_bulk_backlog`` is wired once at server
    #: start, ``_paral_config`` is an atomic whole-object swap versioned
    #: by its writer, and ``_job_exit`` is a write-once exit flag.
    GUARDED_BY = {
        "_bulk_backlog": None,
        "_paral_config": None,
        "_job_exit": None,
    }

    def __init__(
        self,
        rdzv_managers: Dict[str, Any],
        kv_store,
        task_manager,
        job_manager,
        speed_monitor,
        sync_service,
        metric_collector=None,
        state_store=None,
        observability=None,
        rescale_coordinator=None,
        preempt_coordinator=None,
        mutation_locks=None,
        shard_lease=None,
        remediation_policy=None,
        brain_policy=None,
    ):
        self._rdzv_managers = rdzv_managers
        self._kv_store = kv_store
        self._task_manager = task_manager
        self._job_manager = job_manager
        self._speed_monitor = speed_monitor
        self._sync_service = sync_service
        self._metric_collector = metric_collector
        self._state_store = state_store
        self._observability = observability
        self._rescale = rescale_coordinator
        self._preempt = preempt_coordinator
        self._remediation = remediation_policy
        self._brain = brain_policy
        if shard_lease is None:
            from dlrover_tpu.master.shard.lease_service import (
                ShardLeaseService,
            )

            shard_lease = ShardLeaseService(
                task_manager, state_store=state_store
            )
        self._shard_lease = shard_lease
        self._locks = mutation_locks or MutationLocks()
        # Bulk-lane load probe, wired by attach_server: drives the
        # EventReport telemetry-shedding backpressure below.
        self._bulk_backlog: Any = None
        self._paral_config = m.ParallelConfig()
        self._job_exit = None
        self._start_time = time.time()

    @property
    def mutation_locks(self) -> MutationLocks:
        return self._locks

    def attach_server(self, server: RpcServer):
        """Late-bind the transport so handlers can read its lane
        backlog (the backpressure probe)."""
        self._bulk_backlog = lambda: server.backlog("bulk")

    # The transport handler.
    def handle(self, request: Any) -> Any:
        # Whole-handle latency per message type, journal included: the
        # histogram answers "where did the RPC tail go" after the fact.
        t0 = time.perf_counter()  # dtlint: disable=DT011 -- RPC latency telemetry for the histogram, never journaled
        try:
            return self._handle(request)
        finally:
            if self._observability is not None:
                self._observability.observe_rpc(
                    type(request).__name__, time.perf_counter() - t0  # dtlint: disable=DT011 -- RPC latency telemetry for the histogram, never journaled
                )

    def _handle(self, request: Any) -> Any:
        store = self._state_store
        replaying = store is not None and store.replaying
        if not replaying:
            # Injected crashes model a *live* RPC arriving. A replayed
            # journal record must not re-roll the dice (or burn fault
            # budget): the recovering master would crash-loop on the
            # very record whose original arrival killed it.
            chaos = fault_hit(
                ChaosSite.MASTER_CRASH, detail=type(request).__name__
            )
            if chaos is not None:
                if chaos.kind == "kill":
                    # A real master death: no flushes, no atexit —
                    # exactly what SIGKILL on the pod looks like.
                    os.kill(os.getpid(), signal.SIGKILL)
                elif chaos.kind == "exit":
                    os._exit(1)
        handler = self._HANDLERS.get(type(request))
        if handler is None:
            raise ValueError(f"unknown control message {type(request).__name__}")
        if replaying or store is None:
            return handler(self, request)
        if isinstance(request, m.LeaseRequest):
            # Bulk grants are apply-then-log like TaskRequest, but under
            # their own "lease" tag: the record carries the granted ids
            # (not ranges — replay re-pops them from the reproduced todo)
            # plus the lease bookkeeping. Empty answers journal nothing.
            seq = None
            with self._locks.for_message(request):
                lease = handler(self, request)
                payload = self._shard_lease.grant_payload(request, lease)
                if payload is not None:
                    seq = store.append(
                        ("lease", current_request_id(), payload, time.time())  # dtlint: disable=DT011 -- write-path timestamp recorded INTO the lease record; during replay append is a no-op and the value is discarded
                    )
            store.wait_durable(seq)
            return lease
        if isinstance(request, _APPLY_THEN_LOG):
            # Dispatch is journaled AFTER the handler (apply-then-log):
            # the record must carry the chosen shard's exact range, and
            # a lost record is safe — the replayed master still holds
            # the shard in todo and the fenced client re-reports it.
            seq = None
            with self._locks.for_message(request):
                task = handler(self, request)
                if task.exists:
                    seq = store.append(("dispatch", current_request_id(), {
                        "worker": request.node_id,
                        "dataset": task.dataset_name,
                        "task_id": task.task_id,
                        "shard_name": task.shard_name,
                        "start": task.start,
                        "end": task.end,
                        "record_indices": task.record_indices,
                    }, time.time()))  # dtlint: disable=DT011 -- write-path timestamp recorded INTO the dispatch record; during replay append is a no-op and the value is discarded
            # Durability barrier OUTSIDE the shard: the group-commit
            # fsync wait must never serialize unrelated mutations.
            store.wait_durable(seq)
            return task
        if isinstance(request, _JOURNALED):
            with self._locks.for_message(request):
                seq = store.append(
                    ("rpc", current_request_id(), request, time.time())  # dtlint: disable=DT011 -- write-path timestamp recorded INTO the rpc record; during replay append is a no-op and the value is discarded
                )
                resp = handler(self, request)
            store.wait_durable(seq)
            return resp
        return handler(self, request)

    # ---------------- rendezvous ----------------
    def _join_rendezvous(self, req: m.JoinRendezvous):
        mgr = self._rdzv_managers[req.rdzv_name]
        if (
            req.rdzv_name == RendezvousName.TRAINING
            and self._remediation is not None
            and self._remediation.gated(req.node_rank)
        ):
            # Quarantined (or remediation-evicted) nodes park outside
            # the training rendezvous: admitting the join would regrow
            # the world the policy just shrank. The agent's normal
            # retry loop keeps polling, so the moment probation lifts
            # the gate this same path triggers the regrow. Keep the
            # heartbeat — a parked node is alive on purpose.
            if self._job_manager:
                self._job_manager.report_heartbeat(req.node_id, time.time())
            return mgr.current_round()
        active = mgr.current_world()
        if (
            req.rdzv_name == RendezvousName.TRAINING
            and self._brain is not None
            and self._brain.gated_join(req.node_rank, active)
        ):
            # Brain join gate: the node was shrunk out on purpose
            # (parked spare capacity), or the world already sits at the
            # policy's target and this join would overshoot it. Same
            # park-with-heartbeat contract as the remediation gate —
            # the agent keeps polling, so a target raise or a release
            # regrows through this very path with no new machinery.
            if self._job_manager:
                self._job_manager.report_heartbeat(req.node_id, time.time())
            return mgr.current_round()
        round_ = mgr.join_rendezvous(req.node_rank, req.local_world_size)
        if req.rdzv_name == RendezvousName.TRAINING and self._job_manager:
            self._job_manager.report_heartbeat(req.node_id, time.time())
        if self._rescale is not None and active and req.node_rank not in active:
            # A node joining an actively-training world: grow in place
            # instead of making survivors restart (no-op fallback when
            # the coordinator declines).
            plan = self._rescale.on_node_joined(
                req.node_rank, req.local_world_size, req.rdzv_name
            )
            if (
                plan is not None
                and req.rdzv_name == RendezvousName.TRAINING
                and self._brain is not None
            ):
                # With the brain holding the join gate, an admitted
                # grow IS a brain decision: journal it and arm the
                # shared fleet cooldown. Live-only — joins are not
                # journaled RPCs, so this never runs on replay.
                self._brain.on_grow_admitted(
                    req.node_rank, len(active) + 1
                )
        return round_

    def _get_comm_world(self, req: m.CommWorldRequest):
        mgr = self._rdzv_managers[req.rdzv_name]
        round_, group, world = mgr.get_comm_world(req.node_rank)
        return m.CommWorld(
            rdzv_name=req.rdzv_name, round=round_, group=group, world=world
        )

    def _num_nodes_waiting(self, req: m.WaitingNodeNumRequest):
        return self._rdzv_managers[req.rdzv_name].num_nodes_waiting()

    def _world_status(self, req: m.WorldStatusRequest):
        return self._rdzv_managers[req.rdzv_name].world_stale(req.round)

    # ---------------- live rescale ----------------
    def _get_rescale_plan(self, req: m.RescalePlanRequest):
        if self._rescale is None:
            return m.RescalePlan()
        return self._rescale.get_plan(req.rdzv_name, req.node_rank, req.round)

    def _rescale_ack(self, req: m.RescaleAck):
        if self._rescale is None:
            return m.Response(success=False, reason="rescale disabled")
        ok = self._rescale.apply_ack(
            req.plan_id, req.node_rank, req.ok, req.error
        )
        return m.Response(success=ok)

    def _update_rdzv_params(self, req: m.RendezvousParams):
        for mgr in self._rdzv_managers.values():
            mgr.update_rdzv_params(
                req.min_nodes, req.max_nodes, req.waiting_timeout, req.node_unit
            )
        return m.Response()

    # ---------------- device check ----------------
    def _report_check_result(self, req: m.DeviceCheckResult):
        mgr = self._rdzv_managers[RendezvousName.DEVICE_CHECK]
        mgr.report_check_result(
            req.node_rank, req.normal, req.elapsed_time,
            round_=req.round if req.round > 0 else None,
        )
        return m.Response()

    def _get_fault_nodes(self, req: m.FaultNodesRequest):
        mgr = self._rdzv_managers[RendezvousName.DEVICE_CHECK]
        nodes, done = mgr.check_fault_node()
        return m.DiagnosisResult(
            nodes=nodes, done=done, completed_rounds=mgr.completed_rounds()
        )

    def _get_stragglers(self, req: m.StragglersRequest):
        mgr = self._rdzv_managers[RendezvousName.DEVICE_CHECK]
        nodes, done = mgr.check_straggler()
        return m.DiagnosisResult(
            nodes=nodes, done=done, completed_rounds=mgr.completed_rounds()
        )

    def _get_brain_status(self, req: m.BrainStatusRequest):
        if self._brain is None:
            return {}
        return self._brain.status()

    # ---------------- kv store ----------------
    def _kv_set(self, req: m.KVStoreSet):
        self._kv_store.set(req.key, req.value)
        return m.Response()

    def _kv_get(self, req: m.KVStoreGet):
        return self._kv_store.get(req.key)

    def _kv_add(self, req: m.KVStoreAdd):
        return self._kv_store.add(req.key, req.amount)

    def _kv_multi_get(self, req: m.KVStoreMultiGet):
        return self._kv_store.multi_get(req.keys)

    def _kv_delete(self, req: m.KVStoreDelete):
        self._kv_store.delete(req.key)
        return m.Response()

    # ---------------- checkpoint writer election ----------------
    def _ckpt_writer_elect(self, req: m.CkptWriterElect):
        # First claimant wins; the decision lives in the kv store, so it
        # rides in state snapshots for free and a late proposer (or a
        # client retry) reads back the recorded owner.
        key = f"ckpt_writer/{req.epoch}/{req.group}"
        won = self._kv_store.setnx(key, str(req.rank).encode())
        return m.CkptWriterLease(
            group=req.group, epoch=req.epoch, owner_rank=int(won.decode())
        )

    # ---------------- preemption plane ----------------
    def _preempt_notice(self, req: m.PreemptionNotice):
        if self._preempt is None:
            return m.Response(success=False, reason="preempt disabled")
        return self._preempt.on_notice(req)

    # ---------------- data sharding ----------------
    def _new_dataset(self, req: m.DatasetShardParams):
        self._task_manager.new_dataset(
            req.dataset_name,
            req.dataset_size,
            req.shard_size,
            req.num_epochs,
            req.shuffle,
            req.storage_type,
        )
        return m.Response()

    def _get_task(self, req: m.TaskRequest):
        return self._task_manager.get_task(req.node_id, req.dataset_name)

    def _report_task(self, req: m.TaskReport):
        ok = self._task_manager.report_task(
            req.dataset_name, req.task_id, req.success
        )
        return m.Response(success=ok)

    def _report_task_hold(self, req: m.TaskHoldReport):
        ok = self._task_manager.reclaim_task(
            req.node_id, req.dataset_name, {
                "task_id": req.task_id,
                "shard_name": req.shard_name,
                "start": req.start,
                "end": req.end,
                "record_indices": req.record_indices,
            },
        )
        return m.Response(success=ok)

    def _lease_request(self, req: m.LeaseRequest):
        return self._shard_lease.grant(req)

    def _lease_report(self, req: m.LeaseReport):
        return self._shard_lease.report(req)

    def _get_shard_checkpoint(self, req: m.ShardCheckpointRequest):
        return m.ShardCheckpoint(content=self._task_manager.checkpoint())

    def _get_dataset_epoch(self, req: m.DatasetEpochRequest):
        return self._task_manager.get_epoch(req.dataset_name)

    # ---------------- metrics ----------------
    def _report_step(self, req: m.GlobalStep):
        self._speed_monitor.collect_global_step(
            req.step, req.timestamp or time.time(), req.node_id
        )
        if self._observability:
            # Steps close open downtime incidents in the goodput ledger.
            self._observability.note_step(
                req.step, req.timestamp or time.time()
            )
        if self._rescale is not None:
            # Freshness fence for plan snapshots: per-step shm snapshots
            # mean the newest one trails this by at most one step.
            self._rescale.note_step(req.step)
        if self._preempt is not None:
            # Step boundary: issue the proactive shrink for any armed
            # preemption notice while the victim is still alive.
            self._preempt.note_step(req.step)
        if self._metric_collector:
            # Training-speed history feeds the Brain's completion-time
            # prediction (brain/algorithms.py::completion_time).
            self._metric_collector.collect_training_speed(
                req.step, self._speed_monitor.running_speed()
            )
        return m.Response()

    def _report_resource(self, req: m.NodeResourceStats):
        # Device-only reports (cpu/mem < 0, e.g. forwarded TPU stats from
        # the training monitor) must not stomp the resource monitor's
        # real host numbers.
        device_only = req.cpu_percent < 0 or req.used_memory_mb < 0
        node = self._job_manager.get_node(req.node_id) if self._job_manager else None
        if node is not None and not device_only:
            node.used_resource.cpu = req.cpu_percent
            node.used_resource.memory_mb = req.used_memory_mb
        if self._metric_collector:
            if device_only:
                self._metric_collector.collect_device_stats(
                    req.node_id, req.device_stats
                )
            else:
                self._metric_collector.collect_node_resource(req)
        return m.Response()

    def _report_model_info(self, req: m.ModelInfo):
        if self._metric_collector:
            self._metric_collector.collect_model_info(req)
        if self._rescale is not None and req.extra.get("rescale_capable"):
            # A live RescaleEngine advertises itself on construction;
            # the coordinator only plans in place when every survivor
            # has one (a plan nobody can apply just burns the apply
            # timeout before the same restart).
            self._rescale.set_capable(req.node_id)
        if self._rescale is not None and req.extra.get("global_batch"):
            # The trainer advertises its batch contract here; the
            # coordinator journals it (its own "rescale" record — this
            # RPC is not journaled) so plans survive a master relaunch.
            self._rescale.set_batch_config(
                req.extra["global_batch"],
                req.extra.get("micro_batch", 1),
            )
        if self._rescale is not None and req.extra.get("parallel_spec"):
            # Mesh layout + model profile: the inputs the reshape spec
            # search runs on when membership changes. Journaled by the
            # coordinator as a ("reshape", ...) record.
            self._rescale.set_parallel_config(
                req.extra["parallel_spec"],
                req.extra.get("model_profile", {}),
                float(req.extra.get("hbm", 0.0)),
            )
        if self._brain is not None:
            # The brain's auto-configuration inputs ride the same
            # report (live-only feed; only the recommendation derived
            # from it is journaled, by the policy itself).
            profile = dict(req.extra.get("model_profile", {}) or {})
            if not profile.get("param_count") and req.params_count:
                profile["param_count"] = req.params_count
            if profile:
                self._brain.set_model_config(
                    profile,
                    hbm=float(req.extra.get("hbm", 0.0) or 0.0),
                    global_batch=int(
                        req.extra.get("global_batch", 0)
                        or req.batch_size or 0
                    ),
                    spec=req.extra.get("parallel_spec") or None,
                )
        return m.Response()

    def _report_failure(self, req: m.NodeFailure):
        # Master-visible detection point: the node drops out of every
        # rendezvous below. (The agent's own worker.fail event arrives
        # async via EventReport; the ledger folds both into one incident.)
        announced = (
            self._preempt is not None
            and self._preempt.is_active(req.node_id)
        )
        emit(  # dtlint: disable=DT012 -- replay-guarded at the sink: JobMaster._event_sink drops emits while store.replaying
            EventKind.NODE_EVICT, _node_id=req.node_id, _role="master",
            reason=req.level, restart_count=req.restart_count,
            cause="preempt" if announced else "crash",
        )
        if self._job_manager:
            self._job_manager.process_error(
                req.node_id, req.restart_count, req.error_data, req.level
            )
        training = self._rdzv_managers.get(RendezvousName.TRAINING)
        old_world = training.current_world() if training else {}
        for mgr in self._rdzv_managers.values():
            mgr.remove_alive_node(req.node_id)
        if self._task_manager:
            self._task_manager.recover_worker_tasks(req.node_id)
            # Leased shards were just requeued as doing entries; drop the
            # lease bookkeeping so expiry cannot requeue them twice.
            self._shard_lease.drop_agent(req.node_id)
        if self._preempt is not None:
            # An announced departure: mark the notice handled so the
            # false-alarm timer never fires for a node that really died.
            # When the proactive shrink already ran, the victim is out
            # of old_world and the rescale trigger below is a no-op —
            # the kill is the non-event the notice paid for.
            self._preempt.on_node_removed(req.node_id)
        if self._rescale is not None and req.node_id in old_world:
            # This path bypasses the master's _evict_node (the agent
            # reported the failure directly): give the coordinator the
            # same shot at an in-place shrink for the survivors.
            self._rescale.on_node_removed(req.node_id, old_world)
        return m.Response()

    def _report_events(self, req: m.EventReport):
        if self._observability:
            events = req.events
            store = self._state_store
            replaying = store is not None and store.replaying
            if not replaying and self._bulk_backlog is not None:
                # Backpressure: when the bulk lane is backed up, shed the
                # ring-only telemetry kinds (metric.*, step.phases,
                # probe.link) and keep only durable incident events, so
                # a telemetry storm can never starve rendezvous/rescale
                # RPCs. Replay never sheds (the probe reads 0 backlog) —
                # acceptable nondeterminism for explicitly loss-tolerant
                # sampling data.
                try:
                    backlog = self._bulk_backlog()
                except Exception:
                    backlog = 0
                if backlog > env_utils.EVENT_SHED_BACKLOG.get():
                    kept = [e for e in events
                            if not is_telemetry(getattr(e, "kind", ""))]
                    shed = len(events) - len(kept)
                    if shed:
                        self._observability.note_shed(shed)
                        events = kept
            # Not re-journaled per event: this EventReport is itself a
            # journaled RPC and replays through this same path.
            self._observability.ingest_report(events)
        return m.Response()

    def _report_heartbeat(self, req: m.NodeHeartbeat):
        if self._job_manager:
            self._job_manager.report_heartbeat(req.node_id, req.timestamp)
        return m.Response()

    def _agent_beat(self, req: m.AgentBeat):
        """The coalesced agent heartbeat: one RPC folds the node
        heartbeat, the newest step progress and the latest link-probe
        sample, applied as a single dispatch instead of three."""
        if self._job_manager:
            self._job_manager.report_heartbeat(req.node_id, req.timestamp)
        if req.step >= 0:
            self._report_step(m.GlobalStep(
                node_id=req.node_id, node_type=req.node_type,
                step=req.step, timestamp=req.step_ts or req.timestamp,
            ))
        if req.probe and self._observability is not None:
            self._observability.ingest_probe(req.node_id, req.probe)
        return m.Response()

    def _report_node_status(self, req: m.NodeStatusReport):
        if self._job_manager:
            self._job_manager.update_node_status(
                req.node_id, req.status, req.exit_reason
            )
        if self._task_manager and req.status in ("failed", "deleted"):
            self._task_manager.recover_worker_tasks(req.node_id)
            self._shard_lease.drop_agent(req.node_id)
        return m.Response()

    # ---------------- sync ----------------
    def _sync_join(self, req: m.SyncJoin):
        return self._sync_service.join_sync(req.sync_name, req.worker_rank)

    def _sync_finished(self, req: m.SyncFinish):
        return self._sync_service.sync_finished(req.sync_name)

    def _sync_barrier(self, req: m.SyncBarrierRequest):
        if req.notify:
            return self._sync_service.notify_barrier(req.sync_name)
        return self._sync_service.barrier_reached(req.sync_name)

    # ---------------- parallel config ----------------
    def _get_paral_config(self, req: m.ParallelConfigRequest):
        return self._paral_config

    def set_paral_config(self, config: m.ParallelConfig):
        config.version = self._paral_config.version + 1
        self._paral_config = config

    # ---------------- master hot standby (WAL streaming) ----------------
    def _wal_subscribe(self, req: m.WalSubscribe):
        """Serve one replication pull to a standby.

        Read-only and never journaled: the replication stream must not
        feed back into the journal it ships. Durability gating happens
        in the store (only bytes behind the group-commit barrier are
        readable), so a segment the standby holds is always state the
        primary itself would recover.
        """
        store = self._state_store
        if store is None:
            return m.WalSegment(kind="segment")
        cap = env_utils.MASTER_HA_SEGMENT_BYTES.get()
        max_bytes = min(req.max_bytes, cap) if req.max_bytes > 0 else cap
        seg = store.read_segment(req.from_seq, req.from_offset, max_bytes)
        chaos = fault_hit(
            ChaosSite.WAL_STREAM,
            detail=f"seq{req.from_seq}+{req.from_offset}",
        )
        if chaos is not None:
            if chaos.kind == "drop":
                # Lose this pull entirely: answer empty at the same
                # cursor; the standby's next tick retries.
                seg = dict(seg, kind="segment", data=b"",
                           seq=req.from_seq, offset=req.from_offset,
                           next_seq=req.from_seq,
                           next_offset=req.from_offset)
            elif chaos.kind == "truncate" and seg["data"]:
                # Ship a torn tail (cut mid-frame): the standby must
                # verify frames itself, keep only the whole prefix, and
                # re-request the remainder from its last durable cursor.
                keep = int(chaos.args.get(
                    "keep_bytes", len(seg["data"]) // 2
                ))
                seg = dict(seg, data=seg["data"][: max(1, keep)])
            elif chaos.kind == "delay":
                time.sleep(float(chaos.args.get("delay_s", 0.1)))
        return m.WalSegment(
            kind=seg["kind"], seq=seg["seq"], offset=seg["offset"],
            data=seg["data"], next_seq=seg["next_seq"],
            next_offset=seg["next_offset"],
            durable_seq=seg["durable_seq"], commit_seq=seg["commit_seq"],
            durable_offset=seg["durable_offset"],
            incarnation=store.incarnation,
        )

    # ---------------- cluster version ----------------
    def _get_cluster_version(self, req: m.ClusterVersionRequest):
        store = self._state_store
        version = store.incarnation if store is not None else 0
        return m.ClusterVersion(version_type=req.version_type, version=version)

    # ---------------- job exit ----------------
    def _handle_job_exit(self, req: m.JobExitRequest):
        self._job_exit = req
        logger.info("job exit requested: success=%s reason=%s",
                    req.success, req.reason)
        return m.Response()

    def job_exit_request(self):
        return self._job_exit

    _HANDLERS = {}


MasterServicer._HANDLERS = {
    m.JoinRendezvous: MasterServicer._join_rendezvous,
    m.CommWorldRequest: MasterServicer._get_comm_world,
    m.WaitingNodeNumRequest: MasterServicer._num_nodes_waiting,
    m.WorldStatusRequest: MasterServicer._world_status,
    m.RescalePlanRequest: MasterServicer._get_rescale_plan,
    m.RescaleAck: MasterServicer._rescale_ack,
    m.RendezvousParams: MasterServicer._update_rdzv_params,
    m.DeviceCheckResult: MasterServicer._report_check_result,
    m.FaultNodesRequest: MasterServicer._get_fault_nodes,
    m.StragglersRequest: MasterServicer._get_stragglers,
    m.BrainStatusRequest: MasterServicer._get_brain_status,
    m.KVStoreSet: MasterServicer._kv_set,
    m.KVStoreGet: MasterServicer._kv_get,
    m.KVStoreAdd: MasterServicer._kv_add,
    m.KVStoreMultiGet: MasterServicer._kv_multi_get,
    m.KVStoreDelete: MasterServicer._kv_delete,
    m.CkptWriterElect: MasterServicer._ckpt_writer_elect,
    m.PreemptionNotice: MasterServicer._preempt_notice,
    m.DatasetShardParams: MasterServicer._new_dataset,
    m.TaskRequest: MasterServicer._get_task,
    m.TaskReport: MasterServicer._report_task,
    m.TaskHoldReport: MasterServicer._report_task_hold,
    m.LeaseRequest: MasterServicer._lease_request,
    m.LeaseReport: MasterServicer._lease_report,
    m.ShardCheckpointRequest: MasterServicer._get_shard_checkpoint,
    m.DatasetEpochRequest: MasterServicer._get_dataset_epoch,
    m.GlobalStep: MasterServicer._report_step,
    m.NodeResourceStats: MasterServicer._report_resource,
    m.ModelInfo: MasterServicer._report_model_info,
    m.NodeFailure: MasterServicer._report_failure,
    m.EventReport: MasterServicer._report_events,
    m.NodeHeartbeat: MasterServicer._report_heartbeat,
    m.AgentBeat: MasterServicer._agent_beat,
    m.NodeStatusReport: MasterServicer._report_node_status,
    m.SyncJoin: MasterServicer._sync_join,
    m.SyncFinish: MasterServicer._sync_finished,
    m.SyncBarrierRequest: MasterServicer._sync_barrier,
    m.ParallelConfigRequest: MasterServicer._get_paral_config,
    m.WalSubscribe: MasterServicer._wal_subscribe,
    m.ClusterVersionRequest: MasterServicer._get_cluster_version,
    m.JobExitRequest: MasterServicer._handle_job_exit,
}


#: High-volume periodic telemetry classes routed to the RPC server's
#: bulk worker lane; everything else (rendezvous, rescale, kv barriers,
#: shard dispatch) stays on the control lane, so a telemetry storm can
#: exhaust bulk workers without queueing ahead of a rescale ack.
_BULK_CLASSES = (
    m.EventReport,
    m.GlobalStep,
    m.NodeResourceStats,
    m.NodeHeartbeat,
    m.AgentBeat,
    m.ModelInfo,
    # The lease data plane: amortized but high-volume at fleet scale —
    # keep the grants/completion batches off the control lane so a data
    # storm can never queue ahead of a rescale ack.
    m.LeaseRequest,
    m.LeaseReport,
    # Replication pulls are periodic and potentially megabyte-sized:
    # keep the standby's tail loop off the control lane.
    m.WalSubscribe,
)


def message_priority(request: Any) -> str:
    """RpcServer lane classifier: ``bulk`` for periodic telemetry,
    ``control`` for everything latency-critical."""
    return "bulk" if isinstance(request, _BULK_CLASSES) else "control"


def create_master_service(port: int, servicer: MasterServicer) -> RpcServer:
    server = RpcServer(port, servicer.handle, classify=message_priority)
    servicer.attach_server(server)
    return server
