"""Master-hosted key-value store.

Used as the rendezvous/bootstrap store by agents and trainers (parity:
reference ``master/elastic_training/kv_store_service.py`` +
``elastic_agent/torch/master_kv_store.py``).
"""

import threading

from dlrover_tpu.common.lockdep import instrumented_lock
from typing import Dict, Optional, Tuple


class KVStoreService:
    #: dtlint DT009: every access to the declared attrs must hold the
    #: named lock (see docs/static_analysis.md, "Annotating guarded
    #: state").
    GUARDED_BY = {"_store": "master.kv_store"}

    def __init__(self):
        self._store: Dict[str, bytes] = {}
        self._lock = instrumented_lock("master.kv_store")

    def set(self, key: str, value: bytes):
        with self._lock:
            self._store[key] = value

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self._store.get(key)

    def add(self, key: str, amount: int) -> int:
        with self._lock:
            current = int(self._store.get(key, b"0"))
            current += amount
            self._store[key] = str(current).encode()
            return current

    def multi_get(self, keys: Tuple[str, ...]):
        with self._lock:
            return {k: self._store.get(k) for k in keys}

    def setnx(self, key: str, value: bytes) -> bytes:
        """Set `key` to `value` only if absent; return the winning value.

        The atomic first-claimant-wins primitive behind the checkpoint
        writer election: every replica proposes itself and all of them
        observe the same winner, including under concurrent proposals."""
        with self._lock:
            current = self._store.get(key)
            if current is None:
                self._store[key] = value
                return value
            return current

    def delete(self, key: str):
        with self._lock:
            self._store.pop(key, None)

    def scan(self, prefix: str) -> Dict[str, bytes]:
        """Snapshot of every entry whose key starts with `prefix`, in
        sorted key order (deterministic for journal-replayed callers —
        the preemption plane walks writer leases with it)."""
        with self._lock:
            return {
                k: self._store[k]
                for k in sorted(self._store)
                if k.startswith(prefix)
            }

    def clear(self):
        with self._lock:
            self._store.clear()

    # ------------- master state snapshot/restore -------------
    def export_state(self) -> Dict[str, bytes]:
        with self._lock:
            return dict(self._store)

    def restore_state(self, state: Dict[str, bytes]):
        with self._lock:
            self._store = dict(state)
