"""Per-role job resource bookkeeping + OOM-driven adjustment.

Parity: the reference's ``master/resource/job.py`` (``JobResource``:
per-role NodeGroupResource accounting, 569 LoC with PS/chief/evaluator
machinery) and the OOM-adjustment paths of its JobResourceOptimizer
(``adjust_oom_resource``). The TPU/allreduce cut keeps the roles generic
(workers dominate; PS is N/A by design — SURVEY §2.2) and the policy
explicit: every role's requested resources live here, scalers read the
CURRENT truth from one place, and an OOM kill escalates the role's
memory geometrically up to a cap before giving up.
"""

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from dlrover_tpu.common.log import logger
from dlrover_tpu.common.node import Node, NodeGroupResource, NodeResource


class JobResource:
    """The job's per-role resource table (requested state)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._groups: Dict[str, NodeGroupResource] = {}

    # ------------- accounting -------------
    def update_node_group_resource(self, node_type: str, num: int,
                                   cpu: float, memory_mb: int):
        with self._lock:
            self._groups[node_type] = NodeGroupResource(
                count=num,
                node_resource=NodeResource(cpu=cpu, memory_mb=memory_mb),
            )

    def get_node_group_resource(
        self, node_type: str
    ) -> Optional[NodeGroupResource]:
        with self._lock:
            return self._groups.get(node_type)

    def get_node_types(self) -> List[str]:
        with self._lock:
            return list(self._groups)

    def _count(self, node_type: str) -> int:
        g = self.get_node_group_resource(node_type)
        return g.count if g else 0

    @property
    def worker_num(self) -> int:
        return self._count("worker")

    @property
    def evaluator_num(self) -> int:
        return self._count("evaluator")

    def to_dict(self) -> Dict:
        with self._lock:
            return {
                t: {
                    "num": g.count,
                    "cpu": g.node_resource.cpu,
                    "memory_mb": g.node_resource.memory_mb,
                }
                for t, g in self._groups.items()
            }

    @staticmethod
    def from_dict(doc: Dict) -> "JobResource":
        jr = JobResource()
        for t, g in doc.items():
            jr.update_node_group_resource(
                t, g.get("num", 0), g.get("cpu", 0.0),
                g.get("memory_mb", 0),
            )
        return jr


@dataclass
class OomPolicy:
    """Geometric memory escalation on OOM kills (parity:
    ``_adjust_oom_worker_resource``'s stepped increments)."""

    factor: float = 1.5
    max_memory_mb: int = 262144  # 256 GiB host RAM ceiling
    max_escalations: int = 4


class JobResourceManager:
    """Owns the JobResource truth; turns resource plans and OOM events
    into updated per-role requests the scaler realizes.

    Composition (matches the reference flow): the resource optimizer
    (local stats / Brain) proposes plans -> this manager records them in
    JobResource -> the auto-scaler/scaler read the current request when
    (re)launching nodes; an OOM-killed node escalates its role's memory
    before relaunch instead of crash-looping at the same size.
    """

    def __init__(self, policy: Optional[OomPolicy] = None):
        self.job_resource = JobResource()
        self.policy = policy or OomPolicy()
        self._oom_counts: Dict[str, int] = {}

    def init_from_config(self, worker_num: int, cpu: float = 0.0,
                         memory_mb: int = 0):
        self.job_resource.update_node_group_resource(
            "worker", worker_num, cpu, memory_mb
        )

    def apply_resource_plan(self, plan) -> bool:
        """Record an optimizer plan (``master.scaling.ResourcePlan``)."""
        if plan is None or plan.empty():
            return False
        self.job_resource.update_node_group_resource(
            "worker", plan.worker_num, plan.worker_cpu,
            plan.worker_memory_mb,
        )
        return True

    def adjust_oom_resource(self, node: Node) -> Optional[NodeGroupResource]:
        """Escalate the role's memory after an OOM kill; returns the new
        group resource, or None when the cap/escalation budget is spent
        (the node should then be treated as fatally failed, not
        relaunched into the same OOM loop)."""
        role = node.type
        count = self._oom_counts.get(role, 0)
        if count >= self.policy.max_escalations:
            logger.error(
                "role %s hit the OOM escalation budget (%d); giving up",
                role, count,
            )
            return None
        group = self.job_resource.get_node_group_resource(role)
        if group is None:
            return None
        cur = group.node_resource.memory_mb
        new_mem = min(
            int(max(cur, 1024) * self.policy.factor),
            self.policy.max_memory_mb,
        )
        if new_mem <= cur:
            logger.error(
                "role %s already at the memory ceiling (%d MB)", role, cur
            )
            return None
        self._oom_counts[role] = count + 1
        self.job_resource.update_node_group_resource(
            role, group.count, group.node_resource.cpu, new_mem
        )
        logger.info(
            "OOM on %s-%s: memory %d -> %d MB (escalation %d/%d)",
            role, node.id, cur, new_mem, count + 1,
            self.policy.max_escalations,
        )
        return self.job_resource.get_node_group_resource(role)
