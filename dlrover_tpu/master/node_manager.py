"""Node lifecycle management on the master.

Parity: reference ``master/node/dist_job_manager.py`` + ``local_job_manager.py``
— the master tracks one :class:`~dlrover_tpu.common.node.Node` per agent,
consumes status reports/heartbeats/failures, decides relaunch vs abort via
the status flow, and (on a scheduler-backed platform) drives a scaler with
``ScalePlan``s. The local platform has no scheduler, so relaunch decisions
only feed rendezvous membership; the agent's own process supervision does
the respawning.
"""

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from dlrover_tpu.common.constants import (
    NodeEventType,
    NodeExitReason,
    NodeStatus,
    NodeType,
)
from dlrover_tpu.common.lockdep import instrumented_lock
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.node import Node, NodeGroupResource, NodeResource
from dlrover_tpu.master.monitor.error_monitor import ErrorMonitor
from dlrover_tpu.master.status_flow import get_node_state_flow, should_relaunch


@dataclass
class ScalePlan:
    """A requested change to the node set (parity: ScalePlan CRD)."""

    node_group_resources: Dict[str, NodeGroupResource] = field(default_factory=dict)
    launch_nodes: List[Node] = field(default_factory=list)
    remove_nodes: List[Node] = field(default_factory=list)

    def empty(self) -> bool:
        return not (
            self.node_group_resources or self.launch_nodes or self.remove_nodes
        )


class Scaler:
    """Platform backend that realizes a ScalePlan (k8s/GKE later)."""

    def scale(self, plan: ScalePlan):
        raise NotImplementedError


class NoopScaler(Scaler):
    def scale(self, plan: ScalePlan):
        if not plan.empty():
            logger.info("noop scaler ignoring plan %s", plan)


@dataclass
class NodeEvent:
    event_type: str
    node: Node


class JobManager:
    #: dtlint DT009. ``_event_callbacks`` is append-only at wiring time
    #: and iterated lock-free on purpose: callbacks dispatch node events
    #: into subsystems that take their own locks and must never run
    #: inside ours.
    GUARDED_BY = {
        "_nodes": "master.node_manager",
        "_preempting": "master.node_manager",
        "_event_callbacks": None,
    }

    """Tracks job nodes and reacts to their lifecycle events."""

    def __init__(
        self,
        node_num: int = 1,
        max_relaunch_count: int = 3,
        scaler: Optional[Scaler] = None,
        error_monitor: Optional[ErrorMonitor] = None,
        heartbeat_timeout: float = 120.0,
        resource_manager=None,
    ):
        self._lock = instrumented_lock("master.node_manager")
        self._nodes: Dict[int, Node] = {}
        self._node_num = node_num
        self._max_relaunch_count = max_relaunch_count
        self._scaler = scaler or NoopScaler()
        self._error_monitor = error_monitor or ErrorMonitor()
        self._heartbeat_timeout = heartbeat_timeout
        # Per-role resource bookkeeping + OOM escalation
        # (master/job_resource.py; optional — tests may inject).
        if resource_manager is None:
            from dlrover_tpu.master.job_resource import JobResourceManager

            resource_manager = JobResourceManager()
            resource_manager.init_from_config(node_num)
        self.resource_manager = resource_manager
        self._stopped = False
        self._event_callbacks = []
        # Node ids with an active preemption notice: their upcoming exit
        # is planned, so process_error must not treat it as a crash
        # (no relaunch, no OOM escalation). Set/cleared by the
        # PreemptionCoordinator.
        self._preempting: set = set()
        for i in range(node_num):
            node = Node(
                NodeType.WORKER, i, max_relaunch_count=max_relaunch_count
            )
            self._nodes[i] = node

    # ---------------- queries ----------------
    def get_node(self, node_id: int) -> Optional[Node]:
        with self._lock:
            return self._nodes.get(node_id)

    def all_nodes(self) -> List[Node]:
        with self._lock:
            return list(self._nodes.values())

    def alive_worker_ranks(self) -> List[int]:
        with self._lock:
            return [
                n.rank_index
                for n in self._nodes.values()
                if n.status in (NodeStatus.RUNNING, NodeStatus.PENDING,
                                NodeStatus.INITIAL)
            ]

    def all_workers_exited(self) -> bool:
        with self._lock:
            return bool(self._nodes) and all(
                n.exited() for n in self._nodes.values()
            )

    def all_workers_succeeded(self) -> bool:
        with self._lock:
            return bool(self._nodes) and all(
                n.status == NodeStatus.SUCCEEDED for n in self._nodes.values()
            )

    def any_node_failed_fatally(self) -> bool:
        with self._lock:
            return any(
                n.status == NodeStatus.FAILED and not n.relaunchable
                for n in self._nodes.values()
            )

    # ---------------- event intake ----------------
    def add_event_callback(self, callback):
        self._event_callbacks.append(callback)

    def update_node_status(self, node_id: int, status: str, exit_reason: str = ""):
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                node = Node(NodeType.WORKER, node_id,
                            max_relaunch_count=self._max_relaunch_count)
                self._nodes[node_id] = node
            old_status = node.status
            flow = get_node_state_flow(old_status, status)
            node.update_status(status)
            if exit_reason:
                node.exit_reason = exit_reason
            relaunch = False
            if flow.should_relaunch:
                relaunch = should_relaunch(node, flow, self._max_relaunch_count)
                if relaunch:
                    node.inc_relaunch_count()
            event = NodeEvent(NodeEventType.MODIFIED, node)
        for cb in self._event_callbacks:
            try:
                cb(event)
            except Exception:
                logger.exception("node event callback failed")
        if relaunch:
            self._relaunch_node(node)
        return relaunch

    def report_heartbeat(self, node_id: int, timestamp: float):
        with self._lock:
            node = self._nodes.get(node_id)
            if node:
                node.heartbeat_time = timestamp or time.time()
                if node.status in (NodeStatus.INITIAL, NodeStatus.PENDING):
                    node.update_status(NodeStatus.RUNNING)

    # ---------------- preemption plane ----------------
    def mark_preempting(self, node_id: int):
        """Flag a node as under an active preemption notice: its coming
        exit is a planned departure, not a crash."""
        with self._lock:
            self._preempting.add(int(node_id))

    def clear_preempting(self, node_id: int):
        with self._lock:
            self._preempting.discard(int(node_id))

    def is_preempting(self, node_id: int) -> bool:
        with self._lock:
            return int(node_id) in self._preempting

    def process_error(
        self, node_id: int, restart_count: int, error_data: str, level: str
    ) -> bool:
        if self.is_preempting(node_id):
            # Planned departure: the infrastructure announced this exit
            # ahead of time and the preemption plane already flushed and
            # handed off. No relaunch decision, no OOM escalation —
            # the node registry just records the preempted status.
            self.update_node_status(
                node_id, NodeStatus.FAILED, NodeExitReason.PREEMPTED
            )
            return False
        relaunch_node = self._error_monitor.process_error(
            node_id, restart_count, error_data, level
        )
        if relaunch_node:
            reason = self._error_monitor.classify(error_data)
            if reason == NodeExitReason.OOM:
                # Escalate the role's memory request before the relaunch
                # (parity: JobResourceOptimizer.adjust_oom_resource) —
                # relaunching into the same size just OOM-loops. A spent
                # escalation budget makes the failure fatal.
                node = self.get_node(node_id)
                if node is not None:
                    adjusted = self.resource_manager.adjust_oom_resource(
                        node
                    )
                    if adjusted is None:
                        node.relaunchable = False
                        relaunch_node = False
            self.update_node_status(node_id, NodeStatus.FAILED, reason)
        return relaunch_node

    def _relaunch_node(self, node: Node):
        logger.info("relaunching node %s (count %s)", node.id, node.relaunch_count)
        plan = ScalePlan(launch_nodes=[node.get_relaunch_node()],
                         remove_nodes=[node])
        self._scaler.scale(plan)
        with self._lock:
            fresh = node.get_relaunch_node()
            fresh.update_status(NodeStatus.PENDING)
            self._nodes[node.id] = fresh

    # ---------------- hang detection ----------------
    def find_dead_nodes(self) -> List[int]:
        """Nodes whose heartbeat went stale."""
        now = time.time()
        dead = []
        with self._lock:
            for node in self._nodes.values():
                if (
                    node.status == NodeStatus.RUNNING
                    and node.heartbeat_time > 0
                    and now - node.heartbeat_time > self._heartbeat_timeout
                ):
                    dead.append(node.id)
        return dead

    def remove_node(self, node_id: int, reason: str = "") -> bool:
        """Scale-in a permanently-lost node: it stops counting toward
        all_workers_exited/succeeded so survivors can finish the job.
        (The local platform has no scheduler to bring it back; a node
        that does come back re-registers via its next status report.)"""
        with self._lock:
            node = self._nodes.pop(node_id, None)
            remaining = len(self._nodes)
        if node is None:
            return False
        logger.warning(
            "removed node %s from the job (%s); %s nodes remain",
            node_id, reason or "permanent loss", remaining,
        )
        return True

    def stop(self):
        self._stopped = True

    # ------------- master state snapshot/restore -------------
    def export_nodes(self) -> List[Dict]:
        with self._lock:
            return [
                {
                    "id": n.id,
                    "type": n.type,
                    "rank_index": n.rank_index,
                    "name": n.name,
                    "status": n.status,
                    "exit_reason": n.exit_reason,
                    "relaunch_count": n.relaunch_count,
                    "relaunchable": n.relaunchable,
                    "max_relaunch_count": n.max_relaunch_count,
                    "preempting": n.id in self._preempting,
                }
                for n in self._nodes.values()
            ]

    def restore_nodes(self, dumped: List[Dict]):
        with self._lock:
            self._nodes.clear()
            self._preempting.clear()
            for d in dumped:
                if d.get("preempting"):
                    self._preempting.add(int(d["id"]))
                node = Node(
                    d["type"], d["id"], rank_index=d.get("rank_index"),
                    name=d.get("name", ""),
                    max_relaunch_count=d.get(
                        "max_relaunch_count", self._max_relaunch_count
                    ),
                )
                node.status = d.get("status", NodeStatus.INITIAL)
                node.exit_reason = d.get("exit_reason", "")
                node.relaunch_count = d.get("relaunch_count", 0)
                node.relaunchable = d.get("relaunchable", True)
                # heartbeat_time stays 0: find_dead_nodes skips such
                # nodes, so a restored registry cannot mass-evict before
                # fenced clients re-register and heartbeat again.
                self._nodes[node.id] = node


class LocalJobManager(JobManager):
    """Single-host deployment: the agent supervises processes itself."""
