"""Job master composition and run loop.

Parity: reference ``master/dist_master.py`` + ``local_master.py`` — composes
the job manager, task manager, both rendezvous managers, speed monitor,
sync service and the RPC servicer; ``run()`` watches exit conditions
(all workers done, fatal node failure, no-task-manager-progress).
"""

import os
import threading
import time
from typing import Optional

from dlrover_tpu.brain.policy import BrainPolicy
from dlrover_tpu.brain.store import BrainMetricsStore
from dlrover_tpu.common import env_utils, lockdep
from dlrover_tpu.common.constants import JobStage, RendezvousName
from dlrover_tpu.common.global_context import get_context
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.rpc import DEDUP_TTL
from dlrover_tpu.master.kv_store import KVStoreService
from dlrover_tpu.observability.events import (
    EventKind,
    emit,
    install_sink,
    uninstall_sink,
)
from dlrover_tpu.observability.plane import (
    METRICS_PORT_ENV,
    ObservabilityPlane,
)
from dlrover_tpu.master.monitor.link_profile import LinkProfileAggregator
from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor
from dlrover_tpu.master.monitor.straggler import StragglerDetector
from dlrover_tpu.master.mutation_locks import MutationLocks
from dlrover_tpu.master.node_manager import JobManager, LocalJobManager
from dlrover_tpu.master.rendezvous import (
    DeviceCheckRendezvousManager,
    ElasticTrainingRendezvousManager,
)
from dlrover_tpu.master.preempt import PreemptionCoordinator
from dlrover_tpu.master.remediation import RemediationPolicy
from dlrover_tpu.master.rescale import RescaleCoordinator
from dlrover_tpu.master.servicer import MasterServicer, create_master_service
from dlrover_tpu.master.shard.lease_service import ShardLeaseService
from dlrover_tpu.master.shard.task_manager import TaskManager
from dlrover_tpu.master.state_store import MasterStateStore
from dlrover_tpu.master.stats import JobMetricCollector
from dlrover_tpu.master.sync_service import SyncService


class JobMaster:
    def __init__(
        self,
        port: int = 0,
        node_num: int = 1,
        job_name: str = "local-job",
        job_manager: Optional[JobManager] = None,
        scaler=None,
        state_dir: str = "",
        metrics_port: Optional[int] = None,
        ha=None,
    ):
        ctx = get_context()
        self.job_name = job_name
        # Durable state (opt-in via --state_dir): snapshots + WAL so a
        # relaunched master at the same address resumes the previous
        # incarnation's shard cursors, kv store, node registry and
        # rendezvous rounds instead of booting blank.
        self.state_store: Optional[MasterStateStore] = None
        self.incarnation = 0
        self.last_recovery_stats = {}
        # Primacy lease (master hot standby): when set, this master only
        # mutates while it holds the lease — the renew thread fences the
        # store and aborts the moment a newer incarnation appears.
        self.ha = ha
        if state_dir:
            self.state_store = MasterStateStore(state_dir)
            self.incarnation = self.state_store.next_incarnation()
            if ha is not None:
                held = ha.incarnation
                if held <= 0:
                    # Fresh primary: take primacy now, folding the local
                    # relaunch history into the fleet-wide mint. A
                    # promoted standby arrives with the lease already
                    # held (acquired before construction).
                    held = ha.acquire(floor=self.incarnation)
                if not held:
                    raise RuntimeError(
                        "another master holds the primacy lease; "
                        "refusing to start as primary"
                    )
                self.incarnation = self.state_store.set_incarnation(held)
        self.speed_monitor = SpeedMonitor(hang_seconds=ctx.hang_detection_seconds)
        self.job_manager = job_manager or LocalJobManager(
            node_num=node_num, heartbeat_timeout=ctx.heartbeat_timeout
        )
        self.task_manager = TaskManager(self.speed_monitor)
        self.rdzv_managers = {
            RendezvousName.TRAINING: ElasticTrainingRendezvousManager(
                RendezvousName.TRAINING
            ),
            RendezvousName.DEVICE_CHECK: DeviceCheckRendezvousManager(
                RendezvousName.DEVICE_CHECK,
                check_timeout=ctx.device_check_timeout,
            ),
        }
        for mgr in self.rdzv_managers.values():
            mgr.update_rdzv_params(
                node_num, node_num, ctx.rdzv_waiting_timeout, 1
            )
        self.kv_store = KVStoreService()
        self.sync_service = SyncService(self.job_manager)
        self.metric_collector = JobMetricCollector()
        # Observability plane: the job-wide event log + goodput ledger
        # + /metrics source. Master-local emits flow through the sink
        # below; agent/worker emits arrive as EventReport RPCs.
        self.observability = ObservabilityPlane()
        # Straggler attribution: phase/probe telemetry events feed the
        # detector (EventLog listener), the node-monitor loop ticks it,
        # and its verdict events book straggler:<kind> incidents in the
        # goodput ledger. Eviction (when enabled) rides _evict_node.
        self.straggler_detector = StragglerDetector(
            speed_monitor=self.speed_monitor,
            evict_cb=self._evict_node,
        )
        self.observability.event_log.add_listener(
            self.straggler_detector.observe
        )
        # Link-aware comms plane: the same probe.link telemetry also
        # feeds the fleet link-profile aggregator, whose folded per-axis
        # profile is published through the kv store (riding master
        # snapshots, so it survives failover) and steers the reshape
        # search + worker-side comms governor.
        self.link_aggregator = None
        if env_utils.COMMS_PROFILE.get():
            self.link_aggregator = LinkProfileAggregator(
                kv_store=self.kv_store
            )
            self.observability.event_log.add_listener(
                self.link_aggregator.observe
            )
        self.observability.attach(
            speed_monitor=self.speed_monitor,
            job_manager=self.job_manager,
            task_manager=self.task_manager,
            straggler_detector=self.straggler_detector,
            link_aggregator=self.link_aggregator,
        )
        self.metric_collector.add_sink(self.observability.metric_sink)
        self._metrics_port_cfg = metrics_port
        self.metrics_port = 0
        # Bind once: uninstall_sink removes by identity, and bound-method
        # attribute access would mint a different object each time.
        self._event_sink_fn = self._event_sink
        install_sink(self._event_sink_fn)
        if self.state_store is not None:
            self.task_manager.set_journal(self.state_store.append)
            for mgr in self.rdzv_managers.values():
                mgr.set_state_listener(self._journal_rdzv_state)
            self.observability.event_log.journal = self.state_store.append
            # WAL write/fsync durations land in the plane's histograms
            # (ROADMAP item 4: native histogram metrics).
            self.state_store.timing_sink = self.observability.observe_wal
        # Live rescale plane: membership changes with a surviving quorum
        # become in-place transitions (journaled RescalePlans) instead of
        # full restarts.
        self.rescale = RescaleCoordinator(
            rdzv_managers=self.rdzv_managers,
            state_store=self.state_store,
        )
        if self.link_aggregator is not None:
            # Reshape searches price candidates at the measured link
            # profile (and gain the collective-strategy dimension).
            self.rescale.set_link_profile_fn(
                self.link_aggregator.search_profile
            )
        # Preemption plane: a known-ahead termination notice becomes a
        # planned transition — writer-lease handoff on arrival, shrink
        # at the next step boundary, clean cancel on false alarm.
        self.preempt = PreemptionCoordinator(
            rdzv_managers=self.rdzv_managers,
            kv_store=self.kv_store,
            job_manager=self.job_manager,
            rescale_coordinator=self.rescale,
            state_store=self.state_store,
        )
        # Shard-lease data plane: bulk dispatch to agent brokers so the
        # per-shard traffic never reaches the master in steady state.
        self.shard_lease = ShardLeaseService(
            self.task_manager, state_store=self.state_store
        )
        self.observability.attach(shard_lease=self.shard_lease)
        # Per-subsystem mutation shards replace the old global mutation
        # lock; the snapshot quiesce holds ALL of them (in canonical
        # order) so no journal record can land past a rotation it isn't
        # covered by.
        self.mutation_locks = MutationLocks()
        if self.state_store is not None:
            self.state_store.quiesce = self.mutation_locks.all
        # Automatic straggler remediation: the node-monitor loop ticks
        # the policy right after the detector; a sustained verdict
        # becomes a journaled quarantine (in-place shrink), probe
        # recovery a probation regrow, chronic failure an eviction.
        self.remediation = RemediationPolicy(
            straggler_detector=self.straggler_detector,
            rdzv_managers=self.rdzv_managers,
            rescale_coordinator=self.rescale,
            task_manager=self.task_manager,
            shard_lease=self.shard_lease,
            speed_monitor=self.speed_monitor,
            state_store=self.state_store,
            mutation_locks=self.mutation_locks,
            evict_cb=self._evict_node,
        )
        self.observability.attach(remediation=self.remediation)
        # Brain decision layer: history-driven start recommendation +
        # goodput-driven grow/shrink (opt-in via DLROVER_TPU_BRAIN).
        # The cross-job metrics store rides the state dir so the next
        # job of this name starts from this one's observed throughput.
        self.brain_store: Optional[BrainMetricsStore] = None
        if state_dir and env_utils.BRAIN.get():
            self.brain_store = BrainMetricsStore(
                os.path.join(state_dir, "brain_metrics.log")
            )
        self.brain = BrainPolicy(
            job_name=job_name,
            rdzv_managers=self.rdzv_managers,
            rescale_coordinator=self.rescale,
            straggler_detector=self.straggler_detector,
            speed_monitor=self.speed_monitor,
            remediation=self.remediation,
            task_manager=self.task_manager,
            shard_lease=self.shard_lease,
            state_store=self.state_store,
            mutation_locks=self.mutation_locks,
            metrics_store=self.brain_store,
        )
        self.observability.attach(brain=self.brain)
        # Role/fencing gauge source (the standby attaches its own).
        self.observability.attach(master_ha=self)
        self.servicer = MasterServicer(
            rdzv_managers=self.rdzv_managers,
            kv_store=self.kv_store,
            task_manager=self.task_manager,
            job_manager=self.job_manager,
            speed_monitor=self.speed_monitor,
            sync_service=self.sync_service,
            metric_collector=self.metric_collector,
            state_store=self.state_store,
            observability=self.observability,
            rescale_coordinator=self.rescale,
            preempt_coordinator=self.preempt,
            mutation_locks=self.mutation_locks,
            shard_lease=self.shard_lease,
            remediation_policy=self.remediation,
            brain_policy=self.brain,
        )
        self._server = create_master_service(port, self.servicer)
        self.port = self._server.port
        if self.state_store is not None:
            self._server.incarnation = self.incarnation
            self._recover_state()
            # Fold whatever was recovered into a fresh generation right
            # away: opens this incarnation's journal and bounds the next
            # recovery's replay to post-boot mutations.
            self.state_store.snapshot(self._collect_state)
        self.stage = JobStage.INIT
        self._stopped = threading.Event()
        self._abort_reason: Optional[str] = None
        self._monitor_thread: Optional[threading.Thread] = None
        self._ha_thread: Optional[threading.Thread] = None
        # Opt-in auto-scaling: needs a platform scaler backend (the local
        # platform default is agent-side supervision, no scaler).
        self.auto_scaler = None
        if scaler is not None and ctx.auto_scale_enabled:
            from dlrover_tpu.master.scaling import (
                AllreduceAutoScaler,
                LocalResourceOptimizer,
            )

            self.auto_scaler = AllreduceAutoScaler(
                self.job_manager, scaler,
                resource_optimizer=LocalResourceOptimizer(
                    self.metric_collector
                ),
                target_worker_num=node_num,
            )

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.port}"

    # ------------- events -------------
    def _event_sink(self, ev):
        """Process-wide emit() sink for the master. Dropped while the
        journal is replaying: locally-emitted events were journaled as
        ``("event", ...)`` records and replay themselves — re-recording
        the handler's side-effect emits would double them."""
        store = self.state_store
        if store is not None and store.replaying:
            return
        self.observability.event_log.append(ev)

    # ------------- durable state -------------
    def _journal_rdzv_state(self, name: str, state: dict):
        # Absolute counter values, so replaying a duplicate is a no-op
        # (restore() takes the max) and journal-after-apply is safe.
        self.state_store.append(("rdzv", name, state, time.time()))

    def _collect_state(self) -> dict:
        return {
            "version": 1,
            "incarnation": self.incarnation,
            "time": time.time(),
            "task_manager": self.task_manager.checkpoint(),
            "kv": self.kv_store.export_state(),
            "nodes": self.job_manager.export_nodes(),
            "rdzv": {
                name: mgr.checkpoint()
                for name, mgr in self.rdzv_managers.items()
            },
            "speed": self.speed_monitor.checkpoint(),
            "events": self.observability.event_log.export_state(),
            "rescale": self.rescale.checkpoint(),
            "preempt": self.preempt.checkpoint(),
            "shard_lease": self.shard_lease.checkpoint(),
            "remediation": self.remediation.checkpoint(),
            "brain": self.brain.checkpoint(),
        }

    def _recover_state(self):
        """Load the newest valid snapshot, replay the journal chain over
        it, and seed the RPC dedup cache with the replayed responses so
        in-flight client retries are answered, not re-applied."""
        store = self.state_store
        state, records = store.recover()
        if state is None and not records:
            return
        store.replaying = True
        seeds = []
        now = time.time()
        applied = 0
        try:
            if state is not None:
                self.task_manager.restore(
                    state.get("task_manager", ""), exact=True
                )
                self.kv_store.restore_state(state.get("kv", {}))
                self.job_manager.restore_nodes(state.get("nodes", []))
                for name, st in state.get("rdzv", {}).items():
                    mgr = self.rdzv_managers.get(name)
                    if mgr is not None:
                        mgr.restore(st)
                self.speed_monitor.restore(state.get("speed", {}))
                ev_state = state.get("events")
                if ev_state:
                    # Replays through the listeners, so the goodput
                    # ledger rebuilds its incident history too.
                    self.observability.event_log.restore_state(ev_state)
                self.rescale.restore(state.get("rescale", {}))
                self.preempt.restore(state.get("preempt", {}))
                self.shard_lease.restore(state.get("shard_lease", {}))
                self.remediation.restore(state.get("remediation", {}))
                self.brain.restore(state.get("brain", {}))
            for rec in records:
                try:
                    kind = rec[0]
                    if kind == "rpc":
                        _, req_id, request, ts = rec
                        resp = self.servicer.handle(request)
                        if req_id and now - ts < DEDUP_TTL:
                            seeds.append((req_id, resp))
                    elif kind == "dispatch":
                        _, req_id, d, ts = rec
                        task = self.task_manager.replay_dispatch(d)
                        if req_id and task is not None and now - ts < DEDUP_TTL:
                            seeds.append((req_id, task))
                    elif kind == "shards":
                        _, dataset, st, ts = rec
                        self.task_manager.replay_shards(dataset, st)
                    elif kind == "reclaim":
                        _, dataset, ids, ts = rec
                        self.task_manager.replay_reclaim(dataset, ids)
                    elif kind == "evict":
                        _, node_id, reason, ts = rec
                        self._evict_node(node_id, f"replayed: {reason}")
                    elif kind == "rdzv":
                        _, name, st, ts = rec
                        mgr = self.rdzv_managers.get(name)
                        if mgr is not None:
                            mgr.restore(st)
                    elif kind == "event":
                        _, ev, ts = rec
                        self.observability.event_log.append(
                            ev, journal=False
                        )
                    elif kind == "rescale":
                        _, payload, ts = rec
                        self.rescale.replay(payload)
                    elif kind == "reshape":
                        _, payload, ts = rec
                        self.rescale.replay_reshape(payload)
                    elif kind == "preempt":
                        _, payload, ts = rec
                        self.preempt.replay(payload)
                    elif kind == "remediate":
                        _, payload, ts = rec
                        self.remediation.replay(payload)
                    elif kind == "brain":
                        _, payload, ts = rec
                        self.brain.replay(payload)
                    elif kind == "lease":
                        _, req_id, payload, ts = rec
                        resp = self.shard_lease.replay(payload)
                        if req_id and resp is not None and now - ts < DEDUP_TTL:
                            seeds.append((req_id, resp))
                    else:
                        logger.warning("skipping unknown journal record %r",
                                       kind)
                        continue
                    applied += 1
                except Exception:
                    logger.exception("skipping unreplayable journal record")
        finally:
            store.replaying = False
        for req_id, resp in seeds:
            self._server.seed_dedup(req_id, resp)
        stats = dict(store.last_recovery_stats)
        stats.update(replayed=applied, dedup_seeded=len(seeds))
        self.last_recovery_stats = stats
        logger.info(
            "recovered master state: incarnation=%s snapshot_seq=%s "
            "journal_records=%s replayed=%s dedup_seeded=%s torn_tails=%s "
            "quarantined=%s",
            self.incarnation, stats.get("snapshot_seq"),
            stats.get("journal_records"), applied, len(seeds),
            stats.get("torn_tails"), stats.get("quarantined_snapshots"),
        )

    def ha_status(self) -> dict:
        """Role/fencing snapshot for the observability plane's
        ``dlrover_tpu_master_role`` gauge."""
        fenced = bool(self.state_store is not None and self.state_store.fenced)
        return {
            "role": "fenced" if fenced else "primary",
            "incarnation": self.incarnation,
        }

    def _ha_renew_loop(self):
        """Primacy-lease heartbeat. Losing the lease (a standby promoted
        over us — e.g. after a partition that only looked like our
        death) fences the state store so late writes raise instead of
        acking, and aborts the run loop: two masters can never both
        mutate."""
        renew_s = env_utils.MASTER_HA_RENEW_S.get()
        while not self._stopped.wait(renew_s):
            try:
                if not self.ha.renew():
                    self.state_store.fence(
                        f"incarnation {self.ha.incarnation} superseded"
                    )
                    emit(
                        EventKind.MASTER_FENCED, _role="master",
                        incarnation=self.incarnation,
                    )
                    self._abort_reason = (
                        "primacy lease lost: a newer master incarnation "
                        "holds the lease"
                    )
                    return
            except Exception:
                logger.exception("primacy lease renewal failed")

    def prepare(self):
        self._server.start()
        self.stage = JobStage.RUNNING
        self._monitor_thread = threading.Thread(
            target=self._node_monitor_loop, daemon=True,
            name="node-monitor",
        )
        self._monitor_thread.start()
        if self.ha is not None and self.state_store is not None:
            self.ha.publish_endpoint(self.addr)
            self._ha_thread = threading.Thread(
                target=self._ha_renew_loop, daemon=True,
                name="ha-renew",
            )
            self._ha_thread.start()
        if self.auto_scaler is not None:
            self.auto_scaler.start()
        port_cfg = self._metrics_port_cfg
        if port_cfg is None:
            env_port = env_utils.METRICS_PORT.get()
            port_cfg = env_port if env_port >= 0 else None
        if port_cfg is not None and port_cfg >= 0:
            try:
                self.metrics_port = self.observability.start_exporter(
                    port_cfg
                )
            except Exception:
                logger.exception("metrics exporter failed to start")
        logger.info("master %s serving on port %s", self.job_name, self.port)

    # ------------- failure detection -------------
    def _node_monitor_loop(self):
        """Failure detection (parity: reference
        ``master/node/dist_job_manager.py:401-533``, condensed):

        - *Node death* (stale heartbeat — the agent itself is gone):
          evict the node (scale-in; the local platform has no scheduler
          to relaunch into) so survivors re-form a smaller world.
        - *Training hang* (agents heartbeat but step progress stopped):
          synchronous SPMD stalls ALL nodes at once, so eviction would
          kill the whole job; instead invalidate the round — every agent
          flushes its shm checkpoint, restarts its workers and
          re-rendezvouses (restart-in-place recovery).
        """
        ctx = get_context()
        interval = ctx.node_monitor_interval
        strategy_gen = None
        if ctx.auto_paral_tuning:
            from dlrover_tpu.master.hyperparams import SimpleStrategyGenerator

            strategy_gen = SimpleStrategyGenerator(self.metric_collector)
        last_summary = time.monotonic()
        while not self._stopped.wait(interval):
            try:
                if time.monotonic() - last_summary >= ctx.reporting_interval:
                    last_summary = time.monotonic()
                    s = self.metric_collector.summary()
                    if s["nodes"]:
                        logger.info(
                            "job stats: %s nodes, avg cpu %.0f%%, peak mem "
                            "%s MB, %.2f steps/s",
                            s["nodes"], s["cpu_percent_avg"],
                            s["used_memory_mb_max"],
                            self.speed_monitor.running_speed(),
                        )
                    if strategy_gen is not None:
                        tuned = strategy_gen.generate()
                        if tuned is not None:
                            self.servicer.set_paral_config(tuned)
                for node_id in self.job_manager.find_dead_nodes():
                    self._evict_node(node_id, "heartbeat timeout")
                if self.speed_monitor.worker_hang():
                    logger.error(
                        "training hang: no step progress for %.0fs; "
                        "invalidating the round so agents restart",
                        self.speed_monitor.hang_seconds,
                    )
                    emit(
                        EventKind.NODE_HANG, _role="master",
                        hang_seconds=self.speed_monitor.hang_seconds,
                    )
                    for mgr in self.rdzv_managers.values():
                        mgr.invalidate_round()
                    # Restarted workers report steps again; clearing the
                    # stale report times re-arms detection instead of
                    # re-firing every pass.
                    self.speed_monitor.reset_worker_reports()
                self.rescale.tick()
                self.preempt.tick()
                self.shard_lease.tick()
                self.straggler_detector.tick()
                if self.link_aggregator is not None:
                    # The aggregator needs to know which mesh axes cross
                    # hosts to map fleet link figures onto axes; the
                    # rescale plane derives it from the reported spec.
                    self.link_aggregator.set_axis_links(
                        self.rescale.axis_crossing()
                    )
                    self.link_aggregator.tick()
                self.remediation.tick()
                self.brain.tick()
                if self.brain_store is not None:
                    self.brain_store.maybe_sync()
                if self.state_store is not None:
                    self.state_store.maybe_snapshot(self._collect_state)
                if not self.job_manager.all_nodes():
                    self._abort_reason = "all nodes lost"
                    return
            except Exception:
                logger.exception("node monitor iteration failed")

    def _evict_node(self, node_id: int, reason: str):
        from dlrover_tpu.utils.tracing import get_tracer

        get_tracer().instant("evict-node", node_id=node_id, reason=reason)
        logger.error("evicting node %s: %s", node_id, reason)
        # During journal replay the sink drops this (the live eviction's
        # own ("event", ...) record replays it instead).
        emit(  # dtlint: disable=DT012 -- replay-guarded at the sink: _event_sink drops emits while store.replaying; the journaled ("event", ...) record replays the live emission
            EventKind.NODE_EVICT, _node_id=node_id, _role="master",
            reason=reason,
        )
        store = self.state_store
        if store is not None and not store.replaying:
            # Write-ahead. Eviction spans tasks/nodes/rdzv, so it holds
            # every mutation shard: the queue requeues serialize against
            # concurrent RPC mutations in journal order.
            with self.mutation_locks.all():
                seq = store.append(("evict", node_id, reason, time.time()))
                self._apply_evict(node_id, reason)
            store.wait_durable(seq)
            return
        self._apply_evict(node_id, reason)

    def _apply_evict(self, node_id: int, reason: str):
        training = self.rdzv_managers.get(RendezvousName.TRAINING)
        old_world = training.current_world() if training else {}
        self.job_manager.remove_node(node_id, reason)
        for mgr in self.rdzv_managers.values():
            mgr.remove_alive_node(node_id)
        self.task_manager.recover_worker_tasks(node_id)
        # Leased shards re-entered todo just now; drop the lease
        # bookkeeping so expiry cannot requeue them twice.
        self.shard_lease.drop_agent(node_id)
        self.speed_monitor.remove_worker(node_id)
        self.straggler_detector.remove_worker(node_id)
        if self.link_aggregator is not None:
            self.link_aggregator.remove_worker(node_id)
        self.metric_collector.remove_node(node_id)
        # An announced departure must not later read as a false alarm.
        self.preempt.on_node_removed(node_id)
        # Drop (or confirm, for the policy's own evictions) the node's
        # remediation record so an unrelated eviction never leaves a
        # stale join gate behind.
        self.remediation.on_node_evicted(node_id)
        # Same contract for the brain's parked set: an evicted node is
        # gone for real, not spare capacity.
        self.brain.on_node_evicted(node_id)
        if node_id in old_world:
            # Survivors of the shrunken world may transition in place
            # instead of restarting (no-op during journal replay and
            # whenever the coordinator declines).
            self.rescale.on_node_removed(node_id, old_world)

    def run(self, poll_interval: float = 1.0) -> int:
        """Block until the job finishes; returns an exit code."""
        try:
            while not self._stopped.is_set():
                # Event.wait, not sleep: stop() takes effect immediately
                # instead of up to a full poll interval later.
                self._stopped.wait(poll_interval)
                exit_req = self.servicer.job_exit_request()
                if exit_req is not None:
                    self.stage = (
                        JobStage.SUCCEEDED if exit_req.success else JobStage.FAILED
                    )
                    break
                if self._abort_reason:
                    logger.error("aborting job: %s", self._abort_reason)
                    self.stage = JobStage.FAILED
                    break
                if self.job_manager.all_workers_exited():
                    self.stage = (
                        JobStage.SUCCEEDED
                        if self.job_manager.all_workers_succeeded()
                        else JobStage.FAILED
                    )
                    break
        finally:
            self.stop()
        logger.info("master exiting with stage %s", self.stage)
        return 0 if self.stage == JobStage.SUCCEEDED else 1

    def stop(self):
        self._stopped.set()
        if self.auto_scaler is not None:
            self.auto_scaler.stop()
        self._server.stop()
        export_path = env_utils.LOCKDEP_EXPORT.get()
        if export_path:
            # Everything this run's drills exercised, for dtlint's
            # static+runtime merged lock-order check (DT010).
            try:
                lockdep.export_graph(export_path)
            except OSError:
                logger.exception("lockdep graph export failed")
        uninstall_sink(self._event_sink_fn)
        self.observability.stop()
        if self.brain_store is not None:
            try:
                self.brain_store.close()
            except OSError:
                logger.exception("brain metrics store close failed")
        if self.state_store is not None:
            # Sockets are severed, so no mutation can race the final
            # snapshot; best-effort — a failure here is exactly the
            # crash case the journal already covers.
            try:
                self.state_store.snapshot(self._collect_state)
            except Exception:
                logger.exception("final state snapshot failed")
            self.state_store.close()


# Aliases matching the reference composition names.
LocalJobMaster = JobMaster
DistributedJobMaster = JobMaster
