"""Job master composition and run loop.

Parity: reference ``master/dist_master.py`` + ``local_master.py`` — composes
the job manager, task manager, both rendezvous managers, speed monitor,
sync service and the RPC servicer; ``run()`` watches exit conditions
(all workers done, fatal node failure, no-task-manager-progress).
"""

import threading
import time
from typing import Optional

from dlrover_tpu.common.constants import JobStage, RendezvousName
from dlrover_tpu.common.global_context import get_context
from dlrover_tpu.common.log import logger
from dlrover_tpu.master.kv_store import KVStoreService
from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor
from dlrover_tpu.master.node_manager import JobManager, LocalJobManager
from dlrover_tpu.master.rendezvous import (
    DeviceCheckRendezvousManager,
    ElasticTrainingRendezvousManager,
)
from dlrover_tpu.master.servicer import MasterServicer, create_master_service
from dlrover_tpu.master.shard.task_manager import TaskManager
from dlrover_tpu.master.sync_service import SyncService


class JobMaster:
    def __init__(
        self,
        port: int = 0,
        node_num: int = 1,
        job_name: str = "local-job",
        job_manager: Optional[JobManager] = None,
    ):
        ctx = get_context()
        self.job_name = job_name
        self.speed_monitor = SpeedMonitor(hang_seconds=ctx.hang_detection_seconds)
        self.job_manager = job_manager or LocalJobManager(node_num=node_num)
        self.task_manager = TaskManager(self.speed_monitor)
        self.rdzv_managers = {
            RendezvousName.TRAINING: ElasticTrainingRendezvousManager(
                RendezvousName.TRAINING
            ),
            RendezvousName.DEVICE_CHECK: DeviceCheckRendezvousManager(
                RendezvousName.DEVICE_CHECK,
                check_timeout=ctx.device_check_timeout,
            ),
        }
        for mgr in self.rdzv_managers.values():
            mgr.update_rdzv_params(
                node_num, node_num, ctx.rdzv_waiting_timeout, 1
            )
        self.kv_store = KVStoreService()
        self.sync_service = SyncService(self.job_manager)
        self.servicer = MasterServicer(
            rdzv_managers=self.rdzv_managers,
            kv_store=self.kv_store,
            task_manager=self.task_manager,
            job_manager=self.job_manager,
            speed_monitor=self.speed_monitor,
            sync_service=self.sync_service,
        )
        self._server = create_master_service(port, self.servicer)
        self.port = self._server.port
        self.stage = JobStage.INIT
        self._stopped = threading.Event()

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.port}"

    def prepare(self):
        self._server.start()
        self.stage = JobStage.RUNNING
        logger.info("master %s serving on port %s", self.job_name, self.port)

    def run(self, poll_interval: float = 1.0) -> int:
        """Block until the job finishes; returns an exit code."""
        try:
            while not self._stopped.is_set():
                time.sleep(poll_interval)
                exit_req = self.servicer.job_exit_request()
                if exit_req is not None:
                    self.stage = (
                        JobStage.SUCCEEDED if exit_req.success else JobStage.FAILED
                    )
                    break
                if self.job_manager.all_workers_exited():
                    self.stage = (
                        JobStage.SUCCEEDED
                        if self.job_manager.all_workers_succeeded()
                        else JobStage.FAILED
                    )
                    break
        finally:
            self.stop()
        logger.info("master exiting with stage %s", self.stage)
        return 0 if self.stage == JobStage.SUCCEEDED else 1

    def stop(self):
        self._stopped.set()
        self._server.stop()


# Aliases matching the reference composition names.
LocalJobMaster = JobMaster
DistributedJobMaster = JobMaster
