"""Rendezvous managers: elastic-training membership + device-check diagnosis.

Capability parity with the reference's
``master/elastic_training/rdzv_manager.py``:

- ``RendezvousManager`` — waiting-node admission with min/max nodes,
  ``node_unit`` granularity and a last-call timeout; a frozen *round* is the
  communication world handed to every agent.
- ``ElasticTrainingRendezvousManager`` — one global group per round.
- ``DeviceCheckRendezvousManager`` — the 2-round paired-group diagnosis that
  localizes fault nodes, plus the elapsed-time median×N straggler rule.

TPU specifics: a "node" is one TPU host of a pod slice; the check exercise
runs JAX collectives over ICI instead of NCCL allgathers, but the master
side is transport-agnostic — it only sees join/report RPCs.
"""

import statistics
import threading
import time
from abc import ABC, abstractmethod
from typing import Dict, List, Optional, Set, Tuple

from dlrover_tpu.common.log import logger


class RendezvousManager(ABC):
    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._min_nodes = 1
        self._max_nodes = 1
        self._node_unit = 1
        self._waiting_timeout = 30.0
        self._lastcall_timeout = 3.0
        # node_rank -> local world size, for nodes asking to join.
        self._waiting_nodes: Dict[int, int] = {}
        # The frozen world of the current round.
        self._rdzv_nodes: Dict[int, int] = {}
        self._rdzv_round = 0
        self._lastcall_time = 0.0
        self._alive_nodes: Set[int] = set()
        self._start_rdzv_time = 0.0

    # ---------------- configuration ----------------
    def update_rdzv_params(
        self,
        min_nodes: int,
        max_nodes: int,
        waiting_timeout: float = 30.0,
        node_unit: int = 1,
    ):
        with self._lock:
            self._min_nodes = min_nodes
            self._max_nodes = max_nodes
            self._waiting_timeout = waiting_timeout
            self._node_unit = max(1, node_unit)

    # ---------------- membership ----------------
    def add_alive_node(self, node_rank: int):
        with self._lock:
            self._alive_nodes.add(node_rank)

    def remove_alive_node(self, node_rank: int):
        with self._lock:
            self._alive_nodes.discard(node_rank)
            if node_rank in self._waiting_nodes:
                del self._waiting_nodes[node_rank]
            if node_rank in self._rdzv_nodes:
                # A member of the active world died: the next join starts a
                # fresh round and agents observe num_nodes_waiting > 0.
                logger.info(
                    "rdzv %s: node %s left active world of round %s",
                    self.name, node_rank, self._rdzv_round,
                )

    def join_rendezvous(
        self, node_rank: int, local_world_size: int = 1
    ) -> int:
        """Register intent to join; returns the round being formed."""
        with self._lock:
            if node_rank in self._rdzv_nodes and node_rank not in self._waiting_nodes:
                # Rejoin after restart: previous world is stale.
                self._rdzv_nodes = {}
            if not self._waiting_nodes:
                self._start_rdzv_time = time.monotonic()
            self._waiting_nodes[node_rank] = local_world_size
            self._alive_nodes.add(node_rank)
            self._lastcall_time = time.monotonic()
            return self._rdzv_round

    def _freeze_ready(self) -> bool:
        """Called with the lock held: can the waiting set become a round?"""
        count = len(self._waiting_nodes)
        if count < max(self._min_nodes, 1):
            return False
        if count >= self._max_nodes:
            return True
        waited = time.monotonic() - self._start_rdzv_time
        lastcall = time.monotonic() - self._lastcall_time
        if waited >= self._waiting_timeout:
            return True
        return lastcall >= self._lastcall_timeout and count >= self._min_nodes

    def _freeze_round(self):
        """Admit a node_unit-aligned subset of the waiting set as the world."""
        count = len(self._waiting_nodes)
        admitted = (count // self._node_unit) * self._node_unit
        if admitted <= 0:
            return
        ranks = sorted(self._waiting_nodes)[:admitted]
        self._rdzv_nodes = {r: self._waiting_nodes[r] for r in ranks}
        for r in ranks:
            del self._waiting_nodes[r]
        self._rdzv_round += 1
        logger.info(
            "rdzv %s: froze round %s with nodes %s",
            self.name, self._rdzv_round, sorted(self._rdzv_nodes),
        )

    def num_nodes_waiting(self) -> int:
        """Agents poll this to detect membership changes (>0 => restart)."""
        with self._lock:
            return len(self._waiting_nodes)

    @abstractmethod
    def get_comm_world(
        self, node_rank: int
    ) -> Tuple[int, int, Dict[int, int]]:
        """Returns (round, group, {node_rank: local_world_size}).

        An empty world means "keep polling" — the round is still forming.
        """


class ElasticTrainingRendezvousManager(RendezvousManager):
    """One global communication world per round."""

    def get_comm_world(self, node_rank: int):
        with self._lock:
            if node_rank in self._rdzv_nodes:
                return self._rdzv_round, 0, dict(self._rdzv_nodes)
            if self._freeze_ready():
                self._freeze_round()
                if node_rank in self._rdzv_nodes:
                    return self._rdzv_round, 0, dict(self._rdzv_nodes)
            return self._rdzv_round, 0, {}


class DeviceCheckRendezvousManager(RendezvousManager):
    """Paired-group check rounds for fault/straggler localization.

    Round r=0: nodes are paired sequentially ``(0,1)(2,3)...``; each pair
    runs an allgather+matmul exercise. A failed pair makes both members
    suspects. Round r=1: suspects are re-paired with known-good nodes. A
    node that fails both rounds is the fault node; with only one round of
    data the diagnosis is not ``done``.
    """

    def __init__(self, name: str):
        super().__init__(name)
        self._node_status: Dict[int, Dict[int, bool]] = {}  # round -> rank -> ok
        self._node_times: Dict[int, Dict[int, float]] = {}  # round -> rank -> sec
        self._check_round = 0
        self._straggler_ratio = 2.0

    def join_rendezvous(self, node_rank: int, local_world_size: int = 1) -> int:
        with self._lock:
            if not self._waiting_nodes:
                self._start_rdzv_time = time.monotonic()
            self._waiting_nodes[node_rank] = local_world_size
            self._alive_nodes.add(node_rank)
            self._lastcall_time = time.monotonic()
            return self._rdzv_round

    def get_comm_world(self, node_rank: int):
        with self._lock:
            if not self._rdzv_nodes and self._freeze_ready():
                self._freeze_round()
                self._check_round += 1
            if node_rank in self._rdzv_nodes:
                groups = self._build_groups()
                for group_idx, members in enumerate(groups):
                    if node_rank in members:
                        world = {r: self._rdzv_nodes[r] for r in members}
                        return self._rdzv_round, group_idx, world
            return self._rdzv_round, 0, {}

    def _build_groups(self) -> List[List[int]]:
        """Pair nodes; in later check rounds, shift pairing so a suspect
        lands with a node that succeeded in the previous round."""
        ranks = sorted(self._rdzv_nodes)
        round_idx = self._check_round
        if round_idx > 1 and len(ranks) > 2:
            # Rotate by one so every node gets a different partner than in
            # the previous round (reference: re-pair suspects with good).
            ranks = ranks[1:] + ranks[:1]
        groups = []
        for i in range(0, len(ranks) - 1, 2):
            groups.append([ranks[i], ranks[i + 1]])
        if len(ranks) % 2:
            if groups:
                groups[-1].append(ranks[-1])
            else:
                groups.append([ranks[-1]])
        return groups

    def report_check_result(self, node_rank: int, normal: bool, elapsed: float):
        with self._lock:
            r = self._check_round
            self._node_status.setdefault(r, {})[node_rank] = normal
            self._node_times.setdefault(r, {})[node_rank] = elapsed
            # The reported world is consumed; allow the next check round to
            # freeze once every member reported.
            if set(self._node_status[r]) >= set(self._rdzv_nodes):
                self._rdzv_nodes = {}

    def check_fault_node(self) -> Tuple[List[int], bool]:
        """Returns (fault node ranks, diagnosis finished)."""
        with self._lock:
            rounds = sorted(self._node_status)
            if not rounds:
                return [], False
            last = rounds[-1]
            current = self._node_status[last]
            suspects = {r for r, ok in current.items() if not ok}
            if not suspects:
                return [], True
            if len(rounds) < 2:
                return sorted(suspects), False
            prev = self._node_status[rounds[-2]]
            confirmed = [r for r in suspects if not prev.get(r, True)]
            return sorted(confirmed), True

    def check_straggler(self) -> Tuple[List[int], bool]:
        """Elapsed-time median×ratio rule (reference rdzv_manager.py:492)."""
        with self._lock:
            rounds = sorted(self._node_times)
            if not rounds:
                return [], False
            times = self._node_times[rounds[-1]]
            if len(times) < 2:
                return [], True
            median = statistics.median(times.values())
            if median <= 0:
                return [], True
            stragglers = [
                r for r, t in times.items() if t > median * self._straggler_ratio
            ]
            return sorted(stragglers), True
