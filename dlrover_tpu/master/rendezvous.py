"""Rendezvous managers: elastic-training membership + device-check diagnosis.

Capability parity with the reference's
``master/elastic_training/rdzv_manager.py``:

- ``RendezvousManager`` — waiting-node admission with min/max nodes,
  ``node_unit`` granularity and a last-call timeout; a frozen *round* is the
  communication world handed to every agent.
- ``ElasticTrainingRendezvousManager`` — one global group per round.
- ``DeviceCheckRendezvousManager`` — the 2-round paired-group diagnosis that
  localizes fault nodes, plus the elapsed-time median×N straggler rule.

TPU specifics: a "node" is one TPU host of a pod slice; the check exercise
runs JAX collectives over ICI instead of NCCL allgathers, but the master
side is transport-agnostic — it only sees join/report RPCs.
"""

import statistics
import threading
import time
from abc import ABC, abstractmethod
from typing import Dict, List, Optional, Set, Tuple

from dlrover_tpu.common.lockdep import instrumented_lock
from dlrover_tpu.common.log import logger
from dlrover_tpu.observability.events import EventKind, emit


class RendezvousManager(ABC):
    #: dtlint DT009: the three membership sets are the rendezvous state
    #: machine; every transition happens under this manager's rdzv.*
    #: lock. The _freeze_* helpers run inside callers' critical sections
    #: (see their holds() markers).
    GUARDED_BY = {
        "_waiting_nodes": "rdzv.*",
        "_rdzv_nodes": "rdzv.*",
        "_alive_nodes": "rdzv.*",
    }

    def __init__(self, name: str):
        self.name = name
        self._lock = instrumented_lock(f"rdzv.{name}")
        self._min_nodes = 1
        self._max_nodes = 1
        self._node_unit = 1
        self._waiting_timeout = 30.0
        self._lastcall_timeout = 3.0
        # node_rank -> local world size, for nodes asking to join.
        self._waiting_nodes: Dict[int, int] = {}
        # The frozen world of the current round.
        self._rdzv_nodes: Dict[int, int] = {}
        self._rdzv_round = 0
        self._lastcall_time = 0.0
        self._alive_nodes: Set[int] = set()
        self._start_rdzv_time = 0.0
        # Rounds <= this are invalidated (a member died); survivors must
        # re-rendezvous.
        self._stale_round = 0
        # Observer fired (outside the lock) whenever the round/stale
        # counters change — the state-store-backed master journals the
        # new values so a relaunched master cannot hand out already-used
        # round numbers, which would make world_stale() mis-classify
        # agents holding previous-incarnation round tokens.
        self._on_state_change = None

    def set_state_listener(self, listener):
        self._on_state_change = listener

    def checkpoint(self) -> dict:
        with self._lock:
            return {
                "round": self._rdzv_round,
                "stale_round": self._stale_round,
            }

    def restore(self, state: dict):
        with self._lock:
            self._rdzv_round = max(
                self._rdzv_round, int(state.get("round", 0))
            )
            self._stale_round = max(
                self._stale_round, int(state.get("stale_round", 0))
            )

    def _notify_state(self):
        """Call WITHOUT the lock held."""
        listener = self._on_state_change
        if listener is not None:
            try:
                listener(self.name, self.checkpoint())
            except Exception:
                logger.exception("rdzv state listener failed")

    # ---------------- configuration ----------------
    def update_rdzv_params(
        self,
        min_nodes: int,
        max_nodes: int,
        waiting_timeout: float = 30.0,
        node_unit: int = 1,
    ):
        with self._lock:
            self._min_nodes = min_nodes
            self._max_nodes = max_nodes
            self._waiting_timeout = waiting_timeout
            self._node_unit = max(1, node_unit)

    # ---------------- membership ----------------
    def add_alive_node(self, node_rank: int):
        with self._lock:
            self._alive_nodes.add(node_rank)

    def remove_alive_node(self, node_rank: int):
        changed = False
        with self._lock:
            self._alive_nodes.discard(node_rank)
            if node_rank in self._waiting_nodes:
                del self._waiting_nodes[node_rank]
            if node_rank in self._rdzv_nodes:
                # A member of the active world died: invalidate the round
                # so surviving agents (polling world_stale) restart their
                # workers and re-form without the dead node.
                del self._rdzv_nodes[node_rank]
                changed = self._stale_round != self._rdzv_round
                self._stale_round = self._rdzv_round
                logger.info(
                    "rdzv %s: node %s left active world; round %s is now "
                    "stale, survivors must re-form",
                    self.name, node_rank, self._rdzv_round,
                )
            round_ = self._rdzv_round
        # Emits (like _notify_state) stay outside the lock: the journal
        # path must never nest inside the rendezvous lock.
        if changed:
            self._notify_state()
            emit(  # dtlint: disable=DT012 -- replay-guarded at the sink: JobMaster._event_sink drops emits while store.replaying; the live emission's own ("event", ...) record replays instead
                EventKind.RDZV_INVALIDATED, _node_id=node_rank,
                _role="master", rdzv=self.name, round=round_,
                reason="member-left",
            )

    def world_stale(self, round_: int) -> bool:
        """True when the given round was invalidated by a member death."""
        with self._lock:
            return round_ <= self._stale_round

    def invalidate_round(self):
        """Invalidate the current round without evicting anyone (hang
        recovery: every member flushes, restarts and rejoins)."""
        changed = False
        with self._lock:
            if self._rdzv_nodes:
                changed = self._stale_round != self._rdzv_round
                self._stale_round = self._rdzv_round
                logger.info(
                    "rdzv %s: round %s invalidated; members must re-form",
                    self.name, self._rdzv_round,
                )
            round_ = self._rdzv_round
        if changed:
            self._notify_state()
            emit(  # dtlint: disable=DT012 -- replay-guarded at the sink: JobMaster._event_sink drops emits while store.replaying
                EventKind.RDZV_INVALIDATED, _role="master",
                rdzv=self.name, round=round_, reason="invalidated",
            )

    def join_rendezvous(
        self, node_rank: int, local_world_size: int = 1
    ) -> int:
        """Register intent to join; returns the round being formed."""
        with self._lock:
            if node_rank in self._rdzv_nodes and node_rank not in self._waiting_nodes:
                # Rejoin after restart: previous world is stale.
                self._rdzv_nodes = {}
            first = not self._waiting_nodes
            if first:
                self._start_rdzv_time = time.monotonic()
            self._waiting_nodes[node_rank] = local_world_size
            self._alive_nodes.add(node_rank)
            self._lastcall_time = time.monotonic()
            round_ = self._rdzv_round
        if first:
            emit(
                EventKind.RDZV_ROUND_START, _role="master",
                rdzv=self.name, round=round_ + 1,
            )
        emit(
            EventKind.RDZV_JOIN, _node_id=node_rank, _role="master",
            rdzv=self.name, round=round_ + 1,
        )
        return round_

    def _freeze_ready(self) -> bool:  # dtlint: holds(rdzv.*)
        """Called with the lock held: can the waiting set become a round?"""
        count = len(self._waiting_nodes)
        if count < max(self._min_nodes, 1):
            return False
        if count >= self._max_nodes:
            return True
        waited = time.monotonic() - self._start_rdzv_time
        lastcall = time.monotonic() - self._lastcall_time
        if waited >= self._waiting_timeout:
            return True
        return lastcall >= self._lastcall_timeout and count >= self._min_nodes

    def _freeze_round(self):  # dtlint: holds(rdzv.*)
        """Admit a node_unit-aligned subset of the waiting set as the world."""
        count = len(self._waiting_nodes)
        admitted = (count // self._node_unit) * self._node_unit
        if admitted <= 0:
            return
        ranks = sorted(self._waiting_nodes)[:admitted]
        self._rdzv_nodes = {r: self._waiting_nodes[r] for r in ranks}
        for r in ranks:
            del self._waiting_nodes[r]
        self._rdzv_round += 1
        logger.info(
            "rdzv %s: froze round %s with nodes %s",
            self.name, self._rdzv_round, sorted(self._rdzv_nodes),
        )

    def num_nodes_waiting(self) -> int:
        """Agents poll this to detect membership changes (>0 => restart)."""
        with self._lock:
            return len(self._waiting_nodes)

    def current_world(self) -> Dict[int, int]:
        """The frozen world of the current round (empty while forming)."""
        with self._lock:
            return dict(self._rdzv_nodes)

    def current_round(self) -> int:
        """The newest round number (frozen or being formed). Lets the
        rescale coordinator abort an obsolete plan without invalidating
        a newer round that superseded it."""
        with self._lock:
            return self._rdzv_round

    def absorb_world(self, world: Dict[int, int]) -> int:
        """Install `world` as the next frozen round without a rendezvous.

        The rescale coordinator's primitive: survivors of an in-place
        transition adopt the returned round directly (via the plan RPC)
        instead of rejoining, so the old stale round is superseded
        without anyone tearing down. Members of `world` still sitting in
        the waiting set (a grown node that joined normally) are absorbed
        out of it. Every prior round is marked stale so survivors notice
        the transition through the same world_stale() poll that detects
        deaths — the plan RPC then tells them it is an in-place move.
        """
        with self._lock:
            self._rdzv_nodes = dict(world)
            for rank in world:
                self._waiting_nodes.pop(rank, None)
                self._alive_nodes.add(rank)
            self._stale_round = max(self._stale_round, self._rdzv_round)
            self._rdzv_round += 1
            round_ = self._rdzv_round
            logger.info(
                "rdzv %s: absorbed world %s as round %s (in-place rescale)",
                self.name, sorted(world), round_,
            )
        self._notify_state()
        emit(  # dtlint: disable=DT012 -- replay-guarded at the sink: JobMaster._event_sink drops emits while store.replaying
            EventKind.RDZV_ROUND_COMPLETE, _role="master",
            rdzv=self.name, round=round_, nodes=len(world), rescale=True,
        )
        return round_

    @abstractmethod
    def get_comm_world(
        self, node_rank: int
    ) -> Tuple[int, int, Dict[int, int]]:
        """Returns (round, group, {node_rank: local_world_size}).

        An empty world means "keep polling" — the round is still forming.
        """


class ElasticTrainingRendezvousManager(RendezvousManager):
    """One global communication world per round."""

    def get_comm_world(self, node_rank: int):
        froze = False
        froze_round = froze_nodes = 0
        try:
            with self._lock:
                if node_rank in self._rdzv_nodes:
                    return self._rdzv_round, 0, dict(self._rdzv_nodes)
                if self._freeze_ready():
                    before = self._rdzv_round
                    self._freeze_round()
                    froze = self._rdzv_round != before
                    froze_round = self._rdzv_round
                    froze_nodes = len(self._rdzv_nodes)
                    if node_rank in self._rdzv_nodes:
                        return self._rdzv_round, 0, dict(self._rdzv_nodes)
                return self._rdzv_round, 0, {}
        finally:
            if froze:
                self._notify_state()
                emit(
                    EventKind.RDZV_ROUND_COMPLETE, _role="master",
                    rdzv=self.name, round=froze_round, nodes=froze_nodes,
                )


class DeviceCheckRendezvousManager(RendezvousManager):
    """Paired-group check rounds for fault/straggler localization.

    Check round 1: nodes are paired sequentially ``(0,1)(2,3)...``; each
    pair runs an allgather+matmul exercise. A failed pair makes both
    members suspects. Check round 2: every suspect is deliberately
    re-paired with a node that passed round 1 (parity: reference
    ``rdzv_manager.py:449-507``). A node that fails both rounds is the
    fault node; with only one round of data the diagnosis is not ``done``.

    A report deadline guards against a node dying mid-check: members that
    fail to report within ``check_timeout`` of the round freezing are
    recorded as failed, so the diagnosis can never wedge on a silent node.
    """

    def __init__(self, name: str, check_timeout: float = 120.0):
        super().__init__(name)
        self._node_status: Dict[int, Dict[int, bool]] = {}  # round -> rank -> ok
        self._node_times: Dict[int, Dict[int, float]] = {}  # round -> rank -> sec
        self._round_members: Dict[int, Set[int]] = {}  # round -> frozen members
        self._check_round = 0
        self._straggler_ratio = 2.0
        self._check_timeout = check_timeout
        self._round_frozen_time = 0.0
        # Groups snapshotted at freeze time so every member of a round sees
        # the same pairing even if earlier-round data changes underneath.
        self._groups: List[List[int]] = []

    def join_rendezvous(self, node_rank: int, local_world_size: int = 1) -> int:
        with self._lock:
            if not self._waiting_nodes:
                self._start_rdzv_time = time.monotonic()
            self._waiting_nodes[node_rank] = local_world_size
            self._alive_nodes.add(node_rank)
            self._lastcall_time = time.monotonic()
            return self._rdzv_round

    def get_comm_world(self, node_rank: int):
        froze = False
        froze_round = froze_nodes = 0
        try:
            with self._lock:
                self._expire_round()
                if not self._rdzv_nodes and self._freeze_ready():
                    before = self._rdzv_round
                    self._freeze_round()
                    froze = self._rdzv_round != before
                    froze_round = self._rdzv_round
                    froze_nodes = len(self._rdzv_nodes)
                    if self._rdzv_nodes:  # node_unit may admit zero nodes
                        self._check_round += 1
                        self._round_members[self._check_round] = set(
                            self._rdzv_nodes
                        )
                        self._round_frozen_time = time.monotonic()
                        self._groups = self._build_groups()
                if node_rank in self._rdzv_nodes:
                    for group_idx, members in enumerate(self._groups):
                        if node_rank in members:
                            world = {r: self._rdzv_nodes[r] for r in members}
                            return self._rdzv_round, group_idx, world
                return self._rdzv_round, 0, {}
        finally:
            if froze:
                self._notify_state()
                emit(
                    EventKind.RDZV_ROUND_COMPLETE, _role="master",
                    rdzv=self.name, round=froze_round, nodes=froze_nodes,
                )

    def _expire_round(self):
        """With the lock held: time out members that never reported."""
        if not self._rdzv_nodes or self._round_frozen_time <= 0:
            return
        if time.monotonic() - self._round_frozen_time < self._check_timeout:
            return
        r = self._check_round
        reported = set(self._node_status.get(r, {}))
        for rank in set(self._rdzv_nodes) - reported:
            logger.warning(
                "device check %s: node %s never reported in round %s; "
                "recording as failed", self.name, rank, r,
            )
            self._node_status.setdefault(r, {})[rank] = False
            self._node_times.setdefault(r, {})[rank] = float("inf")
        self._rdzv_nodes = {}

    def _build_groups(self) -> List[List[int]]:
        """Pair nodes; from check round 2 on, pair each suspect (failed the
        previous round) with a known-good node so the faulty member of a
        failed pair is isolated."""
        ranks = sorted(self._rdzv_nodes)
        prev = self._node_status.get(self._check_round - 1, {})
        suspects = [r for r in ranks if prev.get(r) is False]
        good = [r for r in ranks if r not in set(suspects)]
        if self._check_round > 1 and suspects and good:
            pairs: List[List[int]] = []
            g, s = list(good), list(suspects)
            while s and g:
                pairs.append([g.pop(0), s.pop(0)])
            rest = g + s
            for i in range(0, len(rest) - 1, 2):
                pairs.append([rest[i], rest[i + 1]])
            if len(rest) % 2:
                if pairs:
                    pairs[-1].append(rest[-1])
                else:
                    pairs.append([rest[-1]])
            return pairs
        groups = []
        for i in range(0, len(ranks) - 1, 2):
            groups.append([ranks[i], ranks[i + 1]])
        if len(ranks) % 2:
            if groups:
                groups[-1].append(ranks[-1])
            else:
                groups.append([ranks[-1]])
        return groups

    def report_check_result(self, node_rank: int, normal: bool,
                            elapsed: float, round_: Optional[int] = None):
        with self._lock:
            r = self._check_round if round_ is None else round_
            members = self._round_members.get(r)
            if members is not None and node_rank not in members:
                logger.warning(
                    "device check %s: dropping report from node %s for "
                    "round %s it was not a member of", self.name, node_rank, r,
                )
                return
            if members is not None and set(
                self._node_status.get(r, {})
            ) >= members:
                # The round already completed (possibly via expiry): a late
                # report must not flip a diagnosis others have acted on.
                logger.warning(
                    "device check %s: dropping late report from node %s for "
                    "completed round %s", self.name, node_rank, r,
                )
                return
            self._node_status.setdefault(r, {})[node_rank] = normal
            self._node_times.setdefault(r, {})[node_rank] = elapsed
            # The reported world is consumed; allow the next check round to
            # freeze once every member of the current round reported.
            if r == self._check_round and set(
                self._node_status[r]
            ) >= set(self._rdzv_nodes):
                self._rdzv_nodes = {}

    def _complete_rounds(self) -> List[int]:
        """With the lock held: rounds where every frozen member reported."""
        done = []
        for r, members in self._round_members.items():
            if set(self._node_status.get(r, {})) >= members:
                done.append(r)
        return sorted(done)

    def completed_rounds(self) -> int:
        with self._lock:
            self._expire_round()
            return len(self._complete_rounds())

    def check_fault_node(self) -> Tuple[List[int], bool]:
        """Returns (fault node ranks, diagnosis finished)."""
        with self._lock:
            self._expire_round()
            rounds = self._complete_rounds()
            if not rounds:
                return [], False
            last = rounds[-1]
            current = self._node_status[last]
            suspects = {r for r, ok in current.items() if not ok}
            if not suspects:
                return [], True
            if len(rounds) < 2:
                return sorted(suspects), False
            prev = self._node_status[rounds[-2]]
            confirmed = [r for r in suspects if not prev.get(r, True)]
            return sorted(confirmed), True

    def check_straggler(self) -> Tuple[List[int], bool]:
        """Elapsed-time median×ratio rule (reference rdzv_manager.py:492)."""
        with self._lock:
            rounds = self._complete_rounds()
            if not rounds:
                return [], False
            times = self._node_times[rounds[-1]]
            finite = [t for t in times.values() if t != float("inf")]
            if len(times) < 2 or not finite:
                return [], True
            median = statistics.median(finite)
            if median <= 0:
                return [], True
            stragglers = [
                r for r, t in times.items() if t > median * self._straggler_ratio
            ]
            return sorted(stragglers), True
