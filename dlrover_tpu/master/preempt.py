"""Master-side preemption plane: known-ahead failures as planned moves.

Production TPU fleets run predominantly on preemptible capacity, where
the common failure is not a surprise SIGKILL but a termination notice
with a 30-120 s grace window. Before this coordinator the framework only
reacted after death, paying the full detect+rescale tax. The preemption
plane instead treats the notice as the start of a planned transition:

- the victim's agent reports a journaled
  :class:`~dlrover_tpu.common.messages.PreemptionNotice` (and flushes its
  own shm snapshot to storage while the grace clock runs);
- :meth:`PreemptionCoordinator.on_notice` pre-elects a replacement
  checkpoint writer for every PR-9 lease the victim owns, so the next
  checkpoint epoch never blocks on a dead writer;
- at the next step boundary (:meth:`note_step`) the coordinator removes
  the victim from the rendezvous and hands the survivors an in-place
  shrink plan through the rescale coordinator — while the victim is
  still alive. The eventual kill is a non-event: the node is already
  out of the world, so the failure report finds nothing left to do.

A notice that expires without a kill (false alarm) cancels cleanly in
:meth:`tick`: writer leases revert to their prior owners, any still
in-flight shrink plan is superseded WITHOUT round invalidation, and the
victim — never restarted — rejoins through the normal grow path.

Durability: the notice itself replays through its journaled RPC record;
the transitions driven by unjournaled inputs — the writer pre-election
(the live rendezvous world is not a journal input), the step-boundary
shrink and the timer-driven cancel — write their own
``("preempt", payload, ts)`` records.
"""

import time
from typing import Any, Dict, List, Optional

from dlrover_tpu.common import env_utils
from dlrover_tpu.common import messages as m
from dlrover_tpu.common.constants import RendezvousName
from dlrover_tpu.common.lockdep import instrumented_lock
from dlrover_tpu.common.log import logger
from dlrover_tpu.observability.events import EventKind, emit

NOTICE_ACTIVE = "active"
NOTICE_HANDLED = "handled"
NOTICE_CANCELLED = "cancelled"

#: kv namespace the PR-9 writer election claims leases under
#: (servicer._ckpt_writer_elect: "ckpt_writer/{epoch}/{group}").
WRITER_LEASE_PREFIX = "ckpt_writer/"


class PreemptionCoordinator:
    #: dtlint DT009: the notice table (deadlines, handoff backups, plan
    #: linkage) moves as one unit under the coordinator lock.
    GUARDED_BY = {
        "_notices": "master.preempt",
    }

    """Tracks termination notices and converts them into planned
    transitions.

    Wiring: the servicer's journaled ``PreemptionNotice`` handler calls
    :meth:`on_notice`; ``_report_step`` calls :meth:`note_step` (the
    step boundary is where the proactive shrink issues); the failure /
    evict paths call :meth:`on_node_removed`; the master's monitor loop
    calls :meth:`tick` for false-alarm expiry.
    """

    def __init__(
        self,
        rdzv_managers: Optional[Dict[str, Any]] = None,
        kv_store=None,
        job_manager=None,
        rescale_coordinator=None,
        state_store=None,
    ):
        self._lock = instrumented_lock("master.preempt")
        self._rdzv_managers = rdzv_managers or {}
        self._kv_store = kv_store
        self._job_manager = job_manager
        self._rescale = rescale_coordinator
        self._store = state_store
        # node_rank -> {deadline_ts, grace_s, source, reason, status,
        #               planned, plan_id, leases: [[key, heir, prior]]}
        self._notices: Dict[int, Dict[str, Any]] = {}

    # ---------------- journal plumbing ----------------
    @property
    def _replaying(self) -> bool:
        return self._store is not None and self._store.replaying

    def _journal(self, payload: Dict[str, Any]):
        if self._store is not None and not self._store.replaying:
            self._store.append(("preempt", payload, time.time()))

    # ---------------- notice intake (journaled RPC) ----------------
    def on_notice(self, req: m.PreemptionNotice) -> m.Response:
        """Record a termination notice and hand off the victim's
        checkpoint writer leases.

        Reached via the journaled ``PreemptionNotice`` RPC, so a master
        failover mid-notice replays it exactly once; duplicate reports
        (client retries, several sources firing) dedupe here — the
        first deadline wins.
        """
        if not env_utils.PREEMPT.get():  # dtlint: disable=DT011 -- operator kill-switch deliberately read live; with the plane off the notice must be a no-op on replay too
            return m.Response(success=False, reason="preempt disabled")
        victim = int(req.node_rank)
        if victim < 0:
            return m.Response(success=False, reason="bad node_rank")
        with self._lock:
            existing = self._notices.get(victim)
            if existing is not None and existing["status"] == NOTICE_ACTIVE:
                # Duplicate notice for an already-armed victim: the
                # first deadline wins, nothing re-runs.
                return m.Response(success=True, reason="duplicate")
            self._notices[victim] = {
                "deadline_ts": float(req.deadline_ts),
                "grace_s": float(req.grace_s),
                "source": req.source,
                "reason": req.reason,
                "status": NOTICE_ACTIVE,
                "planned": False,
                "plan_id": -1,
                "leases": [],
            }
        handoffs = self._preelect_writers(victim)
        if handoffs:
            with self._lock:
                notice = self._notices.get(victim)
                if notice is not None:
                    notice["leases"] = handoffs
            # The handoff depends on the LIVE rendezvous world (who
            # survives), which is not reconstructed by the journal —
            # record the computed result so replay re-applies it
            # verbatim instead of re-deriving it from divergent state.
            self._journal({
                "rec": "leases", "node": victim, "leases": handoffs,
            })
        if self._job_manager is not None:
            self._job_manager.mark_preempting(victim)
        logger.info(
            "preempt notice for node %s (source=%s deadline=%.1f "
            "grace=%.1fs): %d writer lease(s) handed off",
            victim, req.source, req.deadline_ts, req.grace_s,
            len(handoffs),
        )
        emit(  # dtlint: disable=DT012 -- replay-guarded at the sink: JobMaster._event_sink drops emits while store.replaying
            EventKind.PREEMPT_NOTICE, _node_id=victim, _role="master",
            deadline_ts=req.deadline_ts, grace_s=req.grace_s,
            source=req.source, reason=req.reason,
            handoffs=[entry[0] for entry in handoffs],
        )
        return m.Response(success=True)

    def _preelect_writers(self, victim: int) -> List[List[Any]]:
        """Move every writer lease the victim owns onto the lowest
        surviving rank, remembering the prior owner for the false-alarm
        revert. Deterministic (sorted scan over replayed kv state), so
        The live rendezvous world is an unjournaled input, so the
        computed handoffs are journaled as a ``"leases"`` record and
        this recomputation is skipped on replay."""
        if self._kv_store is None or self._replaying:
            return []
        training = self._rdzv_managers.get(RendezvousName.TRAINING)
        world = training.current_world() if training is not None else {}
        survivors = sorted(r for r in world if r != victim)
        handoffs: List[List[Any]] = []
        for key, value in self._kv_store.scan(WRITER_LEASE_PREFIX).items():
            try:
                owner = int(value.decode())
            except (ValueError, AttributeError):
                continue
            if owner != victim or not survivors:
                continue
            heir = survivors[0]
            self._kv_store.delete(key)
            self._kv_store.setnx(key, str(heir).encode())
            handoffs.append([key, heir, owner])
        return handoffs

    def _revert_leases(self, handoffs: List[List[Any]]):
        if self._kv_store is None:
            return
        for key, _heir, prior in handoffs:
            self._kv_store.set(key, str(int(prior)).encode())

    # ---------------- step boundary: proactive shrink ----------------
    def note_step(self, step: int):
        """Issue the in-place shrink for every active, not-yet-planned
        notice. Runs at the step boundary (the servicer's step report)
        so survivors transition between steps, not mid-step."""
        if self._replaying or not env_utils.PREEMPT.get():
            return
        pending: List[int] = []
        with self._lock:
            for node in sorted(self._notices):
                notice = self._notices[node]
                if notice["status"] == NOTICE_ACTIVE and not notice["planned"]:
                    pending.append(node)
        for node in pending:
            self._plan_shrink(node, step)

    def _plan_shrink(self, victim: int, step: int):
        training = self._rdzv_managers.get(RendezvousName.TRAINING)
        old_world = training.current_world() if training is not None else {}
        plan = None
        if victim in old_world:
            # Same sequence as the failure path, just ahead of the kill:
            # drop the victim from every rendezvous, then give the
            # rescale coordinator its shot at an in-place plan. When it
            # declines (no quorum, no batch config) the world has still
            # shrunk, and the stale-round full-restart fallback takes
            # over once the kill lands.
            for mgr in self._rdzv_managers.values():
                mgr.remove_alive_node(victim)
            if self._rescale is not None:
                plan = self._rescale.on_node_removed(victim, old_world)
        plan_id = plan.plan_id if plan is not None else -1
        with self._lock:
            notice = self._notices.get(victim)
            if notice is None or notice["status"] != NOTICE_ACTIVE:
                return
            notice["planned"] = True
            notice["plan_id"] = plan_id
        self._journal({"rec": "planned", "node": victim, "plan_id": plan_id})
        logger.info(
            "preempt: shrink for node %s issued at step boundary %s "
            "(plan %s); the coming kill is a non-event",
            victim, step, plan_id if plan_id >= 0 else "declined",
        )
        emit(
            EventKind.PREEMPT_HANDLED, _node_id=victim, _role="master",
            step=step, plan_id=plan_id, proactive=True,
        )

    # ---------------- the kill (or evict) lands ----------------
    def on_node_removed(self, node_rank: int) -> bool:
        """The node actually left (failure report or master evict).

        Marks an active notice handled so tick never false-alarms it.
        Returns whether a notice was active — True means the departure
        was announced and (if planned) already paid for. Replay-pure:
        reached from journaled NodeFailure replay and evict records.
        """
        with self._lock:
            notice = self._notices.get(int(node_rank))
            if notice is None or notice["status"] != NOTICE_ACTIVE:
                return False
            notice["status"] = NOTICE_HANDLED
        return True

    def is_active(self, node_rank: int) -> bool:
        with self._lock:
            notice = self._notices.get(int(node_rank))
            return notice is not None and notice["status"] == NOTICE_ACTIVE

    # ---------------- false-alarm expiry ----------------
    def tick(self):
        """Periodic driver (master monitor loop): a notice whose
        deadline passed with the node still alive is a false alarm —
        cancel it cleanly."""
        if self._replaying:
            return
        now = time.time()
        slack = env_utils.PREEMPT_FALSE_ALARM_S.get()
        expired: List[int] = []
        with self._lock:
            for node in sorted(self._notices):
                notice = self._notices[node]
                if (
                    notice["status"] == NOTICE_ACTIVE
                    and notice["deadline_ts"] > 0
                    and now > notice["deadline_ts"] + slack
                ):
                    expired.append(node)
        for node in expired:
            self._cancel(node, reason="deadline passed without a kill")

    def _cancel(self, victim: int, reason: str):
        with self._lock:
            notice = self._notices.get(victim)
            if notice is None or notice["status"] != NOTICE_ACTIVE:
                return
            notice["status"] = NOTICE_CANCELLED
            handoffs = [list(entry) for entry in notice["leases"]]
            plan_id = notice["plan_id"]
        self._revert_leases(handoffs)
        if self._job_manager is not None:
            self._job_manager.clear_preempting(victim)
        if plan_id >= 0 and self._rescale is not None:
            # The proactive shrink is obsolete: the victim stays. Abort
            # it through supersede semantics — NEVER round invalidation,
            # which would force-restart a healthy world. Survivors that
            # already applied keep training; the victim rejoins through
            # the normal grow path.
            self._rescale.supersede_plan(plan_id, "preempt-false-alarm")
        self._journal({"rec": "cancel", "node": victim})
        logger.info(
            "preempt notice for node %s cancelled (%s): %d lease(s) "
            "reverted, no restart", victim, reason, len(handoffs),
        )
        emit(
            EventKind.PREEMPT_CANCEL, _node_id=victim, _role="master",
            reason=reason, leases_reverted=len(handoffs),
        )

    # ---------------- durability ----------------
    def pending(self) -> List[int]:
        """Node ranks with an active notice (tests + status surfaces)."""
        with self._lock:
            return sorted(
                node for node, notice in self._notices.items()
                if notice["status"] == NOTICE_ACTIVE
            )

    def notice_state(self, node_rank: int) -> Optional[Dict[str, Any]]:
        with self._lock:
            notice = self._notices.get(int(node_rank))
            return dict(notice) if notice is not None else None

    def checkpoint(self) -> dict:
        with self._lock:
            return {
                "notices": {
                    str(node): dict(notice)
                    for node, notice in self._notices.items()
                },
            }

    def restore(self, state: dict):
        if not state:
            return
        with self._lock:
            for node, notice in state.get("notices", {}).items():
                restored = dict(notice)
                restored["leases"] = [
                    list(entry) for entry in restored.get("leases", [])
                ]
                self._notices[int(node)] = restored

    def replay(self, payload: Dict[str, Any]):
        """Re-apply one journaled ``("preempt", payload, ts)`` record.

        Only the unjournaled-input transitions live here: the notice
        itself replays through its rpc record, while "leases" re-applies
        the recorded writer handoff (derived live from the rendezvous
        world, which the journal does not reconstruct), "planned" is
        pure bookkeeping and "cancel" re-applies the lease revert.
        """
        rec = payload.get("rec")
        if rec == "leases":
            victim = int(payload.get("node", -1))
            handoffs = [list(entry) for entry in payload.get("leases", [])]
            with self._lock:
                notice = self._notices.get(victim)
                if notice is not None:
                    notice["leases"] = handoffs
            if self._kv_store is not None:
                for key, heir, _prior in handoffs:
                    self._kv_store.set(key, str(int(heir)).encode())
        elif rec == "planned":
            with self._lock:
                notice = self._notices.get(int(payload.get("node", -1)))
                if notice is not None:
                    notice["planned"] = True
                    notice["plan_id"] = int(payload.get("plan_id", -1))
        elif rec == "cancel":
            victim = int(payload.get("node", -1))
            with self._lock:
                notice = self._notices.get(victim)
                handoffs = []
                if notice is not None and notice["status"] == NOTICE_ACTIVE:
                    notice["status"] = NOTICE_CANCELLED
                    handoffs = [list(entry) for entry in notice["leases"]]
            self._revert_leases(handoffs)
            if self._job_manager is not None:
                self._job_manager.clear_preempting(victim)
        else:
            logger.warning("skipping unknown preempt record %r", rec)
