"""Scaling stack: scaler backends, node watchers, the job auto-scaler
and the local resource optimizer.

Parity map (all condensed to the TPU/local platform model):

- Scaler backends — reference ``master/scaler/pod_scaler.py:71,143`` /
  ``elasticjob_scaler.py``: realize a ScalePlan against the platform.
  ``ProcessScaler`` is the local backend (spawns/kills agent processes —
  what a single-host elastic job actually scales);
  ``ElasticJobScaler`` emits the ScalePlan as a CRD-style patch through
  an injected client, the k8s-operator integration point (no cluster in
  this environment, so the client is pluggable and faked in tests).
- ``ProcessWatcher`` — reference ``watcher/k8s_watcher.py:151``: turns
  platform state (here: child process liveness) into NodeEvents for the
  job manager.
- ``AllreduceAutoScaler`` — reference ``node/job_auto_scaler.py:254``
  (``AllreduceTrainingAutoScaler``): periodically reconciles alive
  workers against the target count and executes relaunch plans.
- ``LocalResourceOptimizer`` — reference
  ``resource/local_optimizer.py:66``: turns collected runtime stats into
  a per-worker resource plan (the Brain-less local strategy).
"""

import subprocess
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from dlrover_tpu.common.log import logger
from dlrover_tpu.common.node import Node, NodeResource
from dlrover_tpu.common.periodic import PeriodicTask
from dlrover_tpu.master.node_manager import ScalePlan, Scaler


# ---------------------------------------------------------------- scalers


class ProcessScaler(Scaler):
    """Local platform backend: one agent process per node.

    ``command_fn(node) -> argv`` builds the launch command (tests inject
    trivial commands; the CLI integration passes a ``dlrover_tpu.cli``
    invocation with the node's rank).
    """

    def __init__(self, command_fn: Callable[[Node], List[str]]):
        self._command_fn = command_fn
        self._procs: Dict[int, subprocess.Popen] = {}
        self._lock = threading.Lock()

    def scale(self, plan: ScalePlan):
        for node in plan.remove_nodes:
            self._kill(node.id)
        for node in plan.launch_nodes:
            self._launch(node)

    def _launch(self, node: Node):
        with self._lock:
            existing = self._procs.get(node.id)
        if existing is not None and existing.poll() is None:
            logger.warning(
                "scaler: node %s already running (pid %s); not relaunching",
                node.id, existing.pid,
            )
            return
        cmd = self._command_fn(node)
        proc = subprocess.Popen(cmd, start_new_session=True)
        with self._lock:
            self._procs[node.id] = proc
        logger.info("scaler launched node %s (pid %s)", node.id, proc.pid)

    def _kill(self, node_id: int):
        with self._lock:
            proc = self._procs.pop(node_id, None)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
            logger.info("scaler stopped node %s", node_id)

    def alive_nodes(self) -> List[int]:
        with self._lock:
            return [
                nid for nid, p in self._procs.items() if p.poll() is None
            ]

    def dead_nodes(self) -> List[int]:
        """Launched nodes whose process has exited (not yet removed)."""
        with self._lock:
            return [
                nid for nid, p in self._procs.items()
                if p.poll() is not None
            ]

    def stop(self):
        with self._lock:
            ids = list(self._procs)
        for nid in ids:
            self._kill(nid)


class ElasticJobScaler(Scaler):
    """Operator integration point: a ScalePlan becomes a ScalePlan *CRD
    manifest* — the exact schema the Go controller consumes
    (``scaleplan_types.go``; vendored as ``master/crd.py``) — submitted
    through the ``client`` (``patch(body: dict)``): the real k8s client
    on a cluster, a ``ScalePlanStore`` + reconciler locally."""

    def __init__(self, client, job_name: str):
        self._client = client
        self._job_name = job_name
        self._seq = 0

    def scale(self, plan: ScalePlan):
        from dlrover_tpu.master.crd import scaleplan_from_plan

        self._seq += 1
        crd = scaleplan_from_plan(plan, self._job_name, self._seq)
        body = crd.to_manifest()
        self._client.patch(body)
        logger.info("elasticjob scaler submitted %s", crd.name)


# ---------------------------------------------------------------- watcher


class ProcessWatcher:
    """Turn local process liveness into node events (reference
    ``watcher/k8s_watcher.py``: pod events -> NodeEvents)."""

    def __init__(self, scaler: ProcessScaler, job_manager,
                 interval: float = 1.0):
        self._scaler = scaler
        self._job_manager = job_manager
        self._reported_dead: set = set()
        self._task = PeriodicTask(self._poll, interval, "process-watcher")

    def _poll(self):
        # Ask the platform for *exited* launches directly rather than
        # diffing alive sets: a process that dies between two polls (or
        # before the first) must still produce its failure event.
        dead = set(self._scaler.dead_nodes())
        # A relaunch (same id, alive again) clears the report marker so
        # a second death re-reports.
        self._reported_dead &= dead
        for died in dead:
            if died in self._reported_dead:
                continue
            self._reported_dead.add(died)
            logger.info("watcher: node %s process exited", died)
            self._job_manager.update_node_status(died, "failed",
                                                 "process-exit")

    def list(self) -> List[int]:
        return self._scaler.alive_nodes()

    def start(self):
        self._task.start()

    def stop(self):
        self._task.stop()


# ------------------------------------------------------------- optimizer


@dataclass
class ResourcePlan:
    """Per-worker resource suggestion (reference ResourcePlan, lean)."""

    worker_cpu: float = 0.0
    worker_memory_mb: int = 0
    worker_num: int = 0

    def empty(self) -> bool:
        return not (self.worker_cpu or self.worker_memory_mb
                    or self.worker_num)


class LocalResourceOptimizer:
    """Stats -> resource plan, no external service (reference
    ``resource/local_optimizer.py``; the Brain-backed variant plugs in
    through the same ``generate_plan`` interface)."""

    # Headroom over observed peaks, matching the reference's factor-based
    # sizing.
    CPU_FACTOR = 1.5
    MEM_FACTOR = 1.3

    def __init__(self, metric_collector):
        self._collector = metric_collector

    def generate_plan(self, current_workers: int) -> ResourcePlan:
        summary = self._collector.summary()
        if not summary["nodes"]:
            return ResourcePlan()
        return ResourcePlan(
            worker_cpu=round(summary["cpu_percent_avg"] / 100
                             * self.CPU_FACTOR, 2),
            worker_memory_mb=int(
                summary["used_memory_mb_max"] * self.MEM_FACTOR
            ),
            worker_num=current_workers,
        )


# ------------------------------------------------------------ auto-scaler


class AllreduceAutoScaler:
    """Keep the worker fleet at target size; apply resource plans.

    Reference ``node/job_auto_scaler.py:254-316``
    (``AllreduceTrainingAutoScaler``): a periodic loop counting alive
    workers and relaunching the difference through the scaler. Hang- and
    death-driven *shrink* lives in the master's node monitor (scale-in
    is membership removal); this loop owns *grow* and resource sizing.
    """

    # A freshly-launched node gets this long to register before it is
    # presumed failed and relaunched (prevents duplicate launches while
    # an agent is still rendezvousing).
    LAUNCH_GRACE_S = 120.0

    def __init__(self, job_manager, scaler: Scaler,
                 resource_optimizer: Optional[LocalResourceOptimizer] = None,
                 target_worker_num: Optional[int] = None,
                 interval: float = 10.0):
        self._job_manager = job_manager
        self._scaler = scaler
        self._optimizer = resource_optimizer
        self._target = target_worker_num
        self._pending_launches: Dict[int, float] = {}  # node id -> time
        self._last_resource_plan: Optional[ResourcePlan] = None
        self._task = PeriodicTask(self._reconcile, interval, "auto-scaler")

    def start(self):
        self._task.start()

    def stop(self):
        self._task.stop()

    def _reconcile(self):
        now = time.time()
        nodes = {n.id: n for n in self._job_manager.all_nodes()}
        # A pending launch counts toward the target until it registers or
        # its grace expires — otherwise every tick relaunches the same
        # slot and orphans the still-rendezvousing process.
        self._pending_launches = {
            nid: t for nid, t in self._pending_launches.items()
            if nid not in nodes and now - t < self.LAUNCH_GRACE_S
        }
        target = self._target if self._target is not None else len(nodes)
        alive = [n for n in nodes.values() if not n.exited()]
        missing = target - len(alive) - len(self._pending_launches)
        if missing > 0:
            used = set(nodes) | set(self._pending_launches)
            launch = []
            next_id = 0
            for _ in range(missing):
                while next_id in used:
                    next_id += 1
                used.add(next_id)
                launch.append(Node("worker", next_id))
                self._pending_launches[next_id] = now
            plan = ScalePlan(launch_nodes=launch)
            logger.info("auto-scaler: %s alive < target %s; launching %s",
                        len(alive), target, [n.id for n in launch])
            self._scaler.scale(plan)
        if self._optimizer is not None:
            rplan = self._optimizer.generate_plan(target)
            if not rplan.empty() and rplan != self._last_resource_plan:
                self._last_resource_plan = rplan
                self.execute_resource_plan(rplan)

    def execute_resource_plan(self, rplan: ResourcePlan):
        from dlrover_tpu.common.node import NodeGroupResource

        plan = ScalePlan(node_group_resources={
            "worker": NodeGroupResource(
                count=rplan.worker_num,
                node_resource=NodeResource(
                    cpu=rplan.worker_cpu,
                    memory_mb=rplan.worker_memory_mb,
                ),
            )
        })
        self._scaler.scale(plan)
