"""Master-side automatic straggler remediation: close the detect loop.

PR 10 classifies stragglers (compute/input/link) and PR 16 made every
parallelism mode elastic, but acting on a verdict stayed log-only: a
chronically degraded node bled goodput forever unless an operator set
``DLROVER_TPU_STRAGGLER_EVICT`` and accepted a blunt permanent eviction.
This policy drives the full loop autonomously —

    HEALTHY -> SUSPECT -> QUARANTINED -> PROBATION -> HEALTHY | EVICTED

- a sustained :class:`StragglerDetector` verdict makes the node
  SUSPECT; after ``REMEDIATION_SUSTAIN_TICKS`` policy ticks with the
  verdict still standing (hysteresis on top of the detector's own
  sustain), the node is QUARANTINED: dropped from the rendezvous and the
  survivors handed an in-place shrink plan through the
  :class:`RescaleCoordinator` (composing with the PR-16 reshape specs,
  so evicting a TP member reshapes rather than restarts);
- a quarantined node is *parked*, not killed: its agent keeps
  heartbeating and probing, and the servicer's join gate keeps it out of
  the training rendezvous. When its probes recover (the detector clears
  the flag), the node enters PROBATION: the gate lifts and its next join
  poll regrows the world through the ordinary grow path;
- a clean probation window clears the node back to HEALTHY; a node
  whose verdict returns during probation fails it — once back to
  quarantine with backoff, twice and it is permanently EVICTED through
  the node-manager path;
- the action path degrades gracefully: a nacked or declined shrink plan
  reverts the node to SUSPECT with exponential backoff — never a crash,
  never a stuck state. Safety rails bound the blast radius: a cooldown
  between actions, a max-concurrent-remediations cap, and a min-world
  floor (plus the rescale quorum pre-flight) so the policy can never
  shrink below quorum or flap the fleet.

Durability: detection hysteresis is re-derived live from telemetry, but
every *acted* transition (quarantine, revert, probation, probation
fail, clear, evicted) is an apply-then-log ``("remediate", payload,
ts)`` WAL record — a failed-over master reproduces pending quarantines
and in-flight probations exactly once instead of re-shrinking a world
that already shrank. The goodput ledger books each action as a
persistent ``remediation:<kind>`` incident with detect/act/recover
stamps so the credit for acting is measurable per node.
"""

import time
from typing import Any, Callable, Dict, List, Optional

from dlrover_tpu.chaos.injector import fault_hit
from dlrover_tpu.chaos.sites import ChaosSite
from dlrover_tpu.common import env_utils
from dlrover_tpu.common.constants import RendezvousName
from dlrover_tpu.common.lockdep import instrumented_lock
from dlrover_tpu.common.log import logger
from dlrover_tpu.observability.events import EventKind, emit

STATE_SUSPECT = "suspect"
STATE_QUARANTINED = "quarantined"
STATE_PROBATION = "probation"
STATE_EVICTED = "evicted"

#: States that keep a node out of the training rendezvous (the
#: servicer's join gate): quarantined nodes park until probation,
#: evicted nodes park forever.
_GATED_STATES = (STATE_QUARANTINED, STATE_EVICTED)


def _new_record(kind: str, now: float, detect_ts: float,
                since_ts: float) -> Dict[str, Any]:
    return {
        "state": STATE_SUSPECT,
        "kind": kind,
        "streak": 1,
        "since_ts": float(since_ts),
        "detect_ts": float(detect_ts),
        "act_ts": 0.0,
        "plan_id": -1,
        "fails": 0,
        "backoff_until": 0.0,
        "probation_until": 0.0,
        "evidence": "",
        "first_seen_ts": float(now),
    }


class RemediationPolicy:
    #: dtlint DT009: the per-node state table and the action rate
    #: limiter move as one unit under the policy lock; the counters are
    #: exporter bookkeeping folded in the same critical sections.
    GUARDED_BY = {
        "_nodes": "master.remediation",
        "_last_action_ts": "master.remediation",
        "_actions": "master.remediation",
    }

    """Tick-driven state machine turning straggler verdicts into
    journaled quarantine / regrow / evict actions.

    Wiring: the master's node-monitor loop calls :meth:`tick` right
    after ``StragglerDetector.tick`` (the policy polls the detector's
    verdict table — no callback plumbing, so the two evolve
    independently); the servicer's ``_join_rendezvous`` asks
    :meth:`gated` before admitting a node to the training rendezvous;
    ``JobMaster._apply_evict`` calls :meth:`on_node_evicted` so an
    eviction from any path clears (or confirms) the node's record.
    """

    def __init__(
        self,
        straggler_detector=None,
        rdzv_managers: Optional[Dict[str, Any]] = None,
        rescale_coordinator=None,
        task_manager=None,
        shard_lease=None,
        speed_monitor=None,
        state_store=None,
        mutation_locks=None,
        evict_cb: Optional[Callable[[int, str], None]] = None,
    ):
        self._lock = instrumented_lock("master.remediation")
        self._detector = straggler_detector
        self._rdzv_managers = rdzv_managers or {}
        self._rescale = rescale_coordinator
        self._task_manager = task_manager
        self._shard_lease = shard_lease
        self._speed_monitor = speed_monitor
        self._store = state_store
        self._mutation_locks = mutation_locks
        self._evict_cb = evict_cb
        # node_rank -> record (see _new_record)
        self._nodes: Dict[int, Dict[str, Any]] = {}
        self._last_action_ts = 0.0
        # action name -> count, for the exporter counter.
        self._actions: Dict[str, int] = {}

    # ---------------- journal plumbing ----------------
    @property
    def _replaying(self) -> bool:
        return self._store is not None and self._store.replaying

    def _journal(self, payload: Dict[str, Any]):
        if self._store is not None and not self._store.replaying:
            self._store.append(("remediate", payload, time.time()))

    # ---------------- queries ----------------
    def gated(self, node_rank: int) -> bool:
        """True while the node must stay out of the training rendezvous
        (quarantined or permanently evicted). The servicer's join gate:
        without it a quarantined node's agent — alive on purpose — would
        rejoin and instantly regrow the world the policy just shrank."""
        with self._lock:
            rec = self._nodes.get(int(node_rank))
            return rec is not None and rec["state"] in _GATED_STATES

    def state(self, node_rank: int) -> Optional[str]:
        with self._lock:
            rec = self._nodes.get(int(node_rank))
            return rec["state"] if rec is not None else None

    def node_state(self, node_rank: int) -> Optional[Dict[str, Any]]:
        with self._lock:
            rec = self._nodes.get(int(node_rank))
            return dict(rec) if rec is not None else None

    def states(self) -> Dict[int, str]:
        with self._lock:
            return {n: rec["state"] for n, rec in self._nodes.items()}

    def last_action_ts(self) -> float:
        """When this policy (or any peer via :meth:`note_fleet_action`)
        last moved the world — the fleet-wide cooldown stamp the brain
        policy shares so the two never act inside each other's window."""
        with self._lock:
            return self._last_action_ts

    def note_fleet_action(self, ts: float):
        """A peer policy (the brain) moved the world: arm this policy's
        cooldown too, so remediation holds for its own
        ``REMEDIATION_COOLDOWN_S`` after a brain grow/shrink exactly as
        it would after its own quarantine."""
        with self._lock:
            self._last_action_ts = max(self._last_action_ts, float(ts))

    def acting(self) -> bool:
        """True while a remediation is in flight (a node quarantined or
        on probation): the brain defers wholesale rather than judging
        marginal goodput of a world mid-remediation."""
        with self._lock:
            return any(
                rec["state"] in (STATE_QUARANTINED, STATE_PROBATION)
                for rec in self._nodes.values()
            )

    # ---------------- lifecycle hooks ----------------
    def on_node_evicted(self, node_rank: int):
        """An eviction landed through any path (heartbeat timeout, agent
        failure report replay, or this policy's own evict action): drop
        the node's record unless the policy itself marked it EVICTED —
        a node evicted for unrelated reasons may legitimately come back
        and rejoin, so it must not stay gated. Replay-pure (reached from
        the journaled ``("evict", ...)`` record)."""
        with self._lock:
            rec = self._nodes.get(int(node_rank))
            if rec is not None and rec["state"] != STATE_EVICTED:
                del self._nodes[int(node_rank)]

    # ---------------- the tick ----------------
    def tick(self, now: Optional[float] = None):
        """One policy pass (master node-monitor loop, right after the
        detector tick). Folds the detector's verdict table into the
        state table, settles in-flight plans, and fires at most one
        action per tick — collect under the lock, act outside it."""
        if self._replaying or not env_utils.REMEDIATION.get():
            return
        now = now if now is not None else time.time()
        flagged = self._straggler_details()
        quarantine: Optional[tuple] = None
        evict: Optional[tuple] = None
        fails: List[tuple] = []
        probations: List[tuple] = []
        clears: List[tuple] = []
        plan_polls: List[tuple] = []
        with self._lock:
            for wid, info in flagged.items():
                rec = self._nodes.get(wid)
                if rec is None:
                    self._nodes[wid] = _new_record(
                        info["kind"], now,
                        info.get("detect_ts") or now,
                        info.get("since_ts") or now,
                    )
                elif rec["state"] == STATE_SUSPECT:
                    rec["streak"] += 1
                    rec["kind"] = info["kind"]
                elif rec["state"] == STATE_PROBATION:
                    # The verdict came back while on probation: failed.
                    rec["fails"] += 1
                    rec["kind"] = info["kind"]
                    if rec["fails"] >= env_utils.REMEDIATION_PROBATION_FAILS.get():
                        evict = (wid, rec["kind"], rec["fails"])
                    else:
                        backoff = (
                            env_utils.REMEDIATION_BACKOFF_S.get()
                            * (2 ** (rec["fails"] - 1))
                        )
                        rec["state"] = STATE_SUSPECT
                        # Re-arm fully sustained: after the backoff the
                        # next eligible tick may re-quarantine at once.
                        rec["streak"] = env_utils.REMEDIATION_SUSTAIN_TICKS.get()
                        rec["backoff_until"] = now + backoff
                        fails.append((wid, rec["kind"], rec["fails"],
                                      rec["backoff_until"]))
            for wid in sorted(self._nodes):
                rec = self._nodes[wid]
                if wid in flagged:
                    pass
                elif rec["state"] == STATE_SUSPECT:
                    # Recovered before any action: hysteresis absorbed
                    # the flap. Nothing was acted, nothing to journal.
                    del self._nodes[wid]
                    continue
                elif rec["state"] == STATE_QUARANTINED and rec["plan_id"] < 0:
                    # Probes recovered while parked: start probation.
                    until = now + env_utils.REMEDIATION_PROBATION_S.get()
                    rec["state"] = STATE_PROBATION
                    rec["probation_until"] = until
                    probations.append((wid, rec["kind"], until))
                    continue
                elif (
                    rec["state"] == STATE_PROBATION
                    and now >= rec["probation_until"]
                ):
                    clears.append((wid, rec["kind"]))
                    del self._nodes[wid]
                    continue
                if rec["state"] == STATE_QUARANTINED and rec["plan_id"] >= 0:
                    plan_polls.append((wid, rec["plan_id"]))
            if evict is None:
                quarantine = self._pick_quarantine(now)
        for wid, plan_id in plan_polls:
            self._settle_plan(wid, plan_id, now)
        for wid, kind, n_fails, until in fails:
            self._journal({
                "rec": "fail", "node": wid, "kind": kind,
                "fails": n_fails, "backoff_until": until,
            })
            logger.warning(
                "remediation: node %s failed probation (%s returned, "
                "fail %d); re-suspect with backoff until %.0f",
                wid, kind, n_fails, until,
            )
            emit(
                EventKind.REMEDIATION_REVERT, _node_id=wid, _role="master",
                kind=kind, reason="probation-failed", fails=n_fails,
                backoff_until=until,
            )
            self._count("probation_fail")
        for wid, kind, until in probations:
            self._journal({
                "rec": "probation", "node": wid, "kind": kind,
                "until": until,
            })
            logger.info(
                "remediation: node %s probes recovered; probation until "
                "%.0f — join gate lifted, regrow rides the join path",
                wid, until,
            )
            emit(
                EventKind.REMEDIATION_PROBATION, _node_id=wid,
                _role="master", kind=kind, until=until,
            )
            self._count("probation")
        for wid, kind in clears:
            self._journal({"rec": "clear", "node": wid})
            logger.info(
                "remediation: node %s finished probation clean; healthy",
                wid,
            )
            emit(
                EventKind.REMEDIATION_CLEAR, _node_id=wid, _role="master",
                kind=kind,
            )
            self._count("clear")
        if evict is not None:
            self._do_evict(*evict)
        elif quarantine is not None:
            self._do_quarantine(*quarantine, now=now)

    def _straggler_details(self) -> Dict[int, Dict[str, Any]]:
        if self._detector is None:
            return {}
        details = getattr(self._detector, "straggler_details", None)
        if details is not None:
            return details()
        return {
            wid: {"kind": kind}
            for wid, kind in self._detector.stragglers().items()
        }

    # ---------------- quarantine ----------------
    def _pick_quarantine(self, now: float) -> Optional[tuple]:  # dtlint: holds(master.remediation)
        """Lowest eligible SUSPECT rank, or None. Lock held. The rails:
        policy hysteresis (sustain ticks), per-node backoff, the global
        action cooldown, and the concurrent-remediations cap. World
        size / quorum are checked at act time (outside the lock)."""
        if now - self._last_action_ts < env_utils.REMEDIATION_COOLDOWN_S.get():
            return None
        active = sum(
            1 for rec in self._nodes.values()
            if rec["state"] in (STATE_QUARANTINED, STATE_PROBATION)
        )
        if active >= env_utils.REMEDIATION_MAX_CONCURRENT.get():
            return None
        sustain = env_utils.REMEDIATION_SUSTAIN_TICKS.get()
        for wid in sorted(self._nodes):
            rec = self._nodes[wid]
            if (
                rec["state"] == STATE_SUSPECT
                and rec["streak"] >= sustain
                and now >= rec["backoff_until"]
            ):
                return (wid, rec["kind"], rec["detect_ts"], rec["since_ts"])
        return None

    def _do_quarantine(self, wid: int, kind: str, detect_ts: float,
                       since_ts: float, now: float):
        """The action: drop the node from the rendezvous and hand the
        survivors an in-place shrink plan. Pre-flighted — the world is
        only touched when the coordinator confirms it would plan —
        because an issued-then-declined shrink forces the full-restart
        fallback this policy exists to avoid."""
        training = self._rdzv_managers.get(RendezvousName.TRAINING)
        old_world = training.current_world() if training is not None else {}
        if wid not in old_world:
            # Not in the active world (mid-restart, already gone):
            # nothing to shrink; the record stays SUSPECT and the
            # verdict re-evaluates next tick.
            return
        floor = env_utils.REMEDIATION_MIN_WORLD.get()
        if len(old_world) - 1 < floor:
            logger.warning(
                "remediation: node %s is a sustained %s straggler but "
                "shrinking %d -> %d would breach the min-world floor "
                "(%d); holding", wid, kind, len(old_world),
                len(old_world) - 1, floor,
            )
            return
        if self._rescale is not None:
            ok, why = self._rescale.can_plan_shrink(wid, old_world)
            if not ok:
                logger.warning(
                    "remediation: shrink for node %s not plannable (%s); "
                    "holding in SUSPECT", wid, why,
                )
                return
        chaos = fault_hit(ChaosSite.REMEDIATION_ACT, detail=f"node{wid}")
        if chaos is not None:
            if chaos.kind == "delay":
                time.sleep(chaos.delay_s)
            elif chaos.kind in ("deny", "drop"):
                logger.warning(
                    "remediation: chaos denied the quarantine action "
                    "for node %s this tick", wid,
                )
                return
        plan = None
        locks = self._mutation_locks
        if locks is not None:
            # Same span as _evict_node: the apply mutates tasks, leases,
            # rendezvous and the rescale plane, so it serializes against
            # concurrent RPC mutations in journal order.
            with locks.all():
                plan = self._apply_quarantine(wid, old_world)
        else:
            plan = self._apply_quarantine(wid, old_world)
        if plan is None:
            # The coordinator declined after the pre-flight (raced
            # config change): the world already shrank, the stale-round
            # restart fallback is in charge, and the node reverts to
            # SUSPECT with backoff so the fleet reforms with it.
            self._revert(wid, kind, now, reason="plan-declined")
            return
        with self._lock:
            rec = self._nodes.get(wid)
            if rec is None:
                return
            rec["state"] = STATE_QUARANTINED
            rec["plan_id"] = plan.plan_id
            rec["act_ts"] = now
            self._last_action_ts = now
        self._journal({
            "rec": "quarantine", "node": wid, "kind": kind,
            "plan_id": plan.plan_id, "detect_ts": detect_ts,
            "since_ts": since_ts, "act_ts": now,
        })
        logger.warning(
            "remediation: quarantined sustained %s straggler node %s "
            "(plan %s, world %s -> %s); parked pending probe recovery",
            kind, wid, plan.plan_id, sorted(old_world),
            sorted(plan.new_world),
        )
        emit(
            EventKind.REMEDIATION_QUARANTINE, _node_id=wid, _role="master",
            kind=kind, plan_id=plan.plan_id, detect_ts=detect_ts,
            since_ts=since_ts, old_world=sorted(old_world),
            new_world=sorted(plan.new_world),
        )
        self._count("quarantine")

    def _apply_quarantine(self, wid: int, old_world: Dict[int, int]):
        """Drop the node everywhere the eviction path does — except the
        node registry and the straggler profiles: the agent stays alive
        (still heartbeats, still probes) and the detector must keep the
        frozen-baseline profile to see the recovery."""
        for mgr in self._rdzv_managers.values():
            mgr.remove_alive_node(wid)
        if self._task_manager is not None:
            self._task_manager.recover_worker_tasks(wid)
        if self._shard_lease is not None:
            # Leased shards re-entered todo just now; drop the lease
            # bookkeeping so expiry cannot requeue them twice.
            self._shard_lease.drop_agent(wid)
        if self._speed_monitor is not None:
            self._speed_monitor.remove_worker(wid)
        if self._rescale is None:
            return None
        return self._rescale.on_node_removed(wid, old_world)

    # ---------------- plan settlement ----------------
    def _settle_plan(self, wid: int, plan_id: int, now: float):
        """Poll the in-flight shrink plan: complete confirms the
        quarantine (the node waits parked for probe recovery); aborted
        — a survivor nacked or the apply timed out — reverts the node
        to SUSPECT with backoff. Idempotent by construction: a failed-
        over master that lost the revert record re-derives it from the
        replayed plan state on its first tick."""
        if self._rescale is None:
            return
        status = self._rescale.plan_status(plan_id)
        if status == "complete":
            with self._lock:
                rec = self._nodes.get(wid)
                if rec is not None and rec["plan_id"] == plan_id:
                    rec["plan_id"] = -1
        elif status == "aborted" or status is None:
            kind = ""
            with self._lock:
                rec = self._nodes.get(wid)
                if rec is None or rec["plan_id"] != plan_id:
                    return
                kind = rec["kind"]
            self._revert(wid, kind, now, reason=f"plan-{plan_id}-aborted")

    def _revert(self, wid: int, kind: str, now: float, reason: str):
        """Nacked/declined action -> SUSPECT with exponential backoff.
        Never a crash, never a stuck state: the join gate lifts (the
        node may reform with the restarting fleet) and the verdict gets
        another shot only after the backoff."""
        with self._lock:
            rec = self._nodes.get(wid)
            if rec is None:
                return
            rec["fails"] = rec.get("fails", 0)
            backoff = (
                env_utils.REMEDIATION_BACKOFF_S.get()
                * (2 ** min(rec["fails"], 4))
            )
            rec["state"] = STATE_SUSPECT
            rec["plan_id"] = -1
            rec["streak"] = 0
            rec["backoff_until"] = now + backoff
            until = rec["backoff_until"]
        self._journal({
            "rec": "revert", "node": wid, "kind": kind,
            "reason": reason, "backoff_until": until,
        })
        logger.warning(
            "remediation: quarantine of node %s reverted (%s); SUSPECT "
            "with backoff until %.0f", wid, reason, until,
        )
        emit(
            EventKind.REMEDIATION_REVERT, _node_id=wid, _role="master",
            kind=kind, reason=reason, backoff_until=until,
        )
        self._count("revert")

    # ---------------- permanent eviction ----------------
    def _do_evict(self, wid: int, kind: str, n_fails: int):
        """Second probation failure: the node is chronically bad —
        evict permanently through the node-manager path (the journaled
        ``("evict", ...)`` record). The eviction drops our record
        (:meth:`on_node_evicted`); the ``evicted`` record recreates it
        as EVICTED so the join gate outlives the node registry."""
        reason = f"remediation:{kind} (failed probation x{n_fails})"
        if self._evict_cb is not None:
            try:
                self._evict_cb(wid, reason)
            except Exception as e:
                logger.exception(
                    "remediation: eviction of node %s failed", wid
                )
                emit(
                    EventKind.REMEDIATION_FAILED, _node_id=wid,
                    _role="master", action="evict", kind=kind,
                    error=f"{type(e).__name__}: {e}",
                )
                self._count("evict_failed")
                # Not evicted: fall back to another quarantine round
                # rather than a stuck EVICTED-but-present state.
                with self._lock:
                    rec = self._nodes.get(wid)
                    if rec is not None:
                        rec["state"] = STATE_SUSPECT
                        rec["streak"] = 0
                return
        with self._lock:
            rec = self._nodes.get(wid)
            if rec is None:
                rec = self._nodes[wid] = _new_record(
                    kind, 0.0, 0.0, 0.0
                )
            rec["state"] = STATE_EVICTED
            rec["kind"] = kind
            rec["fails"] = n_fails
        self._journal({
            "rec": "evicted", "node": wid, "kind": kind, "fails": n_fails,
        })
        logger.error(
            "remediation: node %s permanently evicted after %d failed "
            "probations (%s)", wid, n_fails, kind,
        )
        emit(
            EventKind.REMEDIATION_EVICT, _node_id=wid, _role="master",
            kind=kind, fails=n_fails,
        )
        self._count("evict")

    def _count(self, action: str):
        with self._lock:
            self._actions[action] = self._actions.get(action, 0) + 1

    # ---------------- durability ----------------
    def checkpoint(self) -> dict:
        with self._lock:
            return {
                "nodes": {
                    str(wid): dict(rec)
                    for wid, rec in self._nodes.items()
                },
                "last_action_ts": self._last_action_ts,
                "actions": dict(self._actions),
            }

    def restore(self, state: dict):
        if not state:
            return
        with self._lock:
            for wid, rec in state.get("nodes", {}).items():
                self._nodes[int(wid)] = dict(rec)
            self._last_action_ts = max(
                self._last_action_ts,
                float(state.get("last_action_ts", 0.0)),
            )
            for action, n in state.get("actions", {}).items():
                self._actions[action] = max(
                    self._actions.get(action, 0), int(n)
                )

    def replay(self, payload: Dict[str, Any]):
        """Re-apply one journaled ``("remediate", payload, ts)`` record.

        Pure bookkeeping — no emits, no rendezvous or rescale side
        effects (those subsystems replay from their own records): only
        the policy's state table moves, so a failed-over master holds
        exactly the pending quarantines/probations it held before.
        """
        rec = payload.get("rec")
        wid = int(payload.get("node", -1))
        with self._lock:
            if rec == "quarantine":
                node = self._nodes.setdefault(
                    wid, _new_record(payload.get("kind", ""), 0.0, 0.0, 0.0)
                )
                node["state"] = STATE_QUARANTINED
                node["kind"] = payload.get("kind", node["kind"])
                node["plan_id"] = int(payload.get("plan_id", -1))
                node["detect_ts"] = float(payload.get("detect_ts", 0.0))
                node["since_ts"] = float(payload.get("since_ts", 0.0))
                node["act_ts"] = float(payload.get("act_ts", 0.0))
                self._last_action_ts = max(
                    self._last_action_ts, node["act_ts"]
                )
            elif rec == "revert":
                node = self._nodes.get(wid)
                if node is not None:
                    node["state"] = STATE_SUSPECT
                    node["plan_id"] = -1
                    node["streak"] = 0
                    node["backoff_until"] = float(
                        payload.get("backoff_until", 0.0)
                    )
            elif rec == "probation":
                node = self._nodes.get(wid)
                if node is not None:
                    node["state"] = STATE_PROBATION
                    node["plan_id"] = -1
                    node["probation_until"] = float(
                        payload.get("until", 0.0)
                    )
            elif rec == "fail":
                node = self._nodes.get(wid)
                if node is not None:
                    node["state"] = STATE_SUSPECT
                    node["fails"] = int(payload.get("fails", 0))
                    node["streak"] = 0
                    node["backoff_until"] = float(
                        payload.get("backoff_until", 0.0)
                    )
            elif rec == "clear":
                self._nodes.pop(wid, None)
            elif rec == "evicted":
                node = self._nodes.setdefault(
                    wid, _new_record(payload.get("kind", ""), 0.0, 0.0, 0.0)
                )
                node["state"] = STATE_EVICTED
                node["kind"] = payload.get("kind", node["kind"])
                node["fails"] = int(payload.get("fails", 0))
            else:
                logger.warning("skipping unknown remediate record %r", rec)

    # ---------------- outputs ----------------
    def metrics(self) -> List:
        """Exporter gauges (appended by the ObservabilityPlane)."""
        with self._lock:
            by_state_kind: Dict[tuple, int] = {}
            for rec in self._nodes.values():
                key = (rec["state"], rec["kind"] or "unknown")
                by_state_kind[key] = by_state_kind.get(key, 0) + 1
            actions = dict(self._actions)
        return [
            (
                "dlrover_tpu_remediation", "gauge",
                "Nodes per remediation-policy state and straggler kind.",
                [({"state": s, "kind": k}, float(v))
                 for (s, k), v in sorted(by_state_kind.items())]
                or [(None, 0.0)],
            ),
            (
                "dlrover_tpu_remediation_actions_total", "counter",
                "Remediation actions taken since master start.",
                [({"action": a}, float(v))
                 for a, v in sorted(actions.items())] or [(None, 0.0)],
            ),
        ]
