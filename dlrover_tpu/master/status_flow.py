"""Legal node status transitions + relaunch decisions.

Parity: reference ``master/node/status_flow.py`` — a transition table from
(from_status, to_status, exit_reason) to whether the node should be
relaunched.
"""

from dataclasses import dataclass

from dlrover_tpu.common.constants import NodeExitReason, NodeStatus


@dataclass
class NodeStateFlow:
    from_status: str
    to_status: str
    should_relaunch: bool


_FLOWS = [
    NodeStateFlow(NodeStatus.INITIAL, NodeStatus.PENDING, False),
    NodeStateFlow(NodeStatus.INITIAL, NodeStatus.RUNNING, False),
    NodeStateFlow(NodeStatus.PENDING, NodeStatus.RUNNING, False),
    NodeStateFlow(NodeStatus.PENDING, NodeStatus.SUCCEEDED, False),
    NodeStateFlow(NodeStatus.PENDING, NodeStatus.FAILED, True),
    NodeStateFlow(NodeStatus.PENDING, NodeStatus.DELETED, True),
    NodeStateFlow(NodeStatus.RUNNING, NodeStatus.SUCCEEDED, False),
    NodeStateFlow(NodeStatus.RUNNING, NodeStatus.FAILED, True),
    NodeStateFlow(NodeStatus.RUNNING, NodeStatus.DELETED, True),
    NodeStateFlow(NodeStatus.SUCCEEDED, NodeStatus.DELETED, False),
    NodeStateFlow(NodeStatus.FAILED, NodeStatus.DELETED, False),
]


def get_node_state_flow(from_status: str, to_status: str) -> NodeStateFlow:
    if from_status == to_status:
        return NodeStateFlow(from_status, to_status, False)
    for flow in _FLOWS:
        if flow.from_status == from_status and flow.to_status == to_status:
            return flow
    # Unknown transition: allow it, do not relaunch.
    return NodeStateFlow(from_status, to_status, False)


def should_relaunch(node, flow: NodeStateFlow, relaunch_on_worker_failure: int = 3):
    """Refine the table decision with node-level facts."""
    decision = flow.should_relaunch
    if not decision:
        return False
    if not node.relaunchable:
        return False
    if node.exit_reason == NodeExitReason.SUCCEEDED:
        return False
    if node.exit_reason == NodeExitReason.FATAL_ERROR:
        return False
    if node.exit_reason == NodeExitReason.PREEMPTED:
        # Planned departure announced by the preemption plane; the
        # survivors already transitioned in place — relaunching the
        # victim would fight the shrink plan it was removed by.
        return False
    if node.relaunch_count >= min(node.max_relaunch_count, relaunch_on_worker_failure):
        return False
    return True
