"""Dataset splitters for dynamic data sharding.

Parity: reference ``master/shard/dataset_splitter.py`` — a ``Shard`` is a
record range [start, end) (optionally with explicit per-sample indices); a
splitter produces the shards of each epoch, supports shuffling, and is
checkpointable so a restarted job resumes mid-epoch.
"""

import json
import random
from dataclasses import dataclass, field
from typing import List, Optional

from dlrover_tpu.common.log import logger


@dataclass
class Shard:
    name: str
    start: int
    end: int
    record_indices: Optional[List[int]] = None

    @property
    def size(self) -> int:
        return self.end - self.start


class DatasetSplitter:
    def __init__(self, dataset_name: str, dataset_size: int, shard_size: int,
                 num_epochs: int = 1):
        self.dataset_name = dataset_name
        self.dataset_size = dataset_size
        self.shard_size = shard_size
        self.num_epochs = num_epochs
        self.epoch = 0

    def create_shards(self) -> List[Shard]:
        raise NotImplementedError

    def epoch_finished(self) -> bool:
        return self.epoch >= self.num_epochs

    def checkpoint(self) -> dict:
        return {
            "dataset_name": self.dataset_name,
            "dataset_size": self.dataset_size,
            "shard_size": self.shard_size,
            "num_epochs": self.num_epochs,
            "epoch": self.epoch,
        }

    def restore(self, state: dict):
        self.epoch = state.get("epoch", 0)


class TableDatasetSplitter(DatasetSplitter):
    """Range shards over a table-like dataset (row ranges)."""

    def __init__(self, dataset_name: str, dataset_size: int, shard_size: int,
                 num_epochs: int = 1, shuffle: bool = False):
        super().__init__(dataset_name, dataset_size, shard_size, num_epochs)
        self.shuffle = shuffle

    def create_shards(self) -> List[Shard]:
        shards = []
        num = (self.dataset_size + self.shard_size - 1) // self.shard_size
        for i in range(num):
            start = i * self.shard_size
            end = min(start + self.shard_size, self.dataset_size)
            shards.append(
                Shard(name=f"{self.dataset_name}-e{self.epoch}-s{i}",
                      start=start, end=end)
            )
        if self.shuffle:
            random.shuffle(shards)
        self.epoch += 1
        logger.info(
            "dataset %s: epoch %s -> %s shards", self.dataset_name, self.epoch, num
        )
        return shards


class TextDatasetSplitter(DatasetSplitter):
    """Shards carrying explicit (optionally shuffled) sample indices."""

    def __init__(self, dataset_name: str, dataset_size: int, shard_size: int,
                 num_epochs: int = 1, shuffle: bool = False):
        super().__init__(dataset_name, dataset_size, shard_size, num_epochs)
        self.shuffle = shuffle

    def create_shards(self) -> List[Shard]:
        indices = list(range(self.dataset_size))
        if self.shuffle:
            random.shuffle(indices)
        shards = []
        for i in range(0, self.dataset_size, self.shard_size):
            chunk = indices[i : i + self.shard_size]
            shards.append(
                Shard(
                    name=f"{self.dataset_name}-e{self.epoch}-s{i // self.shard_size}",
                    start=i,
                    end=i + len(chunk),
                    record_indices=chunk,
                )
            )
        self.epoch += 1
        return shards


class StreamingDatasetSplitter(DatasetSplitter):
    """Open-ended stream: shards are generated as offsets advance.

    Parity: reference ``dataset_splitter.py:359`` — dataset_size < 0 means
    unbounded; the splitter hands out fixed-size ranges from a moving
    offset and checkpoints the offset.
    """

    def __init__(self, dataset_name: str, shard_size: int,
                 dataset_size: int = -1, fetch_batch: int = 16):
        super().__init__(dataset_name, dataset_size, shard_size, num_epochs=1)
        self._offset = 0
        self._fetch_batch = fetch_batch

    def create_shards(self) -> List[Shard]:
        shards = []
        for _ in range(self._fetch_batch):
            if 0 <= self.dataset_size <= self._offset:
                break
            end = self._offset + self.shard_size
            if self.dataset_size >= 0:
                end = min(end, self.dataset_size)
            shards.append(
                Shard(
                    name=f"{self.dataset_name}-o{self._offset}",
                    start=self._offset,
                    end=end,
                )
            )
            self._offset = end
        if 0 <= self.dataset_size <= self._offset:
            self.epoch = 1  # exhausted
        return shards

    def checkpoint(self) -> dict:
        state = super().checkpoint()
        state["offset"] = self._offset
        return state

    def restore(self, state: dict):
        super().restore(state)
        self._offset = state.get("offset", 0)


def create_dataset_splitter(
    dataset_name: str,
    dataset_size: int,
    shard_size: int,
    num_epochs: int = 1,
    shuffle: bool = False,
    storage_type: str = "table",
) -> DatasetSplitter:
    if storage_type == "text":
        return TextDatasetSplitter(
            dataset_name, dataset_size, shard_size, num_epochs, shuffle
        )
    if storage_type == "stream":
        return StreamingDatasetSplitter(dataset_name, shard_size, dataset_size)
    return TableDatasetSplitter(
        dataset_name, dataset_size, shard_size, num_epochs, shuffle
    )
