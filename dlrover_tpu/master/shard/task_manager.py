"""Dispatch dataset shards as tasks; recover tasks of failed workers.

Parity: reference ``master/shard/task_manager.py`` + ``batch_dataset_manager.py``
— todo/doing bookkeeping per dataset, worker-failure task recovery
(``task_manager.py:165``), epoch advancement, and shard checkpoints so a
restarted master resumes mid-epoch.
"""

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, Optional, Tuple

from dlrover_tpu.common import env_utils
from dlrover_tpu.common.lockdep import instrumented_lock
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.messages import ShardTask
from dlrover_tpu.master.shard.splitter import (
    DatasetSplitter,
    Shard,
    create_dataset_splitter,
)


@dataclass
class DoingTask:
    task: ShardTask
    worker_id: int
    start_time: float


class DatasetManager:
    """Todo/doing queues for one dataset."""

    #: dtlint DT009: a DatasetManager has no lock of its own — every
    #: method runs inside the owning TaskManager's critical section
    #: (hence the holds() marker on each def). The queues are exactly
    #: the shard state the PR-11 store->task_manager inversion raced on.
    GUARDED_BY = {
        "todo": "master.task_manager",
        "doing": "master.task_manager",
    }

    # A shard held in `doing` longer than this is presumed abandoned (its
    # worker hung or exited without acking) and is returned to `todo` —
    # the liveness fallback behind the clients' block-until-finished
    # fetch. Worker *failures* are recovered immediately via
    # recover_worker_tasks; this only catches silent abandonment.
    DOING_TASK_TIMEOUT = 300.0

    def __init__(self, splitter: DatasetSplitter,
                 doing_timeout: Optional[float] = None):
        self.splitter = splitter
        self.todo: Deque[ShardTask] = deque()
        self.doing: Dict[int, DoingTask] = {}
        self.doing_timeout = (
            doing_timeout if doing_timeout is not None
            else self.DOING_TASK_TIMEOUT
        )
        self._task_id = 0
        self._completed_tasks = 0
        # WAL hook (MasterStateStore.append). Shard *creation* and
        # timeout *reclaims* mutate the queues outside any RPC record —
        # without journaling them a replayed master would re-split with
        # a different shuffle (double-dispatch) or resurrect reclaimed
        # doing entries (lost shards).
        self.journal = None

    def _requeue(self, task: ShardTask):  # dtlint: holds(master.task_manager)
        """Re-dispatch under a FRESH task id: a late ack from the
        original holder must not pop the new dispatchee's doing entry
        (it finds no matching id and is ignored)."""
        self.todo.appendleft(self._new_task(Shard(
            name=task.shard_name, start=task.start, end=task.end,
            record_indices=task.record_indices,
        )))

    def _reclaim_stale(self):  # dtlint: holds(master.task_manager)
        now = time.time()
        stale = [
            tid for tid, d in self.doing.items()
            if now - d.start_time > self.doing_timeout
        ]
        if stale and self.journal is not None:
            self.journal(
                ("reclaim", self.splitter.dataset_name, list(stale),
                 time.time())
            )
        for tid in stale:
            doing = self.doing.pop(tid)
            logger.warning(
                "shard task %s of worker %s timed out after %.0fs; "
                "re-dispatching", tid, doing.worker_id, self.doing_timeout,
            )
            self._requeue(doing.task)

    def _refill(self):  # dtlint: holds(master.task_manager)
        self._reclaim_stale()
        if self.todo or self.splitter.epoch_finished():
            return
        pre_split = self.splitter.checkpoint()
        first_id = self._task_id
        created = []
        for shard in self.splitter.create_shards():
            task = self._new_task(shard)
            self.todo.append(task)
            created.append(task)
        if created and self.journal is not None:
            if getattr(self.splitter, "shuffle", False):
                # Shuffling splitters draw from the global RNG, so a
                # replay cannot re-split identically — journal the exact
                # ranges and the splitter cursor AFTER the split.
                self.journal(
                    ("shards", self.splitter.dataset_name, {
                        "splitter": self.splitter.checkpoint(),
                        "tasks": [self._task_dict(t) for t in created],
                    }, time.time())
                )
            else:
                # Deterministic splitters re-split identically from the
                # pre-split cursor, so an O(1) record replaces the
                # per-shard range list — at lease-plane rates an epoch
                # is hundreds of thousands of shards, and the exact
                # record would dominate the journal.
                self.journal(
                    ("shards", self.splitter.dataset_name, {
                        "resplit": pre_split,
                        "first_task_id": first_id,
                        "count": len(created),
                    }, time.time())
                )

    @staticmethod
    def _task_dict(task: ShardTask) -> dict:
        return {
            "task_id": task.task_id,
            "shard_name": task.shard_name,
            "start": task.start,
            "end": task.end,
            "record_indices": task.record_indices,
        }

    @staticmethod
    def _task_from_dict(d: dict, dataset_name: str) -> ShardTask:
        return ShardTask(
            task_id=d["task_id"],
            dataset_name=dataset_name,
            shard_name=d.get("shard_name", ""),
            start=d["start"],
            end=d["end"],
            record_indices=d.get("record_indices"),
        )

    def _new_task(self, shard: Shard) -> ShardTask:  # dtlint: holds(master.task_manager)
        task = ShardTask(
            task_id=self._task_id,
            dataset_name=self.splitter.dataset_name,
            shard_name=shard.name,
            start=shard.start,
            end=shard.end,
            record_indices=shard.record_indices,
        )
        self._task_id += 1
        return task

    def get_task(self, worker_id: int) -> ShardTask:  # dtlint: holds(master.task_manager)
        self._refill()
        if not self.todo:
            # Distinguish "done" from "empty for now": while shards are in
            # `doing`, a failed worker's shards may yet be re-dispatched,
            # so clients must keep polling rather than end the epoch.
            return ShardTask(finished=self.completed())
        task = self.todo.popleft()
        self.doing[task.task_id] = DoingTask(task, worker_id, time.time())
        return task

    def report_task(self, task_id: int, success: bool) -> bool:  # dtlint: holds(master.task_manager)
        doing = self.doing.pop(task_id, None)
        if doing is None:
            return False
        if success:
            self._completed_tasks += 1
        else:
            self._requeue(doing.task)
        return True

    # ------------- bulk lease plumbing -------------
    def get_tasks(self, worker_id: int, n: int):  # dtlint: holds(master.task_manager)
        """Bulk get_task: up to `n` shards in one critical section.
        Returns (tasks, finished) — finished only meaningful when the
        answer came up short."""
        tasks = []
        finished = False
        # One stale sweep per LEASE, then straight deque pops: the
        # per-call path's sweep-per-get is O(doing) and at data-plane
        # rates (thousands of leased shards in `doing`) turns a bulk
        # grant quadratic — 100x slower than the pops themselves.
        self._refill()
        now = time.time()
        while len(tasks) < n:
            if not self.todo:
                self._refill()
                if not self.todo:
                    finished = self.completed()
                    break
            task = self.todo.popleft()
            self.doing[task.task_id] = DoingTask(task, worker_id, now)
            tasks.append(task)
        return tasks, finished

    def report_tasks(self, done_ids, failed_ids) -> int:  # dtlint: holds(master.task_manager)
        """Bulk report_task; returns how many acks landed (ids with no
        doing entry — already acked, or reclaimed and re-dispatched
        under fresh ids — are ignored, same as the per-call path)."""
        acked = 0
        for tid in done_ids:
            if self.report_task(tid, True):
                acked += 1
        for tid in failed_ids:
            self.report_task(tid, False)
        return acked

    def dispatch_exact(self, worker_id: int, task_ids):  # dtlint: holds(master.task_manager)
        """Replay a bulk grant: move exactly these ids from todo to
        doing. Ids already doing are kept (duplicated record); ids
        nowhere (acked by a later replayed report) are skipped — the
        journal suffix settles them."""
        wanted = set(task_ids)
        found = {t.task_id: t for t in self.todo if t.task_id in wanted}
        if found:
            remaining = [t for t in self.todo if t.task_id not in found]
            self.todo.clear()
            self.todo.extend(remaining)
        tasks = []
        for tid in task_ids:
            doing = self.doing.get(tid)
            if doing is not None:
                tasks.append(doing.task)
                continue
            task = found.get(tid)
            if task is None:
                continue
            self.doing[tid] = DoingTask(task, worker_id, time.time())  # dtlint: disable=DT011 -- dispatch-time liveness clock, deliberately re-stamped on replay: staleness reclaim timers are process-local, not journaled state
            self._task_id = max(self._task_id, tid + 1)
            tasks.append(task)
        return tasks

    def recover_worker_tasks(self, worker_id: int) -> int:  # dtlint: holds(master.task_manager)
        """Return a failed worker's in-flight shards to the todo queue."""
        stale = [tid for tid, d in self.doing.items() if d.worker_id == worker_id]
        for tid in stale:
            self._requeue(self.doing.pop(tid).task)
        return len(stale)

    # ------------- journal replay + fencing reclaim -------------
    def replay_shards(self, state: dict):  # dtlint: holds(master.task_manager)
        """Re-apply a journaled split: exact ranges (shuffle) or a
        deterministic re-split from the recorded pre-split cursor."""
        known = {t.task_id for t in self.todo} | set(self.doing)
        if "resplit" in state:
            first = int(state["first_task_id"])
            count = int(state["count"])
            self.splitter.restore(state["resplit"])
            self._task_id = first
            for shard in self.splitter.create_shards():
                task = self._new_task(shard)  # consumes the id even if known
                if task.task_id not in known:
                    self.todo.append(task)
            self._task_id = max(self._task_id, first + count)
            return
        self.splitter.restore(state.get("splitter", {}))
        for d in state.get("tasks", []):
            if d["task_id"] in known:
                continue
            self.todo.append(
                self._task_from_dict(d, self.splitter.dataset_name)
            )
            self._task_id = max(self._task_id, d["task_id"] + 1)

    def replay_dispatch(self, d: dict) -> Optional[ShardTask]:  # dtlint: holds(master.task_manager)
        """Re-apply a journaled get_task answer; returns the task so the
        caller can re-seed the RPC dedup cache with it."""
        tid = d["task_id"]
        self._task_id = max(self._task_id, tid + 1)
        if tid in self.doing:  # duplicated record: already applied
            return self.doing[tid].task
        task = None
        for queued in self.todo:
            if queued.task_id == tid:
                task = queued
                break
        if task is not None:
            self.todo.remove(task)
        else:
            task = self._task_from_dict(d, self.splitter.dataset_name)
        self.doing[tid] = DoingTask(task, d["worker"], time.time())  # dtlint: disable=DT011 -- dispatch-time liveness clock, deliberately re-stamped on replay: staleness reclaim timers are process-local, not journaled state
        return task

    def replay_reclaim(self, task_ids):  # dtlint: holds(master.task_manager)
        for tid in task_ids:
            doing = self.doing.pop(tid, None)
            if doing is not None:
                self._requeue(doing.task)

    def reclaim_task(self, worker_id: int, d: dict) -> bool:  # dtlint: holds(master.task_manager)
        """A fenced client re-reports a shard it still holds. Reaffirm
        the assignment if we know the task; re-install it from the
        carried range if the dispatch was lost with the old incarnation;
        refuse (False) if it was already acked or re-dispatched — the
        client must drop its copy."""
        tid = d["task_id"]
        doing = self.doing.get(tid)
        if doing is not None:
            if doing.worker_id != worker_id:
                return False  # re-dispatched to someone else
            doing.start_time = time.time()  # dtlint: disable=DT011 -- hold-time liveness clock, deliberately re-stamped: reclaim timers are process-local, not journaled state
            return True
        for queued in list(self.todo):
            if (
                queued.task_id == tid
                and queued.start == d["start"]
                and queued.end == d["end"]
            ):
                self.todo.remove(queued)
                self.doing[tid] = DoingTask(queued, worker_id, time.time())  # dtlint: disable=DT011 -- dispatch-time liveness clock, deliberately re-stamped: reclaim timers are process-local, not journaled state
                self._task_id = max(self._task_id, tid + 1)
                return True
        return False

    def completed(self) -> bool:  # dtlint: holds(master.task_manager)
        return (
            self.splitter.epoch_finished()
            and not self.todo
            and not self.doing
        )

    @property
    def epoch(self) -> int:
        return self.splitter.epoch

    def checkpoint(self) -> dict:  # dtlint: holds(master.task_manager)
        # "todo" keeps the legacy merged todo+doing list consumed by the
        # ShardCheckpoint RPC (a *client*-driven restore into a fresh
        # master, where the doing holders are unknown). The exact fields
        # alongside it serve the master's own snapshot/WAL restore,
        # which must preserve ids and assignments for idempotent replay.
        from dlrover_tpu.master.shard.splitter import (
            StreamingDatasetSplitter,
            TextDatasetSplitter,
        )

        storage_type = "table"
        if isinstance(self.splitter, TextDatasetSplitter):
            storage_type = "text"
        elif isinstance(self.splitter, StreamingDatasetSplitter):
            storage_type = "stream"
        return {
            # Enough to re-create this dataset from a snapshot alone —
            # its registration RPC lives in a journal generation the
            # recovery chain no longer replays.
            "params": {
                "dataset_size": self.splitter.dataset_size,
                "shard_size": self.splitter.shard_size,
                "num_epochs": self.splitter.num_epochs,
                "shuffle": getattr(self.splitter, "shuffle", False),
                "storage_type": storage_type,
            },
            "splitter": self.splitter.checkpoint(),
            "todo": [
                {"start": t.start, "end": t.end, "shard_name": t.shard_name}
                for t in self.todo
            ]
            + [
                {"start": d.task.start, "end": d.task.end,
                 "shard_name": d.task.shard_name}
                for d in self.doing.values()
            ],
            "todo_exact": [self._task_dict(t) for t in self.todo],
            "doing": [
                {**self._task_dict(d.task), "worker_id": d.worker_id}
                for d in self.doing.values()
            ],
            "next_task_id": self._task_id,
            "completed": self._completed_tasks,
        }

    def restore(self, state: dict, exact: bool = False):  # dtlint: holds(master.task_manager)
        self.splitter.restore(state.get("splitter", {}))
        self.todo.clear()
        self.doing.clear()
        if exact and "next_task_id" in state:
            name = self.splitter.dataset_name
            for d in state.get("todo_exact", []):
                self.todo.append(self._task_from_dict(d, name))
            for d in state.get("doing", []):
                # The holder may still be alive and riding out the
                # master outage; start_time=now gives it a full timeout
                # window before the shard is presumed abandoned.
                self.doing[d["task_id"]] = DoingTask(
                    self._task_from_dict(d, name), d["worker_id"],
                    time.time(),
                )
            self._task_id = int(state["next_task_id"])
            self._completed_tasks = int(state.get("completed", 0))
            return
        for item in state.get("todo", []):
            shard = Shard(
                name=item.get("shard_name", ""),
                start=item["start"],
                end=item["end"],
            )
            self.todo.append(self._new_task(shard))


class TaskManager:
    """All datasets of a job + the worker-failure recovery hook."""

    #: dtlint DT009: dataset registry + per-worker dispatch clocks.
    GUARDED_BY = {
        "_datasets": "master.task_manager",
        "_worker_last_task": "master.task_manager",
    }

    def __init__(self, speed_monitor=None):
        self._lock = instrumented_lock("master.task_manager")
        self._datasets: Dict[str, DatasetManager] = {}
        self._speed_monitor = speed_monitor
        self._worker_last_task: Dict[int, float] = {}
        self._journal = None

    def set_journal(self, journal):
        """Install the WAL append hook (state-store-backed masters)."""
        with self._lock:
            self._journal = journal
            for ds in self._datasets.values():
                ds.journal = journal

    def new_dataset(
        self,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
        storage_type: str = "table",
    ):
        with self._lock:
            self._create_dataset(
                dataset_name, dataset_size, shard_size, num_epochs, shuffle,
                storage_type,
            )

    def _create_dataset(self, dataset_name, dataset_size, shard_size,  # dtlint: holds(master.task_manager)
                        num_epochs, shuffle, storage_type):
        """With the lock held."""
        if dataset_name in self._datasets:
            return
        splitter = create_dataset_splitter(
            dataset_name, dataset_size, shard_size, num_epochs, shuffle,
            storage_type,
        )
        timeout = env_utils.SHARD_TIMEOUT.get(  # dtlint: disable=DT011 -- reclaim-timeout knob feeds process-local liveness timers, not journaled state; intentionally re-resolved per run
            default=DatasetManager.DOING_TASK_TIMEOUT
        )
        manager = DatasetManager(splitter, doing_timeout=timeout)
        manager.journal = self._journal
        self._datasets[dataset_name] = manager
        logger.info("registered dataset %s (size=%s shard=%s epochs=%s)",
                    dataset_name, dataset_size, shard_size, num_epochs)

    def has_dataset(self, dataset_name: str) -> bool:
        with self._lock:
            return dataset_name in self._datasets

    def queue_depths(self) -> Dict[str, Dict[str, int]]:
        """Per-dataset todo/doing queue sizes (the /metrics exporter's
        shard-queue gauge)."""
        with self._lock:
            return {
                name: {"todo": len(ds.todo), "doing": len(ds.doing)}
                for name, ds in self._datasets.items()
            }

    def get_task(self, worker_id: int, dataset_name: str) -> ShardTask:
        with self._lock:
            ds = self._datasets.get(dataset_name)
            if ds is None:
                # Unknown dataset (e.g. restarted master lost the
                # registration): tell the client to re-register instead
                # of ending its epoch with data still undispatched.
                return ShardTask(unknown=True)
            self._worker_last_task[worker_id] = time.time()
            return ds.get_task(worker_id)

    def report_task(self, dataset_name: str, task_id: int, success: bool) -> bool:
        with self._lock:
            ds = self._datasets.get(dataset_name)
            return ds.report_task(task_id, success) if ds else False

    # ------------- bulk lease plumbing (ShardLeaseService) -------------
    def lease_tasks(self, worker_id: int, dataset_name: str, n: int):
        """Bulk dispatch for a lease grant. Returns (tasks, finished,
        unknown) — one critical section for hundreds of shards instead
        of one lock round-trip each."""
        with self._lock:
            ds = self._datasets.get(dataset_name)
            if ds is None:
                return [], False, True
            self._worker_last_task[worker_id] = time.time()
            tasks, finished = ds.get_tasks(worker_id, n)
            return tasks, finished, False

    def report_tasks(self, dataset_name: str, done_ids, failed_ids=()) -> int:
        """Bulk completion/failure ack; returns the landed-ack count."""
        with self._lock:
            ds = self._datasets.get(dataset_name)
            return ds.report_tasks(done_ids, failed_ids) if ds else 0

    def dispatch_exact(self, worker_id: int, dataset_name: str, task_ids):
        """Replay a bulk grant by id; see DatasetManager.dispatch_exact."""
        with self._lock:
            ds = self._datasets.get(dataset_name)
            return ds.dispatch_exact(worker_id, task_ids) if ds else []

    def reclaim_tasks(self, dataset_name: str, task_ids):
        """Pop the given doing entries and requeue under fresh ids. No
        journal record of its own — callers (lease expiry/release)
        journal their own reason and replay through here again."""
        self.replay_reclaim(dataset_name, task_ids)

    # ------------- journal replay + fencing reclaim -------------
    def replay_shards(self, dataset_name: str, state: dict):
        with self._lock:
            ds = self._datasets.get(dataset_name)
            if ds is not None:
                ds.replay_shards(state)

    def replay_dispatch(self, d: dict):
        with self._lock:
            ds = self._datasets.get(d.get("dataset", ""))
            return ds.replay_dispatch(d) if ds else None

    def replay_reclaim(self, dataset_name: str, task_ids):
        with self._lock:
            ds = self._datasets.get(dataset_name)
            if ds is not None:
                ds.replay_reclaim(task_ids)

    def reclaim_task(self, worker_id: int, dataset_name: str, d: dict) -> bool:
        with self._lock:
            ds = self._datasets.get(dataset_name)
            return ds.reclaim_task(worker_id, d) if ds else False

    def recover_worker_tasks(self, worker_id: int):
        with self._lock:
            for name, ds in self._datasets.items():
                n = ds.recover_worker_tasks(worker_id)
                if n:
                    logger.info(
                        "recovered %s tasks of worker %s on dataset %s",
                        n, worker_id, name,
                    )

    def finished(self) -> bool:
        with self._lock:
            return bool(self._datasets) and all(
                ds.completed() for ds in self._datasets.values()
            )

    def get_epoch(self, dataset_name: str) -> int:
        with self._lock:
            ds = self._datasets.get(dataset_name)
            return ds.epoch if ds else 0

    def checkpoint(self) -> str:
        with self._lock:
            return json.dumps(
                {name: ds.checkpoint() for name, ds in self._datasets.items()}
            )

    def restore(self, content: str, exact: bool = False):
        """Restore from a checkpoint() string.

        ``exact=False`` (the ShardCheckpoint RPC contract): merge
        todo+doing under fresh ids — the restoring master doesn't know
        the doing holders. ``exact=True`` (state-store recovery):
        preserve ids, assignments and the completed count so journaled
        dispatch/report replays line up with the snapshot.
        """
        if not content:
            return
        state = json.loads(content)
        with self._lock:
            for name, ds_state in state.items():
                ds = self._datasets.get(name)
                if ds is None and exact and "params" in ds_state:
                    p = ds_state["params"]
                    self._create_dataset(
                        name, p["dataset_size"], p["shard_size"],
                        p["num_epochs"], p["shuffle"], p["storage_type"],
                    )
                    ds = self._datasets.get(name)
                if ds:
                    ds.restore(ds_state, exact=exact)
