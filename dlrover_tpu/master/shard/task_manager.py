"""Dispatch dataset shards as tasks; recover tasks of failed workers.

Parity: reference ``master/shard/task_manager.py`` + ``batch_dataset_manager.py``
— todo/doing bookkeeping per dataset, worker-failure task recovery
(``task_manager.py:165``), epoch advancement, and shard checkpoints so a
restarted master resumes mid-epoch.
"""

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, Optional, Tuple

from dlrover_tpu.common.log import logger
from dlrover_tpu.common.messages import ShardTask
from dlrover_tpu.master.shard.splitter import (
    DatasetSplitter,
    Shard,
    create_dataset_splitter,
)


@dataclass
class DoingTask:
    task: ShardTask
    worker_id: int
    start_time: float


class DatasetManager:
    """Todo/doing queues for one dataset."""

    # A shard held in `doing` longer than this is presumed abandoned (its
    # worker hung or exited without acking) and is returned to `todo` —
    # the liveness fallback behind the clients' block-until-finished
    # fetch. Worker *failures* are recovered immediately via
    # recover_worker_tasks; this only catches silent abandonment.
    DOING_TASK_TIMEOUT = 300.0

    def __init__(self, splitter: DatasetSplitter,
                 doing_timeout: Optional[float] = None):
        self.splitter = splitter
        self.todo: Deque[ShardTask] = deque()
        self.doing: Dict[int, DoingTask] = {}
        self.doing_timeout = (
            doing_timeout if doing_timeout is not None
            else self.DOING_TASK_TIMEOUT
        )
        self._task_id = 0
        self._completed_tasks = 0

    def _requeue(self, task: ShardTask):
        """Re-dispatch under a FRESH task id: a late ack from the
        original holder must not pop the new dispatchee's doing entry
        (it finds no matching id and is ignored)."""
        self.todo.appendleft(self._new_task(Shard(
            name=task.shard_name, start=task.start, end=task.end,
            record_indices=task.record_indices,
        )))

    def _reclaim_stale(self):
        now = time.time()
        stale = [
            tid for tid, d in self.doing.items()
            if now - d.start_time > self.doing_timeout
        ]
        for tid in stale:
            doing = self.doing.pop(tid)
            logger.warning(
                "shard task %s of worker %s timed out after %.0fs; "
                "re-dispatching", tid, doing.worker_id, self.doing_timeout,
            )
            self._requeue(doing.task)

    def _refill(self):
        self._reclaim_stale()
        if self.todo or self.splitter.epoch_finished():
            return
        for shard in self.splitter.create_shards():
            self.todo.append(self._new_task(shard))

    def _new_task(self, shard: Shard) -> ShardTask:
        task = ShardTask(
            task_id=self._task_id,
            dataset_name=self.splitter.dataset_name,
            shard_name=shard.name,
            start=shard.start,
            end=shard.end,
            record_indices=shard.record_indices,
        )
        self._task_id += 1
        return task

    def get_task(self, worker_id: int) -> ShardTask:
        self._refill()
        if not self.todo:
            # Distinguish "done" from "empty for now": while shards are in
            # `doing`, a failed worker's shards may yet be re-dispatched,
            # so clients must keep polling rather than end the epoch.
            return ShardTask(finished=self.completed())
        task = self.todo.popleft()
        self.doing[task.task_id] = DoingTask(task, worker_id, time.time())
        return task

    def report_task(self, task_id: int, success: bool) -> bool:
        doing = self.doing.pop(task_id, None)
        if doing is None:
            return False
        if success:
            self._completed_tasks += 1
        else:
            self._requeue(doing.task)
        return True

    def recover_worker_tasks(self, worker_id: int) -> int:
        """Return a failed worker's in-flight shards to the todo queue."""
        stale = [tid for tid, d in self.doing.items() if d.worker_id == worker_id]
        for tid in stale:
            self._requeue(self.doing.pop(tid).task)
        return len(stale)

    def completed(self) -> bool:
        return (
            self.splitter.epoch_finished()
            and not self.todo
            and not self.doing
        )

    @property
    def epoch(self) -> int:
        return self.splitter.epoch

    def checkpoint(self) -> dict:
        return {
            "splitter": self.splitter.checkpoint(),
            "todo": [
                {"start": t.start, "end": t.end, "shard_name": t.shard_name}
                for t in self.todo
            ]
            + [
                {"start": d.task.start, "end": d.task.end,
                 "shard_name": d.task.shard_name}
                for d in self.doing.values()
            ],
        }

    def restore(self, state: dict):
        self.splitter.restore(state.get("splitter", {}))
        self.todo.clear()
        self.doing.clear()
        for item in state.get("todo", []):
            shard = Shard(
                name=item.get("shard_name", ""),
                start=item["start"],
                end=item["end"],
            )
            self.todo.append(self._new_task(shard))


class TaskManager:
    """All datasets of a job + the worker-failure recovery hook."""

    def __init__(self, speed_monitor=None):
        self._lock = threading.Lock()
        self._datasets: Dict[str, DatasetManager] = {}
        self._speed_monitor = speed_monitor
        self._worker_last_task: Dict[int, float] = {}

    def new_dataset(
        self,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
        storage_type: str = "table",
    ):
        with self._lock:
            if dataset_name in self._datasets:
                return
            splitter = create_dataset_splitter(
                dataset_name, dataset_size, shard_size, num_epochs, shuffle,
                storage_type,
            )
            timeout = float(os.getenv(
                "DLROVER_TPU_SHARD_TIMEOUT", DatasetManager.DOING_TASK_TIMEOUT
            ))
            self._datasets[dataset_name] = DatasetManager(
                splitter, doing_timeout=timeout
            )
            logger.info("registered dataset %s (size=%s shard=%s epochs=%s)",
                        dataset_name, dataset_size, shard_size, num_epochs)

    def has_dataset(self, dataset_name: str) -> bool:
        with self._lock:
            return dataset_name in self._datasets

    def get_task(self, worker_id: int, dataset_name: str) -> ShardTask:
        with self._lock:
            ds = self._datasets.get(dataset_name)
            if ds is None:
                # Unknown dataset (e.g. restarted master lost the
                # registration): tell the client to re-register instead
                # of ending its epoch with data still undispatched.
                return ShardTask(unknown=True)
            self._worker_last_task[worker_id] = time.time()
            return ds.get_task(worker_id)

    def report_task(self, dataset_name: str, task_id: int, success: bool) -> bool:
        with self._lock:
            ds = self._datasets.get(dataset_name)
            return ds.report_task(task_id, success) if ds else False

    def recover_worker_tasks(self, worker_id: int):
        with self._lock:
            for name, ds in self._datasets.items():
                n = ds.recover_worker_tasks(worker_id)
                if n:
                    logger.info(
                        "recovered %s tasks of worker %s on dataset %s",
                        n, worker_id, name,
                    )

    def finished(self) -> bool:
        with self._lock:
            return bool(self._datasets) and all(
                ds.completed() for ds in self._datasets.values()
            )

    def get_epoch(self, dataset_name: str) -> int:
        with self._lock:
            ds = self._datasets.get(dataset_name)
            return ds.epoch if ds else 0

    def checkpoint(self) -> str:
        with self._lock:
            return json.dumps(
                {name: ds.checkpoint() for name, ds in self._datasets.items()}
            )

    def restore(self, content: str):
        if not content:
            return
        state = json.loads(content)
        with self._lock:
            for name, ds_state in state.items():
                ds = self._datasets.get(name)
                if ds:
                    ds.restore(ds_state)
