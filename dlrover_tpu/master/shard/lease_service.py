"""Master-side shard-lease plane: bulk dispatch without a hot path.

The per-call data path (one ``TaskRequest`` + one ``TaskReport`` per
shard) costs the master 2 RPCs per shard — fine for hundreds of shards
per second, ruinous at 100k+. The lease plane amortizes the same
todo/doing bookkeeping the TaskManager already owns:

- :meth:`grant` bulk-pops hundreds of shards into ``doing`` (worker_id
  = the leasing agent) and answers one :class:`~dlrover_tpu.common.
  messages.ShardLease`; the agent's broker sub-leases them to its
  workers over shm, so steady state costs the master ~1/lease + 1/batch
  RPCs instead of 2/shard.
- :meth:`report` applies a batched completion/renewal/release. It is a
  journaled, deduped RPC, so a retried batch lands exactly once.
- :meth:`tick` expires unrenewed leases exactly like the doing-timeout:
  the WHOLE lease re-enters todo under fresh ids (at-least-once
  preserved; a late ack for a re-dispatched id finds no doing entry and
  is ignored, same as today).

Durability: grants are apply-then-log (the record must carry the shard
ids the handler chose) as ``("lease", req_id, payload, ts)`` records;
replay re-marks the ids as doing, reinstalls the lease table entry and
hands the rebuilt ShardLease back for dedup seeding, so a client retry
of the granted request is answered, not re-applied. Tick expiries write
their own ``("lease", "", payload, ts)`` record (tick is not an RPC).
Reports replay through their ordinary journaled-RPC record. Because
every leased shard is simultaneously a ``doing`` entry, agent failure
recovery (``recover_worker_tasks``) requeues leased shards with zero
new machinery — :meth:`drop_agent` only clears the bookkeeping so a
later expiry cannot double-requeue.
"""

import time
from typing import Any, Dict, List, Optional

from dlrover_tpu.chaos.injector import fault_hit
from dlrover_tpu.chaos.sites import ChaosSite
from dlrover_tpu.common import env_utils
from dlrover_tpu.common import messages as m
from dlrover_tpu.common.lockdep import instrumented_lock
from dlrover_tpu.common.log import logger


class ShardLeaseService:
    #: dtlint DT009: the lease table and its id counter move together
    #: under the service lock; the counters are monotonic stats read
    #: without the lock by the metrics exporter (single-writer, and a
    #: torn read of a gauge is harmless).
    GUARDED_BY = {
        "_leases": "master.shard_lease",
        "_next_lease_id": "master.shard_lease",
        "granted_shards": None,
        "completed_shards": None,
        "expired_leases": None,
    }

    def __init__(self, task_manager, state_store=None):
        self._lock = instrumented_lock("master.shard_lease")
        self._tm = task_manager
        self._store = state_store
        # lease_id -> {agent, dataset, outstanding: set[int],
        #              expire_ts, ttl}
        self._leases: Dict[int, Dict[str, Any]] = {}
        self._next_lease_id = 0
        self.granted_shards = 0
        self.completed_shards = 0
        self.expired_leases = 0

    # ---------------- journal plumbing ----------------
    @property
    def _replaying(self) -> bool:
        return self._store is not None and self._store.replaying

    def _journal(self, payload: Dict[str, Any]):
        if self._store is not None and not self._store.replaying:
            self._store.append(("lease", "", payload, time.time()))

    # ---------------- grant (apply-then-log RPC) ----------------
    def grant(self, req: m.LeaseRequest) -> m.ShardLease:
        """Bulk-dispatch up to ``max_shards`` shards as one lease.

        Live-only (apply-then-log records replay via :meth:`replay`,
        never through this handler). The chaos gate sits BEFORE any
        state moves: a dropped delivery answers empty with nothing
        mutated, so the client's retry is an ordinary fresh grant.
        """
        ev = fault_hit(ChaosSite.SHARD_LEASE_DELIVER, detail=req.dataset_name)
        if ev is not None:
            if ev.kind == "delay":
                time.sleep(ev.delay_s)
            elif ev.kind == "drop":
                return m.ShardLease(dataset_name=req.dataset_name)
        n = req.max_shards or env_utils.SHARD_LEASE_SHARDS.get()
        ttl = env_utils.SHARD_LEASE_TTL_S.get()
        with self._lock:
            tasks, finished, unknown = self._tm.lease_tasks(
                req.node_id, req.dataset_name, max(1, int(n))
            )
            if unknown:
                return m.ShardLease(
                    dataset_name=req.dataset_name, unknown=True
                )
            if not tasks:
                return m.ShardLease(
                    dataset_name=req.dataset_name, finished=finished
                )
            lease_id = self._next_lease_id
            self._next_lease_id += 1
            self._leases[lease_id] = {
                "agent": req.node_id,
                "dataset": req.dataset_name,
                "outstanding": {t.task_id for t in tasks},
                "expire_ts": time.time() + ttl,
                "ttl": ttl,
            }
            self.granted_shards += len(tasks)
        return m.ShardLease(
            lease_id=lease_id, dataset_name=req.dataset_name,
            tasks=tasks, ttl_s=ttl,
        )

    def grant_payload(self, req: m.LeaseRequest,
                      lease: m.ShardLease) -> Optional[Dict[str, Any]]:
        """The apply-then-log record body for a grant the servicer is
        about to journal; None for empty answers (nothing moved). Only
        the ids ride in the record: the todo state at this journal
        position is reproduced by the shards/dispatch records before
        it, so replay re-pops the same tasks by id."""
        if not lease.exists:
            return None
        return {
            "rec": "grant",
            "lease_id": lease.lease_id,
            "agent": req.node_id,
            "dataset": lease.dataset_name,
            "task_ids": [t.task_id for t in lease.tasks],
            "ttl": lease.ttl_s,
        }

    # ---------------- report (journaled RPC, replayed) ----------------
    def report(self, req: m.LeaseReport) -> m.Response:
        """Apply a batched completion/renewal/release.

        Replay-pure: reached live AND from the journaled rpc record. An
        unknown lease (expired, released, lost with a pre-journal crash)
        answers ``success=False`` — its shards were already requeued, so
        the holder must drop local copies and lease afresh; the retrain
        this can cost is exactly the at-least-once contract.
        """
        with self._lock:
            lease = self._leases.get(req.lease_id)
            if lease is None or lease["dataset"] != req.dataset_name:
                return m.Response(success=False, reason="unknown lease")
            acked = self._tm.report_tasks(
                req.dataset_name, req.done_ids, req.failed_ids
            )
            self.completed_shards += acked
            lease["outstanding"] -= set(req.done_ids)
            lease["outstanding"] -= set(req.failed_ids)
            if req.release and lease["outstanding"]:
                # Handback: the still-outstanding rest re-enters todo
                # under fresh ids (same requeue the doing-timeout uses).
                self._tm.reclaim_tasks(
                    req.dataset_name, sorted(lease["outstanding"])
                )
                lease["outstanding"].clear()
            if req.release or not lease["outstanding"]:
                del self._leases[req.lease_id]
            else:
                lease["expire_ts"] = time.time() + lease["ttl"]  # dtlint: disable=DT011 -- lease-renewal liveness clock, deliberately re-stamped on replay: expiry timers are process-local, not journaled state
        return m.Response(success=True)

    # ---------------- expiry sweep (monitor loop) ----------------
    def tick(self):
        """Expire unrenewed leases: whole-lease re-dispatch, journaled
        as a ``("lease", ...)`` expire record (tick has no RPC record of
        its own, mirroring the task manager's reclaim records)."""
        if self._replaying:
            return
        now = time.time()
        with self._lock:
            expired = [
                lid for lid, lease in self._leases.items()
                if now > lease["expire_ts"]
            ]
            for lid in self._leases:
                if lid in expired:
                    continue
                if fault_hit(ChaosSite.SHARD_LEASE_EXPIRE, detail=str(lid)):
                    expired.append(lid)
            for lid in expired:
                lease = self._leases.pop(lid)
                ids = sorted(lease["outstanding"])
                self._journal({
                    "rec": "expire", "lease_id": lid,
                    "dataset": lease["dataset"], "task_ids": ids,
                })
                if ids:
                    self._tm.reclaim_tasks(lease["dataset"], ids)
                self.expired_leases += 1
                logger.warning(
                    "lease %s of agent %s expired; re-dispatching %s "
                    "outstanding shard(s) of %s",
                    lid, lease["agent"], len(ids), lease["dataset"],
                )

    # ---------------- failure plumbing ----------------
    def drop_agent(self, node_id: int):
        """Clear a failed agent's leases. The shards themselves are
        requeued by ``recover_worker_tasks`` (every leased shard is a
        doing entry under this worker id); dropping the bookkeeping here
        keeps a later tick from double-requeuing ids that are already
        back in todo. Deterministic, so the evict/failure records that
        drive it replay identically."""
        with self._lock:
            stale = [
                lid for lid, lease in self._leases.items()
                if lease["agent"] == node_id
            ]
            for lid in stale:
                del self._leases[lid]
        if stale:
            logger.info(
                "dropped %s lease(s) of failed agent %s", len(stale), node_id
            )

    # ---------------- journal replay + snapshots ----------------
    def replay(self, payload: Dict[str, Any]) -> Optional[m.ShardLease]:
        """Apply one ``("lease", ...)`` record; returns the rebuilt
        ShardLease for grant records so the caller can seed the RPC
        dedup cache (a retried LeaseRequest is answered, not re-run)."""
        rec = payload.get("rec")
        if rec == "grant":
            with self._lock:
                lid = int(payload["lease_id"])
                self._next_lease_id = max(self._next_lease_id, lid + 1)
                ttl = float(payload.get("ttl", 0.0))
                if lid in self._leases:  # duplicated record
                    lease = self._leases[lid]
                    tasks = self._tm.dispatch_exact(
                        lease["agent"], lease["dataset"],
                        sorted(lease["outstanding"]),
                    )
                else:
                    tasks = self._tm.dispatch_exact(
                        payload["agent"], payload["dataset"],
                        payload["task_ids"],
                    )
                    self._leases[lid] = {
                        "agent": payload["agent"],
                        "dataset": payload["dataset"],
                        "outstanding": {t.task_id for t in tasks},
                        "expire_ts": time.time() + ttl,  # dtlint: disable=DT011 -- lease-expiry liveness clock, deliberately re-stamped on replay: the holder may be riding out the master outage and gets a full window
                        "ttl": ttl,
                    }
                    self.granted_shards += len(tasks)
            return m.ShardLease(
                lease_id=lid, dataset_name=payload["dataset"],
                tasks=tasks, ttl_s=ttl,
            )
        if rec == "expire":
            with self._lock:
                self._leases.pop(int(payload["lease_id"]), None)
                self._tm.reclaim_tasks(
                    payload["dataset"], payload.get("task_ids", [])
                )
                self.expired_leases += 1
        return None

    def checkpoint(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "next_lease_id": self._next_lease_id,
                "leases": [
                    {
                        "lease_id": lid,
                        "agent": lease["agent"],
                        "dataset": lease["dataset"],
                        "outstanding": sorted(lease["outstanding"]),
                        "ttl": lease["ttl"],
                    }
                    for lid, lease in self._leases.items()
                ],
            }

    def restore(self, state: Dict[str, Any]):
        if not state:
            return
        with self._lock:
            self._leases.clear()
            self._next_lease_id = int(state.get("next_lease_id", 0))
            for item in state.get("leases", []):
                # The holder may still be alive and riding out the
                # master outage; a full fresh TTL window mirrors the
                # doing-restore start_time=now convention.
                self._leases[int(item["lease_id"])] = {
                    "agent": item["agent"],
                    "dataset": item["dataset"],
                    "outstanding": set(item["outstanding"]),
                    "expire_ts": time.time() + float(item["ttl"]),
                    "ttl": float(item["ttl"]),
                }

    # ---------------- metrics ----------------
    def lease_stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "live_leases": len(self._leases),
                "outstanding_shards": sum(
                    len(lease["outstanding"])
                    for lease in self._leases.values()
                ),
                "granted_shards": self.granted_shards,
                "completed_shards": self.completed_shards,
                "expired_leases": self.expired_leases,
            }
