"""Master-side live rescale plane: scale change without the restart tax.

Before this coordinator every membership change paid the full
kill → rendezvous → restore cycle even when most workers never failed
(BENCH_r05's ``restart_breakdown``: spawn+init+restore+recompile is pure
downtime). The rescale plane instead treats a round bump with a
surviving quorum as a *transition*: the coordinator journals and issues
a :class:`~dlrover_tpu.common.messages.RescalePlan` — old world → new
world plus the derived per-rank accumulation schedule preserving the
exact global batch — and installs the new world directly into the
rendezvous manager (:meth:`absorb_world`). Survivors poll the plan when
their round goes stale, re-shard live state in place (see
``train/rescale.py``), and ack; the plan completes when every survivor
acked, or aborts (round invalidated → legacy full restart) on the first
failure or on timeout. Everything the decision depends on is journaled
as ``("rescale", payload, ts)`` records so a relaunched master neither
forgets an issued plan nor re-issues a completed one.
"""

import time
from dataclasses import asdict
from typing import Any, Callable, Dict, List, Optional, Tuple

from dlrover_tpu.chaos.injector import fault_hit
from dlrover_tpu.chaos.sites import ChaosSite
from dlrover_tpu.common import env_utils
from dlrover_tpu.common import messages as m
from dlrover_tpu.common.batching import derive_accum_schedule
from dlrover_tpu.common.constants import RendezvousName
from dlrover_tpu.common.lockdep import instrumented_lock
from dlrover_tpu.common.log import logger
from dlrover_tpu.observability.events import EventKind, emit

PLAN_ISSUED = "issued"
PLAN_COMPLETE = "complete"
PLAN_ABORTED = "aborted"


def plan_survivors(plan: m.RescalePlan) -> List[int]:
    """Ranks that live through the transition (must apply + ack)."""
    return sorted(set(plan.old_world) & set(plan.new_world))


class RescaleCoordinator:
    #: dtlint DT009: plan lifecycle state — issued plans, their ack
    #: matrices, settle deadlines and the capability roster all move
    #: together under the coordinator lock.
    GUARDED_BY = {
        "_plans": "master.rescale",
        "_acks": "master.rescale",
        "_deadlines": "master.rescale",
        "_capable": "master.rescale",
        "_spec": "master.rescale",
        "_profile": "master.rescale",
        "_hbm": "master.rescale",
        "_last_select": "master.rescale",
        # Set once at master wiring, read-only afterwards.
        "_link_profile_fn": None,
    }

    """Decides, journals and tracks in-place scale transitions.

    Wiring: the master calls :meth:`on_node_removed` from its eviction
    path (shrink) and the servicer calls :meth:`on_node_joined` when a
    new node joins an active training world (grow). Both fall back to
    returning ``None`` — which leaves the legacy stale-round/full-restart
    path in charge — whenever the transition is not safely expressible
    in place: rescale disabled, quorum lost, batch config unknown, or
    the schedule unsatisfiable.
    """

    def __init__(
        self,
        rdzv_managers: Optional[Dict[str, Any]] = None,
        state_store=None,
    ):
        self._lock = instrumented_lock("master.rescale")
        self._rdzv_managers = rdzv_managers or {}
        self._store = state_store
        self._plans: Dict[int, m.RescalePlan] = {}
        # plan_id -> node_rank -> ok
        self._acks: Dict[int, Dict[int, bool]] = {}
        self._deadlines: Dict[int, float] = {}
        self._next_plan_id = 1
        self._global_batch = 0
        self._micro_batch = 0
        self._last_step = -1
        # Node ranks that advertised a live RescaleEngine (wired into
        # their training loop). A plan is only issued when EVERY
        # survivor can actually apply it; otherwise the fleet would sit
        # out the full apply timeout training on a stale world before
        # falling back to the restart it could have taken immediately.
        self._capable: set = set()
        # Mesh-reshape inputs (journaled as ("reshape", ...) records):
        # the fleet's current ParallelSpec, its ModelProfile and the
        # per-device HBM, all as plain dicts/floats off ModelInfo.extra.
        # Without them plans stay DP-only (schedule retunes).
        self._spec: Dict[str, Any] = {}
        self._profile: Dict[str, Any] = {}
        self._hbm: float = 0.0
        # The last searched-spec selection, for introspection and so an
        # abort's evidence can name the transition it fenced.
        self._last_select: Dict[str, Any] = {}
        # Measured-link feed (LinkProfileAggregator.search_profile,
        # wired by the master; not journaled — the profile is live
        # telemetry, and a replayed plan carries the spec it chose).
        self._link_profile_fn: Optional[Any] = None

    def set_link_profile_fn(self, fn):
        """Zero-arg callable returning the aggregator's per-axis link
        profile (or None): when present, the reshape search prices
        candidates at measured bandwidth and searches the per-axis
        collective-strategy dimension."""
        self._link_profile_fn = fn

    def axis_crossing(self) -> Dict[str, bool]:
        """Which mesh axes of the fleet's current spec cross hosts —
        the aggregator's ``set_axis_links`` input. Empty until the fleet
        reports its mesh (``set_parallel_config``)."""
        with self._lock:
            spec_d = dict(self._spec)
        if not spec_d:
            return {}
        try:
            from dlrover_tpu.accel.search import _axis_links, spec_from_dict

            cur = spec_from_dict(spec_d)
            mgr = self._rdzv_managers.get(RendezvousName.TRAINING)
            hosts = len(mgr.current_world()) if mgr is not None else 0
            dph = (
                cur.total // hosts if hosts > 1 and cur.total % hosts == 0
                else 0
            )
            return _axis_links(cur, dph)
        except Exception:
            logger.debug("axis crossing derivation failed", exc_info=True)
            return {}

    # ---------------- journal plumbing ----------------
    @property
    def _replaying(self) -> bool:
        return self._store is not None and self._store.replaying

    def _journal(self, payload: Dict[str, Any]):
        if self._store is not None and not self._store.replaying:
            self._store.append(("rescale", payload, time.time()))

    def _journal_reshape(self, payload: Dict[str, Any]):
        if self._store is not None and not self._store.replaying:
            self._store.append(("reshape", payload, time.time()))

    # ---------------- live inputs ----------------
    def set_batch_config(self, global_batch: int, micro_batch: int):
        """Record the fleet's batch contract (journaled): without it no
        accumulation schedule can be derived and every membership change
        falls back to a full restart."""
        with self._lock:
            if (
                self._global_batch == global_batch
                and self._micro_batch == micro_batch
            ):
                return
            self._global_batch = int(global_batch)
            self._micro_batch = int(micro_batch)
        self._journal({
            "rec": "config",
            "global_batch": int(global_batch),
            "micro_batch": int(micro_batch),
        })

    def set_capable(self, node_rank: int):
        """Record that a node's worker runs a live RescaleEngine
        (journaled). The engine advertises on construction via
        ``ModelInfo.extra["rescale_capable"]``; without the flag from
        every survivor the coordinator declines to plan in place."""
        with self._lock:
            if node_rank in self._capable:
                return
            self._capable.add(node_rank)
        self._journal({"rec": "capable", "node": int(node_rank)})

    def set_parallel_config(
        self, spec: Dict[str, Any], profile: Dict[str, Any],
        hbm: float = 0.0,
    ):
        """Record the fleet's mesh layout + model profile (journaled as
        a ``("reshape", ...)`` record): the inputs the constrained-world
        spec search needs. Without them a membership change can only
        retune the accumulation schedule — any job running TP/FSDP/pipe
        degrees would nack the plan and pay the restart tax."""
        spec = dict(spec or {})
        profile = dict(profile or {})
        with self._lock:
            if (
                self._spec == spec and self._profile == profile
                and (hbm <= 0 or self._hbm == hbm)
            ):
                return
            self._spec = spec
            self._profile = profile
            if hbm > 0:
                self._hbm = float(hbm)
        self._journal_reshape({
            "rec": "config", "spec": spec, "profile": profile,
            "hbm": float(hbm),
        })

    def note_step(self, step: int):
        """Track the newest reported global step — the plan's
        ``snapshot_step`` freshness fence (per-step shm snapshots mean
        the newest snapshot is at most one step behind it)."""
        with self._lock:
            self._last_step = max(self._last_step, int(step))

    # ---------------- transition triggers ----------------
    def on_node_removed(
        self,
        node_rank: int,
        old_world: Dict[int, int],
        rdzv_name: str = RendezvousName.TRAINING,
    ) -> Optional[m.RescalePlan]:
        """Shrink path: a member of the active world died/was evicted.

        Called after the rendezvous managers dropped the node (the old
        round is already stale). Returns the issued plan, or ``None``
        to leave the full-restart fallback in charge.
        """
        if self._replaying or not env_utils.RESCALE.get():
            return None
        if node_rank not in old_world:
            return None
        survivors = {
            r: w for r, w in old_world.items() if r != node_rank
        }
        if not survivors:
            return None
        quorum = env_utils.RESCALE_MIN_QUORUM.get()  # dtlint: disable=DT011 -- operator policy deliberately read live; the authoritative plan/abort state replays from ("rescale", ...) records, which overwrite any transient re-derivation
        if len(survivors) / len(old_world) < quorum:
            logger.info(
                "rescale: %d/%d survivors below quorum %.2f; falling "
                "back to full restart", len(survivors), len(old_world),
                quorum,
            )
            return None
        return self._issue_plan(
            rdzv_name, old_world, survivors, transition="shrink"
        )

    def can_plan_shrink(
        self, node_rank: int, old_world: Dict[int, int]
    ) -> Tuple[bool, str]:
        """Pre-flight for the remediation policy: would
        :meth:`on_node_removed` issue a plan for this shrink right now?

        Runs the same gates (rescale enabled, membership, survivor
        quorum, batch config, survivor capability, schedule
        satisfiability) without touching the rendezvous or issuing
        anything. The policy must know BEFORE dropping the node — an
        issued-then-declined shrink falls back to the full restart the
        quarantine exists to avoid. Returns ``(ok, reason)``.
        """
        if self._replaying or not env_utils.RESCALE.get():
            return False, "rescale disabled"
        if node_rank not in old_world:
            return False, f"node {node_rank} not in the active world"
        survivors = {
            r: w for r, w in old_world.items() if r != node_rank
        }
        if not survivors:
            return False, "no survivors"
        quorum = env_utils.RESCALE_MIN_QUORUM.get()
        if len(survivors) / len(old_world) < quorum:
            return False, (
                f"{len(survivors)}/{len(old_world)} survivors below "
                f"quorum {quorum:.2f}"
            )
        with self._lock:
            global_batch, micro_batch = self._global_batch, self._micro_batch
            incapable = sorted(set(survivors) - self._capable)
        if global_batch <= 0:
            return False, "no batch config reported"
        if incapable:
            return False, (
                f"survivors {incapable} never advertised a live rescale "
                "engine"
            )
        try:
            derive_accum_schedule(
                global_batch, micro_batch, sum(survivors.values())
            )
        except ValueError as e:
            return False, f"schedule unsatisfiable ({e})"
        return True, ""

    def plan_status(self, plan_id: int) -> Optional[str]:
        """Settlement state of a plan: ``"issued"`` / ``"complete"`` /
        ``"aborted"``, or ``None`` for an unknown id. The remediation
        policy polls this each tick to confirm (or revert) a pending
        quarantine — idempotently, so a failed-over master re-derives
        the same answer from the replayed plan records."""
        with self._lock:
            plan = self._plans.get(int(plan_id))
            return plan.status if plan is not None else None

    def on_node_joined(
        self, node_rank: int, local_world_size: int, rdzv_name: str
    ) -> Optional[m.RescalePlan]:
        """Grow path: a node joined while a frozen world is training.

        The joiner is absorbed into the next round; it boots through the
        normal worker path (it has no live state) and hydrates from the
        shm snapshot, while survivors transition in place.
        """
        if self._replaying or not env_utils.RESCALE.get():
            return None
        if rdzv_name != RendezvousName.TRAINING:
            return None
        mgr = self._rdzv_managers.get(rdzv_name)
        if mgr is None:
            return None
        old_world = mgr.current_world()
        if not old_world or node_rank in old_world:
            return None
        with self._lock:
            if any(
                p.rdzv_name == rdzv_name and p.status == PLAN_ISSUED
                for p in self._plans.values()
            ):
                # One transition at a time; the joiner waits in the
                # rendezvous waiting set until the in-flight plan
                # settles, then triggers again on its next join poll.
                return None
        new_world = dict(old_world)
        new_world[node_rank] = local_world_size
        return self._issue_plan(
            rdzv_name, old_world, new_world, transition="grow"
        )

    def _issue_plan(
        self,
        rdzv_name: str,
        old_world: Dict[int, int],
        new_world: Dict[int, int],
        transition: str,
    ) -> Optional[m.RescalePlan]:
        mgr = self._rdzv_managers.get(rdzv_name)
        if mgr is None:
            return None
        with self._lock:
            global_batch, micro_batch = self._global_batch, self._micro_batch
            snapshot_step = self._last_step
            incapable = sorted(
                set(old_world) & set(new_world) - self._capable
            )
        if global_batch <= 0:
            logger.info(
                "rescale: no batch config reported; falling back to "
                "full restart for the %s", transition,
            )
            return None
        if incapable:
            # Issuing a plan no survivor can apply would hold the fleet
            # for the full apply timeout — training on a stale world —
            # before the inevitable restart. Decline up front instead.
            logger.info(
                "rescale: survivors %s never advertised a live rescale "
                "engine; falling back to full restart for the %s",
                incapable, transition,
            )
            return None
        total_procs = sum(new_world.values())
        try:
            sched = derive_accum_schedule(
                global_batch, micro_batch, total_procs
            )
        except ValueError as e:
            logger.info(
                "rescale: schedule unsatisfiable (%s); falling back to "
                "full restart", e,
            )
            return None
        old_spec, new_spec = self._select_reshape(
            old_world, new_world, global_batch
        )
        new_round = mgr.absorb_world(new_world)
        superseded: List[m.RescalePlan] = []
        with self._lock:
            # A second membership change inside the apply window makes
            # any in-flight plan obsolete: its round is already stale
            # and survivors will pick up the newer plan instead. Abort
            # it WITHOUT invalidating the round — that would fence the
            # new plan's live round and force-restart a healthy world.
            for old in self._plans.values():
                if old.rdzv_name == rdzv_name and old.status == PLAN_ISSUED:
                    old.status = PLAN_ABORTED
                    self._deadlines.pop(old.plan_id, None)
                    superseded.append(old)
            plan = m.RescalePlan(
                plan_id=self._next_plan_id,
                rdzv_name=rdzv_name,
                old_round=new_round - 1,
                new_round=new_round,
                old_world=dict(old_world),
                new_world=dict(new_world),
                global_batch=global_batch,
                micro_batch=sched.micro_batch,
                accum_counts=list(sched.counts),
                snapshot_step=snapshot_step,
                status=PLAN_ISSUED,
                old_spec=old_spec,
                new_spec=new_spec,
            )
            self._next_plan_id += 1
            self._plans[plan.plan_id] = plan
            self._acks[plan.plan_id] = {}
            self._deadlines[plan.plan_id] = (
                time.monotonic() + env_utils.RESCALE_APPLY_TIMEOUT_S.get()  # dtlint: disable=DT011 -- apply deadlines are process-local liveness timers, deliberately re-armed from the live clock and knob on every run
            )
        for old in superseded:
            self._journal({
                "rec": "abort", "plan_id": old.plan_id,
                "reason": "superseded",
            })
            logger.info(
                "rescale plan %s superseded by plan %s before settling",
                old.plan_id, plan.plan_id,
            )
            emit(  # dtlint: disable=DT012 -- replay-guarded at the sink: JobMaster._event_sink drops emits while store.replaying
                EventKind.RESCALE_ABORT, _role="master",
                plan_id=old.plan_id, reason="superseded",
            )
        self._journal({"rec": "plan", "plan": asdict(plan)})
        diff = ""
        if plan.reshapes:
            from dlrover_tpu.accel.search import spec_diff

            diff = spec_diff(plan.old_spec, plan.new_spec)
            select = {
                "rec": "select", "plan_id": plan.plan_id,
                "old_spec": dict(plan.old_spec),
                "new_spec": dict(plan.new_spec), "diff": diff,
            }
            with self._lock:
                self._last_select = select
            self._journal_reshape(select)
        logger.info(
            "rescale plan %s: %s %s -> %s (round %s -> %s, accum %s, "
            "snapshot_step %s%s)", plan.plan_id, transition,
            sorted(old_world), sorted(new_world), plan.old_round,
            plan.new_round, plan.accum_counts, plan.snapshot_step,
            f", reshape {diff}" if diff else "",
        )
        emit(  # dtlint: disable=DT012 -- replay-guarded at the sink: JobMaster._event_sink drops emits while store.replaying
            EventKind.RESCALE_PLAN, _role="master",
            plan_id=plan.plan_id, transition=transition,
            old_world=sorted(old_world), new_world=sorted(new_world),
            old_round=plan.old_round, new_round=plan.new_round,
            **({"spec_diff": diff} if diff else {}),
        )
        return plan

    def _select_reshape(
        self,
        old_world: Dict[int, int],
        new_world: Dict[int, int],
        global_batch: int,
    ) -> tuple:
        """Pick the surviving world's ParallelSpec via the constrained
        search (``accel/search.py``). Returns ``(old_spec, new_spec)``
        as asdict dicts, or ``({}, {})`` to keep the plan DP-only —
        which is correct whenever the fleet never reported its mesh
        (``set_parallel_config``), runs a trivial 1-device spec, or the
        member→device mapping is not integral. Search failures degrade
        to DP-only, never to a lost plan."""
        with self._lock:
            spec_d = dict(self._spec)
            profile_d = dict(self._profile)
            hbm = self._hbm
        if not env_utils.RESCALE_RESHAPE.get() or not spec_d:  # dtlint: disable=DT011 -- never reached on replay: _issue_plan is guarded by _replaying in both triggers; plans replay via their journaled record
            return {}, {}
        try:
            import dataclasses as _dc

            from dlrover_tpu.accel.search import (
                ModelProfile,
                search_reshape_spec,
                spec_from_dict,
            )

            cur = spec_from_dict(spec_d)
            old_procs = sum(old_world.values())
            new_procs = sum(new_world.values())
            if cur.total <= 1 or old_procs <= 0:
                return {}, {}
            if cur.total % old_procs:
                # No integral member→device mapping: the mesh does not
                # shrink/grow proportionally with membership, so there
                # is nothing principled to search against.
                return {}, {}
            n_devices = (cur.total // old_procs) * new_procs
            fields = {f.name for f in _dc.fields(ModelProfile)}
            profile = ModelProfile(**{
                k: v for k, v in profile_d.items() if k in fields
            })
            # Measured link profile (when the aggregator has one): the
            # search prices candidates at live per-axis bandwidth and
            # the collective-strategy dimension opens up.
            link_profile = None
            if self._link_profile_fn is not None:
                try:
                    link_profile = self._link_profile_fn()
                except Exception:
                    logger.debug(
                        "link profile fetch failed", exc_info=True
                    )
            hosts = len(new_world)
            dph = (
                n_devices // hosts
                if hosts > 1 and n_devices % hosts == 0 else 0
            )
            found = search_reshape_spec(
                profile, n_devices, global_batch,
                hbm or 16e9, current_spec=cur,
                stickiness=env_utils.RESCALE_RESHAPE_STICKINESS.get(),  # dtlint: disable=DT011 -- same guard: spec selection only runs live; the chosen spec is journaled in the plan record
                devices_per_host=dph, link_profile=link_profile,
            )
            if found is None:
                return {}, {}
            return spec_d, _dc.asdict(found[0])
        except Exception as e:
            logger.warning(
                "reshape spec search failed (%s); issuing a DP-only "
                "plan", e,
            )
            return {}, {}

    # ---------------- delivery / acks ----------------
    def get_plan(
        self, rdzv_name: str, node_rank: int, round_: int
    ) -> m.RescalePlan:
        """Answer a survivor's poll: the newest issued plan that covers
        it and supersedes the round it is running. A node that missed an
        intermediate plan correctly applies only the newest one — the
        transition engine re-shards from its *current* state, not from
        ``plan.old_world``."""
        best = m.RescalePlan()
        with self._lock:
            for plan in self._plans.values():
                if (
                    plan.rdzv_name == rdzv_name
                    and plan.status == PLAN_ISSUED
                    and node_rank in plan.new_world
                    and plan.new_round > round_
                    and plan.new_round > best.new_round
                ):
                    best = plan
        if best.exists:
            ev = fault_hit(
                ChaosSite.RESCALE_PLAN_DELIVER,
                detail=f"plan{best.plan_id}:rank{node_rank}",
            )
            if ev is not None:
                if ev.kind == "delay":
                    time.sleep(ev.delay_s)
                elif ev.kind == "drop":
                    return m.RescalePlan()
        return best

    def apply_ack(
        self, plan_id: int, node_rank: int, ok: bool, error: str = ""
    ) -> bool:
        """Record one survivor's ack (reached via the journaled
        ``RescaleAck`` RPC, so replay re-derives plan outcomes). All
        survivors ok → complete; any failure → abort + invalidate the
        round so survivors fall back to a full restart."""
        aborted = completed = False
        with self._lock:
            plan = self._plans.get(plan_id)
            if plan is None:
                return False
            if plan.status != PLAN_ISSUED:
                # Late ack for a settled plan: acknowledged, no effect.
                return True
            self._acks[plan_id][node_rank] = ok
            if not ok:
                plan.status = PLAN_ABORTED
                aborted = True
            else:
                acks = self._acks[plan_id]
                if all(acks.get(r) for r in plan_survivors(plan)):
                    plan.status = PLAN_COMPLETE
                    completed = True
            rdzv_name = plan.rdzv_name
            new_round = plan.new_round
            reshape_diff = ""
            if plan.reshapes:
                from dlrover_tpu.accel.search import spec_diff

                reshape_diff = spec_diff(plan.old_spec, plan.new_spec)
        if self._replaying:
            return True
        if aborted:
            logger.error(
                "rescale plan %s (round %s%s) aborted by node %s: %s; "
                "invalidating round %s for full restart", plan_id,
                new_round,
                f", reshape {reshape_diff}" if reshape_diff else "",
                node_rank, error, new_round,
            )
            emit(  # dtlint: disable=DT012 -- replay-guarded at the sink: JobMaster._event_sink drops emits while store.replaying
                EventKind.RESCALE_ABORT, _node_id=node_rank,
                _role="master", plan_id=plan_id, reason=error or "nack",
                round=new_round,
                **({"spec_diff": reshape_diff} if reshape_diff else {}),
            )
            self._invalidate_if_current(rdzv_name, new_round)
        elif completed:
            logger.info("rescale plan %s complete: every survivor "
                        "transitioned in place", plan_id)
            emit(  # dtlint: disable=DT012 -- replay-guarded at the sink: JobMaster._event_sink drops emits while store.replaying
                EventKind.RESCALE_COMPLETE, _role="master",
                plan_id=plan_id, new_round=new_round,
            )
        return True

    def supersede_plan(self, plan_id: int, reason: str) -> bool:
        """Abort an in-flight plan WITHOUT invalidating its round.

        The preemption plane's false-alarm cancel: the shrink plan it
        issued proactively is obsolete because the victim stays, and
        fencing the live round would force-restart a healthy world.
        Survivors that already applied keep training; a settled plan
        (complete or already aborted) is left untouched.
        """
        with self._lock:
            plan = self._plans.get(plan_id)
            if plan is None or plan.status != PLAN_ISSUED:
                return False
            plan.status = PLAN_ABORTED
            self._deadlines.pop(plan_id, None)
        self._journal({
            "rec": "abort", "plan_id": plan_id, "reason": reason,
        })
        logger.info(
            "rescale plan %s superseded (%s); round left valid",
            plan_id, reason,
        )
        emit(
            EventKind.RESCALE_ABORT, _role="master",
            plan_id=plan_id, reason=reason,
        )
        return True

    def tick(self):
        """Periodic driver (master monitor loop): abort plans whose
        survivors did not all ack within the apply timeout."""
        if self._replaying:
            return
        now = time.monotonic()
        expired: List[m.RescalePlan] = []
        with self._lock:
            for plan_id, deadline in list(self._deadlines.items()):
                plan = self._plans.get(plan_id)
                if plan is None or plan.status != PLAN_ISSUED:
                    self._deadlines.pop(plan_id, None)
                    continue
                if now >= deadline:
                    plan.status = PLAN_ABORTED
                    self._deadlines.pop(plan_id, None)
                    expired.append(plan)
        for plan in expired:
            self._journal({
                "rec": "abort", "plan_id": plan.plan_id,
                "reason": "apply-timeout",
            })
            logger.error(
                "rescale plan %s timed out waiting for survivor acks; "
                "invalidating round %s for full restart",
                plan.plan_id, plan.new_round,
            )
            emit(
                EventKind.RESCALE_ABORT, _role="master",
                plan_id=plan.plan_id, reason="apply-timeout",
            )
            self._invalidate_if_current(plan.rdzv_name, plan.new_round)

    def _invalidate_if_current(self, rdzv_name: str, new_round: int):
        """Fence ``new_round`` for the full-restart fallback — but only
        while it is still the rendezvous manager's newest round. A plan
        that aborts after a newer plan already moved the world on must
        not force-restart that healthy, already-transitioned round."""
        mgr = self._rdzv_managers.get(rdzv_name)
        if mgr is None:
            return
        current = getattr(mgr, "current_round", lambda: new_round)()
        if current == new_round:
            mgr.invalidate_round()
        else:
            logger.info(
                "rescale: round %s already superseded by round %s; "
                "skipping invalidation", new_round, current,
            )

    # ---------------- durability ----------------
    def checkpoint(self) -> dict:
        with self._lock:
            return {
                "plans": [asdict(p) for p in self._plans.values()],
                "acks": {k: dict(v) for k, v in self._acks.items()},
                "next_plan_id": self._next_plan_id,
                "global_batch": self._global_batch,
                "micro_batch": self._micro_batch,
                "last_step": self._last_step,
                "capable": sorted(self._capable),
                "spec": dict(self._spec),
                "profile": dict(self._profile),
                "hbm": self._hbm,
                "last_select": dict(self._last_select),
            }

    def restore(self, state: dict):
        if not state:
            return
        with self._lock:
            for d in state.get("plans", []):
                plan = m.RescalePlan(**d)
                self._plans[plan.plan_id] = plan
                # A plan in flight across a master relaunch gets a fresh
                # apply window rather than an instant timeout-abort.
                if plan.status == PLAN_ISSUED:
                    self._deadlines[plan.plan_id] = (
                        time.monotonic()
                        + env_utils.RESCALE_APPLY_TIMEOUT_S.get()
                    )
            for pid, acks in state.get("acks", {}).items():
                self._acks[int(pid)] = {
                    int(r): bool(ok) for r, ok in acks.items()
                }
            self._next_plan_id = max(
                self._next_plan_id, int(state.get("next_plan_id", 1))
            )
            self._global_batch = int(
                state.get("global_batch", self._global_batch)
            )
            self._micro_batch = int(
                state.get("micro_batch", self._micro_batch)
            )
            self._last_step = max(
                self._last_step, int(state.get("last_step", -1))
            )
            self._capable.update(
                int(r) for r in state.get("capable", [])
            )
            if state.get("spec"):
                self._spec = dict(state["spec"])
            if state.get("profile"):
                self._profile = dict(state["profile"])
            self._hbm = float(state.get("hbm", self._hbm))
            if state.get("last_select"):
                self._last_select = dict(state["last_select"])

    def replay(self, payload: Dict[str, Any]):
        """Re-apply one journaled ``("rescale", payload, ts)`` record.

        Pure bookkeeping — no emits, no rendezvous side effects: the
        rendezvous round counters replay through their own ``rdzv``
        records and events through ``event`` records.
        """
        rec = payload.get("rec")
        if rec == "config":
            with self._lock:
                self._global_batch = int(payload.get("global_batch", 0))
                self._micro_batch = int(payload.get("micro_batch", 0))
        elif rec == "plan":
            with self._lock:
                plan = m.RescalePlan(**payload["plan"])
                self._plans[plan.plan_id] = plan
                self._acks.setdefault(plan.plan_id, {})
                self._next_plan_id = max(
                    self._next_plan_id, plan.plan_id + 1
                )
                if plan.status == PLAN_ISSUED:
                    self._deadlines[plan.plan_id] = (
                        time.monotonic()  # dtlint: disable=DT011 -- a replayed in-flight plan intentionally gets a fresh apply window; the deadline is a process-local timer, not journaled state
                        + env_utils.RESCALE_APPLY_TIMEOUT_S.get()  # dtlint: disable=DT011 -- same fresh apply window: the knob is a liveness timer input, not journaled state
                    )
        elif rec == "capable":
            with self._lock:
                self._capable.add(int(payload.get("node", -1)))
        elif rec == "abort":
            with self._lock:
                plan = self._plans.get(int(payload.get("plan_id", -1)))
                if plan is not None:
                    plan.status = PLAN_ABORTED
        else:
            logger.warning("skipping unknown rescale record %r", rec)

    def replay_reshape(self, payload: Dict[str, Any]):
        """Re-apply one journaled ``("reshape", payload, ts)`` record.

        Pure overwrite bookkeeping: ``config`` restores the spec-search
        inputs (``set_parallel_config``'s snapshot), ``select`` restores
        the last searched transition. The chosen spec itself rides in
        the plan's own ``("rescale", ...)`` record — the search NEVER
        re-runs on replay."""
        rec = payload.get("rec")
        if rec == "config":
            with self._lock:
                self._spec = dict(payload.get("spec", {}))
                self._profile = dict(payload.get("profile", {}))
                hbm = float(payload.get("hbm", 0.0))
                if hbm > 0:
                    self._hbm = hbm
        elif rec == "select":
            with self._lock:
                self._last_select = dict(payload)
        else:
            logger.warning("skipping unknown reshape record %r", rec)
