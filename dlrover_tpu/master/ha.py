"""Primacy lease: which master may mutate, enforced by incarnation.

Master hot standby needs an answer to exactly one question — *who is
primary right now?* — that stays correct through crashes, partitions
and races. The answer here is a small lease record in a shared
coordination directory (``DLROVER_TPU_MASTER_HA_DIR``; both masters
must see the same filesystem):

``lease``
    JSON ``{incarnation, holder, ts}``, written atomically
    (tmp + fsync + replace). The holder re-stamps ``ts`` every
    ``MASTER_HA_RENEW_S``; anyone reading a record older than
    ``MASTER_HA_LEASE_TTL_S`` may treat primacy as forfeit.
``incarnation``
    The fleet-wide monotonic counter. Promotions mint above BOTH this
    counter and the deposed lease's incarnation, so fencing survives
    any interleaving of promotions and plain relaunches.
``claim``
    The promotion mutex: contenders race ``os.open(O_CREAT | O_EXCL)``
    on this file and exactly one wins (the double-promotion race in
    the drill resolves here). A claimant that dies mid-promotion
    leaves the file behind; claims older than
    ``MASTER_HA_CLAIM_STALE_S`` are swept so the fleet is never
    deadlocked on a corpse.
``endpoint``
    The active master's ``host:port``, re-read by ``RpcClient``
    between retry rounds (endpoint re-resolution), so clients ride a
    promotion without process restarts.

Fencing is two-sided: the promoted master starts with a strictly
higher incarnation (clients' PR-3 incarnation-change observers fire on
first contact), and the deposed master's next :meth:`PrimacyLease.renew`
sees the higher recorded incarnation, reports itself fenced, and the
master fences its state store so late writes raise instead of acking.
"""

import json
import os
import socket
import time
from typing import Any, Dict, Optional

from dlrover_tpu.common import env_utils
from dlrover_tpu.common.log import logger

LEASE_FILE = "lease"
INCARNATION_FILE = "incarnation"
CLAIM_FILE = "claim"
ENDPOINT_FILE = "endpoint"


def _atomic_write(path: str, data: str):
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class PrimacyLease:
    """One contender's view of the shared primacy lease.

    Single-threaded per instance by contract: the master calls
    ``acquire``/``renew`` from its renew thread, the standby calls
    ``observe``/``acquire`` from its tail thread — no instance is ever
    shared across threads, so the shared state lives in the files, not
    in this object.
    """

    def __init__(
        self,
        ha_dir: str,
        ttl_s: Optional[float] = None,
        claim_stale_s: Optional[float] = None,
        holder: str = "",
    ):
        os.makedirs(ha_dir, exist_ok=True)
        self.ha_dir = ha_dir
        self.ttl_s = (
            env_utils.MASTER_HA_LEASE_TTL_S.get()
            if ttl_s is None else ttl_s
        )
        self.claim_stale_s = (
            env_utils.MASTER_HA_CLAIM_STALE_S.get()
            if claim_stale_s is None else claim_stale_s
        )
        self.holder = holder or f"{socket.gethostname()}:{os.getpid()}"
        #: incarnation this instance holds primacy under (0 = none)
        self.incarnation = 0
        #: set once renew() observed a newer incarnation in the record
        self.fenced = False

    # ---------------- record I/O ----------------
    def _lease_path(self) -> str:
        return os.path.join(self.ha_dir, LEASE_FILE)

    def observe(self) -> Dict[str, Any]:
        """The current lease record plus derived ``age``/``expired``.
        An unreadable or absent record observes as expired at age
        infinity — a blank coordination dir means primacy is up for
        grabs."""
        rec: Dict[str, Any] = {"incarnation": 0, "holder": "", "ts": 0.0}
        try:
            with open(self._lease_path()) as f:
                loaded = json.load(f)
            if isinstance(loaded, dict):
                rec.update(loaded)
        except (OSError, ValueError):
            pass
        age = time.time() - float(rec.get("ts") or 0.0)
        rec["age"] = age
        rec["expired"] = age >= self.ttl_s
        return rec

    def _read_counter(self) -> int:
        try:
            with open(os.path.join(self.ha_dir, INCARNATION_FILE)) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return 0

    # ---------------- acquisition (CAS via claim file) ----------------
    def acquire(self, floor: int = 0, force: bool = False) -> Optional[int]:
        """Try to take primacy; returns the minted incarnation or
        ``None`` when another holder is alive or another contender won
        the claim race.

        ``floor`` lets a master fold its local state-store incarnation
        into the mint, keeping the fleet counter monotonic with
        pre-HA relaunch history. ``force`` skips the liveness check
        (first boot of a known-sole primary).
        """
        claim = os.path.join(self.ha_dir, CLAIM_FILE)
        try:
            age = time.time() - os.stat(claim).st_mtime
            if age >= self.claim_stale_s:
                os.unlink(claim)
                logger.warning(
                    "swept stale promotion claim (age %.1fs) in %s",
                    age, self.ha_dir,
                )
        except OSError:
            pass
        try:
            fd = os.open(claim, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return None  # lost the race: exactly one contender proceeds
        try:
            os.write(fd, self.holder.encode())
            os.close(fd)
            rec = self.observe()
            if (
                not force
                and not rec["expired"]
                and rec["holder"] not in ("", self.holder)
            ):
                return None  # holder is alive; no hostile takeover
            incarnation = 1 + max(
                self._read_counter(), int(rec.get("incarnation") or 0),
                floor,
            )
            _atomic_write(
                os.path.join(self.ha_dir, INCARNATION_FILE),
                str(incarnation),
            )
            _atomic_write(
                self._lease_path(),
                json.dumps({
                    "incarnation": incarnation,
                    "holder": self.holder,
                    "ts": time.time(),
                }),
            )
            self.incarnation = incarnation
            self.fenced = False
            return incarnation
        finally:
            try:
                os.unlink(claim)
            except OSError:
                pass

    # ---------------- renewal / fencing ----------------
    def renew(self) -> bool:
        """Re-stamp the lease; returns ``False`` (and latches
        ``fenced``) when the record shows a newer incarnation — someone
        promoted over us and our writes must stop."""
        if self.incarnation <= 0 or self.fenced:
            return False
        rec = self.observe()
        if int(rec.get("incarnation") or 0) > self.incarnation:
            self.fenced = True
            logger.error(
                "primacy lost: lease records incarnation %s > ours %s "
                "(holder %s); fencing",
                rec.get("incarnation"), self.incarnation,
                rec.get("holder"),
            )
            return False
        _atomic_write(
            self._lease_path(),
            json.dumps({
                "incarnation": self.incarnation,
                "holder": self.holder,
                "ts": time.time(),
            }),
        )
        return True

    # ---------------- endpoint publication ----------------
    def endpoint_path(self) -> str:
        return os.path.join(self.ha_dir, ENDPOINT_FILE)

    def publish_endpoint(self, addr: str):
        _atomic_write(self.endpoint_path(), addr)

    def read_endpoint(self) -> str:
        try:
            with open(self.endpoint_path()) as f:
                return f.read().strip()
        except OSError:
            return ""
