"""Durable master state: periodic snapshots + a crc-framed WAL.

The master owns every piece of job state that is not re-derivable from
the workers — shard cursors, kv-store contents, the node registry,
rendezvous round counters, the global step. Until this store existed, a
master relaunch rebuilt all of it blank and the job silently restarted
data from shard zero. The store applies the same durability recipe as
the flash-checkpoint stack (Orbax-style committed, versioned state —
see PAPERS.md): every mutation is journaled write-ahead into a
checksummed append-only file, a full snapshot is cut periodically, and
recovery replays the newest valid snapshot plus its journal chain,
tolerating a torn tail (the crash may land mid-append) and quarantining
corrupt snapshots exactly like the checkpoint restore fallback chain.

On-disk layout under ``state_dir``::

    incarnation          monotonic boot counter (fencing epoch)
    snapshot-<seq>.bin   full pickled state, one crc frame
    journal-<seq>.wal    crc frames appended since snapshot <seq>
    *.corrupt            quarantined snapshots (kept for postmortem)

Each journal frame is ``u32 length | u32 checksum | payload`` with the
checksum algorithm stamped once in the file header, reusing
:mod:`dlrover_tpu.common.checksum` so crc32c is used when available.
"""

import os
import pickle
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from dlrover_tpu.common.checksum import (
    DEFAULT_ALGO,
    block_checksum,
    verify_block,
)
from dlrover_tpu.common import env_utils
from dlrover_tpu.common.lockdep import instrumented_lock
from dlrover_tpu.common.log import logger

_FRAME = struct.Struct(">II")  # payload length, payload checksum
_SNAP_MAGIC = b"DLRS1"
_JOURNAL_MAGIC = b"DLRJ1"

SNAPSHOT_PREFIX = "snapshot-"
SNAPSHOT_SUFFIX = ".bin"
JOURNAL_PREFIX = "journal-"
JOURNAL_SUFFIX = ".wal"
QUARANTINE_SUFFIX = ".corrupt"
INCARNATION_FILE = "incarnation"

#: Seconds between periodic snapshots (journal rotation), and the
#: journal-growth backstop that forces one sooner.
SNAPSHOT_INTERVAL_ENV = env_utils.STATE_SNAPSHOT_SECS.name
DEFAULT_SNAPSHOT_INTERVAL = 30.0
DEFAULT_SNAPSHOT_EVERY_RECORDS = 2048


def _write_header(f, magic: bytes, algo: str):
    raw = algo.encode()
    f.write(magic + bytes([len(raw)]) + raw)


def _read_header(data: bytes, magic: bytes) -> Optional[Tuple[str, int]]:
    """Returns (algo, header_len), or None when the header is invalid."""
    if len(data) < len(magic) + 1 or not data.startswith(magic):
        return None
    algo_len = data[len(magic)]
    end = len(magic) + 1 + algo_len
    if len(data) < end:
        return None
    try:
        algo = data[len(magic) + 1 : end].decode()
    except UnicodeDecodeError:
        return None
    return algo, end


def _frame(payload: bytes, algo: str) -> bytes:
    return _FRAME.pack(len(payload), block_checksum(payload, algo)) + payload


def _iter_frames(data: bytes, algo: str) -> Tuple[List[bytes], bool]:
    """Parse crc frames; returns (payloads, torn_tail).

    A short or checksum-failing tail is the expected signature of a
    crash mid-append: everything before it is intact and usable, so the
    parse stops there instead of failing the whole file.
    """
    payloads: List[bytes] = []
    off = 0
    while off < len(data):
        if off + _FRAME.size > len(data):
            return payloads, True
        length, crc = _FRAME.unpack_from(data, off)
        start = off + _FRAME.size
        if start + length > len(data):
            return payloads, True
        payload = data[start : start + length]
        if not verify_block(payload, crc, algo):
            return payloads, True
        payloads.append(payload)
        off = start + length
    return payloads, False


def _seq_of(name: str, prefix: str, suffix: str) -> Optional[int]:
    if not (name.startswith(prefix) and name.endswith(suffix)):
        return None
    try:
        return int(name[len(prefix) : -len(suffix)])
    except ValueError:
        return None


class MasterStateStore:
    """Crash-safe persistence for the master's mutable state.

    Concurrency contract: ``mutation_lock`` (re-entrant) serializes
    every state mutation WITH its journal append, so the journal order
    equals the apply order and replay is deterministic. The servicer
    holds it across each mutating handler; ``snapshot`` holds it across
    collect + rotate so no mutation can land in a journal that the new
    snapshot already covers.
    """

    def __init__(
        self,
        state_dir: str,
        snapshot_interval: Optional[float] = None,
        snapshot_every_records: int = DEFAULT_SNAPSHOT_EVERY_RECORDS,
        keep_generations: int = 3,
    ):
        os.makedirs(state_dir, exist_ok=True)
        self.state_dir = state_dir
        self._algo = DEFAULT_ALGO
        self._lock = instrumented_lock("master.state_store", rlock=True)
        self._journal_file = None
        self._seq = 0
        self._records_since_snapshot = 0
        self._appended_records = 0
        self._last_snapshot_time = time.monotonic()
        if snapshot_interval is None:
            snapshot_interval = env_utils.STATE_SNAPSHOT_SECS.get(
                default=DEFAULT_SNAPSHOT_INTERVAL
            )
        self._snapshot_interval = snapshot_interval
        self._snapshot_every_records = snapshot_every_records
        self._keep_generations = max(1, keep_generations)
        #: True while recovery replays the journal: mutation paths that
        #: would normally append must not re-journal their own replay.
        self.replaying = False
        self.incarnation = 0
        self.last_recovery_stats: Dict[str, Any] = {}
        #: Optional ``(op, seconds)`` callback ("append" = journal record
        #: write, "fsync" = snapshot durability point). The master wires
        #: it to the observability plane's WAL histograms; always invoked
        #: OUTSIDE the mutation lock.
        self.timing_sink: Optional[Callable[[str, float], None]] = None

    @property
    def mutation_lock(self) -> threading.RLock:
        return self._lock

    # ---------------- incarnation fencing ----------------
    def next_incarnation(self) -> int:
        """Mint this boot's fencing epoch: read, bump, persist atomically."""
        path = os.path.join(self.state_dir, INCARNATION_FILE)
        current = 0
        try:
            with open(path) as f:
                current = int(f.read().strip())
        except (OSError, ValueError):
            pass
        self.incarnation = current + 1
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(self.incarnation))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return self.incarnation

    # ---------------- journal ----------------
    def append(self, record: Any):
        """Append one mutation record to the journal (write-ahead).

        No-op while replaying (replay must not re-journal itself) and
        before the first snapshot opened a journal (recovery window —
        the post-recovery snapshot covers that state).
        """
        dt = None
        with self._lock:
            if self._journal_file is None or self.replaying:
                return
            payload = pickle.dumps(record)
            t0 = time.perf_counter()
            self._journal_file.write(_frame(payload, self._algo))
            dt = time.perf_counter() - t0
            self._records_since_snapshot += 1
            self._appended_records += 1
        if dt is not None and self.timing_sink is not None:
            self.timing_sink("append", dt)

    def _open_journal(self, seq: int):
        if self._journal_file is not None:
            try:
                self._journal_file.close()
            except OSError:
                pass
        path = os.path.join(
            self.state_dir, f"{JOURNAL_PREFIX}{seq}{JOURNAL_SUFFIX}"
        )
        # Unbuffered append: a SIGKILL loses at most the record being
        # written (the torn tail recovery tolerates), never buffered
        # whole records.
        f = open(path, "ab", buffering=0)
        if f.tell() == 0:
            raw = self._algo.encode()
            f.write(_JOURNAL_MAGIC + bytes([len(raw)]) + raw)
        self._journal_file = f

    # ---------------- snapshots ----------------
    def snapshot(self, collect_fn: Callable[[], Dict[str, Any]]) -> int:
        """Cut a full snapshot and rotate the journal; returns its seq."""
        fsync_dt = None
        with self._lock:
            state = collect_fn()
            seq = self._seq + 1
            payload = pickle.dumps(state)
            path = os.path.join(
                self.state_dir, f"{SNAPSHOT_PREFIX}{seq}{SNAPSHOT_SUFFIX}"
            )
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:  # dtlint: disable=DT002 -- snapshot+rotate must be atomic w.r.t. appends; mutations block on the lock by design
                _write_header(f, _SNAP_MAGIC, self._algo)
                f.write(_frame(payload, self._algo))
                f.flush()
                t0 = time.perf_counter()
                os.fsync(f.fileno())
                fsync_dt = time.perf_counter() - t0
            os.replace(tmp, path)
            self._open_journal(seq)
            self._seq = seq
            self._records_since_snapshot = 0
            self._last_snapshot_time = time.monotonic()
            self._gc()
        if fsync_dt is not None and self.timing_sink is not None:
            self.timing_sink("fsync", fsync_dt)
        return seq

    def maybe_snapshot(self, collect_fn: Callable[[], Dict[str, Any]]):
        """Periodic-snapshot driver (called from the master's monitor
        loop): cut one when the interval elapsed or the journal grew
        past the record backstop."""
        with self._lock:
            if self._journal_file is None:
                return
            due = (
                time.monotonic() - self._last_snapshot_time
                >= self._snapshot_interval
                or self._records_since_snapshot
                >= self._snapshot_every_records
            )
            if not due or self._records_since_snapshot == 0:
                return
            self.snapshot(collect_fn)

    def _gc(self):
        """Drop generations older than the keep window (lock held)."""
        cutoff = self._seq - self._keep_generations
        for name in os.listdir(self.state_dir):
            base = name[: -len(QUARANTINE_SUFFIX)] if name.endswith(
                QUARANTINE_SUFFIX
            ) else name
            seq = _seq_of(base, SNAPSHOT_PREFIX, SNAPSHOT_SUFFIX)
            if seq is None:
                seq = _seq_of(base, JOURNAL_PREFIX, JOURNAL_SUFFIX)
            if seq is not None and seq <= cutoff:
                try:
                    os.remove(os.path.join(self.state_dir, name))
                except OSError:
                    pass

    # ---------------- recovery ----------------
    def _read_snapshot(self, path: str) -> Optional[Dict[str, Any]]:
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return None
        header = _read_header(data, _SNAP_MAGIC)
        if header is None:
            return None
        algo, off = header
        payloads, torn = _iter_frames(data[off:], algo)
        if torn or len(payloads) != 1:
            return None
        try:
            state = pickle.loads(payloads[0])
        except Exception:
            return None
        return state if isinstance(state, dict) else None

    def _read_journal(self, path: str) -> Tuple[List[Any], bool]:
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return [], False
        header = _read_header(data, _JOURNAL_MAGIC)
        if header is None:
            # Never written past the header (or not at all): empty.
            return [], bool(data)
        algo, off = header
        payloads, torn = _iter_frames(data[off:], algo)
        records = []
        for p in payloads:
            try:
                records.append(pickle.loads(p))
            except Exception:
                torn = True
                break
        return records, torn

    def recover(self) -> Tuple[Optional[Dict[str, Any]], List[Any]]:
        """Load the newest valid snapshot and the journal records after it.

        Corrupt snapshots are renamed ``*.corrupt`` and the scan falls
        back to the previous generation; that generation's journal CHAIN
        (its own journal plus every later one, in sequence order) is
        replayed on top, so no committed mutation is lost even when the
        newest snapshot is unreadable.
        """
        snaps: List[Tuple[int, str]] = []
        journals: Dict[int, str] = {}
        max_seq = 0
        for name in os.listdir(self.state_dir):
            seq = _seq_of(name, SNAPSHOT_PREFIX, SNAPSHOT_SUFFIX)
            if seq is not None:
                snaps.append((seq, os.path.join(self.state_dir, name)))
                max_seq = max(max_seq, seq)
                continue
            seq = _seq_of(name, JOURNAL_PREFIX, JOURNAL_SUFFIX)
            if seq is not None:
                journals[seq] = os.path.join(self.state_dir, name)
                max_seq = max(max_seq, seq)
        state = None
        base_seq = 0
        quarantined = []
        for seq, path in sorted(snaps, reverse=True):
            state = self._read_snapshot(path)
            if state is not None:
                base_seq = seq
                break
            quarantined.append(seq)
            try:
                os.replace(path, path + QUARANTINE_SUFFIX)
                logger.error(
                    "quarantined corrupt master snapshot %s; falling back "
                    "to the previous generation", os.path.basename(path),
                )
            except OSError:
                pass
        records: List[Any] = []
        torn_tails = 0
        replayed_journals = []
        for seq in sorted(journals):
            if seq < base_seq:
                continue
            recs, torn = self._read_journal(journals[seq])
            records.extend(recs)
            torn_tails += int(torn)
            replayed_journals.append(seq)
        self._seq = max_seq
        self.last_recovery_stats = {
            "snapshot_seq": base_seq if state is not None else None,
            "journals": replayed_journals,
            "journal_records": len(records),
            "torn_tails": torn_tails,
            "quarantined_snapshots": quarantined,
        }
        return state, records

    def close(self):
        with self._lock:
            if self._journal_file is not None:
                try:
                    self._journal_file.close()
                except OSError:
                    pass
                self._journal_file = None


def read_journal_records(state_dir: str) -> List[Tuple[int, Any]]:
    """Every journal record under ``state_dir`` as (journal_seq, record),
    in replay order. Tolerates torn tails like recovery does. Used by
    the chaos drills' shard-accounting assertions and ops tooling — NOT
    by recovery, which scopes the chain to the chosen snapshot."""
    store = MasterStateStore.__new__(MasterStateStore)
    out: List[Tuple[int, Any]] = []
    seqs = []
    for name in os.listdir(state_dir):
        seq = _seq_of(name, JOURNAL_PREFIX, JOURNAL_SUFFIX)
        if seq is not None:
            seqs.append((seq, os.path.join(state_dir, name)))
    for seq, path in sorted(seqs):
        records, _ = store._read_journal(path)
        out.extend((seq, r) for r in records)
    return out
