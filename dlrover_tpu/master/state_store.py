"""Durable master state: periodic snapshots + a crc-framed WAL.

The master owns every piece of job state that is not re-derivable from
the workers — shard cursors, kv-store contents, the node registry,
rendezvous round counters, the global step. Until this store existed, a
master relaunch rebuilt all of it blank and the job silently restarted
data from shard zero. The store applies the same durability recipe as
the flash-checkpoint stack (Orbax-style committed, versioned state —
see PAPERS.md): every mutation is journaled write-ahead into a
checksummed append-only file, a full snapshot is cut periodically, and
recovery replays the newest valid snapshot plus its journal chain,
tolerating a torn tail (the crash may land mid-append) and quarantining
corrupt snapshots exactly like the checkpoint restore fallback chain.

On-disk layout under ``state_dir``::

    incarnation          monotonic boot counter (fencing epoch)
    snapshot-<seq>.bin   full pickled state, one crc frame
    journal-<seq>.wal    crc frames appended since snapshot <seq>
    *.corrupt            quarantined snapshots (kept for postmortem)

Each journal frame is ``u32 length | u32 checksum | payload`` with the
checksum algorithm stamped once in the file header, reusing
:mod:`dlrover_tpu.common.checksum` so crc32c is used when available.
"""

import os
import pickle
import struct
import threading
import time
from contextlib import nullcontext
from typing import Any, Callable, ContextManager, Dict, List, Optional, Tuple

from dlrover_tpu.common.checksum import (
    DEFAULT_ALGO,
    block_checksum,
    verify_block,
)
from dlrover_tpu.common import env_utils
from dlrover_tpu.common.lockdep import instrumented_lock
from dlrover_tpu.common.log import logger

_FRAME = struct.Struct(">II")  # payload length, payload checksum
_SNAP_MAGIC = b"DLRS1"
_JOURNAL_MAGIC = b"DLRJ1"

SNAPSHOT_PREFIX = "snapshot-"
SNAPSHOT_SUFFIX = ".bin"
JOURNAL_PREFIX = "journal-"
JOURNAL_SUFFIX = ".wal"
QUARANTINE_SUFFIX = ".corrupt"
INCARNATION_FILE = "incarnation"

#: Seconds between periodic snapshots (journal rotation), and the
#: journal-growth backstop that forces one sooner.
SNAPSHOT_INTERVAL_ENV = env_utils.STATE_SNAPSHOT_SECS.name
DEFAULT_SNAPSHOT_INTERVAL = 30.0
DEFAULT_SNAPSHOT_EVERY_RECORDS = 2048


def _write_header(f, magic: bytes, algo: str):
    raw = algo.encode()
    f.write(magic + bytes([len(raw)]) + raw)


def _read_header(data: bytes, magic: bytes) -> Optional[Tuple[str, int]]:
    """Returns (algo, header_len), or None when the header is invalid."""
    if len(data) < len(magic) + 1 or not data.startswith(magic):
        return None
    algo_len = data[len(magic)]
    end = len(magic) + 1 + algo_len
    if len(data) < end:
        return None
    try:
        algo = data[len(magic) + 1 : end].decode()
    except UnicodeDecodeError:
        return None
    return algo, end


def _frame(payload: bytes, algo: str) -> bytes:
    return _FRAME.pack(len(payload), block_checksum(payload, algo)) + payload


def _iter_frames(data: bytes, algo: str) -> Tuple[List[bytes], bool]:
    """Parse crc frames; returns (payloads, torn_tail).

    A short or checksum-failing tail is the expected signature of a
    crash mid-append: everything before it is intact and usable, so the
    parse stops there instead of failing the whole file.
    """
    payloads: List[bytes] = []
    off = 0
    while off < len(data):
        if off + _FRAME.size > len(data):
            return payloads, True
        length, crc = _FRAME.unpack_from(data, off)
        start = off + _FRAME.size
        if start + length > len(data):
            return payloads, True
        payload = data[start : start + length]
        if not verify_block(payload, crc, algo):
            return payloads, True
        payloads.append(payload)
        off = start + length
    return payloads, False


def _whole_frames_end(data: bytes, off: int, algo: str) -> int:
    """Byte offset just past the last complete, checksum-valid frame in
    ``data`` at or after ``off`` (the prefix before ``off`` — a file
    header — is always kept). Replication segments are trimmed here so
    a standby only ever appends verifiable whole records; a torn tail
    (max_bytes cutting mid-frame, or a chaos truncation) parses to the
    same boundary on the receiving side."""
    if len(data) < off:
        return len(data)
    end = off
    while True:
        if end + _FRAME.size > len(data):
            return end
        length, crc = _FRAME.unpack_from(data, end)
        start = end + _FRAME.size
        if start + length > len(data):
            return end
        if not verify_block(data[start : start + length], crc, algo):
            return end
        end = start + length


class StoreFencedError(RuntimeError):
    """Raised by ``append`` after :meth:`MasterStateStore.fence`: a newer
    incarnation holds primacy and this store must refuse late writes."""


def _seq_of(name: str, prefix: str, suffix: str) -> Optional[int]:
    if not (name.startswith(prefix) and name.endswith(suffix)):
        return None
    try:
        return int(name[len(prefix) : -len(suffix)])
    except ValueError:
        return None


class MasterStateStore:
    """Crash-safe persistence for the master's mutable state.

    Concurrency contract: ``mutation_lock`` (re-entrant) serializes
    every state mutation WITH its journal append, so the journal order
    equals the apply order and replay is deterministic. The servicer
    holds the per-subsystem mutation shard for each mutating handler
    (append itself stays internally serialized, so the journal order
    within a subsystem equals its apply order); ``snapshot`` first
    enters the ``quiesce`` hook (the master wires it to "hold every
    mutation shard") and then holds the store lock across collect +
    rotate, so no mutation can land in a journal that the new snapshot
    already covers.

    Durability contract (``DLROVER_TPU_WAL_SYNC``):

    - ``group`` (default): ``append`` writes the record under the lock
      and returns a commit sequence; a dedicated commit thread fsyncs
      in batches and ``wait_durable(seq)`` blocks the caller on its
      batch's durability barrier. Write-ahead + exactly-once replay are
      byte-for-byte unchanged — only *when* os.fsync runs moves.
    - ``always``: one fsync per mutation, inline (the per-mutation
      baseline the bench compares against).
    - ``none``: never fsync the journal (page-cache durability only —
      the pre-group-commit legacy behavior; snapshots still fsync).
    """

    #: dtlint DT009: the durability barrier lives under the commit
    #: condition's own lock (the only nesting is store-lock ->
    #: commit-lock, see __init__). ``last_recovery_stats`` is written
    #: once by single-threaded recovery and read as a report, lock-free.
    GUARDED_BY = {
        "_commit_seq": "master.state_store.commit",
        "_durable_seq": "master.state_store.commit",
        "_durable_offset": "master.state_store.commit",
        "_fsync_count": "master.state_store.commit",
        "_commit_stop": "master.state_store.commit",
        "fenced": "master.state_store",
        "last_recovery_stats": None,
    }

    def __init__(
        self,
        state_dir: str,
        snapshot_interval: Optional[float] = None,
        snapshot_every_records: Optional[int] = None,
        keep_generations: int = 3,
        sync_policy: Optional[str] = None,
    ):
        os.makedirs(state_dir, exist_ok=True)
        self.state_dir = state_dir
        self._algo = DEFAULT_ALGO
        self._lock = instrumented_lock("master.state_store", rlock=True)
        self._journal_file = None
        self._journal_path: Optional[str] = None
        self._seq = 0
        self._records_since_snapshot = 0
        self._appended_records = 0
        self._last_snapshot_time = time.monotonic()
        if snapshot_interval is None:
            snapshot_interval = env_utils.STATE_SNAPSHOT_SECS.get(
                default=DEFAULT_SNAPSHOT_INTERVAL
            )
        self._snapshot_interval = snapshot_interval
        if snapshot_every_records is None:
            snapshot_every_records = env_utils.STATE_SNAPSHOT_RECORDS.get(
                default=DEFAULT_SNAPSHOT_EVERY_RECORDS
            )
        self._snapshot_every_records = snapshot_every_records
        self._keep_generations = max(1, keep_generations)
        #: True while recovery replays the journal: mutation paths that
        #: would normally append must not re-journal their own replay.
        self.replaying = False
        #: Non-empty once a newer incarnation fenced this store: every
        #: further append raises StoreFencedError, so a deposed primary
        #: cannot ack a mutation the promoted master never saw.
        self.fenced = ""
        self.incarnation = 0
        self.last_recovery_stats: Dict[str, Any] = {}
        #: Optional ``(op, seconds)`` callback ("append" = journal record
        #: write, "fsync" = journal/snapshot durability point). The
        #: master wires it to the observability plane's WAL histograms;
        #: always invoked OUTSIDE the mutation lock.
        self.timing_sink: Optional[Callable[[str, float], None]] = None
        #: Snapshot pre-lock: returns a context manager held across the
        #: whole snapshot. The master wires it to "acquire every
        #: servicer mutation shard", so a snapshot cannot capture state
        #: from a mutation whose journal record lands after rotation
        #: (which replay would then lose). Default: no-op.
        self.quiesce: Callable[[], ContextManager] = nullcontext
        if sync_policy is None:
            sync_policy = env_utils.WAL_SYNC.get()
        if sync_policy not in ("group", "always", "none"):
            logger.warning(
                "unknown WAL sync policy %r; using 'group'", sync_policy
            )
            sync_policy = "group"
        self.sync_policy = sync_policy
        self._group_window = max(0.0, env_utils.WAL_GROUP_WINDOW_S.get())
        # Group-commit plumbing. The condition has its own lock; the
        # only nesting ever used is store-lock -> commit-lock (append,
        # snapshot). The commit thread takes each alone, never nested.
        self._commit_cv = threading.Condition(
            instrumented_lock("master.state_store.commit")
        )
        self._commit_seq = 0        # records written to the journal
        self._durable_seq = 0       # records known fsynced (or covered)
        self._durable_offset = 0    # journal byte offset at the barrier
        self._fsync_count = 0       # journal fsyncs (not snapshot's)
        self._commit_stop = False
        self._commit_thread: Optional[threading.Thread] = None
        if self.sync_policy == "group":
            self._commit_thread = threading.Thread(
                target=self._commit_loop, name="wal-commit", daemon=True
            )
            self._commit_thread.start()

    @property
    def mutation_lock(self) -> threading.RLock:
        return self._lock

    # ---------------- incarnation fencing ----------------
    def next_incarnation(self) -> int:
        """Mint this boot's fencing epoch: read, bump, persist atomically."""
        path = os.path.join(self.state_dir, INCARNATION_FILE)
        current = 0
        try:
            with open(path) as f:
                current = int(f.read().strip())
        except (OSError, ValueError):
            pass
        self.incarnation = current + 1
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(self.incarnation))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return self.incarnation

    def set_incarnation(self, value: int) -> int:
        """Persist an externally-minted incarnation (the HA lease's
        fleet-wide counter) into this store's local file, so a plain
        relaunch from this ``state_dir`` mints above every promotion
        that happened elsewhere. Never moves backwards."""
        self.incarnation = max(self.incarnation, int(value))
        path = os.path.join(self.state_dir, INCARNATION_FILE)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(self.incarnation))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return self.incarnation

    def fence(self, reason: str = ""):
        """Refuse every future ``append``: a newer incarnation holds
        primacy. Extends PR-3 fencing from "clients detect the new
        master" to "two masters cannot both mutate" — the deposed
        primary may keep answering reads, but any mutating handler
        dies in its journal write and the client surfaces the error
        (or rides to the new endpoint)."""
        with self._lock:
            self.fenced = reason or "superseded"

    # ---------------- journal ----------------
    def append(self, record: Any) -> Optional[int]:
        """Append one mutation record to the journal (write-ahead).

        Returns the record's commit sequence — pass it to
        :meth:`wait_durable` for the group-commit durability barrier.
        Returns ``None`` when nothing was journaled: while replaying
        (replay must not re-journal itself) and before the first
        snapshot opened a journal (recovery window — the post-recovery
        snapshot covers that state).
        """
        dt = None
        fsync_dt = None
        with self._lock:
            if self.fenced:
                raise StoreFencedError(
                    f"master state store fenced ({self.fenced}): a newer "
                    "incarnation holds primacy; refusing late write"
                )
            if self._journal_file is None or self.replaying:
                return None
            f = self._journal_file
            payload = pickle.dumps(record)
            t0 = time.perf_counter()
            f.write(_frame(payload, self._algo))
            dt = time.perf_counter() - t0
            pos = f.tell()
            self._records_since_snapshot += 1
            self._appended_records += 1
            with self._commit_cv:
                self._commit_seq += 1
                seq = self._commit_seq
                if self.sync_policy == "group":
                    self._commit_cv.notify_all()
                elif self.sync_policy == "none":
                    # Legacy page-cache durability: the record counts as
                    # committed the moment write() returns.
                    self._durable_seq = seq
                    self._durable_offset = pos
        if self.sync_policy == "always":
            # Inline per-mutation fsync (the bench baseline arm),
            # deliberately OUTSIDE the store lock so it serializes the
            # caller, not every other appender.
            t0 = time.perf_counter()
            try:
                os.fsync(f.fileno())
            except (OSError, ValueError):
                # Rotated mid-flight: _open_journal fsynced the old
                # journal before closing it, so the record is durable.
                pass
            fsync_dt = time.perf_counter() - t0
            with self._commit_cv:
                self._durable_seq = max(self._durable_seq, seq)
                if self._journal_path is not None and f is self._journal_file:
                    self._durable_offset = max(self._durable_offset, pos)
                self._fsync_count += 1
                self._commit_cv.notify_all()
        if self.timing_sink is not None:
            if dt is not None:
                self.timing_sink("append", dt)
            if fsync_dt is not None:
                self.timing_sink("fsync", fsync_dt)
        return seq

    def wait_durable(self, seq: Optional[int], timeout: float = 30.0) -> bool:
        """Block until record ``seq`` is durable (batch-fsynced, or
        covered by a snapshot rotation). This is the group-commit
        durability barrier: a caller that journaled a mutation waits
        here AFTER releasing its mutation shard, so fsync latency never
        serializes unrelated subsystems. Returns ``False`` only on
        timeout; ``seq=None`` (nothing journaled) and non-group sync
        policies return immediately."""
        if seq is None or self.sync_policy != "group":
            return True
        deadline = time.monotonic() + timeout  # dtlint: disable=DT011 -- durability-wait timeout bookkeeping; during replay nothing is appended, seq is None and this path never runs
        with self._commit_cv:
            while self._durable_seq < seq and not self._commit_stop:
                remaining = deadline - time.monotonic()  # dtlint: disable=DT011 -- durability-wait timeout bookkeeping, never journaled
                if remaining <= 0:
                    return False
                self._commit_cv.wait(min(remaining, 1.0))
            # On shutdown close() fsyncs the journal tail itself.
            return True

    def _commit_loop(self):
        """Dedicated group-commit thread: one fsync covers every record
        appended since the previous barrier. Sleeps the accumulation
        window so concurrent appends coalesce, snapshots (file, target
        seq, byte offset) under the store lock, fsyncs OUTSIDE all
        locks, then advances the barrier and wakes the waiters."""
        while True:
            with self._commit_cv:
                while (
                    self._commit_seq <= self._durable_seq
                    and not self._commit_stop
                ):
                    self._commit_cv.wait(1.0)
                if self._commit_stop:
                    return
            if self._group_window > 0:
                time.sleep(self._group_window)  # dtlint: disable=DT003 -- deliberate accumulation window: coalescing appends into one fsync is the point
            with self._lock:
                f = self._journal_file
                path = self._journal_path
                if f is None:
                    continue
                with self._commit_cv:
                    target = self._commit_seq
                try:
                    pos = f.tell()
                except (OSError, ValueError):
                    continue
            t0 = time.perf_counter()
            try:
                os.fsync(f.fileno())
            except (OSError, ValueError):
                # Rotated and closed mid-batch: _open_journal fsynced
                # the old journal before closing, so target is durable.
                pass
            fsync_dt = time.perf_counter() - t0
            with self._commit_cv:
                self._durable_seq = max(self._durable_seq, target)
                if path == self._journal_path:
                    self._durable_offset = max(self._durable_offset, pos)
                self._fsync_count += 1
                self._commit_cv.notify_all()
            if self.timing_sink is not None:
                self.timing_sink("fsync", fsync_dt)

    def wal_status(self) -> Dict[str, Any]:
        """Group-commit counters for the fleet harness, the bench's
        fsyncs-per-mutation arms, and the torn-tail boundary tests
        (``durable_offset`` is the journal byte offset of the last
        durability barrier — truncating there simulates a power cut
        that loses exactly the un-fsynced batch tail)."""
        with self._commit_cv:
            return {
                "policy": self.sync_policy,
                "commit_seq": self._commit_seq,
                "durable_seq": self._durable_seq,
                "durable_offset": self._durable_offset,
                "fsync_count": self._fsync_count,
                "appended_records": self._appended_records,
                "journal_path": self._journal_path,
            }

    # ---------------- replication (hot standby) ----------------
    def replication_cursor(self) -> Tuple[int, int]:
        """(journal generation, durable byte offset): the stream cursor
        a standby caught up *right now* would hold."""
        with self._lock:
            seq = self._seq
            with self._commit_cv:
                return seq, self._durable_offset

    def read_segment(
        self, from_seq: int, from_offset: int, max_bytes: int = 1 << 20
    ) -> Dict[str, Any]:
        """One replication pull: durable journal bytes after the cursor.

        The cursor is (journal generation, byte offset into that
        journal file). Three answers, as a WalSegment-shaped dict:

        - ``kind="segment"``: raw bytes of the current journal in
          ``[from_offset, durable_offset)``, capped at ``max_bytes`` and
          trimmed to whole crc frames (offset 0 includes the file
          header). Empty when the standby is caught up. Only durable
          bytes ship — a segment is shippable once its group-commit
          barrier passed, so replica state never runs ahead of what the
          primary would itself recover.
        - ``kind="snapshot"``: full resync — the newest snapshot file's
          raw bytes plus a fresh cursor at the matching journal's
          start. Sent on bootstrap cursors and whenever the journal
          rotated underneath the cursor: rotation carries un-covered
          tail frames into the new journal, so resuming an old cursor
          against the new file would double-apply them.
        """
        with self._lock:
            seq = self._seq
            path = self._journal_path
            with self._commit_cv:
                durable_offset = self._durable_offset
                durable_seq = self._durable_seq
                commit_seq = self._commit_seq
            base = {
                "durable_seq": durable_seq,
                "commit_seq": commit_seq,
                "durable_offset": durable_offset,
            }
            if path is None:
                # Recovery window: no snapshot cut yet, nothing to ship.
                return dict(base, kind="segment", seq=0, offset=0,
                            data=b"", next_seq=0, next_offset=0)
            if from_seq != seq or from_offset > durable_offset:
                snap = os.path.join(
                    self.state_dir,
                    f"{SNAPSHOT_PREFIX}{seq}{SNAPSHOT_SUFFIX}",
                )
                try:
                    with open(snap, "rb") as sf:  # dtlint: disable=DT002 -- read-only resync pull under the store lock; a rotation mid-read would hand the standby a mixed-generation image
                        data = sf.read()
                except OSError:
                    data = b""
                return dict(base, kind="snapshot", seq=seq, offset=0,
                            data=data, next_seq=seq, next_offset=0)
            want = max(0, min(max_bytes, durable_offset - from_offset))
            try:
                with open(path, "rb") as jf:  # dtlint: disable=DT002 -- read-only replication pull under the store lock; rotation cannot move the file mid-read
                    jf.seek(from_offset)
                    data = jf.read(want)
            except OSError:
                data = b""
            hdr = len(_JOURNAL_MAGIC) + 1 + len(self._algo.encode())
            keep = _whole_frames_end(
                data, max(0, hdr - from_offset), self._algo
            )
            data = data[:keep]
            return dict(base, kind="segment", seq=seq,
                        offset=from_offset, data=data, next_seq=seq,
                        next_offset=from_offset + len(data))

    def _open_journal(self, seq: int):
        if self._journal_file is not None:
            try:
                # Keep the rotated-out journal durable before closing:
                # the corrupt-snapshot fallback chain replays it, and
                # the commit thread may still be mid-batch against it.
                os.fsync(self._journal_file.fileno())  # dtlint: disable=DT002 -- rotation must stay atomic with the snapshot cut; appends block by design
            except (OSError, ValueError):
                pass
            try:
                self._journal_file.close()
            except OSError:
                pass
        path = os.path.join(
            self.state_dir, f"{JOURNAL_PREFIX}{seq}{JOURNAL_SUFFIX}"
        )
        # Unbuffered append: a SIGKILL loses at most the record being
        # written (the torn tail recovery tolerates), never buffered
        # whole records.
        f = open(path, "ab", buffering=0)
        if f.tell() == 0:
            raw = self._algo.encode()
            f.write(_JOURNAL_MAGIC + bytes([len(raw)]) + raw)
        self._journal_file = f
        self._journal_path = path

    # ---------------- snapshots ----------------
    def snapshot(self, collect_fn: Callable[[], Dict[str, Any]]) -> int:
        """Cut a full snapshot and rotate the journal; returns its seq.

        Holds the ``quiesce`` hook (every servicer mutation shard) only
        for ``collect_fn`` — at fleet scale the expensive parts of a
        cut are pickling and fsyncing megabytes of state, and doing
        that under the quiesce used to stall every mutation for whole
        seconds. ``collect_fn`` also runs OUTSIDE the store lock:
        collectors take subsystem locks (task manager, job manager,
        rdzv), and those subsystems journal while holding their own
        lock, so calling them under the store lock would invert the
        canonical ``shard -> subsystem -> store`` order
        (lockdep-enforced).

        Atomicity is preserved by journal carry-forward instead of
        exclusion: any record appended after collect began — sharded
        mutations flowing while the snapshot serializes, plus
        journal-after-apply paths that never hold a shard (the rdzv
        state listener, the rescale coordinator, durable event sinks)
        — lands in the old journal past the carry mark, and rotation
        copies those bytes into the fresh journal so they replay on
        top of the snapshot. A sharded record past the mark cannot be
        reflected in the collected state (its shard was held by the
        quiesce during collect), so replay applies it exactly once;
        non-sharded records are replay-idempotent by contract (rdzv
        counters max-merge, rescale records are set-union/overwrite,
        a duplicated event costs one ring entry).
        """
        with self.quiesce():
            with self._lock:
                # Byte offset where the carry window opens: appends
                # landing past this offset are not reflected in the
                # collected state and must ride into the new journal.
                carry_path = self._journal_path
                carry_from = (
                    self._journal_file.tell()
                    if self._journal_file is not None else 0
                )
            state = collect_fn()
        # Serialize + persist outside quiesce AND store lock: mutations
        # keep flowing (into the old journal, past the carry mark)
        # while the heavy I/O runs. _seq only changes here, and the
        # monitor loop is the single snapshot caller.
        seq = self._seq + 1
        payload = pickle.dumps(state)
        path = os.path.join(
            self.state_dir, f"{SNAPSHOT_PREFIX}{seq}{SNAPSHOT_SUFFIX}"
        )
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            _write_header(f, _SNAP_MAGIC, self._algo)
            f.write(_frame(payload, self._algo))
            f.flush()
            t0 = time.perf_counter()
            os.fsync(f.fileno())
            fsync_dt = time.perf_counter() - t0
        with self._lock:
            os.replace(tmp, path)
            carry = b""
            if carry_path and self._journal_file is not None:
                # Whole frames only: appends are single unbuffered
                # writes under the store lock, which we hold from
                # here through rotation.
                with open(carry_path, "rb") as jf:  # dtlint: disable=DT002 -- carry read must be atomic with the rotation; appends block on the lock by design
                    jf.seek(carry_from)
                    carry = jf.read()
            self._open_journal(seq)
            if carry:
                self._journal_file.write(carry)
                # The old journal was fsynced at rotation but is
                # GC-eligible; the carried tail must be durable in
                # the journal that will actually replay.
                os.fsync(self._journal_file.fileno())  # dtlint: disable=DT002 -- carry tail must outlive the rotated-out journal's GC
            self._seq = seq
            self._records_since_snapshot = 0
            self._last_snapshot_time = time.monotonic()
            with self._commit_cv:
                # Every record journaled so far is covered by this
                # snapshot (or carried into its journal): rebase the
                # durability barrier onto the fresh journal and
                # release any group-commit waiters.
                self._durable_seq = self._commit_seq
                self._durable_offset = self._journal_file.tell()
                self._commit_cv.notify_all()
            self._gc()
        if self.timing_sink is not None:
            self.timing_sink("fsync", fsync_dt)
        return seq

    def maybe_snapshot(self, collect_fn: Callable[[], Dict[str, Any]]):
        """Periodic-snapshot driver (called from the master's monitor
        loop): cut one when the interval elapsed or the journal grew
        past the record backstop.

        The dueness check and the cut are deliberately NOT atomic:
        ``snapshot`` enters the quiesce hook (servicer mutation shards)
        BEFORE the store lock, so holding the store lock across the
        call would invert that order. The single monitor thread is the
        only caller, so the check cannot race another cut.
        """
        with self._lock:
            if self._journal_file is None:
                return
            due = (
                time.monotonic() - self._last_snapshot_time
                >= self._snapshot_interval
                or self._records_since_snapshot
                >= self._snapshot_every_records
            )
            if not due or self._records_since_snapshot == 0:
                return
        self.snapshot(collect_fn)

    def _gc(self):
        """Drop generations older than the keep window (lock held)."""
        cutoff = self._seq - self._keep_generations
        for name in os.listdir(self.state_dir):
            base = name[: -len(QUARANTINE_SUFFIX)] if name.endswith(
                QUARANTINE_SUFFIX
            ) else name
            seq = _seq_of(base, SNAPSHOT_PREFIX, SNAPSHOT_SUFFIX)
            if seq is None:
                seq = _seq_of(base, JOURNAL_PREFIX, JOURNAL_SUFFIX)
            if seq is not None and seq <= cutoff:
                try:
                    os.remove(os.path.join(self.state_dir, name))
                except OSError:
                    pass

    # ---------------- recovery ----------------
    def _read_snapshot(self, path: str) -> Optional[Dict[str, Any]]:
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return None
        header = _read_header(data, _SNAP_MAGIC)
        if header is None:
            return None
        algo, off = header
        payloads, torn = _iter_frames(data[off:], algo)
        if torn or len(payloads) != 1:
            return None
        try:
            state = pickle.loads(payloads[0])
        except Exception:
            return None
        return state if isinstance(state, dict) else None

    def _read_journal(self, path: str) -> Tuple[List[Any], bool]:
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return [], False
        header = _read_header(data, _JOURNAL_MAGIC)
        if header is None:
            # Never written past the header (or not at all): empty.
            return [], bool(data)
        algo, off = header
        payloads, torn = _iter_frames(data[off:], algo)
        records = []
        for p in payloads:
            try:
                records.append(pickle.loads(p))
            except Exception:
                torn = True
                break
        return records, torn

    def recover(self) -> Tuple[Optional[Dict[str, Any]], List[Any]]:
        """Load the newest valid snapshot and the journal records after it.

        Corrupt snapshots are renamed ``*.corrupt`` and the scan falls
        back to the previous generation; that generation's journal CHAIN
        (its own journal plus every later one, in sequence order) is
        replayed on top, so no committed mutation is lost even when the
        newest snapshot is unreadable.
        """
        snaps: List[Tuple[int, str]] = []
        journals: Dict[int, str] = {}
        max_seq = 0
        for name in os.listdir(self.state_dir):
            seq = _seq_of(name, SNAPSHOT_PREFIX, SNAPSHOT_SUFFIX)
            if seq is not None:
                snaps.append((seq, os.path.join(self.state_dir, name)))
                max_seq = max(max_seq, seq)
                continue
            seq = _seq_of(name, JOURNAL_PREFIX, JOURNAL_SUFFIX)
            if seq is not None:
                journals[seq] = os.path.join(self.state_dir, name)
                max_seq = max(max_seq, seq)
        state = None
        base_seq = 0
        quarantined = []
        for seq, path in sorted(snaps, reverse=True):
            state = self._read_snapshot(path)
            if state is not None:
                base_seq = seq
                break
            quarantined.append(seq)
            try:
                os.replace(path, path + QUARANTINE_SUFFIX)
                logger.error(
                    "quarantined corrupt master snapshot %s; falling back "
                    "to the previous generation", os.path.basename(path),
                )
            except OSError:
                pass
        records: List[Any] = []
        torn_tails = 0
        replayed_journals = []
        for seq in sorted(journals):
            if seq < base_seq:
                continue
            recs, torn = self._read_journal(journals[seq])
            records.extend(recs)
            torn_tails += int(torn)
            replayed_journals.append(seq)
        self._seq = max_seq
        self.last_recovery_stats = {
            "snapshot_seq": base_seq if state is not None else None,
            "journals": replayed_journals,
            "journal_records": len(records),
            "torn_tails": torn_tails,
            "quarantined_snapshots": quarantined,
        }
        return state, records

    def close(self):
        if self._commit_thread is not None:
            with self._commit_cv:
                self._commit_stop = True
                self._commit_cv.notify_all()
            self._commit_thread.join(timeout=2.0)
            self._commit_thread = None
        with self._lock:
            if self._journal_file is not None:
                if self.sync_policy != "none":
                    try:
                        # Final durability point: cover any batch tail
                        # the commit thread had not fsynced yet.
                        os.fsync(self._journal_file.fileno())  # dtlint: disable=DT002 -- shutdown path; no concurrent appenders remain
                    except (OSError, ValueError):
                        pass
                try:
                    self._journal_file.close()
                except OSError:
                    pass
                self._journal_file = None


def read_journal_records(state_dir: str) -> List[Tuple[int, Any]]:
    """Every journal record under ``state_dir`` as (journal_seq, record),
    in replay order. Tolerates torn tails like recovery does. Used by
    the chaos drills' shard-accounting assertions and ops tooling — NOT
    by recovery, which scopes the chain to the chosen snapshot."""
    store = MasterStateStore.__new__(MasterStateStore)
    out: List[Tuple[int, Any]] = []
    seqs = []
    for name in os.listdir(state_dir):
        seq = _seq_of(name, JOURNAL_PREFIX, JOURNAL_SUFFIX)
        if seq is not None:
            seqs.append((seq, os.path.join(state_dir, name)))
    for seq, path in sorted(seqs):
        records, _ = store._read_journal(path)
        out.extend((seq, r) for r in records)
    return out
