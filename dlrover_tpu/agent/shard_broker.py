"""Agent-side sub-lease broker: the only process that talks shards to
the master.

The broker turns the master's bulk leases into an agent-local data
plane: it keeps the shm fetch ring (see
:mod:`dlrover_tpu.common.shard_plane`) topped up with sub-leased
:class:`~dlrover_tpu.common.messages.ShardTask` frames, drains the
completion ring, and folds the acks into batched
:class:`~dlrover_tpu.common.messages.LeaseReport` RPCs on the coalesced
beat cadence. Steady state from the master's point of view: one
``LeaseRequest`` per a few hundred shards plus one ``LeaseReport`` per
batch — ~0.01 RPCs per shard instead of 2.

Failure shapes:

- *broker/agent dies*: the lease stops renewing, the master's TTL sweep
  re-dispatches every outstanding shard (at-least-once; frames stranded
  in the dead segment are re-trained elsewhere).
- *master fails over*: replayed grant records reproduce the lease table
  (see ``master/shard/lease_service.py``); the broker just keeps
  reporting. An ``unknown lease`` answer (expired or genuinely lost)
  means the master already requeued the remainder — the broker drops
  its local bookkeeping and leases afresh.
- *rescale requeue*: workers hand unprocessed shards back through the
  completion ring (``REQUEUE`` frames) and the broker re-offers them on
  the fetch ring — sub-leased shards return to the *agent*, never to
  the master (``ShardingClient.requeue_pending`` contract).
"""

import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from dlrover_tpu.common import env_utils
from dlrover_tpu.common.lockdep import instrumented_lock
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.shard_plane import (
    FRAME_DONE,
    FRAME_REQUEUE,
    FRAME_SUBSCRIBE,
    ShardPlane,
)


class _LeaseState:
    """One live lease: outstanding ids + the unflushed ack buffers."""

    def __init__(self, lease_id: int, dataset: str, ttl_s: float,
                 task_ids: Set[int]):
        self.lease_id = lease_id
        self.dataset = dataset
        self.ttl_s = ttl_s
        self.outstanding = set(task_ids)
        self.done: List[int] = []
        self.failed: List[int] = []
        self.last_report = time.monotonic()


class ShardLeaseBroker:
    """The agent's shard sub-lease loop (one background thread)."""

    #: dtlint DT009: lease table, per-dataset registry and the
    #: task->lease index all move together under the broker lock; the
    #: counters are single-writer stats (the loop thread), torn reads
    #: harmless.
    GUARDED_BY = {
        "_leases": "agent.shard_broker",
        "_datasets": "agent.shard_broker",
        "_task_lease": "agent.shard_broker",
        "leases_taken": None,
        "completions_flushed": None,
        "requeues": None,
    }

    def __init__(self, client, plane_name: str,
                 size_mb: Optional[int] = None,
                 batch: Optional[int] = None,
                 flush_s: Optional[float] = None,
                 low_water: Optional[int] = None,
                 poll_s: float = 0.02):
        self._client = client
        self._plane = ShardPlane(
            plane_name, create=True,
            size_mb=size_mb or env_utils.SHARD_LEASE_PLANE_MB.get(),
        )
        self._batch = batch or env_utils.SHARD_LEASE_BATCH.get()
        self._flush_s = (
            flush_s if flush_s is not None
            else env_utils.SHARD_LEASE_FLUSH_S.get()
        )
        self._low_water = (
            low_water if low_water is not None
            else env_utils.SHARD_LEASE_LOW_WATER.get()
        )
        self._poll_s = poll_s
        self._lock = instrumented_lock("agent.shard_broker")
        self._leases: Dict[int, _LeaseState] = {}
        # dataset -> {"finished": bool, "registered": params or None}
        self._datasets: Dict[str, Dict[str, Any]] = {}
        self._task_lease: Dict[Tuple[str, int], int] = {}
        self.leases_taken = 0
        self.completions_flushed = 0
        self.requeues = 0
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def plane_name(self) -> str:
        return self._plane.name

    # ---------------- lifecycle ----------------
    def start(self):
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="shard-broker",
        )
        self._thread.start()

    def stop(self):
        """Release every lease (hand outstanding shards back to the
        master for immediate re-dispatch) and tear the plane down."""
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self.release()
        self._plane.unlink()

    def add_dataset(self, name: str, register_params: Optional[dict] = None):
        """Start sub-leasing `name`. Normally self-discovered from
        worker SUBSCRIBE frames; explicit registration is for agents
        that know their datasets up front."""
        with self._lock:
            if name not in self._datasets:
                self._datasets[name] = {
                    "finished": False, "params": register_params,
                }

    def release(self) -> int:
        """Flush every buffered ack with ``release=True``: the master
        requeues whatever is still outstanding (shutdown / rescale
        teardown). Returns the number of leases released."""
        with self._lock:
            leases = list(self._leases.values())
            self._leases.clear()
            self._task_lease.clear()
        for lease in leases:
            try:
                self._client.report_lease(
                    lease.dataset, lease.lease_id, lease.done,
                    failed_ids=lease.failed, release=True,
                )
            except Exception as e:
                # The TTL sweep re-dispatches it anyway; release just
                # makes the handback prompt.
                logger.warning("lease %s release failed: %s",
                               lease.lease_id, e)
        return len(leases)

    # ---------------- the loop ----------------
    def _loop(self):
        while not self._stopped.wait(self._poll_s):
            try:
                self.pump()
            except Exception:
                logger.exception("shard broker iteration failed")

    def pump(self):
        """One broker iteration: drain acks, flush/renew, refill.
        Public so tests (and a future inline mode) can drive the broker
        without the thread."""
        self._drain()
        self._flush(force=False)
        self._refill()

    def _drain(self):
        for kind, data in self._plane.drain_completions():
            if kind == FRAME_SUBSCRIBE:
                name, params = data
                self.add_dataset(name, params)
            elif kind == FRAME_DONE:
                dataset, task_id, success = data
                with self._lock:
                    lid = self._task_lease.pop((dataset, task_id), None)
                    lease = self._leases.get(lid) if lid is not None else None
                    if lease is None:
                        # Its lease expired or was dropped: the master
                        # already requeued the shard, someone else will
                        # train it again (at-least-once, never lost).
                        continue
                    (lease.done if success else lease.failed).append(task_id)
                    lease.outstanding.discard(task_id)
            elif kind == FRAME_REQUEUE:
                task = data
                # Local re-dispatch: back onto the fetch ring, the
                # master never hears about it. The shard stays in its
                # lease's outstanding set, so TTL/agent-failure recovery
                # still covers it.
                self.requeues += 1
                if not self._plane.push_task(task):
                    # Ring full: fail it upward instead — the master
                    # requeues it for any worker.
                    with self._lock:
                        lid = self._task_lease.pop(
                            (task.dataset_name, task.task_id), None
                        )
                        lease = (
                            self._leases.get(lid) if lid is not None else None
                        )
                        if lease is not None:
                            lease.failed.append(task.task_id)
                            lease.outstanding.discard(task.task_id)

    def _flush(self, force: bool):
        now = time.monotonic()
        to_send: List[_LeaseState] = []
        with self._lock:
            for lease in self._leases.values():
                pending = len(lease.done) + len(lease.failed)
                renewal_due = (
                    lease.ttl_s > 0
                    and lease.outstanding
                    and now - lease.last_report > lease.ttl_s / 3
                )
                if (
                    force or pending >= self._batch
                    or (pending and now - lease.last_report > self._flush_s)
                    or renewal_due
                ):
                    to_send.append(lease)
        for lease in to_send:
            with self._lock:
                done, lease.done = lease.done, []
                failed, lease.failed = lease.failed, []
                lease.last_report = now
                empty = not lease.outstanding and not done and not failed
            if empty:
                with self._lock:
                    self._leases.pop(lease.lease_id, None)
                continue
            try:
                resp = self._client.report_lease(
                    lease.dataset, lease.lease_id, done, failed_ids=failed
                )
            except Exception as e:
                # Put the acks back; LeaseReport is journaled+deduped on
                # the master, so the retry lands exactly once.
                logger.warning("lease %s report failed, will retry: %s",
                               lease.lease_id, e)
                with self._lock:
                    lease.done = done + lease.done
                    lease.failed = failed + lease.failed
                continue
            self.completions_flushed += len(done) + len(failed)
            with self._lock:
                if resp is not None and not resp.success:
                    # Unknown lease: expired or lost — the master already
                    # requeued the remainder. Drop local bookkeeping;
                    # frames still in the ring ack into the void (their
                    # shards get re-trained elsewhere: at-least-once).
                    self._drop_lease(lease)
                elif not lease.outstanding and not lease.done \
                        and not lease.failed:
                    self._leases.pop(lease.lease_id, None)

    def _drop_lease(self, lease: _LeaseState):  # dtlint: holds(agent.shard_broker)
        self._leases.pop(lease.lease_id, None)
        for tid in lease.outstanding:
            self._task_lease.pop((lease.dataset, tid), None)
        lease.outstanding.clear()

    def _refill(self):
        if self._plane.task_backlog() >= self._low_water:
            return
        with self._lock:
            wanted = [
                (name, st) for name, st in self._datasets.items()
                if not st["finished"]
            ]
        for name, st in wanted:
            if st["params"] and not st.get("registered"):
                # Worker shipped the registration params through the
                # ring (fully RPC-free workers): register on its behalf.
                try:
                    self._client.report_dataset_shard_params(**st["params"])
                    st["registered"] = True
                except Exception as e:
                    logger.warning("dataset %s registration failed: %s",
                                   name, e)
                    continue
            try:
                lease = self._client.request_lease(name)
            except Exception as e:
                logger.warning("lease request for %s failed: %s", name, e)
                continue
            if lease is None:
                continue
            if lease.exists:
                state = _LeaseState(
                    lease.lease_id, name, lease.ttl_s,
                    {t.task_id for t in lease.tasks},
                )
                with self._lock:
                    self._leases[lease.lease_id] = state
                    for t in lease.tasks:
                        self._task_lease[(name, t.task_id)] = lease.lease_id
                self.leases_taken += 1
                for t in lease.tasks:
                    if not self._plane.push_task(t):
                        # Ring full mid-lease: hand the rest back now
                        # rather than strand it until the TTL.
                        with self._lock:
                            state.failed.append(t.task_id)
                            state.outstanding.discard(t.task_id)
                            self._task_lease.pop((name, t.task_id), None)
            elif lease.finished:
                with self._lock:
                    st["finished"] = True
                    all_done = all(
                        d["finished"] for d in self._datasets.values()
                    ) and not self._leases
                if all_done:
                    self._plane.set_finished()

    # ---------------- introspection ----------------
    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "live_leases": len(self._leases),
                "outstanding": sum(
                    len(x.outstanding) for x in self._leases.values()
                ),
                "leases_taken": self.leases_taken,
                "completions_flushed": self.completions_flushed,
                "requeues": self.requeues,
            }
