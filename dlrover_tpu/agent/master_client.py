"""Typed client for the master control plane.

Parity: reference ``elastic_agent/master_client.py`` — the singleton used by
both the agent and trainer processes for rendezvous, tasks, kv-store,
metrics, failures and sync barriers.
"""

import os
import threading
import time
from typing import Dict, Optional, Tuple

from dlrover_tpu.common import messages as m
from dlrover_tpu.common.backoff import ExponentialBackoff
from dlrover_tpu.common.constants import NodeEnv, NodeStatus
from dlrover_tpu.common.lockdep import instrumented_lock
from dlrover_tpu.common.log import logger
from dlrover_tpu.common import env_utils
from dlrover_tpu.common.rpc import RpcClient, endpoint_from_file


def _ha_endpoint_source():
    """Endpoint re-resolution callable for masters running under the
    hot-standby plane: when a standby promotes it publishes the new
    ``host:port`` to the shared endpoint file, and the transport
    re-reads it between retry rounds instead of hammering the dead
    primary's address. None when no HA dir/file is configured — the
    transport then keeps its fixed address."""
    path = env_utils.MASTER_HA_ENDPOINT_FILE.get()
    if not path:
        ha_dir = env_utils.MASTER_HA_DIR.get()
        if not ha_dir:
            return None
        from dlrover_tpu.master.ha import ENDPOINT_FILE
        path = os.path.join(ha_dir, ENDPOINT_FILE)
    return endpoint_from_file(path)


class MasterClient:
    _instance: Optional["MasterClient"] = None

    def __init__(self, master_addr: str, node_id: int = 0,
                 node_type: str = "worker"):
        self._client = RpcClient(
            master_addr, endpoint_source=_ha_endpoint_source())
        self._client.on_incarnation_change = self._on_master_incarnation_change
        self._node_id = node_id
        self._node_type = node_type
        self.master_addr = master_addr
        # Shard tasks fetched but not yet acked, keyed by
        # (dataset, task_id) — what a fenced client re-reports to the
        # new master incarnation so records it holds are neither
        # re-dispatched to someone else nor dropped.
        self._inflight_tasks: Dict[Tuple[str, int], m.ShardTask] = {}
        self._inflight_lock = instrumented_lock("master_client.inflight")
        self.fenced_count = 0

    # ---------------- singleton wiring ----------------
    @classmethod
    def singleton_instance(cls) -> "MasterClient":
        if cls._instance is None:
            addr = os.getenv(NodeEnv.MASTER_ADDR, "")
            if not addr:
                raise RuntimeError(
                    f"{NodeEnv.MASTER_ADDR} is not set; no master to talk to"
                )
            node_id = int(os.getenv(NodeEnv.NODE_ID, 0))
            cls._instance = cls(addr, node_id)
        return cls._instance

    @classmethod
    def reset(cls):
        cls._instance = None

    def _fill(self, req: m.BaseRequest) -> m.BaseRequest:
        req.node_id = self._node_id
        req.node_type = self._node_type
        return req

    def _call(self, req, timeout: Optional[float] = None):
        return self._client.call(self._fill(req), timeout=timeout)

    # ---------------- incarnation fencing ----------------
    def _on_master_incarnation_change(self, old: int, new: int):
        """The master restarted (response stamps jumped old -> new):
        re-register this node with the new incarnation and re-report
        every in-flight shard task. Invoked by the transport outside its
        lock, on the thread that observed the change; RPCs issued here
        are ordinary calls against the new master."""
        with self._inflight_lock:
            tasks = list(self._inflight_tasks.values())
        self.fenced_count += 1
        logger.warning(
            "master incarnation changed %s -> %s: re-registering node %s "
            "and re-reporting %s in-flight shard task(s)",
            old, new, self._node_id, len(tasks),
        )
        try:
            self.report_node_status(NodeStatus.RUNNING)
            self.report_heartbeat()
        except Exception as e:
            logger.warning("fencing re-registration failed: %s", e)
        for task in tasks:
            try:
                resp = self._call(m.TaskHoldReport(
                    dataset_name=task.dataset_name,
                    task_id=task.task_id,
                    start=task.start,
                    end=task.end,
                    shard_name=task.shard_name,
                    record_indices=task.record_indices,
                ))
                if resp is not None and not resp.success:
                    # The new master refused the hold (the task was
                    # already acked or re-dispatched): drop our claim so
                    # a later report_task doesn't double-account it.
                    logger.warning(
                        "master rejected hold of shard task %s/%s; "
                        "dropping the local claim",
                        task.dataset_name, task.task_id,
                    )
                    with self._inflight_lock:
                        self._inflight_tasks.pop(
                            (task.dataset_name, task.task_id), None
                        )
            except Exception as e:
                logger.warning(
                    "fencing hold-report of task %s/%s failed: %s",
                    task.dataset_name, task.task_id, e,
                )

    # ---------------- rendezvous ----------------
    def join_rendezvous(self, rdzv_name: str, node_rank: int,
                        local_world_size: int = 1) -> int:
        return self._call(
            m.JoinRendezvous(
                rdzv_name=rdzv_name,
                node_rank=node_rank,
                local_world_size=local_world_size,
            )
        )

    def get_comm_world(
        self, rdzv_name: str, node_rank: Optional[int] = None
    ) -> Tuple[int, int, Dict[int, int]]:
        rank = self._node_id if node_rank is None else node_rank
        resp: m.CommWorld = self._call(
            m.CommWorldRequest(rdzv_name=rdzv_name, node_rank=rank)
        )
        return resp.round, resp.group, resp.world

    def num_nodes_waiting(self, rdzv_name: str) -> int:
        return self._call(m.WaitingNodeNumRequest(rdzv_name=rdzv_name))

    def world_stale(self, rdzv_name: str, round_: int) -> bool:
        """True when the agent's current round was invalidated by a
        member death and survivors must re-form."""
        return bool(self._call(
            m.WorldStatusRequest(rdzv_name=rdzv_name, round=round_)
        ))

    # ---------------- live rescale ----------------
    def get_rescale_plan(self, rdzv_name: str, node_rank: int,
                         round_: int) -> m.RescalePlan:
        """Poll for an active in-place rescale plan covering this node
        (``plan.exists`` is False when there is none)."""
        return self._call(
            m.RescalePlanRequest(
                rdzv_name=rdzv_name, node_rank=node_rank, round=round_,
            )
        )

    def report_rescale_ack(self, plan_id: int, node_rank: int,
                           ok: bool, error: str = ""):
        return self._call(
            m.RescaleAck(
                plan_id=plan_id, node_rank=node_rank, ok=ok, error=error,
            )
        )

    def elect_ckpt_writer(self, group: str, epoch: int,
                          rank: int) -> m.CkptWriterLease:
        """Propose this replica as the checkpoint writer for `group`.

        First claimant wins; the returned lease names the elected owner
        (``lease.owner_rank``), which every proposer of the same
        (group, epoch) observes identically."""
        return self._call(
            m.CkptWriterElect(group=group, epoch=epoch, rank=rank)
        )

    # ---------------- preemption plane ----------------
    def report_preemption_notice(self, node_rank: int, deadline_ts: float,
                                 grace_s: float, source: str,
                                 reason: str = "") -> m.Response:
        """Report a known-ahead termination notice for this node.

        Journaled + deduped on the master: retries and multiple sources
        firing for the same node collapse to one armed notice."""
        return self._call(
            m.PreemptionNotice(
                node_rank=node_rank, deadline_ts=deadline_ts,
                grace_s=grace_s, source=source, reason=reason,
            )
        )

    def report_rdzv_params(self, min_nodes: int, max_nodes: int,
                           waiting_timeout: float, node_unit: int):
        return self._call(
            m.RendezvousParams(
                min_nodes=min_nodes,
                max_nodes=max_nodes,
                waiting_timeout=waiting_timeout,
                node_unit=node_unit,
            )
        )

    # ---------------- device check ----------------
    def report_check_result(self, node_rank: int, normal: bool,
                            elapsed: float, round_: int = 0):
        return self._call(
            m.DeviceCheckResult(
                node_rank=node_rank, normal=normal, elapsed_time=elapsed,
                round=round_,
            )
        )

    def get_fault_nodes(self):
        resp: m.DiagnosisResult = self._call(m.FaultNodesRequest())
        return resp.nodes, resp.done, resp.completed_rounds

    def get_stragglers(self):
        resp: m.DiagnosisResult = self._call(m.StragglersRequest())
        return resp.nodes, resp.done, resp.completed_rounds

    # ---------------- kv store ----------------
    def kv_store_set(self, key: str, value: bytes):
        return self._call(m.KVStoreSet(key=key, value=value))

    def kv_store_get(self, key: str) -> Optional[bytes]:
        return self._call(m.KVStoreGet(key=key))

    def kv_store_add(self, key: str, amount: int = 1) -> int:
        return self._call(m.KVStoreAdd(key=key, amount=amount))

    def kv_store_multi_get(self, keys) -> Dict[str, Optional[bytes]]:
        return self._call(m.KVStoreMultiGet(keys=tuple(keys)))

    def kv_store_delete(self, key: str):
        return self._call(m.KVStoreDelete(key=key))

    def kv_store_wait(self, keys, timeout: float = 300.0) -> Dict[str, bytes]:
        # Jittered backoff, not a fixed 0.1 s poll: every worker of the
        # job waits on the same barrier keys at the same moment, and
        # synchronized polling multiplies master RPC load by world size.
        deadline = time.monotonic() + timeout
        backoff = ExponentialBackoff(initial=0.05, max_delay=1.0)
        while True:
            values = self.kv_store_multi_get(keys)
            if all(v is not None for v in values.values()):
                return values
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            backoff.sleep(remaining)
        raise TimeoutError(f"kv keys {keys} not all set within {timeout}s")

    # ---------------- data sharding ----------------
    def report_dataset_shard_params(
        self,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
        storage_type: str = "table",
    ):
        return self._call(
            m.DatasetShardParams(
                dataset_name=dataset_name,
                dataset_size=dataset_size,
                shard_size=shard_size,
                num_epochs=num_epochs,
                shuffle=shuffle,
                storage_type=storage_type,
            )
        )

    def get_task(self, dataset_name: str) -> m.ShardTask:
        task = self._call(m.TaskRequest(dataset_name=dataset_name))
        if task is not None and task.exists:
            with self._inflight_lock:
                self._inflight_tasks[(task.dataset_name, task.task_id)] = task
        return task

    def report_task(self, dataset_name: str, task_id: int, success: bool = True):
        resp = self._call(
            m.TaskReport(dataset_name=dataset_name, task_id=task_id,
                         success=success)
        )
        with self._inflight_lock:
            self._inflight_tasks.pop((dataset_name, task_id), None)
        return resp

    def request_lease(self, dataset_name: str,
                      max_shards: int = 0) -> m.ShardLease:
        """Bulk-lease up to `max_shards` shards (0 = the master's
        per-dataset target). The agent broker's refill path — NOT
        tracked in _inflight_tasks: lease recovery is the master's TTL
        plus the broker re-leasing after an unknown-lease answer, not
        the per-task hold-report fencing."""
        return self._call(
            m.LeaseRequest(dataset_name=dataset_name, max_shards=max_shards)
        )

    def report_lease(self, dataset_name: str, lease_id: int, done_ids,
                     failed_ids=(), release: bool = False) -> m.Response:
        """Batched completion/failure acks for one lease; also the
        renewal (any report renews the TTL) and the release."""
        return self._call(
            m.LeaseReport(
                dataset_name=dataset_name, lease_id=lease_id,
                done_ids=list(done_ids), failed_ids=list(failed_ids),
                release=release,
            )
        )

    def get_shard_checkpoint(self, dataset_name: str) -> str:
        resp: m.ShardCheckpoint = self._call(
            m.ShardCheckpointRequest(dataset_name=dataset_name)
        )
        return resp.content

    def get_dataset_epoch(self, dataset_name: str) -> int:
        return self._call(m.DatasetEpochRequest(dataset_name=dataset_name))

    # ---------------- metrics / lifecycle ----------------
    def report_global_step(self, step: int, timestamp: float = 0.0):
        return self._call(m.GlobalStep(step=step, timestamp=timestamp or time.time()))

    def report_resource_stats(self, cpu_percent: float, used_memory_mb: int,
                              device_stats=None):
        return self._call(
            m.NodeResourceStats(
                cpu_percent=cpu_percent,
                used_memory_mb=used_memory_mb,
                device_stats=device_stats or [],
            )
        )

    def report_model_info(self, params_count: int, flops_per_step: float,
                          batch_size: int = 0, seq_len: int = 0, extra=None):
        return self._call(
            m.ModelInfo(
                params_count=params_count,
                flops_per_step=flops_per_step,
                batch_size=batch_size,
                seq_len=seq_len,
                extra=extra or {},
            )
        )

    def report_failure(self, error_data: str, level: str = "process_error",
                       restart_count: int = 0):
        try:
            return self._call(
                m.NodeFailure(
                    error_data=error_data, level=level,
                    restart_count=restart_count,
                )
            )
        except Exception as e:
            logger.warning("failed reporting failure to master: %s", e)

    def report_heartbeat(self):
        return self._call(m.NodeHeartbeat(timestamp=time.time()))

    def report_beat(self, step: int = -1, step_ts: float = 0.0,
                    probe: Optional[Dict] = None):
        """The coalesced periodic beat: heartbeat + newest step progress
        + latest probe sample in ONE RPC (see ``m.AgentBeat``)."""
        return self._call(m.AgentBeat(
            timestamp=time.time(), step=step, step_ts=step_ts,
            probe=probe or {},
        ))

    def report_events(self, events, timeout: Optional[float] = None):
        """Forward a batch of JobEvents to the master's event log."""
        return self._call(
            m.EventReport(events=list(events)), timeout=timeout
        )

    def report_node_status(self, status: str, exit_reason: str = ""):
        return self._call(
            m.NodeStatusReport(status=status, exit_reason=exit_reason)
        )

    # ---------------- sync ----------------
    def join_sync(self, sync_name: str, worker_rank: int = 0) -> bool:
        return self._call(m.SyncJoin(sync_name=sync_name, worker_rank=worker_rank))

    def sync_finished(self, sync_name: str) -> bool:
        return self._call(m.SyncFinish(sync_name=sync_name))

    def barrier(self, sync_name: str, notify: bool = False) -> bool:
        return self._call(m.SyncBarrierRequest(sync_name=sync_name, notify=notify))

    # ---------------- config / exit ----------------
    def get_paral_config(self) -> m.ParallelConfig:
        return self._call(m.ParallelConfigRequest())

    def report_job_exit(self, success: bool, reason: str = ""):
        return self._call(m.JobExitRequest(success=success, reason=reason))

    def close(self):
        self._client.close()


def build_master_client(master_addr: str = "", node_id: int = 0) -> MasterClient:
    if master_addr:
        return MasterClient(master_addr, node_id)
    return MasterClient.singleton_instance()
