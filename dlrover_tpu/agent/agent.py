"""The per-host elastic agent.

Capability parity with the reference's ``elastic_agent/torch/training.py``:

- ``ElasticLaunchConfig`` — launch knobs (min/max nodes, procs per node,
  device check, restarts, straggler policy).
- ``MasterRendezvousHandler`` — rendezvous *through the master* (join RPC +
  comm-world polling), not through a c10d store.
- ``ElasticTrainingAgent`` — spawns one training process per local worker,
  assigns global ranks from the frozen world, monitors processes, reports
  failures, flushes the shm flash-checkpoint on death, and restarts workers
  on failure or membership change.

TPU specifics: workers are JAX processes; the agent hands each one
``DLROVER_TPU_COORDINATOR_ADDR`` / ``PROCESS_ID`` / ``NUM_PROCESSES`` so the
trainer's :func:`dlrover_tpu.train.init_training` can call
``jax.distributed.initialize``. The JAX runtime cannot change world size
in-process, so every recovery is a worker restart + flash-checkpoint
restore — the same model the reference uses for NCCL.
"""

import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.chaos.sites import ChaosSite
from dlrover_tpu.common import env_utils
from dlrover_tpu.common.backoff import ExponentialBackoff
from dlrover_tpu.common.constants import (
    NodeEnv,
    NodeStatus,
    RendezvousName,
    TrainingExceptionLevel,
)
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.rpc import find_free_port
from dlrover_tpu.observability.events import (
    EventKind,
    emit,
    flush_events,
    set_identity,
)


@dataclass
class ElasticLaunchConfig:
    min_nodes: int = 1
    max_nodes: int = 1
    nproc_per_node: int = 1
    node_rank: int = 0
    job_name: str = "local-job"
    rdzv_timeout: float = 600.0
    waiting_timeout: float = 30.0
    monitor_interval: float = 1.0
    max_restarts: int = 3
    network_check: bool = False
    exclude_straggler: bool = False
    node_unit: int = 1
    log_dir: str = ""
    # Extra env vars for every worker.
    worker_env: Dict[str, str] = field(default_factory=dict)


class RendezvousOutcome:
    def __init__(self, round_: int, world: Dict[int, int], node_rank: int,
                 coordinator_addr: str):
        self.round = round_
        self.world = world  # node_rank -> local_world_size
        self.node_rank = node_rank
        self.coordinator_addr = coordinator_addr
        ranks = sorted(world)
        self.node_index = ranks.index(node_rank)
        self.num_nodes = len(ranks)
        self.world_size = sum(world.values())
        self.rank_offset = sum(world[r] for r in ranks[: self.node_index])

    def adopt(self, round_: int, world: Dict[int, int]):
        """Re-derive this outcome for a new round/world without a
        rendezvous (an in-place rescale transition)."""
        self.round = round_
        self.world = dict(world)
        ranks = sorted(self.world)
        self.node_index = ranks.index(self.node_rank)
        self.num_nodes = len(ranks)
        self.world_size = sum(self.world.values())
        self.rank_offset = sum(
            self.world[r] for r in ranks[: self.node_index]
        )


class MasterRendezvousHandler:
    """Rendezvous via master RPCs (parity: training.py:137)."""

    def __init__(self, client: MasterClient, rdzv_name: str, node_rank: int,
                 local_world_size: int, timeout: float = 600.0):
        self._client = client
        self._name = rdzv_name
        self._node_rank = node_rank
        self._local_world_size = local_world_size
        self._timeout = timeout

    def next_rendezvous(self) -> RendezvousOutcome:
        self._client.join_rendezvous(
            self._name, self._node_rank, self._local_world_size
        )
        deadline = time.monotonic() + self._timeout
        backoff = ExponentialBackoff(initial=0.1, max_delay=1.0)
        while time.monotonic() < deadline:
            round_, _, world = self._client.get_comm_world(
                self._name, self._node_rank
            )
            if world and self._node_rank in world:
                coordinator = self._setup_coordinator(round_, world)
                return RendezvousOutcome(
                    round_, world, self._node_rank, coordinator
                )
            if world and self._node_rank not in world:
                # Frozen without us (node_unit clipping): rejoin next round.
                self._client.join_rendezvous(
                    self._name, self._node_rank, self._local_world_size
                )
            backoff.sleep(deadline - time.monotonic())
        raise TimeoutError(
            f"rendezvous {self._name} did not complete within {self._timeout}s"
        )

    def _setup_coordinator(self, round_: int, world: Dict[int, int]) -> str:
        """The lowest node rank hosts the JAX coordinator; its address is
        published through the master kv-store, keyed by round."""
        key = f"coordinator/{self._name}/{round_}"
        first = sorted(world)[0]
        if self._node_rank == first:
            host = env_utils.HOST_IP.get()
            addr = f"{host}:{find_free_port()}"
            self._client.kv_store_set(key, addr.encode())
            return addr
        return self._client.kv_store_wait([key], timeout=60.0)[key].decode()


class WorkerSpec:
    def __init__(self, entrypoint: str, args: List[str]):
        self.entrypoint = entrypoint
        self.args = args


class ElasticTrainingAgent:
    """Spawn/supervise local training processes (parity: training.py:318)."""

    def __init__(self, config: ElasticLaunchConfig, spec: WorkerSpec,
                 client: Optional[MasterClient] = None):
        self._config = config
        self._spec = spec
        self._client = client or MasterClient.singleton_instance()
        self._workers: List[subprocess.Popen] = []
        self._restart_count = 0
        self._ckpt_saver = None  # wired by start_saver()
        self._stopped = threading.Event()
        # Heartbeat coalescing (DLROVER_TPU_AGENT_BEAT): monitors deposit
        # their newest observations here and the periodic beat folds them
        # into ONE AgentBeat RPC — at 10k agents the master sees one
        # request per agent per interval instead of three.
        self._beat_mode = env_utils.AGENT_BEAT.get()
        self._beat_lock = threading.Lock()
        self._beat_step: Tuple[int, float] = (-1, 0.0)
        self._beat_probe: Optional[Dict] = None

    # ---------------- checkpoint saver hook ----------------
    def start_saver(self):
        """Start the async flash-checkpoint saver thread in this process."""
        from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver

        AsyncCheckpointSaver.start_async_saving_ckpt(self._config.node_rank)

    def _save_shm_to_storage(self):
        from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver
        from dlrover_tpu.utils.tracing import get_tracer

        saver = AsyncCheckpointSaver.get_ckpt_saver()
        if saver is not None:
            try:
                with get_tracer().span("ckpt-crash-flush"):
                    saver.save_shm_to_storage()
            except Exception:
                logger.exception("flash-checkpoint crash flush failed")

    # ---------------- run loop ----------------
    def _note_step(self, step: int, ts: float):
        """TrainingMonitor sink: keep the newest observation for the
        next beat. Monotonic max — a restarted worker replaying earlier
        steps still refreshes the timestamp (liveness first)."""
        with self._beat_lock:
            self._beat_step = (max(step, self._beat_step[0]), ts)

    def _note_probe(self, sample: Dict):
        """LinkProbe sink: latest-wins — the straggler profile wants the
        current link state, not a backlog of stale samples."""
        with self._beat_lock:
            self._beat_probe = sample

    def _send_beat(self):
        with self._beat_lock:
            step, step_ts = self._beat_step
            probe = self._beat_probe
            # Clear after snapshot: a beat only carries step progress the
            # monitors observed since the last one, so the master's hang
            # detection still sees silence when workers stop writing
            # metrics (a sticky step would mask the hang forever).
            self._beat_step = (-1, 0.0)
            self._beat_probe = None
        self._client.report_beat(
            step=step, step_ts=step_ts, probe=probe or {}
        )

    def _start_heartbeats(self):
        """Agent-level liveness, independent of worker state: covers the
        stop-workers/re-rendezvous gaps so the master's heartbeat monitor
        never mistakes a restarting agent for a dead one."""
        from dlrover_tpu.common.periodic import PeriodicTask

        self._heartbeat_task = PeriodicTask(
            self._send_beat if self._beat_mode
            else self._client.report_heartbeat,
            self._config.monitor_interval,
            "agent-heartbeat",
        )
        self._heartbeat_task.start()

    def _start_monitors(self):
        from dlrover_tpu.agent.monitor import ResourceMonitor, TrainingMonitor
        from dlrover_tpu.common.constants import ConfigPath
        from dlrover_tpu.common.global_context import get_context

        interval = get_context().reporting_interval
        self._resource_monitor = ResourceMonitor(
            self._client, interval=interval
        )
        self._resource_monitor.start()
        # Workers drop per-step metrics here (train.report_training_metrics)
        # and the monitor forwards them — a job-unique default so stock
        # deployments get the liveness channel without any configuration.
        self._metrics_path = os.getenv(ConfigPath.ENV_RUNTIME_METRICS) or (
            os.path.join(
                ConfigPath.ROOT,
                f"runtime_metrics_{self._config.job_name}"
                f"_n{self._config.node_rank}.jsonl",
            )
        )
        self._training_monitor = TrainingMonitor(
            self._metrics_path, self._client,
            step_sink=self._note_step if self._beat_mode else None,
        )
        self._training_monitor.start()
        # The tuner loop only runs when auto-tuning is enabled (same gate
        # as the master's strategy generator): with it off, polling every
        # few seconds and pointing workers at a never-written file would
        # be pure overhead.
        self._config_tuner = None
        if get_context().auto_paral_tuning:
            from dlrover_tpu.agent.config_tuner import ParalConfigTuner

            self._config_tuner = ParalConfigTuner(self._client)
            self._config_tuner.start()
        # Continuous link telemetry (probe.link events feeding the
        # master's straggler detector); DLROVER_TPU_PROBE_INTERVAL=0
        # leaves it off.
        from dlrover_tpu.agent.device_check import LinkProbe

        self._link_probe = LinkProbe(
            self._client,
            sink=self._note_probe if self._beat_mode else None,
        )
        self._link_probe.start()
        # Shard-lease broker (DLROVER_TPU_SHARD_LEASE_PLANE): sub-leases
        # bulk shard grants to this node's workers over shm, so the
        # steady-state data path makes zero per-worker master RPCs.
        self._shard_broker = None
        plane_cfg = env_utils.SHARD_LEASE_PLANE.get()
        if plane_cfg:
            from dlrover_tpu.agent.shard_broker import ShardLeaseBroker

            # "auto" = a per-node name; anything else is used verbatim
            # (shared-host test jobs must not collide on the segment).
            plane_name = (
                f"shard_plane_{self._config.job_name}"
                f"_n{self._config.node_rank}"
                if plane_cfg == "auto" else plane_cfg
            )
            self._shard_broker = ShardLeaseBroker(self._client, plane_name)
            self._shard_broker.start()
        # Preemption watcher: notice sources -> journaled report + grace
        # flush, so the master can shrink in place before the kill.
        from dlrover_tpu.agent.preempt import PreemptionWatcher

        self._preempt_watcher = PreemptionWatcher(
            client=self._client,
            node_rank=self._config.node_rank,
            flush_fn=self._save_shm_to_storage,
            kill_fn=self._kill_all_workers,
        )
        self._preempt_watcher.start()

    def run(self) -> int:
        self._start_heartbeats()
        self._start_monitors()
        self._client.report_rdzv_params(
            self._config.min_nodes,
            self._config.max_nodes,
            self._config.waiting_timeout,
            self._config.node_unit,
        )
        if self._config.network_check:
            from dlrover_tpu.agent.device_check import run_device_check

            ok = run_device_check(self._config, self._client)
            if not ok:
                logger.error("device check flagged this node as faulty")
                self._client.report_node_status(
                    NodeStatus.FAILED, "hardware-error"
                )
                return 1
        self.start_saver()
        while self._restart_count <= self._config.max_restarts:
            outcome = self._rendezvous()
            self._start_workers(outcome)
            result = self._monitor_workers(outcome)
            self._stop_workers()
            if result == "succeeded":
                self._client.report_node_status(NodeStatus.SUCCEEDED)
                return 0
            if result == "failed":
                self._restart_count += 1
                logger.info(
                    "workers failed; restart %s/%s",
                    self._restart_count, self._config.max_restarts,
                )
            elif result == "membership_changed":
                logger.info("membership changed; re-forming rendezvous")
            elif result == "stopped":
                return 1
            from dlrover_tpu.utils.tracing import get_tracer

            get_tracer().instant(
                f"workers-{result}", restart=self._restart_count
            )
            get_tracer().export()  # no-op unless DLROVER_TPU_TRACE_FILE
            # Reaching here means the loop restarts the workers (failure
            # or membership change).
            emit(
                EventKind.WORKER_RESTART, reason=result,
                restart=self._restart_count,
            )
        self._client.report_node_status(NodeStatus.FAILED, "fatal-error")
        return 1

    def _rendezvous(self) -> RendezvousOutcome:
        from dlrover_tpu.utils.tracing import get_tracer

        handler = MasterRendezvousHandler(
            self._client,
            RendezvousName.TRAINING,
            self._config.node_rank,
            self._config.nproc_per_node,
            self._config.rdzv_timeout,
        )
        with get_tracer().span(
            "rendezvous", node_rank=self._config.node_rank,
            restart=self._restart_count,
        ):
            outcome = handler.next_rendezvous()
        logger.info(
            "rendezvous round %s: %s nodes, world size %s, coordinator %s",
            outcome.round, outcome.num_nodes, outcome.world_size,
            outcome.coordinator_addr,
        )
        return outcome

    def _worker_env(self, outcome: RendezvousOutcome, local_rank: int) -> Dict:
        from dlrover_tpu.common.constants import ConfigPath

        env = dict(os.environ)
        env.update(self._config.worker_env)
        if getattr(self, "_config_tuner", None) is not None:
            # Workers hot-reload the tuned parallel config from this file
            # (ElasticDataLoader.load_config).
            env[ConfigPath.ENV_PARAL_CONFIG] = self._config_tuner.path
        if getattr(self, "_metrics_path", ""):
            env[ConfigPath.ENV_RUNTIME_METRICS] = self._metrics_path
        if getattr(self, "_shard_broker", None) is not None:
            # Workers' ShardingClients attach to this node's sub-lease
            # plane instead of fetching shards over RPC.
            env[env_utils.SHARD_LEASE_PLANE.name] = (
                self._shard_broker.plane_name
            )
        env.update(
            {
                NodeEnv.JOB_NAME: self._config.job_name,
                NodeEnv.MASTER_ADDR: self._client.master_addr,
                NodeEnv.NODE_ID: str(self._config.node_rank),
                NodeEnv.NODE_RANK: str(self._config.node_rank),
                NodeEnv.NODE_NUM: str(outcome.num_nodes),
                NodeEnv.COORDINATOR_ADDR: outcome.coordinator_addr,
                NodeEnv.PROCESS_ID: str(outcome.rank_offset + local_rank),
                NodeEnv.NUM_PROCESSES: str(outcome.world_size),
                NodeEnv.LOCAL_RANK: str(local_rank),
                NodeEnv.LOCAL_WORLD_SIZE: str(self._config.nproc_per_node),
                NodeEnv.RESTART_COUNT: str(self._restart_count),
                # Restart-latency attribution: workers measure their
                # spawn->entry phase against this stamp.
                env_utils.SPAWN_TS.name: repr(time.time()),
            }
        )
        # One persistent compile cache per job: every incarnation of
        # every worker on this host reuses compiled executables instead
        # of replaying XLA compilation after a restart (goodput lever).
        from dlrover_tpu.common.env_utils import default_compile_cache_dir

        env.setdefault(
            env_utils.COMPILE_CACHE.name,
            default_compile_cache_dir(self._config.job_name),
        )
        return env

    def _start_workers(self, outcome: RendezvousOutcome):
        from dlrover_tpu.agent.forkserver import ForkServer

        self._workers = []
        use_forkserver = ForkServer.enabled()
        if use_forkserver:
            # The template imports jax with the AGENT's env; per-worker
            # overrides of import-sensitive vars would silently not
            # apply in a forked child — fall back to real spawns.
            sensitive = {
                k: v for k, v in self._config.worker_env.items()
                if k.startswith(("JAX_", "XLA_"))
            }
            if any(os.environ.get(k) != v for k, v in sensitive.items()):
                logger.warning(
                    "worker_env overrides import-sensitive vars %s; "
                    "disabling the fork server for this job",
                    sorted(sensitive),
                )
                use_forkserver = False
        if use_forkserver:
            if getattr(self, "_forkserver", None) is None:
                self._forkserver = ForkServer()
            try:
                # First start pays the preload (~2 s); every restart
                # after that forks in milliseconds — the spawn_s lever
                # of the restart-latency breakdown.
                self._forkserver.start()
            except Exception as e:
                logger.warning(
                    "fork server unavailable (%s); falling back to "
                    "subprocess spawn", e,
                )
                use_forkserver = False
        for local_rank in range(self._config.nproc_per_node):
            env = self._worker_env(outcome, local_rank)
            log_path = ""
            if self._config.log_dir:
                os.makedirs(self._config.log_dir, exist_ok=True)
                rank = outcome.rank_offset + local_rank
                log_path = os.path.join(
                    self._config.log_dir, f"rank{rank}.log"
                )
            if use_forkserver:
                proc = self._forkserver.spawn(
                    self._spec.entrypoint, self._spec.args, env,
                    log_path=log_path,
                )
            else:
                cmd = [
                    sys.executable, self._spec.entrypoint,
                    *self._spec.args,
                ]
                stdout = stderr = None
                if log_path:
                    stdout = open(log_path, "ab")
                    stderr = subprocess.STDOUT
                proc = subprocess.Popen(
                    cmd, env=env, stdout=stdout, stderr=stderr,
                    start_new_session=True,
                )
            self._workers.append(proc)
        self._client.report_node_status(NodeStatus.RUNNING)
        logger.info("started %s worker processes%s", len(self._workers),
                    " (fork server)" if use_forkserver else "")

    def _chaos_hit_workers(self):
        """Scripted worker kill/hang (chaos drills).

        Fires from the monitor loop so the resulting failure travels the
        REAL detection path: a killed worker is seen as a nonzero exit by
        the next poll; a hung (SIGSTOPped) one stops heartbeating and is
        flagged by the master's hang detection."""
        from dlrover_tpu.chaos.injector import fault_hit

        event = fault_hit(ChaosSite.AGENT_MONITOR)
        if event is None:
            return
        local_rank = int(event.args.get("rank", 0))
        if local_rank >= len(self._workers):
            return
        proc = self._workers[local_rank]
        if proc.poll() is not None:
            return
        try:
            pgid = os.getpgid(proc.pid)
        except ProcessLookupError:
            return
        if event.kind == "kill":
            logger.warning(
                "CHAOS: SIGKILL worker local_rank=%s pid=%s",
                local_rank, proc.pid,
            )
            os.killpg(pgid, signal.SIGKILL)
        elif event.kind == "hang":
            logger.warning(
                "CHAOS: SIGSTOP worker local_rank=%s pid=%s",
                local_rank, proc.pid,
            )
            os.killpg(pgid, signal.SIGSTOP)
            resume_after = float(event.args.get("resume_after_s", 0))
            if resume_after > 0:
                def _resume():
                    try:
                        os.killpg(pgid, signal.SIGCONT)
                    except (ProcessLookupError, PermissionError):
                        pass

                threading.Timer(resume_after, _resume).start()

    def _kill_all_workers(self):
        """Node-level kill, as the platform delivers it (chaos preempt
        drills): every live worker group gets SIGKILL at once."""
        logger.warning("CHAOS: preemption kill of all local workers")
        for proc in self._workers:
            if proc.poll() is not None:
                continue
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass

    def _monitor_workers(self, outcome: RendezvousOutcome) -> str:
        while not self._stopped.is_set():
            # Interruptible: stop() wakes the monitor immediately
            # instead of leaving it asleep for a full poll interval.
            if self._stopped.wait(self._config.monitor_interval):
                break
            self._chaos_hit_workers()
            codes = [p.poll() for p in self._workers]
            if any(c is not None and c != 0 for c in codes):
                failed = [
                    (i, c) for i, c in enumerate(codes) if c not in (None, 0)
                ]
                logger.error("worker processes failed: %s", failed)
                # An exit inside an active preemption window is the
                # announced kill, not a crash — the ledger/timeline
                # book it under preempt:handled instead.
                watcher = getattr(self, "_preempt_watcher", None)
                cause = (
                    "preempt" if watcher is not None and watcher.active
                    else "crash"
                )
                emit(
                    EventKind.WORKER_FAIL, codes=failed,
                    restart=self._restart_count, cause=cause,
                )
                self._client.report_failure(
                    f"worker exit codes {failed}",
                    level=TrainingExceptionLevel.PROCESS_ERROR,
                    restart_count=self._restart_count,
                )
                self._save_shm_to_storage()
                return "failed"
            if all(c == 0 for c in codes):
                return "succeeded"
            try:
                waiting = self._client.num_nodes_waiting(RendezvousName.TRAINING)
                stale = self._client.world_stale(
                    RendezvousName.TRAINING, outcome.round
                )
            except Exception as e:
                logger.warning("master unreachable from monitor loop: %s", e)
                continue
            if stale:
                if self._try_rescale_in_place(outcome):
                    continue
                # No in-place plan (rescale off, quorum lost, plan
                # aborted...): flush the shm checkpoint and re-form
                # without the dead member.
                logger.info(
                    "round %s invalidated by a member death; re-forming",
                    outcome.round,
                )
                self._save_shm_to_storage()
                return "membership_changed"
            if waiting > 0:
                # A joiner is normally absorbed by a grow plan (which
                # also stales our round); persistent waiters mean the
                # coordinator declined — full restart.
                if self._try_rescale_in_place(outcome):
                    continue
                self._save_shm_to_storage()
                return "membership_changed"
        return "stopped"

    def _try_rescale_in_place(self, outcome: RendezvousOutcome) -> bool:
        """Stale round: wait for a rescale plan covering this node and
        for it to settle. The workers apply the plan themselves (their
        trainers poll the same RPC and re-shard live state); the agent
        only keeps them alive and adopts the new round. Returns True
        when the transition completed and monitoring should continue."""
        if not env_utils.RESCALE.get():
            return False
        interval = max(0.05, env_utils.RESCALE_POLL_INTERVAL_S.get())
        deadline = (
            time.monotonic() + env_utils.RESCALE_APPLY_TIMEOUT_S.get()
        )
        # Short grace for the plan to appear: the coordinator issues it
        # in the same call that staled the round, so "no plan" after a
        # few polls means it declined (full-restart fallback).
        grace = time.monotonic() + max(3.0, 5 * interval)
        plan = None
        while not self._stopped.is_set() and time.monotonic() < deadline:
            try:
                found = self._client.get_rescale_plan(
                    RendezvousName.TRAINING, self._config.node_rank,
                    outcome.round,
                )
            except Exception as e:
                logger.warning("rescale plan poll failed: %s", e)
                return False
            if found.exists:
                plan = found
                break
            if time.monotonic() >= grace:
                return False
            self._stopped.wait(interval)
        if plan is None:
            return False
        logger.info(
            "rescale plan %s covers this node: world %s -> %s (round "
            "%s -> %s); holding workers for in-place transition",
            plan.plan_id, sorted(plan.old_world), sorted(plan.new_world),
            plan.old_round, plan.new_round,
        )
        from dlrover_tpu.agent.device_check import LinkProbe

        # The workers' d2d resharding transfers run inside this settle
        # window; bracket it so concurrent link-probe samples carry the
        # transfer flag (the master's link aggregator keeps them out of
        # its saturation baseline — transition traffic is not link
        # degradation).
        with LinkProbe.transfer_window():
            return self._settle_rescale_plan(outcome, plan, deadline, interval)

    def _settle_rescale_plan(self, outcome, plan, deadline, interval) -> bool:
        while not self._stopped.is_set() and time.monotonic() < deadline:
            if any(
                p.poll() not in (None, 0) for p in self._workers
            ):
                # A worker died mid-transition; let the failure path
                # handle it on the next monitor pass.
                return False
            try:
                aborted = self._client.world_stale(
                    RendezvousName.TRAINING, plan.new_round
                )
                still = self._client.get_rescale_plan(
                    RendezvousName.TRAINING, self._config.node_rank,
                    outcome.round,
                )
            except Exception as e:
                logger.warning("rescale settle poll failed: %s", e)
                return False
            if aborted:
                logger.info(
                    "rescale plan %s aborted (round %s stale); falling "
                    "back to full restart", plan.plan_id, plan.new_round,
                )
                return False
            if still.exists and still.plan_id != plan.plan_id:
                # Superseded by a newer transition mid-apply.
                plan = still
                continue
            if not still.exists:
                # The plan settled between the two reads above — but an
                # ABORT also makes it disappear, and the stale check ran
                # first, so re-read it before trusting "completed".
                try:
                    if self._client.world_stale(
                        RendezvousName.TRAINING, plan.new_round
                    ):
                        logger.info(
                            "rescale plan %s aborted as it settled; "
                            "falling back to full restart", plan.plan_id,
                        )
                        return False
                except Exception as e:
                    logger.warning("rescale settle re-check failed: %s", e)
                    return False
                # Settled and the new round is live: transition done.
                outcome.adopt(plan.new_round, plan.new_world)
                logger.info(
                    "in-place rescale complete: now round %s, %s nodes, "
                    "world size %s", outcome.round, outcome.num_nodes,
                    outcome.world_size,
                )
                return True
            self._stopped.wait(interval)
        return False

    def _stop_workers(self, timeout: float = 15.0):
        for p in self._workers:
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass
        deadline = time.monotonic() + timeout
        for p in self._workers:
            remaining = max(0.1, deadline - time.monotonic())
            try:
                p.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                p.wait()
        self._workers = []

    def stop(self):
        self._stopped.set()
        for attr in ("_heartbeat_task", "_resource_monitor",
                     "_training_monitor", "_config_tuner", "_link_probe",
                     "_preempt_watcher", "_shard_broker"):
            task = getattr(self, attr, None)
            if task is not None:
                task.stop()
        self._stop_workers()
        fs = getattr(self, "_forkserver", None)
        if fs is not None:
            fs.stop()
        # Drain the event-forwarding buffer so the master's timeline
        # gets this agent's final events before the process exits.
        flush_events()


def launch_agent(config: ElasticLaunchConfig, entrypoint: str,
                 args: List[str]) -> int:
    """Entry used by the CLI (parity: training.py:655)."""
    spec = WorkerSpec(entrypoint, args)
    client = MasterClient.singleton_instance()
    set_identity(config.node_rank, "agent")
    agent = ElasticTrainingAgent(config, spec, client)

    def _on_sigterm(signum, frame):
        logger.info("agent received signal %s; flushing checkpoint", signum)
        agent._save_shm_to_storage()
        agent.stop()
        sys.exit(143)

    signal.signal(signal.SIGTERM, _on_sigterm)
    try:
        return agent.run()
    finally:
        agent.stop()
