"""Agent-side preemption watcher: notice sources -> one armed report.

The infrastructure announces a preemption ahead of the kill — a notice
file appearing, an env flip, a metadata server flagging the VM, or (in
drills) the ``preempt.notice`` chaos site. This watcher polls every
source on one cadence and, the first time any of them fires:

1. reports a journaled ``PreemptionNotice`` RPC to the master (which
   hands off writer leases and shrinks at the next step boundary);
2. flushes the shm checkpoint snapshot to storage while the grace clock
   runs — the proactive twin of the crash flush, and it raises the
   saver's busy signal so the LinkProbe skips samples instead of racing
   the grace-window snapshot;
3. arms an ``active`` flag + deadline the agent monitor reads to
   classify a worker exit during the window as ``cause="preempt"``
   rather than a crash.

A notice whose deadline passes with the workers still alive is a false
alarm: the watcher disarms locally (the master cancels on its own
clock), so a much later crash is not misclassified as preemption. The
source that raised the false alarm is latched as *spent* until its
evidence clears — a notice file that keeps sitting on disk or an env
flag nobody unset must not re-arm a fresh notice/cancel cycle every
window; deleting and re-creating the file (a genuinely new notice)
re-arms.
"""

import threading
import time
from typing import Callable, Dict, Optional

from dlrover_tpu.chaos.injector import fault_hit
from dlrover_tpu.chaos.sites import ChaosSite
from dlrover_tpu.common import env_utils
from dlrover_tpu.common.log import logger


class PreemptionWatcher:
    """Polls the pluggable notice sources and arms exactly one notice.

    ``metadata_fn`` is the metadata-server shim: any callable returning
    ``None`` (no notice) or a dict with optional ``deadline_ts``,
    ``grace_s`` and ``reason`` keys — tests and real cloud metadata
    pollers plug in the same way. ``kill_fn`` (chaos drills) receives
    no arguments and must kill the local workers like the platform
    would.
    """

    def __init__(
        self,
        client=None,
        node_rank: int = 0,
        metadata_fn: Optional[Callable[[], Optional[Dict]]] = None,
        flush_fn: Optional[Callable[[], None]] = None,
        kill_fn: Optional[Callable[[], None]] = None,
    ):
        self._client = client
        self._node_rank = node_rank
        self._metadata_fn = metadata_fn
        self._flush_fn = flush_fn
        self._kill_fn = kill_fn
        self._lock = threading.Lock()
        self._active = False
        self._deadline_ts = 0.0
        self._source = ""
        self._task = None
        self._kill_timer: Optional[threading.Timer] = None
        # Sources whose notice already expired as a false alarm and
        # whose evidence has not cleared since (poll thread only).
        self._spent = set()

    # ---------------- lifecycle ----------------
    def start(self):
        from dlrover_tpu.common.periodic import PeriodicTask

        interval = env_utils.PREEMPT_POLL_INTERVAL_S.get()
        if not env_utils.PREEMPT.get() or interval <= 0:
            return
        self._task = PeriodicTask(
            self.poll_once, interval, "preempt-watcher"
        )
        self._task.start()

    def stop(self):
        if self._task is not None:
            self._task.stop()
            self._task = None
        if self._kill_timer is not None:
            self._kill_timer.cancel()
            self._kill_timer = None

    # ---------------- monitor-facing state ----------------
    @property
    def active(self) -> bool:
        """True while a reported notice's window is open — the agent
        monitor classifies a worker exit in this state as preemption."""
        with self._lock:
            if not self._active:
                return False
            slack = env_utils.PREEMPT_FALSE_ALARM_S.get()
            if (
                self._deadline_ts > 0
                and time.time() > self._deadline_ts + slack
            ):
                # Deadline long gone, workers still alive: false alarm.
                # Disarm so a later real crash is not misclassified;
                # the master cancels on its own clock. Latch the source
                # as spent so its lingering evidence (a notice file
                # still on disk, an env flag nobody unset) cannot churn
                # out a fresh notice/cancel cycle every window.
                self._active = False
                self._spent.add(self._source)
                return False
            return True

    @property
    def deadline_ts(self) -> float:
        with self._lock:
            return self._deadline_ts

    # ---------------- sources ----------------
    def _check_file(self) -> Optional[Dict]:
        path = env_utils.PREEMPT_NOTICE_FILE.get()
        if not path:
            return None
        notice: Dict = {"reason": "notice file"}
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line.startswith("deadline="):
                        notice["deadline_ts"] = float(
                            line.split("=", 1)[1]
                        )
        except FileNotFoundError:
            return None
        except (OSError, ValueError) as e:
            logger.warning("unreadable preempt notice file %s: %s", path, e)
        return notice

    def _check_env(self) -> Optional[Dict]:
        if env_utils.PREEMPT_NOW.get():
            return {"reason": "env flip"}
        return None

    def _check_metadata(self) -> Optional[Dict]:
        if self._metadata_fn is None:
            return None
        try:
            return self._metadata_fn()
        except Exception as e:
            logger.warning("preempt metadata shim failed: %s", e)
            return None

    def _check_chaos(self) -> Optional[Dict]:
        ev = fault_hit(
            ChaosSite.PREEMPT_NOTICE, detail=str(self._node_rank)
        )
        if ev is None or ev.kind != "notice":
            return None
        notice: Dict = {"reason": "chaos drill"}
        window = float(ev.args.get("window_s", 0))
        if window > 0:
            notice["grace_s"] = window
        kill_after = ev.args.get("kill_after_s")
        if kill_after is not None and float(kill_after) >= 0:
            notice["kill_after_s"] = float(kill_after)
        return notice

    # ---------------- the poll ----------------
    def poll_once(self):
        """One pass over every source; arms at most one notice."""
        if self.active:
            return
        for source, check in (
            ("file", self._check_file),
            ("env", self._check_env),
            ("metadata", self._check_metadata),
            ("chaos", self._check_chaos),
        ):
            notice = check()
            if notice is None:
                # Evidence cleared (file deleted, env unset): the next
                # time this source fires it is a genuinely new notice.
                self._spent.discard(source)
            elif source not in self._spent:
                self._arm(source, notice)
                return

    def _arm(self, source: str, notice: Dict):
        kill_after = notice.get("kill_after_s")
        if kill_after is not None and float(kill_after) <= 0:
            # Kill-before-window variant: the kill beats the notice, so
            # there is no window to use and nothing to report — this IS
            # the ordinary crash path, and nothing double-handles it.
            if self._kill_fn is not None:
                self._kill_fn()
            return
        grace = float(
            notice.get("grace_s", env_utils.PREEMPT_GRACE_S.get())
        )
        deadline = float(
            notice.get("deadline_ts", time.time() + grace)
        )
        with self._lock:
            self._active = True
            self._deadline_ts = deadline
            self._source = source
        logger.warning(
            "preemption notice (%s): %s; deadline in %.1fs",
            source, notice.get("reason", ""), deadline - time.time(),
        )
        if self._client is not None:
            try:
                self._client.report_preemption_notice(
                    node_rank=self._node_rank, deadline_ts=deadline,
                    grace_s=grace, source=source,
                    reason=str(notice.get("reason", "")),
                )
            except Exception:
                logger.exception("preemption notice report failed; the "
                                 "grace flush still runs locally")
        # The grace-window flush: the victim persists its own shm
        # snapshot while still alive, so survivors (and its eventual
        # replacement) restore without data loss even if the kill beats
        # the next checkpoint. Raises the saver busy signal -> the
        # LinkProbe skips rather than racing the snapshot.
        if self._flush_fn is not None:
            try:
                self._flush_fn()
            except Exception:
                logger.exception("preemption grace flush failed")
        if kill_after is not None and self._kill_fn is not None:
            self._kill_timer = threading.Timer(
                float(kill_after), self._kill_fn
            )
            self._kill_timer.daemon = True
            self._kill_timer.start()
