"""The spawned device-check exercise program.

Capability parity with the reference's
``dlrover/trainer/torch/run_network_check.py:44-111`` (timed allgather +
matmul benches, with ``MOCK_ERR_RANK``-style fault injection for tests),
lowered to JAX: a bf16 matmul exercises the chip's MXU and a repeated
cross-process allgather exercises ICI/DCN. The measured compute+collective
time is written to ``DLROVER_TPU_CHECK_RESULT_PATH`` for the master's
straggler rule; any crash/hang surfaces as a nonzero exit or a timeout in
the supervising agent.
"""

import os
import sys
import time

from dlrover_tpu.common import env_utils
from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.fsutil import atomic_write_text
from dlrover_tpu.common.log import logger

_MATMUL_SIZE = env_utils.CHECK_MATMUL_SIZE.get()
_ALLGATHER_ROUNDS = env_utils.CHECK_ALLGATHER_ROUNDS.get()


def main() -> int:
    node_rank = int(os.getenv(NodeEnv.NODE_RANK, "0"))
    mock_err = os.getenv(NodeEnv.MOCK_ERR_RANK, "")
    if mock_err and int(mock_err) == node_rank:
        logger.error("mock error injected on node %s", node_rank)
        return 1

    import jax
    import jax.numpy as jnp

    coordinator = os.getenv(NodeEnv.COORDINATOR_ADDR, "")
    num_processes = int(os.getenv(NodeEnv.NUM_PROCESSES, "1"))
    process_id = int(os.getenv(NodeEnv.PROCESS_ID, "0"))
    if num_processes > 1 and coordinator:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )

    start = time.monotonic()

    # MXU exercise: a chain of bf16 matmuls, timed after compile.
    key = jax.random.PRNGKey(node_rank)
    a = jax.random.normal(key, (_MATMUL_SIZE, _MATMUL_SIZE), jnp.bfloat16)

    @jax.jit
    def matmul_chain(x):
        for _ in range(4):
            x = x @ x / _MATMUL_SIZE
        return x

    matmul_chain(a).block_until_ready()  # compile
    t0 = time.monotonic()
    out = matmul_chain(a).block_until_ready()
    matmul_time = time.monotonic() - t0
    if not bool(jnp.isfinite(out.astype(jnp.float32)).all()):
        logger.error("matmul produced non-finite values")
        return 1

    # ICI/DCN exercise: repeated cross-process allgather.
    allgather_time = 0.0
    if num_processes > 1:
        from jax.experimental import multihost_utils

        payload = jnp.arange(1024, dtype=jnp.float32) + process_id
        multihost_utils.process_allgather(payload)  # compile/warm-up
        t0 = time.monotonic()
        for _ in range(_ALLGATHER_ROUNDS):
            gathered = multihost_utils.process_allgather(payload)
        allgather_time = time.monotonic() - t0
        if gathered.shape[0] != num_processes:
            logger.error("allgather returned wrong world size")
            return 1

    mock_straggler = os.getenv(NodeEnv.MOCK_STRAGGLER_RANK, "")
    if mock_straggler and int(mock_straggler) == node_rank:
        time.sleep(env_utils.MOCK_STRAGGLER_SECS.get())

    elapsed = time.monotonic() - start
    result_path = env_utils.CHECK_RESULT_PATH.get()
    if result_path:
        # Atomic: the agent polls this path and must never read a torn
        # result as "check passed in 0s".
        atomic_write_text(result_path, str(elapsed))
    logger.info(
        "device check ok: matmul %.4fs allgather %.4fs total %.4fs",
        matmul_time, allgather_time, elapsed,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
