"""Preloaded fork server: millisecond worker respawn.

The restart-latency breakdown (``train.bootstrap_timings``) shows a
relaunched worker spends ~2.2 s in ``spawn_s`` — CPython startup plus
importing jax/optax/numpy — dwarfing every other phase once the
persistent compile cache removes recompilation. The reference never
sees this because its unit of recovery is a pod; ours is a process, so
we can do what CPython's own ``multiprocessing`` forkserver does,
specialized for elastic training:

- the agent starts ONE template process per job
  (``python -m dlrover_tpu.agent.forkserver``) which imports the heavy
  modules and then blocks on a pipe — it never initializes a JAX
  backend, so forking it is safe (no XLA runtime threads to lose);
- each (re)start forks the template: the child gets the fully-imported
  interpreter for the price of a page-table copy (~10 ms), swaps in
  the worker env, redirects stdio, ``setsid()``s (the agent's
  process-group kill contract), and ``runpy``-executes the training
  script as ``__main__``;
- the template reaps its children and streams exit events back, so
  the agent-side :class:`ForkedWorker` handle offers the same
  ``poll``/``wait``/``pid`` surface as ``subprocess.Popen``.

Opt out with ``DLROVER_TPU_FORKSERVER=0`` (e.g. a worker whose
module-level imports must not run before env is set).
"""

import os
import pickle
import struct
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from dlrover_tpu.common import env_utils
from dlrover_tpu.common.backoff import ExponentialBackoff
from dlrover_tpu.common.log import logger

_LEN = struct.Struct(">I")

#: Modules the template pre-imports. jax alone is ~1.5-2 s; the rest
#: round out the trainer stack's import closure.
PRELOAD = (
    "jax",
    "jax.numpy",
    "numpy",
    "optax",
    "dlrover_tpu.train",
    "dlrover_tpu.train.checkpoint",
    "dlrover_tpu.train.data",
    "dlrover_tpu.agent.master_client",
)


def _write_msg(f, obj: Any):
    data = pickle.dumps(obj)
    f.write(_LEN.pack(len(data)) + data)
    f.flush()


def _read_msg(f) -> Any:
    header = f.read(_LEN.size)
    if len(header) < _LEN.size:
        raise EOFError("fork server pipe closed")
    (n,) = _LEN.unpack(header)
    data = f.read(n)
    if len(data) < n:
        raise EOFError("fork server pipe closed mid-message")
    return pickle.loads(data)


# --------------------------------------------------------------------
# template-process side
# --------------------------------------------------------------------

def _child_main(req: Dict):
    """Runs in the forked child: become the worker process."""
    os.setsid()  # agent kills by process group
    log_path = req.get("log_path")
    if log_path:
        fd = os.open(log_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                     0o644)
        os.dup2(fd, 1)
        os.dup2(fd, 2)
        os.close(fd)
    os.environ.clear()
    os.environ.update(req["env"])
    # The template imported dlrover_tpu.train long ago; this process's
    # spawn phase starts NOW or the breakdown reports template age.
    try:
        import dlrover_tpu.train as _t

        _t._ENTRY_TS = time.time()
    except Exception:  # dtlint: disable=DT001 -- forked worker boot must never die on a metrics stamp
        pass
    import runpy

    sys.argv = [req["entrypoint"], *req["args"]]
    try:
        runpy.run_path(req["entrypoint"], run_name="__main__")
    except SystemExit as e:
        code = e.code if isinstance(e.code, int) else (
            0 if e.code is None else 1
        )
        os._exit(code)
    except BaseException:
        import traceback

        traceback.print_exc()
        os._exit(1)
    os._exit(0)


def template_main():
    """Entry of ``python -m dlrover_tpu.agent.forkserver``."""
    for mod in PRELOAD:
        try:
            __import__(mod)
        except Exception as e:  # worker may not need it; keep going
            print(f"forkserver: preload {mod} failed: {e}",
                  file=sys.stderr, flush=True)
    # Move the agent protocol OFF fds 0/1: forked children inherit this
    # process's stdio, and a worker print into the protocol pipe would
    # corrupt it (and crash the worker once the pipe fd is gone). After
    # this, fd 0 is /dev/null and fd 1 aliases stderr, so a child with
    # no log_path still has sane, visible stdio.
    proto_in_fd = os.dup(0)
    proto_out_fd = os.dup(1)
    devnull = os.open(os.devnull, os.O_RDONLY)
    os.dup2(devnull, 0)
    os.close(devnull)
    os.dup2(2, 1)
    sys.stdout = os.fdopen(1, "w", buffering=1, closefd=False)
    inp = os.fdopen(proto_in_fd, "rb")
    out = os.fdopen(proto_out_fd, "wb")
    _write_msg(out, {"ready": True})
    children: List[int] = []
    import select

    while True:
        # Wake regularly to reap + report exits even with no requests.
        ready, _, _ = select.select([inp], [], [], 0.05)
        if ready:
            try:
                req = _read_msg(inp)
            except EOFError:
                break  # agent went away: exit (children are orphaned
                       # to init on purpose — the agent kills by pgid)
            if req.get("cmd") == "spawn":
                pid = os.fork()
                if pid == 0:
                    inp.close()   # protocol dups only — fds 0/1 are
                    out.close()   # already /dev/null + stderr alias
                    _child_main(req)  # never returns
                # Exits are keyed by the caller's unique token, not the
                # pid: pids recycle, tokens never do, and a token can't
                # collide with an exit event already in flight.
                children.append((pid, req.get("token")))
                _write_msg(out, {"pid": pid, "token": req.get("token")})
            elif req.get("cmd") == "stop":
                break
        for pid, token in list(children):
            done, status = os.waitpid(pid, os.WNOHANG)
            if done:
                children.remove((pid, token))
                code = (
                    os.waitstatus_to_exitcode(status)
                    if hasattr(os, "waitstatus_to_exitcode")
                    else (status >> 8)
                )
                _write_msg(out, {"exit": token, "code": code})


# --------------------------------------------------------------------
# agent side
# --------------------------------------------------------------------

class ForkedWorker:
    """Popen-shaped handle for a fork-server child."""

    def __init__(self, pid: int, token: int, server: "ForkServer"):
        self.pid = pid
        self.token = token
        self._server = server
        self.returncode: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self.returncode is None:
            code = self._server.exit_code(self.token)
            if code is None and not self._server.alive():
                # Template gone: exit events can never arrive and the
                # child (reparented to init) cannot be waited from
                # here. If it is gone too, report an unknown-code
                # sentinel (-9): the agent then restarts the
                # incarnation from its checkpoint — conservative but
                # correct even if the worker actually exited 0, and
                # strictly better than hanging.
                try:
                    os.kill(self.pid, 0)
                except ProcessLookupError:
                    code = -9
                except PermissionError:
                    pass
            self.returncode = code
        return self.returncode

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = None if timeout is None else (
            time.monotonic() + timeout
        )
        backoff = ExponentialBackoff(initial=0.01, max_delay=0.2)
        while self.poll() is None:
            if deadline is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired(
                    f"forked-worker-{self.pid}", timeout
                )
            backoff.sleep(
                None if deadline is None else deadline - time.monotonic()
            )
        return self.returncode


class ForkServer:
    """Agent-side handle: one preloaded template, many fast forks."""

    def __init__(self):
        self._proc: Optional[subprocess.Popen] = None
        self._lock = threading.Lock()
        self._exits: Dict[int, int] = {}   # spawn token -> exit code
        self._reader: Optional[threading.Thread] = None
        self._next_token = 0

    @staticmethod
    def enabled() -> bool:
        return env_utils.FORKSERVER.get()

    def start(self, timeout: float = 120.0):
        import select

        if self._proc is not None and self._proc.poll() is None:
            return
        # _exits survives a template restart: tokens are unique across
        # templates, and clearing would drop codes of already-exited
        # workers nobody polled yet.
        self._proc = subprocess.Popen(
            [sys.executable, "-m", "dlrover_tpu.agent.forkserver"],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            start_new_session=True,
        )
        t0 = time.perf_counter()
        # Bounded handshake: a template wedged in preload (hung import,
        # driver lock) must not hang the agent — the caller falls back
        # to plain subprocess spawn.
        ready, _, _ = select.select(
            [self._proc.stdout], [], [], timeout
        )
        if not ready:
            self._proc.kill()
            self._proc.wait()
            raise TimeoutError(
                f"fork server preload exceeded {timeout:.0f}s"
            )
        msg = _read_msg(self._proc.stdout)
        assert msg.get("ready"), f"fork server bad handshake: {msg}"
        logger.info(
            "fork server preloaded in %.1f s (pid %s)",
            time.perf_counter() - t0, self._proc.pid,
        )
        self._reader = threading.Thread(
            target=self._read_loop, name="forkserver-reader",
            args=(self._proc.stdout,), daemon=True,
        )
        self._pending: List[Dict] = []
        self._reader.start()

    def _read_loop(self, stdout):
        # `stdout` is captured at thread creation: after a template
        # restart the stale reader EOFs on the OLD pipe and exits
        # instead of racing the new template's reader for frames.
        while True:
            try:
                msg = _read_msg(stdout)
            except (EOFError, ValueError, OSError):
                return
            with self._lock:
                if "exit" in msg:
                    self._exits[msg["exit"]] = msg["code"]
                else:
                    self._pending.append(msg)

    def _take_reply(self, token: int, timeout: float = 30.0) -> Dict:
        # Match by the echoed token, not FIFO order: two threads calling
        # spawn() concurrently would otherwise each pop whichever reply
        # landed first and hand back the OTHER spawn's pid.
        deadline = time.monotonic() + timeout
        backoff = ExponentialBackoff(initial=0.002, max_delay=0.05)
        while time.monotonic() < deadline:
            with self._lock:
                for i, msg in enumerate(self._pending):
                    if msg.get("token") == token:
                        return self._pending.pop(i)
            backoff.sleep(deadline - time.monotonic())
        raise TimeoutError("fork server did not answer")

    def spawn(self, entrypoint: str, args: List[str], env: Dict[str, str],
              log_path: str = "") -> ForkedWorker:
        with self._lock:
            alive = self._proc is not None and self._proc.poll() is None
            self._next_token += 1
            token = self._next_token
        if not alive:
            self.start()
        _write_msg(self._proc.stdin, {
            "cmd": "spawn", "entrypoint": entrypoint,
            "args": list(args), "env": dict(env),
            "log_path": log_path or None,
            "token": token,
        })
        reply = self._take_reply(token)
        return ForkedWorker(int(reply["pid"]), token, self)

    def exit_code(self, token: int) -> Optional[int]:
        with self._lock:
            return self._exits.get(token)

    def alive(self) -> bool:
        with self._lock:
            return self._proc is not None and self._proc.poll() is None

    def stop(self):
        if self._proc is None:
            return
        try:
            _write_msg(self._proc.stdin, {"cmd": "stop"})
        except (OSError, ValueError):
            pass
        try:
            self._proc.wait(timeout=5.0)
        except subprocess.TimeoutExpired:
            self._proc.kill()
            self._proc.wait()
        self._proc = None


if __name__ == "__main__":
    template_main()
