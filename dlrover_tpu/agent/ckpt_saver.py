"""Agent-side async flash-checkpoint saver.

Parity: reference ``dlrover/python/elastic_agent/torch/ckpt_saver.py:344-785``
— the saver singleton is created on demand from a registration the trainer
pushes through the "factory" SharedQueue; a persist thread wakes on save
events, copies each local shard out of shared memory to storage under the
shard lock (dirty-write protection), writes per-shard done files, and the
committer node publishes the tracker file once every global shard is done.
``save_shm_to_storage`` is the crash/SIGTERM flush: it persists the *last
memory snapshot*, which is what makes every-step memory checkpoints
recoverable.

The agent process never imports jax — shards are opaque (meta, bytes) pairs.
"""

import concurrent.futures
import os
import pickle
import queue
import threading
import time
from typing import Dict, Optional

from dlrover_tpu.common import ckpt_persist, env_utils
from dlrover_tpu.common.backoff import ExponentialBackoff
from dlrover_tpu.common.ckpt_meta import (
    SaveEvent,
    SaverRegistration,
    ShardMeta,
    ckpt_event_queue,
    ckpt_factory_queue,
    ckpt_lock_name,
    ckpt_meta_dict,
)
from dlrover_tpu.common.comm import SharedDict, SharedLock, SharedQueue
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.shared_memory import SharedMemory
from dlrover_tpu.common.storage import get_checkpoint_storage
from dlrover_tpu.observability.events import EventKind, emit


class CommonDirCheckpointSaver:
    """Persists this node's local shards into per-step directories.

    One instance per agent; covers the replicated (1 global shard) and
    sharded (shard per process) layouts — which local ranks publish metadata
    decides what gets persisted, so no per-layout subclasses are needed
    (the reference splits DDP/Megatron/DeepSpeed savers mainly over torch
    file naming, ``ckpt_saver.py:979-1029``).
    """

    def __init__(self, reg: SaverRegistration, job: str = ""):
        self._job = job or env_utils.JOB_NAME.get()
        self._node_rank = reg.node_rank
        self.checkpoint_dir = reg.checkpoint_dir
        self.local_shard_num = reg.local_shard_num
        self.global_shard_num = reg.global_shard_num
        self.is_committer = reg.is_committer
        self.keep_latest = reg.keep_latest
        self.storage = get_checkpoint_storage()
        self._last_persisted = -1
        self._flush_lock = threading.Lock()
        self._stopped = False
        # Persist rounds currently in flight; the agent's LinkProbe
        # reads this (via `busy`) to stay off the disks and links while
        # checkpoint I/O is running.
        self._persisting = 0
        # Aggregated persist_shard stats of the current save round,
        # appended under _io_lock (shards persist concurrently).
        self._io_lock = threading.Lock()
        self._io_stats: list = []

        self._meta = SharedDict(
            ckpt_meta_dict(self._node_rank), create=True, job=self._job
        )
        self._events = SharedQueue(
            ckpt_event_queue(self._node_rank), create=True, job=self._job
        )
        self._locks = [
            SharedLock(ckpt_lock_name(self._node_rank, i), create=True,
                       job=self._job)
            for i in range(self.local_shard_num)
        ]
        self._persist_thread = threading.Thread(
            target=self._persist_loop, name="ckpt-persist", daemon=True
        )
        self._persist_thread.start()
        logger.info(
            "checkpoint saver up: dir=%s local_shards=%s global_shards=%s "
            "committer=%s",
            self.checkpoint_dir, self.local_shard_num, self.global_shard_num,
            self.is_committer,
        )

    def update(self, reg: SaverRegistration):
        """Re-registration after a worker restart (idempotent)."""
        self.checkpoint_dir = reg.checkpoint_dir
        self.global_shard_num = reg.global_shard_num
        self.keep_latest = reg.keep_latest
        if reg.local_shard_num > len(self._locks):
            for i in range(len(self._locks), reg.local_shard_num):
                self._locks.append(
                    SharedLock(ckpt_lock_name(self._node_rank, i),
                               create=True, job=self._job)
                )
            self.local_shard_num = reg.local_shard_num

    # ------------- persist machinery -------------
    def _persist_loop(self):
        backoff = ExponentialBackoff(initial=0.5, max_delay=5.0)
        while not self._stopped:
            try:
                event: SaveEvent = self._events.get(block=True, timeout=5.0)
            except queue.Empty:
                continue
            except Exception:
                if self._stopped:
                    return
                logger.exception("checkpoint event queue failure")
                backoff.sleep()
                continue
            backoff.reset()
            if event.kind == "stop":
                return
            try:
                self.save_step_checkpoint(event.step)
            except Exception:
                logger.exception("persist of step %s failed", event.step)

    def _local_metas(self) -> Dict[int, ShardMeta]:
        metas = {}
        for key, raw in self._meta.copy().items():
            if not key.startswith("rank_"):
                continue
            try:
                metas[int(key[5:])] = pickle.loads(raw)
            except Exception:
                logger.warning("undecodable checkpoint meta under %s", key)
        return metas

    def _persist_one(self, local_rank: int, meta: ShardMeta) -> bool:
        """Copy one shard out of shm under its lock. Refuses a dirty shard
        (writer mid-copy) — the lock is the consistency boundary (parity:
        ``ckpt_saver.py:590-594``)."""
        lock = self._locks[local_rank] if local_rank < len(self._locks) else None
        if lock is not None and not lock.acquire(blocking=True, timeout=30.0):
            logger.error(
                "shard %s lock busy >30s; skipping persist", local_rank
            )
            return False
        try:
            # Re-read the meta under the lock — the writer may have finished
            # a newer step between wake-up and acquisition. A different step
            # is skipped: its own save event will persist it (persisting it
            # here would scatter done files across step dirs).
            fresh = self._local_metas().get(local_rank, meta)
            if fresh.step != meta.step:
                logger.warning(
                    "shard %s moved from step %s to %s under persist; "
                    "skipping", local_rank, meta.step, fresh.step,
                )
                return False
            if not SharedMemory.exists(fresh.shm_name):
                logger.error("shm %s vanished; cannot persist", fresh.shm_name)
                return False
            shm = SharedMemory(fresh.shm_name)
            try:
                stats = ckpt_persist.persist_shard(
                    self.storage, self.checkpoint_dir, fresh, shm.buf
                )
                with self._io_lock:
                    self._io_stats.append(stats)
            finally:
                shm.close()
            return True
        finally:
            if lock is not None:
                lock.release()

    def save_step_checkpoint(self, step: int, commit_timeout: float = 600.0):
        """Persist every local shard at a consistent step >= `step`, then
        (committer only) publish the tracker once all global shards' done
        files exist.

        A shm buffer only holds its *latest* snapshot, so if the trainer has
        already staged a newer step by the time we wake up, we chase forward
        and persist that newer step instead of silently dropping the save
        (the reference logs an error and loses it, ``ckpt_saver.py:521``)."""
        if step <= self._last_persisted:
            # A previous event already chased past this step; re-copying a
            # multi-GB buffer for a step that is on disk is pure waste.
            return
        self._persisting += 1
        try:
            self._save_step_checkpoint(step, commit_timeout)
        finally:
            self._persisting -= 1

    @property
    def busy(self) -> bool:
        """True while a persist round is in flight (LinkProbe backs off)."""
        return self._persisting > 0

    def _save_step_checkpoint(self, step: int, commit_timeout: float):
        commit_at = -1
        persist_t0 = time.monotonic()
        with self._io_lock:
            self._io_stats = []
        # The commit wait (potentially minutes, multi-node) runs OUTSIDE
        # _flush_lock — the crash/SIGTERM flush must never queue behind it.
        with self._flush_lock:
            target = step
            prev_steps = None
            # Bounded wall clock: a local rank that died mid-memory-save
            # never advances, and the crash flush (which needs _flush_lock)
            # must not wait minutes behind it.
            deadline = time.monotonic() + 90.0
            while time.monotonic() < deadline:
                metas = self._wait_local_step(target, timeout=10.0)
                to_save = {
                    r: m for r, m in metas.items() if m.persist
                }
                if not to_save:
                    # This node owns no disk shard (replicated mode, node>0);
                    # still run the commit if we are the committer.
                    commit_at = target
                    break
                steps = {r: m.step for r, m in to_save.items()}
                if len(set(steps.values())) > 1:
                    if steps == prev_steps:
                        # No progress across a full wait: a writer is dead.
                        # Give up; the crash flush persists per-step groups.
                        logger.error(
                            "persist of step %s: shards stuck at %s",
                            step, steps,
                        )
                        break
                    prev_steps = steps
                    target = max(steps.values())  # wait for laggards, retry
                    continue
                target = next(iter(steps.values()))
                if target < step:
                    logger.error(
                        "persist of step %s: shards stuck at %s", step, target
                    )
                    break
                with concurrent.futures.ThreadPoolExecutor(
                    max_workers=max(1, len(to_save))
                ) as pool:
                    results = list(
                        pool.map(
                            lambda item: self._persist_one(item[0], item[1]),
                            to_save.items(),
                        )
                    )
                if all(results):
                    self._last_persisted = max(self._last_persisted, target)
                    commit_at = target
                    break
                # Some shard moved ahead mid-persist; chase the new step.
                target += 1
                prev_steps = None
            else:
                logger.error(
                    "persist of step %s never converged (trainer outpacing "
                    "saver)", step,
                )
        if commit_at >= 0:
            with self._io_lock:
                io_bytes = sum(s["bytes"] for s in self._io_stats)
                io_wall = max(
                    (s["persist_s"] for s in self._io_stats), default=0.0
                )
            emit(
                EventKind.CKPT_SAVE, step=commit_at,
                duration_s=round(time.monotonic() - persist_t0, 3),
                bytes=int(io_bytes),
                persist_mbps=round(io_bytes / io_wall / 1e6, 1)
                if io_wall > 0 else 0.0,
            )
            self._finish_step(commit_at, commit_timeout)

    def _wait_local_step(self, step: int, timeout: float) -> Dict[int, ShardMeta]:
        """Give laggard local ranks a moment to finish their memory copy of
        `step` before declaring them stale."""
        deadline = time.monotonic() + timeout
        backoff = ExponentialBackoff(initial=0.05, max_delay=0.5)
        while True:
            metas = self._local_metas()
            if metas and all(m.step >= step for m in metas.values()):
                return metas
            if time.monotonic() >= deadline:
                return metas
            backoff.sleep(deadline - time.monotonic())

    def _finish_step(self, step: int, commit_timeout: float):
        if self.is_committer:
            commit_t0 = time.monotonic()
            ok = ckpt_persist.commit_step(
                self.storage, self.checkpoint_dir, step,
                self.global_shard_num, timeout=commit_timeout,
            )
            if ok:
                emit(
                    EventKind.CKPT_COMMIT, step=step,
                    duration_s=round(time.monotonic() - commit_t0, 3),
                )
                ckpt_persist.gc_steps(
                    self.storage, self.checkpoint_dir, self.keep_latest
                )

    # ------------- crash / SIGTERM flush -------------
    def save_shm_to_storage(self, commit_timeout: float = 60.0):
        """Persist the last memory snapshot if it is newer than anything on
        disk. Called by the agent on worker failure, membership change,
        SIGTERM, and proactively inside a preemption grace window
        (parity: ``ckpt_saver.py:566``). Raises the same ``busy`` signal
        as the per-step persist path so the LinkProbe skips its samples
        instead of racing the flush for I/O bandwidth."""
        self._persisting += 1
        try:
            self._save_shm_to_storage(commit_timeout)
        finally:
            self._persisting -= 1

    def _save_shm_to_storage(self, commit_timeout: float):
        metas = {
            r: m for r, m in self._local_metas().items() if m.persist
        }
        steps = sorted({m.step for m in metas.values() if m.step >= 0})
        if not steps:
            logger.info("crash flush: no memory snapshot to persist")
            return
        tracker = ckpt_persist.read_tracker(self.storage, self.checkpoint_dir)
        if tracker is not None:
            steps = [s for s in steps if s > tracker]
        if not steps:
            logger.info("crash flush: storage is already up to date")
            return
        if len(steps) > 1:
            # A shard's buffer only holds its latest step, so a torn snapshot
            # (crash mid-memory-save) flushes each shard at its own step; the
            # commit of an incomplete step times out and is never published.
            logger.warning(
                "crash flush: local shards at different steps %s", steps
            )
        with self._flush_lock:
            for step in steps:
                group = {
                    r: m for r, m in metas.items() if m.step == step
                }
                logger.info(
                    "crash flush: persisting %s shard(s) of step %s",
                    len(group), step,
                )
                for local_rank, meta in group.items():
                    self._persist_one(local_rank, meta)
        # Commit outside _flush_lock; spend the real budget on the newest
        # step only (older torn steps almost never complete globally).
        for i, step in enumerate(steps):
            timeout = commit_timeout if i == len(steps) - 1 else 5.0
            self._finish_step(step, timeout)

    def stop(self):
        self._stopped = True
        try:
            self._events.put(SaveEvent(kind="stop"), timeout=1.0)
        except Exception:  # dtlint: disable=DT001 -- shutdown: the IPC queue may already be closed or full; stop() must not raise
            pass
        self._persist_thread.join(timeout=5.0)
        self._meta.close()
        self._events.close()
        for lock in self._locks:
            lock.close()


class AsyncCheckpointSaver:
    """Class-level facade the agent drives (parity: ``ckpt_saver.py:344``).

    ``start_async_saving_ckpt`` opens the factory queue and waits for a
    trainer registration; the saver singleton is created from the first one.
    """

    _saver: Optional[CommonDirCheckpointSaver] = None
    _factory: Optional[SharedQueue] = None
    _thread: Optional[threading.Thread] = None
    _lock = threading.Lock()
    _stopped = False

    @classmethod
    def start_async_saving_ckpt(cls, node_rank: int = 0):
        with cls._lock:
            if cls._thread is not None and cls._thread.is_alive():
                return
            cls._stopped = False
            cls._factory = SharedQueue(
                ckpt_factory_queue(node_rank), create=True
            )
            cls._thread = threading.Thread(
                target=cls._factory_loop, name="ckpt-factory", daemon=True
            )
            cls._thread.start()

    @classmethod
    def _factory_loop(cls):
        backoff = ExponentialBackoff(initial=0.5, max_delay=5.0)
        while not cls._stopped:
            try:
                reg: SaverRegistration = cls._factory.get(
                    block=True, timeout=5.0
                )
            except queue.Empty:
                continue
            except Exception:
                if cls._stopped:
                    return
                backoff.sleep()
                continue
            backoff.reset()
            with cls._lock:
                if cls._stopped:
                    # stop() won the lock between our dequeue and here; do
                    # not resurrect a saver nothing will ever stop.
                    return
                if cls._saver is None:
                    try:
                        cls._saver = CommonDirCheckpointSaver(reg)
                    except Exception:
                        logger.exception("failed to create checkpoint saver")
                else:
                    cls._saver.update(reg)

    @classmethod
    def get_ckpt_saver(cls) -> Optional[CommonDirCheckpointSaver]:
        return cls._saver

    @classmethod
    def stop(cls):
        cls._stopped = True
        with cls._lock:
            if cls._saver is not None:
                cls._saver.stop()
                cls._saver = None
            if cls._factory is not None:
                cls._factory.close()
                cls._factory = None
            cls._thread = None
