"""Agent-side async flash-checkpoint saver (full engine lands in train/checkpoint).

Placeholder registry so the agent can flush on crash before phase 4 wires
the real saver hierarchy.
"""

import threading
from typing import Optional


class AsyncCheckpointSaver:
    _saver: Optional["AsyncCheckpointSaver"] = None
    _lock = threading.Lock()

    @classmethod
    def start_async_saving_ckpt(cls):
        """Start the factory thread waiting for trainer saver registrations."""
        # Full implementation arrives with the flash-checkpoint phase.

    @classmethod
    def get_ckpt_saver(cls) -> Optional["AsyncCheckpointSaver"]:
        return cls._saver

    def save_shm_to_storage(self):
        """Persist the last shm snapshot (crash flush)."""
