"""Agent-side parallel-config tuner.

Parity: reference
``dlrover/python/elastic_agent/config/paral_config_tuner.py:31``
(``ParalConfigTuner``: poll the master's tuned config, drop it into the
file workers watch). The worker side is already wired: the agent exports
``ConfigPath.ENV_PARAL_CONFIG`` to every worker and
``ElasticDataLoader.load_config`` hot-reloads batch size at batch
boundaries when the file's version advances.
"""

import json
import os
from typing import Optional

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common.constants import ConfigPath, NodeEnv
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.periodic import PeriodicTask


class ParalConfigTuner:
    def __init__(self, client: Optional[MasterClient] = None,
                 path: Optional[str] = None, interval: float = 5.0):
        self._client = client or MasterClient.singleton_instance()
        job = os.getenv(NodeEnv.JOB_NAME, "local-job")
        node = os.getenv(NodeEnv.NODE_RANK, "0")
        self.path = path or os.path.join(
            ConfigPath.ROOT, f"paral_config_{job}_n{node}.json"
        )
        self._version = 0
        self._task = PeriodicTask(
            self._poll_quiet, interval, "paral-config-tuner"
        )

    def poll_once(self) -> bool:
        """Fetch the master's config; write the worker file when its
        version advanced. Returns True when a new config landed."""
        config = self._client.get_paral_config()
        if config is None or config.version <= self._version:
            return False
        self._version = config.version
        payload = {
            "version": config.version,
            "dataloader": dict(config.dataloader),
            "mesh": dict(config.mesh),
        }
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        tmp = f"{self.path}.tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, self.path)  # atomic: workers never read half a file
        logger.info("tuned parallel config v%s -> %s",
                    config.version, self.path)
        return True

    def _poll_quiet(self):
        self.poll_once()

    def start(self):
        self._task.start()

    def stop(self):
        self._task.stop()
