"""Agent-side monitors: node resources + training progress.

Parity: reference ``dlrover/python/elastic_agent/monitor/resource.py:90``
(``ResourceMonitor``: psutil CPU/memory + GPU stats reported to the
master on a timer) and ``monitor/training.py:79`` (``TorchTrainingMonitor``:
reads the per-step metrics file workers drop and reports the global step).
TPU specifics: device stats come from the *worker's* JAX client (the agent
process holds no TPU), so workers append them to the metrics file and the
training monitor forwards them with the step report.
"""

import json
import os
from typing import Dict, List, Optional

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.periodic import PeriodicTask


class ResourceMonitor:
    """Report the node's resource usage to the master on a timer."""

    def __init__(self, client: Optional[MasterClient] = None,
                 interval: float = 15.0):
        self._client = client or MasterClient.singleton_instance()
        self._pid = os.getpid()
        # psutil Process objects must be CACHED: cpu_percent(interval=None)
        # diffs against per-instance state, so a fresh instance always
        # reports 0.0.
        self._procs: Dict[int, object] = {}
        self._task = PeriodicTask(
            self.report_once, interval, "resource-monitor"
        )
        self._tree_stats()  # prime the CPU counters

    def start(self):
        self._task.start()

    def stop(self):
        self._task.stop()

    def _tree_stats(self) -> Dict:
        """CPU% and RSS of the agent's process tree (agent + workers)."""
        try:
            import psutil
        except ImportError:  # monitoring is best-effort, never fatal
            return {"cpu_percent": 0.0, "used_memory_mb": 0}

        try:
            root = self._procs.get(self._pid)
            if root is None:
                root = psutil.Process(self._pid)
                self._procs[self._pid] = root
            current = {self._pid: root}
            for child in root.children(recursive=True):
                current[child.pid] = self._procs.get(child.pid, child)
        except psutil.Error:
            return {"cpu_percent": 0.0, "used_memory_mb": 0}
        self._procs = current
        cpu = 0.0
        rss = 0
        for p in current.values():
            try:
                cpu += p.cpu_percent(interval=None)
                rss += p.memory_info().rss
            except psutil.Error:
                continue
        return {"cpu_percent": cpu, "used_memory_mb": rss // (1024 * 1024)}

    def report_once(self):
        stats = self._tree_stats()
        self._client.report_resource_stats(
            cpu_percent=stats["cpu_percent"],
            used_memory_mb=stats["used_memory_mb"],
            device_stats=self._device_stats(),
        )

    def _device_stats(self) -> List[Dict]:
        """Host-visible accelerator stats, best effort: the agent process
        does not own the TPU client, so this only reports what the
        platform exposes without initializing a backend."""
        return []


class TrainingMonitor:
    """Forward worker-dropped training metrics to the master.

    Workers append JSON lines ``{"step": N, "timestamp": T, ...}`` to the
    metrics file (``ConfigPath.ENV_RUNTIME_METRICS``, written via
    :func:`dlrover_tpu.train.report_training_metrics`); this monitor tails
    it and reports the newest step — so trainers that never link the
    master client still feed the speed monitor and hang detection.

    Every batch of new records triggers a report, even when the step did
    not advance past a previous incarnation's (a worker restarted from a
    checkpoint replays earlier steps): the report is a *liveness* signal
    for hang detection first, a progress counter second.
    """

    def __init__(self, metrics_path: str,
                 client: Optional[MasterClient] = None,
                 interval: float = 5.0,
                 step_sink=None):
        self._path = metrics_path
        self._client = client or MasterClient.singleton_instance()
        self._offset = 0
        # Optional (step, ts) sink: with heartbeat coalescing on, the
        # agent collects steps here and folds them into its periodic
        # AgentBeat instead of a dedicated GlobalStep RPC per tail.
        self._step_sink = step_sink
        self._task = PeriodicTask(
            self.report_once, interval, "training-monitor"
        )

    def start(self):
        self._task.start()

    def stop(self):
        self._task.stop()

    def report_once(self):
        try:
            f = open(self._path)
        except FileNotFoundError:
            return
        with f:
            if os.fstat(f.fileno()).st_size < self._offset:
                self._offset = 0  # file was rotated: re-tail from the start
            f.seek(self._offset)
            lines = f.readlines()
            self._offset = f.tell()
        newest = None
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "step" in rec:
                newest = rec
        if newest is not None:
            if self._step_sink is not None:
                self._step_sink(
                    int(newest["step"]), float(newest.get("timestamp", 0.0))
                )
            else:
                self._client.report_global_step(
                    int(newest["step"]), float(newest.get("timestamp", 0.0))
                )
            # Workers may attach device stats (the agent process holds no
            # TPU client, so this is the only channel for them). They ride
            # their own report — a zeroed cpu/mem report would stomp the
            # ResourceMonitor's real numbers, so the servicer routes
            # device-only reports to the collector's device channel.
            if newest.get("device_stats"):
                self._client.report_resource_stats(
                    cpu_percent=-1.0, used_memory_mb=-1,
                    device_stats=newest["device_stats"],
                )
