"""Pre-flight device/ICI check (agent side).

Capability parity with the reference's ``NetworkCheckElasticAgent``
(``elastic_agent/torch/training.py:767-906``): before training starts, the
agent joins the master's device-check rendezvous, the master pairs nodes
into small groups, and every group runs a timed collective + matmul
exercise in a spawned process (:mod:`dlrover_tpu.agent.run_device_check`).
Results go back to the master, whose
:class:`~dlrover_tpu.master.rendezvous.DeviceCheckRendezvousManager`
localizes fault nodes by re-pairing suspects with known-good nodes in a
second round, and flags stragglers by the elapsed-time median×2 rule.

TPU specifics: the exercise runs JAX collectives (over ICI on real chips,
over the CPU backend in tests) instead of NCCL allgathers; a hung or dead
partner surfaces as an exercise-process timeout, which is exactly the
failure signature of a sick chip or link.
"""

import os
import subprocess
import sys
import tempfile
import time
from typing import Dict, Tuple

from dlrover_tpu.common import env_utils
from dlrover_tpu.common.backoff import ExponentialBackoff
from dlrover_tpu.common.constants import NodeEnv, RendezvousName
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.rpc import find_free_port

_MAX_CHECK_ROUNDS = 3


def _exercise_timeout() -> float:
    # How long a single exercise process may run before we call the node
    # (or its partner) faulty. Tests shrink this via the environment.
    return env_utils.CHECK_EXERCISE_TIMEOUT.get()


def _setup_group_coordinator(client, round_: int, group: int,
                             world: Dict[int, int], node_rank: int) -> str:
    """The lowest rank of the check group hosts a JAX coordinator; the
    address is published through the master kv-store."""
    key = f"devcheck/{round_}/{group}"
    first = sorted(world)[0]
    if node_rank == first:
        host = env_utils.HOST_IP.get()
        addr = f"{host}:{find_free_port()}"
        client.kv_store_set(key, addr.encode())
        return addr
    return client.kv_store_wait([key], timeout=60.0)[key].decode()


def _run_exercise(config, client, round_: int, group: int,
                  world: Dict[int, int], node_rank: int) -> Tuple[bool, float]:
    """Spawn the check program for this group; returns (normal, elapsed)."""
    members = sorted(world)
    try:
        coordinator = _setup_group_coordinator(client, round_, group, world,
                                               node_rank)
    except TimeoutError:
        # The group leader died before publishing the coordinator address:
        # report a failed check instead of crashing the healthy agent.
        logger.error("device check: group %s coordinator never appeared",
                     group)
        return False, float("inf")
    result_path = tempfile.mktemp(prefix="dlrover_tpu_devcheck_")
    env = dict(os.environ)
    env.update({
        NodeEnv.JOB_NAME: config.job_name,
        NodeEnv.NODE_RANK: str(node_rank),
        NodeEnv.COORDINATOR_ADDR: coordinator,
        NodeEnv.PROCESS_ID: str(members.index(node_rank)),
        NodeEnv.NUM_PROCESSES: str(len(members)),
        env_utils.CHECK_RESULT_PATH.name: result_path,
    })
    cmd = [sys.executable, "-m", "dlrover_tpu.agent.run_device_check"]
    start = time.monotonic()
    timeout = _exercise_timeout()
    try:
        proc = subprocess.run(
            cmd, env=env, timeout=timeout,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        normal = proc.returncode == 0
        if not normal:
            logger.error(
                "device-check exercise failed (rc=%s):\n%s",
                proc.returncode, proc.stdout.decode(errors="replace")[-2000:],
            )
    except subprocess.TimeoutExpired:
        logger.error("device-check exercise timed out after %ss", timeout)
        normal = False
    elapsed = time.monotonic() - start
    if normal:
        try:
            with open(result_path) as f:
                elapsed = float(f.read().strip())
        except (ValueError, OSError):
            pass  # no/garbled result file: fall back to wall time
    try:
        os.unlink(result_path)
    except FileNotFoundError:
        pass
    return normal, elapsed


def run_device_check(config, client) -> bool:
    """Run check rounds until the diagnosis is done.

    Returns False when this node must not join training: it was confirmed
    faulty, or it is a straggler and ``--exclude-straggler`` is set.
    """
    node_rank = config.node_rank
    for check_round in range(_MAX_CHECK_ROUNDS):
        client.join_rendezvous(
            RendezvousName.DEVICE_CHECK, node_rank, config.nproc_per_node
        )
        # Wait for the master to freeze the round and hand us a group.
        deadline = time.monotonic() + config.rdzv_timeout
        world: Dict[int, int] = {}
        backoff = ExponentialBackoff(initial=0.1, max_delay=1.0)
        while time.monotonic() < deadline:
            round_, group, world = client.get_comm_world(
                RendezvousName.DEVICE_CHECK, node_rank
            )
            if world and node_rank in world:
                break
            backoff.sleep(deadline - time.monotonic())
        if not world:
            logger.warning("device check round never formed; skipping check")
            return True
        logger.info(
            "device check round %s: group %s members %s",
            round_, group, sorted(world),
        )
        normal, elapsed = _run_exercise(
            config, client, round_, group, world, node_rank
        )
        client.report_check_result(node_rank, normal, elapsed, round_=round_)

        # Poll the diagnosis: done -> act; suspects AND our round fully
        # reported -> another round; otherwise keep waiting for reports.
        poll_deadline = time.monotonic() + _exercise_timeout() + 60.0
        need_new_round = False
        backoff = ExponentialBackoff(initial=0.1, max_delay=1.0)
        while time.monotonic() < poll_deadline:
            fault_nodes, done, completed = client.get_fault_nodes()
            if done:
                stragglers, _, _ = client.get_stragglers()
                if node_rank in fault_nodes:
                    logger.error(
                        "device check: this node (%s) is a confirmed fault "
                        "node", node_rank,
                    )
                    return False
                if node_rank in stragglers:
                    logger.warning(
                        "device check: this node (%s) is a straggler "
                        "(exclude=%s)", node_rank, config.exclude_straggler,
                    )
                    if config.exclude_straggler:
                        return False
                logger.info(
                    "device check passed (fault=%s stragglers=%s)",
                    fault_nodes, stragglers,
                )
                return True
            if fault_nodes and completed >= round_:
                need_new_round = True
                break
            backoff.sleep(poll_deadline - time.monotonic())
        if not need_new_round:
            logger.warning("device-check diagnosis timed out; proceeding")
            return True
    logger.warning("device check inconclusive after %s rounds; proceeding",
                   _MAX_CHECK_ROUNDS)
    return True
