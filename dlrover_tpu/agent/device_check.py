"""Pre-flight device/ICI check (agent side).

Capability parity with the reference's ``NetworkCheckElasticAgent``
(``elastic_agent/torch/training.py:767-906``): before training starts, the
agent joins the master's device-check rendezvous, the master pairs nodes
into small groups, and every group runs a timed collective + matmul
exercise in a spawned process (:mod:`dlrover_tpu.agent.run_device_check`).
Results go back to the master, whose
:class:`~dlrover_tpu.master.rendezvous.DeviceCheckRendezvousManager`
localizes fault nodes by re-pairing suspects with known-good nodes in a
second round, and flags stragglers by the elapsed-time median×2 rule.

TPU specifics: the exercise runs JAX collectives (over ICI on real chips,
over the CPU backend in tests) instead of NCCL allgathers; a hung or dead
partner surfaces as an exercise-process timeout, which is exactly the
failure signature of a sick chip or link.
"""

import os
import subprocess
import sys
import tempfile
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from dlrover_tpu.chaos.injector import fault_hit
from dlrover_tpu.chaos.sites import ChaosSite
from dlrover_tpu.common import env_utils
from dlrover_tpu.common.backoff import ExponentialBackoff
from dlrover_tpu.common.constants import NodeEnv, RendezvousName
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.periodic import PeriodicTask
from dlrover_tpu.common.rpc import find_free_port
from dlrover_tpu.observability.events import EventKind, emit

_MAX_CHECK_ROUNDS = 3


def _exercise_timeout() -> float:
    # How long a single exercise process may run before we call the node
    # (or its partner) faulty. Tests shrink this via the environment.
    return env_utils.CHECK_EXERCISE_TIMEOUT.get()


def _setup_group_coordinator(client, round_: int, group: int,
                             world: Dict[int, int], node_rank: int) -> str:
    """The lowest rank of the check group hosts a JAX coordinator; the
    address is published through the master kv-store."""
    key = f"devcheck/{round_}/{group}"
    first = sorted(world)[0]
    if node_rank == first:
        host = env_utils.HOST_IP.get()
        addr = f"{host}:{find_free_port()}"
        client.kv_store_set(key, addr.encode())
        return addr
    return client.kv_store_wait([key], timeout=60.0)[key].decode()


def _run_exercise(config, client, round_: int, group: int,
                  world: Dict[int, int], node_rank: int) -> Tuple[bool, float]:
    """Spawn the check program for this group; returns (normal, elapsed)."""
    members = sorted(world)
    try:
        coordinator = _setup_group_coordinator(client, round_, group, world,
                                               node_rank)
    except TimeoutError:
        # The group leader died before publishing the coordinator address:
        # report a failed check instead of crashing the healthy agent.
        logger.error("device check: group %s coordinator never appeared",
                     group)
        return False, float("inf")
    result_path = tempfile.mktemp(prefix="dlrover_tpu_devcheck_")
    env = dict(os.environ)
    env.update({
        NodeEnv.JOB_NAME: config.job_name,
        NodeEnv.NODE_RANK: str(node_rank),
        NodeEnv.COORDINATOR_ADDR: coordinator,
        NodeEnv.PROCESS_ID: str(members.index(node_rank)),
        NodeEnv.NUM_PROCESSES: str(len(members)),
        env_utils.CHECK_RESULT_PATH.name: result_path,
    })
    cmd = [sys.executable, "-m", "dlrover_tpu.agent.run_device_check"]
    start = time.monotonic()
    timeout = _exercise_timeout()
    try:
        proc = subprocess.run(
            cmd, env=env, timeout=timeout,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        normal = proc.returncode == 0
        if not normal:
            logger.error(
                "device-check exercise failed (rc=%s):\n%s",
                proc.returncode, proc.stdout.decode(errors="replace")[-2000:],
            )
    except subprocess.TimeoutExpired:
        logger.error("device-check exercise timed out after %ss", timeout)
        normal = False
    elapsed = time.monotonic() - start
    if normal:
        try:
            with open(result_path) as f:
                elapsed = float(f.read().strip())
        except (ValueError, OSError):
            pass  # no/garbled result file: fall back to wall time
    try:
        os.unlink(result_path)
    except FileNotFoundError:
        pass
    return normal, elapsed


def run_device_check(config, client) -> bool:
    """Run check rounds until the diagnosis is done.

    Returns False when this node must not join training: it was confirmed
    faulty, or it is a straggler and ``--exclude-straggler`` is set.
    """
    node_rank = config.node_rank
    for check_round in range(_MAX_CHECK_ROUNDS):
        client.join_rendezvous(
            RendezvousName.DEVICE_CHECK, node_rank, config.nproc_per_node
        )
        # Wait for the master to freeze the round and hand us a group.
        deadline = time.monotonic() + config.rdzv_timeout
        world: Dict[int, int] = {}
        backoff = ExponentialBackoff(initial=0.1, max_delay=1.0)
        while time.monotonic() < deadline:
            round_, group, world = client.get_comm_world(
                RendezvousName.DEVICE_CHECK, node_rank
            )
            if world and node_rank in world:
                break
            backoff.sleep(deadline - time.monotonic())
        if not world:
            logger.warning("device check round never formed; skipping check")
            return True
        logger.info(
            "device check round %s: group %s members %s",
            round_, group, sorted(world),
        )
        normal, elapsed = _run_exercise(
            config, client, round_, group, world, node_rank
        )
        client.report_check_result(node_rank, normal, elapsed, round_=round_)

        # Poll the diagnosis: done -> act; suspects AND our round fully
        # reported -> another round; otherwise keep waiting for reports.
        poll_deadline = time.monotonic() + _exercise_timeout() + 60.0
        need_new_round = False
        backoff = ExponentialBackoff(initial=0.1, max_delay=1.0)
        while time.monotonic() < poll_deadline:
            fault_nodes, done, completed = client.get_fault_nodes()
            if done:
                stragglers, _, _ = client.get_stragglers()
                if node_rank in fault_nodes:
                    logger.error(
                        "device check: this node (%s) is a confirmed fault "
                        "node", node_rank,
                    )
                    return False
                if node_rank in stragglers:
                    logger.warning(
                        "device check: this node (%s) is a straggler "
                        "(exclude=%s)", node_rank, config.exclude_straggler,
                    )
                    if config.exclude_straggler:
                        return False
                logger.info(
                    "device check passed (fault=%s stragglers=%s)",
                    fault_nodes, stragglers,
                )
                return True
            if fault_nodes and completed >= round_:
                need_new_round = True
                break
            backoff.sleep(poll_deadline - time.monotonic())
        if not need_new_round:
            logger.warning("device-check diagnosis timed out; proceeding")
            return True
    logger.warning("device check inconclusive after %s rounds; proceeding",
                   _MAX_CHECK_ROUNDS)
    return True


# ---------------- continuous link probe ----------------


class LinkProbe:
    """Background link telemetry: the pre-flight check above answers
    "was the link sane at start" exactly once; this thread keeps
    answering it for the rest of the job.

    Every ``DLROVER_TPU_PROBE_INTERVAL`` seconds it samples, off the
    training hot path:

    - **H2D/D2H bandwidth proxy** — a small write+read through the shm
      staging directory, the same path checkpoint snapshots take. With
      ``DLROVER_TPU_PROBE_DEVICE=1`` it additionally times a real
      ``jax`` host↔device round trip (off by default: the *workers* own
      the TPU runtime; an agent-side client would steal the chips).
    - **master RPC round-trip** — a read-only kv-store get, the
      cross-host control-link microbenchmark every agent can run.

    Samples go out as ``probe.link`` events (ring-only on the master —
    never journaled) for the straggler detector's per-worker link
    profile. The probe is rate-limited by construction and *pauses
    under checkpoint pressure*: while the saver has a persist round in
    flight — a periodic persist or the proactive preemption grace-window
    flush, both raise the same busy signal — the sample is skipped, so
    probe I/O never contends with checkpoint I/O on the same disks and
    links.

    The ``probe.link degrade`` chaos site scales measured bandwidth
    down (and inflates RTT) by ``args["factor"]`` — the deterministic
    link-degradation drill.
    """

    def __init__(self, client=None,
                 interval: Optional[float] = None,
                 payload_mb: Optional[int] = None,
                 busy_fn: Optional[Callable[[], bool]] = None,
                 sample_fn: Optional[Callable[[], Dict]] = None,
                 sink: Optional[Callable[[Dict], None]] = None):
        self._client = client
        self._interval = (
            interval if interval is not None
            else env_utils.PROBE_INTERVAL.get()
        )
        self._mb = max(1, payload_mb or env_utils.PROBE_MB.get())
        self._busy_fn = busy_fn or self._saver_busy
        self._sample_fn = sample_fn
        # Optional sample sink: with heartbeat coalescing on, the agent
        # collects samples here and folds the newest into its periodic
        # AgentBeat — the master synthesizes the probe.link event, so
        # emitting one here too would double-count.
        self._sink = sink
        self._seq = 0
        self.skipped = 0
        self._task: Optional[PeriodicTask] = None

    # Process-wide count of rescale/reshape d2d transfers in flight
    # (brackets around the agent's in-place transition window). The ckpt
    # saver raises its own busy signal; transition traffic moves through
    # the very same host links without one, so without this bracket a
    # sample taken mid-transfer would read as a degraded link and could
    # trip the fleet saturation flag on every reshape.
    _transfers = 0
    _transfers_lock = threading.Lock()

    @classmethod
    def transfer_window(cls):
        """Context manager marking a rescale/reshape d2d transfer in
        flight; probe samples taken inside are flagged ``transfer``
        (the master-side aggregator drops them from the baseline fold)."""
        import contextlib

        @contextlib.contextmanager
        def _window():
            with cls._transfers_lock:
                cls._transfers += 1
            try:
                yield
            finally:
                with cls._transfers_lock:
                    cls._transfers -= 1

        return _window()

    @classmethod
    def transfer_active(cls) -> bool:
        with cls._transfers_lock:
            return cls._transfers > 0

    @staticmethod
    def _saver_busy() -> bool:
        from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver

        saver = AsyncCheckpointSaver.get_ckpt_saver()
        return bool(saver is not None and getattr(saver, "busy", False))

    def start(self):
        if self._interval <= 0:
            return
        self._task = PeriodicTask(
            self.sample_once, self._interval, name="link-probe"
        )
        self._task.start()

    def stop(self, join_timeout: float = 2.0):
        if self._task is not None:
            self._task.stop(join_timeout)
            self._task = None

    # ------------- one sample -------------
    def sample_once(self) -> Optional[Dict]:
        self._seq += 1
        try:
            if self._busy_fn():
                # Checkpoint persist in flight: stay off its disks/links.
                self.skipped += 1
                return None
        except Exception:  # dtlint: disable=DT001 -- a broken busy probe must not stop link telemetry
            pass
        transfer = self.transfer_active()
        sample = (
            self._sample_fn() if self._sample_fn is not None
            else self._measure()
        )
        if transfer:
            # Taken while a rescale/reshape d2d transfer held the link:
            # real traffic, not link health. Flag it so the aggregator
            # keeps it out of the saturation baseline; the straggler
            # detector still sees a sample (gap-free rings).
            sample["transfer"] = True
        chaos = fault_hit(ChaosSite.PROBE_LINK, detail=str(self._seq))
        if chaos is not None and chaos.kind == "degrade":
            factor = float(chaos.args.get("factor", 0.1)) or 0.1
            for key in ("h2d_mbps", "d2h_mbps"):
                if key in sample:
                    sample[key] *= factor
            if "rtt_ms" in sample:
                sample["rtt_ms"] /= factor
        if self._sink is not None:
            self._sink(dict(sample, seq=self._seq))
        else:
            emit(EventKind.PROBE_LINK, seq=self._seq, **sample)
        return sample

    def _measure(self) -> Dict:
        sample: Dict = {}
        sample.update(self._measure_shm())
        if self._client is not None:
            t0 = time.perf_counter()
            try:
                self._client.kv_store_get("__linkprobe__")
                sample["rtt_ms"] = round(
                    (time.perf_counter() - t0) * 1e3, 3
                )
            except Exception:  # dtlint: disable=DT001 -- master briefly down: the probe keeps sampling local links
                pass
        if env_utils.PROBE_DEVICE.get():
            sample.update(self._measure_device())
        return sample

    def _measure_shm(self) -> Dict:
        """Write+read through the shm staging dir — the checkpoint D2H
        path proxy available to every agent without touching the TPU."""
        shm_dir = env_utils.SHM_DIR.get() or "/dev/shm"
        if not os.path.isdir(shm_dir):
            shm_dir = tempfile.gettempdir()
        path = os.path.join(
            shm_dir, f".dlrover_tpu_linkprobe_{os.getpid()}"
        )
        payload = os.urandom(1 << 20) * self._mb
        mb = len(payload) / 1e6
        try:
            t0 = time.perf_counter()
            with open(path, "wb") as f:
                f.write(payload)
                f.flush()
            t1 = time.perf_counter()
            with open(path, "rb") as f:
                f.read()
            t2 = time.perf_counter()
        except OSError as e:
            logger.warning("link probe shm sample failed: %s", e)
            return {}
        finally:
            try:
                os.unlink(path)
            except OSError:
                pass
        return {
            "h2d_mbps": round(mb / max(t1 - t0, 1e-9), 1),
            "d2h_mbps": round(mb / max(t2 - t1, 1e-9), 1),
        }

    def _measure_device(self) -> Dict:
        """True host↔device transfer timing; opt-in only (the agent
        grabbing the TPU runtime would evict the workers)."""
        try:
            import jax
            import numpy as np

            host = np.zeros((self._mb, 1 << 20 >> 2), dtype=np.float32)
            mb = host.nbytes / 1e6
            t0 = time.perf_counter()
            dev = jax.block_until_ready(jax.device_put(host))
            t1 = time.perf_counter()
            np.asarray(dev)
            t2 = time.perf_counter()
            return {
                "dev_h2d_mbps": round(mb / max(t1 - t0, 1e-9), 1),
                "dev_d2h_mbps": round(mb / max(t2 - t1, 1e-9), 1),
            }
        except Exception as e:  # dtlint: disable=DT001 -- no usable backend: device numbers are optional extras
            logger.debug("link probe device sample unavailable: %s", e)
            return {}
