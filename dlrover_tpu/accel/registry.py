"""Sharding registry — annotate arbitrary models with logical axes.

Parity: the reference's distributed-modules registry
(``atorch/atorch/modules/distributed_modules/modules_registry.py``, 1325
LoC of per-torch-module replacement tables mapping nn.Linear/attention
classes to their TP shards). GSPMD needs no module swapping — sharding a
model is purely a matter of *naming axes* on its params — so the TPU
registry maps **param paths/shapes to logical axis names** instead of
modules to replacement classes:

- built-in defaults give any plain flax model working FSDP: the largest
  dim of every >=2D kernel becomes ``embed`` (the fsdp-sharded axis) and
  embedding-like tables get ``("vocab", "embed")``;
- ``register(pattern, axes)`` adds model-specific TP knowledge the same
  way the reference registers custom modules (e.g.
  ``register(r".*attn.*/kernel", ("embed", "heads"))``);
- optimizer state whose pytree structure mirrors the params (optax
  moments) inherits the params' axes, so ZeRO-style optimizer sharding
  keeps working for auto-annotated models too.

``auto_accelerate`` applies the default registry automatically when a
model carries no logical-axis metadata of its own.
"""

import re
from typing import List, Optional, Sequence, Tuple

import jax

from dlrover_tpu.common.log import logger


def _default_axes(path: str, shape) -> Tuple:
    """Shape/name heuristics: FSDP-ready out of the box."""
    if len(shape) == 0:
        return ()
    lowered = path.lower()
    if len(shape) >= 2 and (
        "embedding" in lowered or "embed" in lowered.rsplit("/", 1)[-1]
    ):
        return ("vocab", "embed") + (None,) * (len(shape) - 2)
    if len(shape) == 1:
        return (None,)
    # Shard the largest dim (ties: the last) over the fsdp axis.
    largest = max(range(len(shape)), key=lambda i: (shape[i], i))
    return tuple(
        "embed" if i == largest else None for i in range(len(shape))
    )


class ShardingRegistry:
    def __init__(self):
        self._rules: List[Tuple[re.Pattern, Sequence]] = []

    def register(self, pattern: str, axes: Sequence):
        """Axes for params whose ``/``-joined path matches ``pattern``
        (first registered match wins; falls back to the defaults)."""
        self._rules.append((re.compile(pattern), tuple(axes)))
        return self

    def axes_for(self, path: str, shape) -> Tuple:
        for pat, axes in self._rules:
            if pat.search(path):
                if len(axes) < len(shape):
                    # Leading lifted dims (nn.scan layer stacks, pipeline
                    # stage banks) left-pad as unsharded.
                    axes = (None,) * (len(shape) - len(axes)) + axes
                if len(axes) != len(shape):
                    raise ValueError(
                        f"registered axes {axes} rank-mismatch param "
                        f"{path} of shape {tuple(shape)}"
                    )
                return axes
        return _default_axes(path, shape)

    # ------------- tree annotation -------------
    def annotate_params(self, abstract_params):
        """Box every leaf with logical names derived from its path."""
        import flax.linen as nn

        flat = jax.tree_util.tree_flatten_with_path(abstract_params)[0]
        treedef = jax.tree_util.tree_structure(abstract_params)
        boxed = []
        for path, leaf in flat:
            name = "/".join(
                str(getattr(p, "key", getattr(p, "name", p)))
                for p in path
            )
            boxed.append(nn.LogicallyPartitioned(
                value=leaf, names=self.axes_for(name, leaf.shape),
            ))
        return jax.tree_util.tree_unflatten(treedef, boxed)

    def annotate_state(self, abstract_state):
        """Annotate a {params, opt, ...} train state: params by path;
        any opt subtree that structurally mirrors the params (optax
        moments) inherits the params' axes."""
        params = abstract_state["params"]
        boxed_params = self.annotate_params(params)
        params_def = jax.tree_util.tree_structure(params)
        boxed_leaves = jax.tree_util.tree_leaves(
            boxed_params, is_leaf=_is_box
        )

        def fix_opt(node):
            try:
                if jax.tree_util.tree_structure(node) == params_def:
                    return jax.tree_util.tree_unflatten(
                        params_def,
                        [
                            type(b)(value=leaf, names=b.names)
                            for b, leaf in zip(
                                boxed_leaves,
                                jax.tree_util.tree_leaves(node),
                            )
                        ],
                    )
            except Exception:  # dtlint: disable=DT001 -- layout probe: any failure means "not this optimizer layout" and the walk falls back
                pass
            return None

        def walk(node):
            fixed = fix_opt(node)
            if fixed is not None:
                return fixed
            if isinstance(node, dict):
                return {k: walk(v) for k, v in node.items()}
            if isinstance(node, (list, tuple)):
                walked = [walk(v) for v in node]
                if hasattr(node, "_fields"):  # NamedTuple (optax states)
                    return type(node)(*walked)
                return type(node)(walked)
            return node

        out = dict(abstract_state)
        out["params"] = boxed_params
        if "opt" in out:
            out["opt"] = walk(out["opt"])
        return out


def _is_box(x) -> bool:
    return hasattr(x, "names") and hasattr(x, "value")


default_registry = ShardingRegistry()


def has_annotations(tree) -> bool:
    """Does any leaf carry logical-axis metadata already?"""
    for leaf in jax.tree_util.tree_leaves(tree, is_leaf=_is_box):
        if _is_box(leaf):
            return True
    return False
