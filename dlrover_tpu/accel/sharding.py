"""Sharding-rule library: DP / FSDP(ZeRO-3) / TP / SP / EP as rules.

Parity: the reference implements each parallelism as a wrapper module or
optimizer shim (torch DDP, fairscale/FSDP ``zero_optimization.py:115-240``,
Megatron-style TP layers ``distributed_modules/layers.py:239-549``). Here a
parallelism is just a mapping from *logical* axis names (annotated on model
params/activations) to *mesh* axis names; GSPMD inserts the collectives:

- DP:   batch -> data axis (gradient psum)
- ZeRO-1: zero_dp -> data axis on optimizer-state dims only
        (``accel/zero.py``; params stay replicated — weight-update
        sharding from annotations alone)
- FSDP: batch -> fsdp axis too; embed -> fsdp (params+opt state sharded,
        all-gathered per layer = ZeRO-3)
- TP:   heads/mlp/vocab -> tensor axis (sharded matmuls, activation
        all-reduces — Megatron semantics without Megatron plumbing)
- SP:   seq -> seq axis (ring attention over ICI, ``dlrover_tpu.ops``)
- EP:   expert -> expert axis (MoE alltoall, ``dlrover_tpu.accel.moe``)
"""

from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence, Tuple

from dlrover_tpu.common.log import logger

# logical name -> tuple of mesh axes (order = priority; first available wins)
ShardingRules = Sequence[Tuple[str, Any]]


def logical_rules(
    data: int = 1,
    fsdp: int = 1,
    tensor: int = 1,
    seq: int = 1,
    expert: int = 1,
    pipe: int = 1,
    vocab_size: int = 0,
    zero: bool = False,
) -> List[Tuple[str, Any]]:
    """Build flax logical-axis rules for the given parallel degrees.

    Only axes with degree > 1 appear in the rules — a rule naming a mesh
    axis that doesn't exist in the Mesh raises in flax, so callers pass the
    same degrees they built the mesh with. ``vocab_size`` (when known)
    guards the vocab rule's divisibility; 0 keeps the unguarded rules.
    """
    batch_axes = [a for a, n in (("data", data), ("fsdp", fsdp)) if n > 1]
    # Vocab shards over tensor AND pipe: under pipeline parallelism the
    # embedding/LM-head live outside the stage bank, and without this
    # every pipe device would replicate both vocab x d_model tensors —
    # the two largest in the model. Sharding vocab over the pipe axis is
    # the SPMD analog of the reference's first/last-stage placement
    # (PipelineStage.py graph-split stages): per-device vocab memory is
    # V/(tensor*pipe), balanced across stages instead of dumped on two.
    vocab_axes = [
        a for a, n in (("tensor", tensor), ("pipe", pipe)) if n > 1
    ]
    vocab_shard = tensor * pipe
    if vocab_axes and vocab_size and vocab_size % vocab_shard:
        # The searched path never proposes this (enumerate_specs guards
        # divisibility), but an explicit spec with e.g. GPT-2's 50257
        # (prime-ish) vocab would get an uneven shard that fails at
        # materialization. Replicating the vocab axis is the previous,
        # correct placement — pay the memory, keep the job running.
        logger.warning(
            "vocab %s is not divisible by tensor*pipe=%s; replicating "
            "the vocab axis instead of sharding it (costs V x d_model "
            "per device — pad the vocab to a multiple of %s to shard)",
            vocab_size, vocab_shard, vocab_shard,
        )
        vocab_axes = []
    rules: List[Tuple[str, Any]] = [
        ("batch", tuple(batch_axes) if batch_axes else None),
        ("layers", None),
        ("embed", "fsdp" if fsdp > 1 else None),
        ("heads", "tensor" if tensor > 1 else None),
        ("mlp", "tensor" if tensor > 1 else None),
        ("vocab", tuple(vocab_axes) if vocab_axes else None),
        ("kv", None),
        ("seq", "seq" if seq > 1 else None),
        ("expert", "expert" if expert > 1 else None),
        ("stage", "pipe" if pipe > 1 else None),
    ]
    if zero and data > 1:
        # ZeRO-1 weight-update sharding (accel/zero.py): optimizer-state
        # dims relabeled to this axis shard over the data replicas while
        # the params they update stay replicated — GSPMD turns the pair
        # into reduce-scatter(grads) / sliced update / all-gather(params).
        from dlrover_tpu.accel.zero import ZERO_AXIS

        rules.append((ZERO_AXIS, "data"))
    return rules


def state_shardings(mesh, abstract_state, rules):
    """Map a (possibly flax-``Partitioned``-boxed) abstract pytree to
    ``NamedSharding``s. Opt-state leaves mirror their params' boxes because
    ``optax.init`` tree-maps over boxed leaves, so ZeRO-style optimizer
    sharding falls out for free (the reference needs a dedicated ZeRO
    engine for this, ``zero_optimization.py:115``)."""
    import flax.linen as nn

    specs = nn.get_partition_spec(abstract_state)
    return nn.logical_to_mesh_sharding(specs, mesh, list(rules))


def unbox(tree):
    import flax.linen as nn

    return nn.meta.unbox(tree)
