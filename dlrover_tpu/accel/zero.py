"""Compile-level ZeRO-1: weight-update sharding as sharding annotations.

Parity: the reference reaches ZeRO through ATorch's optimizer shims
(fairscale ``zero_optimization.py:115-240`` — a wrapper that partitions
the optimizer, reduce-scatters gradients, and all-gathers updated params
by hand). On TPU none of that machinery is needed: following SimpleFSDP
(arxiv 2411.00284) the *entire* transform is metadata. Re-annotate the
optimizer-state leaves of the abstract train state so each one carries a
``zero_dp`` logical axis on a dim the spec leaves unsharded, map that
axis to the ``data`` mesh axis in the sharding rules, and hand the
result to the same jitted train step everyone else uses. XLA's SPMD
partitioner sees replicated params, data-sharded optimizer state, and a
gradient that feeds both — and schedules the reduce-scatter / slice
update / updated-param all-gather of ZeRO-1 (arxiv 2004.13336) on its
own. The optimizer's ``update`` function is never touched; shapes,
dtypes and values are identical — only ``.names`` metadata changes
(asserted by ``tests/test_zero.py``).

What gets sharded: everything ``optimizer.init`` produced — Adam m/v,
the fp32 master copies of ``optim/bf16.py``'s ``bf16_master_weights``,
AGD's ``exp_avg``/``exp_avg_sq``/``max_exp_avg_sq``. Scalar leaves
(optax step counts) and leaves with no dim divisible by the data degree
stay replicated; they are bytes-irrelevant.

The checkpoint engine already stages sharded leaves block-per-shard and
persists only replica-0 copies, so under multi-process ZeRO each replica
persists only its owned optimizer slice (~Ndp× less per rank); the saved
degree is stamped into ``ShardMeta.zero_degree`` so a cross-degree
restore that cannot be re-sliced fails naming both degrees. See
``docs/zero.md``.
"""

from typing import Any, Dict, List, Optional, Sequence, Tuple

from dlrover_tpu.common.log import logger

# Logical axis name carried by zero-sharded optimizer-state dims; mapped
# to the "data" mesh axis by sharding.logical_rules(zero=True).
ZERO_AXIS = "zero_dp"


def zero_degree_of(spec) -> int:
    """Data-axis degree the optimizer state is ZeRO-sharded over under
    ``spec`` (0 when the spec doesn't shard weight updates)."""
    if getattr(spec, "zero", False) and getattr(spec, "data", 1) > 1:
        return spec.data
    return 0


def _is_box(x) -> bool:
    return hasattr(x, "names") and hasattr(x, "value")


def _resolved_axes(name, rules: Dict[str, Any]):
    """Mesh axes a logical dim name maps to under the spec's rules."""
    if not name:
        return ()
    axes = rules.get(name)
    if axes is None:
        return ()
    if isinstance(axes, str):
        return (axes,)
    return tuple(axes)


def shard_optimizer_state(
    abstract_opt,
    data: int,
    rules: Sequence[Tuple[str, Any]],
    axis_name: str = ZERO_AXIS,
):
    """Re-annotate optimizer-state leaves with a data-axis sharding.

    For every boxed leaf (``nn.Partitioned`` / ``nn.LogicallyPartitioned``
    — optax ``init`` tree-maps over boxed params, so opt state mirrors
    the params' boxes) pick the largest dim that (a) resolves to no mesh
    axis under ``rules`` — dims the spec already shards over fsdp/tensor
    stay put, ZeRO composes with them — and (b) is divisible by ``data``,
    and rename it to ``axis_name``. Leaves with no eligible dim (scalars,
    odd shapes) are returned unchanged, i.e. replicated.

    Pure metadata: shapes, dtypes, values and the optimizer ``update``
    fn are untouched; GSPMD derives the ZeRO-1 collectives from the
    resulting jit in/out shardings alone.
    """
    import jax

    if data <= 1:
        return abstract_opt
    rd = dict(rules)

    def relabel(leaf):
        if not _is_box(leaf):
            return leaf
        names = tuple(leaf.names)
        shape = getattr(leaf.value, "shape", ())
        if len(names) != len(shape):
            return leaf
        best: Optional[int] = None
        for i, dim in enumerate(shape):
            if _resolved_axes(names[i], rd):
                continue                     # already mesh-sharded
            if dim < data or dim % data:
                continue                     # uneven slice: keep replicated
            if best is None or dim > shape[best]:
                best = i
        if best is None:
            return leaf
        new_names = names[:best] + (axis_name,) + names[best + 1:]
        return type(leaf)(value=leaf.value, names=new_names)

    return jax.tree_util.tree_map(relabel, abstract_opt, is_leaf=_is_box)


def zero_sharded_paths(opt_tree, axis_name: str = ZERO_AXIS) -> List[str]:
    """Key paths of opt-state leaves carrying the zero axis (for tests,
    bench, and the engine's shard accounting)."""
    import jax

    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(
        opt_tree, is_leaf=_is_box
    )[0]:
        if _is_box(leaf) and axis_name in tuple(leaf.names):
            out.append(jax.tree_util.keystr(path))
    return out


def apply_zero(abstract_state, spec, rules, warn: bool = True):
    """Apply the ZeRO-1 transform to a full abstract train state for
    ``spec`` (no-op unless ``spec.zero`` with a real data axis). Returns
    a shallow-copied state dict with the ``opt`` subtree re-annotated."""
    degree = zero_degree_of(spec)
    if not degree or not isinstance(abstract_state, dict):
        return abstract_state
    opt = abstract_state.get("opt")
    if opt is None:
        return abstract_state
    sharded = shard_optimizer_state(opt, degree, rules)
    n = len(zero_sharded_paths(sharded))
    if not n and warn:
        logger.warning(
            "zero=True but no optimizer-state leaf could be sharded over "
            "data=%s (no boxed leaf has an unsharded dim divisible by the "
            "degree) — optimizer state stays replicated", degree,
        )
    out = dict(abstract_state)
    out["opt"] = sharded
    return out
