"""Device-mesh construction — the ``create_parallel_group`` analog.

Parity: reference ``atorch/atorch/distributed/distributed.py:320``
(``create_parallel_group(([(name,size)...], rank_order))`` builds one torch
process group per named dim). On TPU there are no process groups: ONE
``jax.sharding.Mesh`` carries every named axis, and XLA lowers collectives
onto the ICI torus (intra-slice) or DCN (inter-slice) from sharding
annotations alone.

Axis order convention (outermost first): ``data`` and ``fsdp`` outermost —
their collectives (gradient/param all-reduce-scatter) tolerate DCN latency —
then ``pipe``, ``seq``, ``expert``, with ``tensor`` innermost so its
per-layer all-gathers ride the fastest ICI dimension. This is the standard
mesh layout from the scaling-book recipe.
"""

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

from dlrover_tpu.common.log import logger

# Canonical axis order, outermost (slowest, DCN-tolerant) to innermost
# (fastest ICI). Matches the reference's rank_order semantics
# (distributed.py:263 _get_pg_ranks) re-keyed for ICI locality.
AXIS_ORDER = ("data", "fsdp", "pipe", "seq", "expert", "tensor")


@dataclass
class MeshConfig:
    """Named axes with sizes; -1 means "absorb remaining devices"."""

    axes: List[Tuple[str, int]] = field(default_factory=list)

    def resolved(self, n_devices: int) -> List[Tuple[str, int]]:
        sizes = dict(self.axes)
        known = 1
        wildcard = None
        for name, size in self.axes:
            if size == -1:
                if wildcard is not None:
                    raise ValueError("at most one axis may be -1")
                wildcard = name
            else:
                known *= size
        if wildcard is not None:
            if n_devices % known:
                raise ValueError(
                    f"{n_devices} devices not divisible by {known}"
                )
            sizes[wildcard] = n_devices // known
            known *= sizes[wildcard]
        if known != n_devices:
            raise ValueError(
                f"mesh axes {dict(self.axes)} use {known} devices, have "
                f"{n_devices}"
            )
        return [(name, sizes[name]) for name, _ in self.axes]


def _canonical_order(axes: Sequence[Tuple[str, int]]) -> List[Tuple[str, int]]:
    known = [a for a in axes if a[0] in AXIS_ORDER]
    extra = [a for a in axes if a[0] not in AXIS_ORDER]
    return sorted(known, key=lambda a: AXIS_ORDER.index(a[0])) + extra


def create_mesh(axes: Sequence[Tuple[str, int]],
                devices: Optional[Sequence] = None,
                reorder: bool = True):
    """Build a ``jax.sharding.Mesh`` from named (axis, size) dims.

    ``devices`` defaults to all devices; sizes may contain one ``-1``
    wildcard. With ``reorder=True`` axes are put in the canonical
    ICI-locality order (see AXIS_ORDER) regardless of argument order, so
    callers can say ``[("tensor", 4), ("data", -1)]`` without thinking
    about torus layout.
    """
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    resolved = MeshConfig(list(axes)).resolved(len(devices))
    if reorder:
        resolved = _canonical_order(resolved)
    names = tuple(n for n, _ in resolved)
    shape = tuple(s for _, s in resolved)
    try:
        dev_array = mesh_utils.create_device_mesh(
            shape, devices=devices, allow_split_physical_axes=True
        )
    except (ValueError, AssertionError):
        # CPU/virtual or odd topologies: plain reshape is always valid.
        dev_array = np.asarray(devices).reshape(shape)
    mesh = Mesh(dev_array, names)
    logger.info("created mesh %s", dict(zip(names, shape)))
    return mesh


def local_mesh(axis: str = "data"):
    """A 1-axis mesh over this process's addressable devices (debug/tests)."""
    import jax

    return create_mesh([(axis, -1)], devices=jax.local_devices(),
                       reorder=False)
