"""Automatic tensor-parallel placement for arbitrary flax models.

Parity: the reference's MIP TP planner
(``atorch/atorch/auto/opt_lib/shard_planners/mip_tp_planner.py``, 496
LoC: build the op graph, solve an integer program assigning each matmul a
row/column shard that minimizes resharding). GSPMD collapses the problem:
"placing" TP is just naming axes on kernels, and the graph signal needed
to pair row- with column-parallel kernels is recoverable from ONE
abstract trace — no solver required:

1. a flax method interceptor records every projection call (path, in/out
   widths, and the *identity* of its input tracer, in call order);
2. classification per scope:
   - sibling projections sharing one input tracer form column-parallel
     branch groups: >=2 same-input squares (MHA q/k/v), and twin
     contractions of identical out width plus their lone square sibling
     (GQA q/k/v — k/v are contractions, out = kv_heads x head_dim <
     d_model, that the width rule alone would wrongly mark row-parallel
     and split the Megatron col->row pair). Singleton contractions
     sharing an input (a d->1 value head next to the LM head) stay with
     the width rule;
   - expansion kernels (out > in) are column-parallel — shard the
     output dim;
   - contraction kernels (in > out) are row-parallel — shard the input
     dim (the Megatron pair: no resharding between them);
   - a square kernel in a scope that already has column shards is
     their row-parallel closer (the attention output projection);
3. the result is a :class:`ShardingRegistry` whose rules name the
   ``mlp`` logical axis on those dims (mapped to the ``tensor`` mesh
   axis by the sharding rules), stacked on the FSDP defaults.

Embedding-like tables keep the registry defaults; an LM head whose
output width equals the embedding vocab is sharded over ``vocab``.
"""

from collections import defaultdict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax

from dlrover_tpu.accel.registry import ShardingRegistry, default_registry
from dlrover_tpu.common.log import logger


@dataclass
class _ProjRecord:
    path: Tuple[str, ...]
    in_features: int
    out_features: int
    input_id: int
    order: int
    role: Optional[str] = None  # "col" | "row" | None


def _trace_projections(module, rng, *example_args) -> List[_ProjRecord]:
    """One abstract init trace; record every call that looks like a
    projection (last-dim-to-last-dim map on a >=2D input) on a module
    that actually owns a ``kernel`` param — LayerNorm/RMSNorm are
    width-preserving ``__call__``s too, but they have scale/bias, not a
    kernel, and must not participate in col/row pairing."""
    import flax.linen as nn

    records: List[_ProjRecord] = []
    counter = [0]
    # Input tracers are kept alive for the duration of the trace so
    # ``id(x)`` cannot be reused by the allocator after a tracer is
    # collected mid-trace (two different inputs colliding on one id
    # would merge unrelated records into a false sibling group).
    live_inputs: List[Any] = []

    def interceptor(next_fn, args, kwargs, context):
        out = next_fn(*args, **kwargs)
        try:
            x = args[0] if args else None
            y = out[0] if isinstance(out, tuple) else out
            if (
                context.method_name == "__call__"
                and hasattr(x, "shape") and hasattr(y, "shape")
                and getattr(x, "ndim", 0) >= 2
                and getattr(y, "ndim", 0) >= 2
                and x.shape[:-1] == y.shape[:-1]
                and context.module.path
                and context.module.has_variable("params", "kernel")
            ):
                live_inputs.append(x)
                records.append(_ProjRecord(
                    path=tuple(context.module.path),
                    in_features=int(x.shape[-1]),
                    out_features=int(y.shape[-1]),
                    input_id=id(x),
                    order=counter[0],
                ))
                counter[0] += 1
        except Exception:  # dtlint: disable=DT001 -- shape probe inside the flax interceptor: failure means "site not traceable", the planner proceeds without it
            pass
        return out

    def trace():
        with nn.intercept_methods(interceptor):
            return module.init(rng, *example_args)

    jax.eval_shape(trace)
    del live_inputs
    return records


def _classify(records: List[_ProjRecord]):
    """Assign col/row roles per scope (see module docstring)."""
    by_scope: Dict[Tuple, List[_ProjRecord]] = defaultdict(list)
    for r in records:
        by_scope[r.path[:-1]].append(r)

    for scope, rs in by_scope.items():
        rs.sort(key=lambda r: r.order)
        # dataflow first. Two same-input sibling shapes are column
        # branch groups:
        #   - >=2 squares reading one tracer (MHA q/k/v);
        #   - twin contractions with identical out widths (GQA/cross-
        #     attention k/v: out = kv_heads x head_dim < d_model — the
        #     width rule alone would wrongly mark them row-parallel,
        #     but they must shard over kv heads to compose with head-
        #     sharded attention), plus their lone square sibling (the
        #     GQA q).
        # A *singleton* contraction sharing an input (e.g. a d->1 value
        # head next to the LM head) is NOT pulled into the group — it
        # stays with the width rule, whose row placement never shards
        # the tiny output dim.
        by_input: Dict[int, List[_ProjRecord]] = defaultdict(list)
        for r in rs:
            by_input[r.input_id].append(r)
        for group in by_input.values():
            if len(group) < 2:
                continue
            squares = [
                g for g in group if g.in_features == g.out_features
            ]
            contractions = [
                g for g in group if g.out_features < g.in_features
            ]
            widths = defaultdict(int)
            for g in contractions:
                widths[g.out_features] += 1
            twins = [
                g for g in contractions if widths[g.out_features] >= 2
            ]
            if len(squares) >= 2:
                for g in squares:
                    g.role = "col"
            if twins:
                for g in twins:
                    g.role = "col"
                if len(squares) == 1:
                    squares[0].role = "col"
        for r in rs:
            if r.role is not None:
                continue
            if r.out_features > r.in_features:
                r.role = "col"
            elif r.in_features > r.out_features:
                r.role = "row"
        # square closers: a still-unclassified square after any col in
        # the same scope becomes its row-parallel pair
        for i, r in enumerate(rs):
            if r.role is None and r.in_features == r.out_features:
                if any(
                    p.role == "col" and p.order < r.order for p in rs
                ):
                    r.role = "row"
    return records


def plan_tp(
    module,
    rng,
    *example_args,
    vocab_size: Optional[int] = None,
    base: Optional[ShardingRegistry] = None,
) -> ShardingRegistry:
    """Build a registry with automatic TP placement for ``module``.

    Returns a fresh :class:`ShardingRegistry` whose rules cover the
    model's projection kernels (column: ``(..., "embed", "mlp")``, row:
    ``(..., "mlp", "embed")``); anything unmatched falls through to the
    FSDP defaults. ``vocab_size`` (or the largest embedding dim found)
    marks LM heads for ``vocab`` sharding.
    """
    import re

    records = _classify(_trace_projections(module, rng, *example_args))
    reg = ShardingRegistry()
    if base is not None:
        reg._rules.extend(base._rules)

    n_col = n_row = 0
    for r in records:
        path = "/".join(r.path)
        pattern = rf"^{re.escape(path)}/kernel$"
        if r.role == "col":
            # vocab sharding only for top-level heads: a block-internal
            # expansion that merely *equals* the vocab width is mlp.
            out_ax = (
                "vocab"
                if vocab_size and r.out_features == vocab_size
                and len(r.path) == 1
                else "mlp"
            )
            reg.register(pattern, ("embed", out_ax))
            reg.register(
                rf"^{re.escape(path)}/bias$", (out_ax,)
            )
            n_col += 1
        elif r.role == "row":
            reg.register(pattern, ("mlp", "embed"))
            reg.register(rf"^{re.escape(path)}/bias$", (None,))
            n_row += 1
    logger.info(
        "tp planner: %d column + %d row shards over %d projections",
        n_col, n_row, len(records),
    )
    return reg
