"""Strategy search engine — cost-model-driven ``ParallelSpec`` selection.

Parity: the reference's acceleration engine searches the strategy space by
generating candidate optimization-method combinations, scoring them, and
dry-running the survivors (``atorch/atorch/auto/engine/acceleration_engine.py:13``,
``executor.py:36``, ``sg_algo/bayes_opt_sg.py``). The TPU-first version
searches a much cleaner space — a ``ParallelSpec`` is six mesh degrees, so
the engine can *enumerate* every factorization of the device count instead
of sampling, score each with an analytic memory + roofline model, and
optionally dry-run the top-K on the real mesh (the existing
``profile=True`` path).

The cost model has two parts:

- **Memory** (feasibility): per-device *train-state* bytes are computed
  EXACTLY from the abstract boxed state — each leaf's logical axis names
  are mapped through the spec's sharding rules and its dims divided by the
  mesh-axis sizes, which is precisely what GSPMD will do. Activations,
  gradients and the fp32 loss-path logits are estimated analytically from
  the model profile (layers, d_model, ff, vocab, remat policy).
- **Time** (ranking): compute seconds from the model FLOPs at a derated
  MXU peak, a pipeline-bubble multiplier ``(M+P-1)/M``, plus per-collective
  ICI terms using the standard volume formulas (all-gather/reduce-scatter
  for FSDP, grad all-reduce for DP, activation all-reduces for TP, KV ring
  for SP, dispatch/combine all-to-all for EP) — the scaling-book recipe.

Capability gating keeps the search honest: ``tensor`` requires head/ff
divisibility, ``seq`` requires ring attention support, ``expert`` requires
an MoE model, ``pipe`` requires a model that can be re-configured into
stages. Models expose these through their config dataclass (GPTConfig /
LlamaConfig duck-typing); arbitrary flax modules degrade to the
data/fsdp-only space, which is always safe.
"""

import dataclasses
import math
from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple

from dlrover_tpu.common.log import logger

# Derate factor on peak FLOPs — CALIBRATED against measured single-chip
# step times on TPU v5e (BENCH_r04, 2026-07-30, this repo's bench.py):
# small 124M 40.6% MFU, medium 355M 43.0%, GPT-2-xl 1.5B 36.0%, LLaMA
# 1.15B 51.6%. 0.42 is their geometric mean; every preset's measured
# step time is then within +-30% of estimate().step_s, pinned by
# tests/test_search.py::TestCalibratedAgainstChip. (Remat recompute is
# inside the derate: flops_per_token counts algorithmic FLOPs only.)
_MFU_DERATE = 0.42
# ICI per-device bandwidth (bytes/s) — v5e-class 2D torus, per the public
# spec sheet ~186 GB/s aggregate; one link direction ~45 GB/s. Ranking
# constant, overridable for tests.
_ICI_BW = 9e10
_PEAK_FLOPS_DEFAULT = 197e12  # v5e bf16
# Per-collective launch/synchronization latency (seconds). The bandwidth
# terms dominate at real scale; this term is what makes fine-grained
# parallelism (a collective every layer) correctly lose to pure DP (one
# grad all-reduce) on models too small to amortize it.
_COLL_LAT = 5e-6
# Inter-host (DCN) figures: per-device bandwidth and per-collective
# latency for mesh axes whose neighbours live on different hosts.
_DCN_BW = 2.5e9
_DCN_LAT = 100e-6
# HBM bandwidth (bytes/s), v5e spec sheet. Used for the pipeline
# weight-traffic floor: each schedule tick re-reads the device's
# resident stage weights, so a pipelined step cannot run faster than
# ticks x resident-bytes / HBM — the term that stops the search from
# picking deep pipelines at memory-bound (small-batch) operating points
# where the bubble model alone looks fine. The circular schedule with
# the default "slice" chunk selection has the same per-pass weight
# traffic as GPipe (measured on-chip, docs/pipeline_schedules.md), so
# one term covers both.
_HBM_BW = 8.19e11


def _axis_links(spec, devices_per_host: int):
    """Per-axis (bandwidth_kind) map: which mesh axes cross hosts.

    Device order follows the canonical mesh layout (mesh.AXIS_ORDER,
    outermost first); an axis is host-local iff the block its
    collectives span — its own size times everything inner to it — fits
    in one host. With ``devices_per_host=0`` (single host) every axis is
    ICI.
    """
    from dlrover_tpu.accel.mesh import AXIS_ORDER

    sizes = _axis_sizes(spec)
    crossing = {}
    for i, axis in enumerate(AXIS_ORDER):
        inner = 1
        for later in AXIS_ORDER[i + 1:]:
            inner *= sizes.get(later, 1)
        span = inner * sizes.get(axis, 1)
        crossing[axis] = bool(
            devices_per_host and span > devices_per_host
        )
    return crossing


@dataclass(frozen=True)
class ModelProfile:
    """What the search needs to know about a model. Extracted from the
    model's config dataclass when it has one (``from_config``); the
    conservative fallback (``from_params``) only enables data/fsdp."""

    param_count: int
    num_layers: int = 0
    d_model: int = 0
    ff_dim: int = 0
    seq_len: int = 0
    vocab_size: int = 0
    num_heads: int = 0
    num_experts: int = 0
    moe_top_k: int = 2
    remat: bool = False
    remat_policy: str = "nothing"
    supports_ring: bool = False      # attn_impl can be switched to "ring"
    supports_pipeline: bool = False  # cfg has pipeline_stages
    mlp_int8: bool = False           # AQT int8 MLP matmuls are ACTIVE
    vocab_params: int = 0            # embed (+ untied head) params that
                                     # live outside the layer stack
    expert_ffn_params: int = 0       # expert-sharded FFN params (all
                                     # layers, all experts); 0 when dense
    dtype_bytes: int = 2             # activation dtype (bf16)
    param_dtype_bytes: int = 4       # param (and grad) dtype; bf16
                                     # models store/grad in 2 bytes but
                                     # their optimizer state still
                                     # widens to fp32 (see below)
    # Analytic train-state bytes/param, mixed-precision recipe: param +
    # grad at param dtype, fp32 adam m/v (8), plus a separate fp32
    # master copy (4) when params are not already fp32 — the dtype
    # widening ZeRO exists to shard. fp32: 4+4+8=16; bf16: 2+2+8+4=16.
    # Exact when the abstract tree is available (state_bytes_per_device).
    state_bytes_per_param: float = 16.0
    flops_per_token: float = 0.0

    @staticmethod
    def from_config(cfg, param_count: Optional[int] = None) -> "ModelProfile":
        """Duck-typed extraction from a GPTConfig/LlamaConfig-shaped
        dataclass (the framework's model families share this shape)."""
        count = param_count
        if count is None:
            count = int(cfg.param_count())
        fields = {f.name for f in dataclasses.fields(cfg)}
        # Expert-sharded FFN params: only these divide by the expert
        # degree in per-device residency. Llama's SwiGLU has three
        # bias-free projections (gate/up/down = 3*d*f); GPT's MLP is two
        # biased denses (2*d*f + f + d). The router (d*num_experts) is
        # expert-REPLICATED, so it stays out.
        n_exp = getattr(cfg, "num_experts", 0)
        d = getattr(cfg, "d_model", 0)
        f_dim = getattr(cfg, "ff_dim", 0)
        per_expert = (
            3 * d * f_dim if "num_kv_heads" in fields
            else 2 * d * f_dim + f_dim + d
        )
        expert_ffn = (
            getattr(cfg, "num_layers", 0) * n_exp * per_expert
            if n_exp > 1 else 0
        )
        import numpy as np

        pd = 4
        try:
            pdt = getattr(cfg, "param_dtype", None)
            if pdt is not None:
                pd = int(np.dtype(pdt).itemsize)
        except Exception:
            pd = 4
        # Widened-optimizer recipe (see the field comment): param + grad
        # at param dtype + fp32 m/v + fp32 master for non-fp32 params.
        sbpp = 2.0 * pd + 8.0 + (0.0 if pd == 4 else 4.0)
        return ModelProfile(
            param_count=count,
            num_layers=getattr(cfg, "num_layers", 0),
            d_model=getattr(cfg, "d_model", 0),
            ff_dim=getattr(cfg, "ff_dim", 0),
            seq_len=getattr(cfg, "max_seq_len", 0),
            vocab_size=getattr(cfg, "vocab_size", 0),
            num_heads=getattr(cfg, "num_heads", 0),
            num_experts=getattr(cfg, "num_experts", 0),
            moe_top_k=getattr(cfg, "moe_top_k", 2),
            remat=getattr(cfg, "remat", False),
            remat_policy=getattr(cfg, "remat_policy", "nothing"),
            supports_ring="attn_impl" in fields,
            supports_pipeline="pipeline_stages" in fields,
            mlp_int8=getattr(cfg, "mlp_precision", "bf16") == "int8",
            vocab_params=(
                int(cfg.vocab_param_count())
                if hasattr(cfg, "vocab_param_count")
                else getattr(cfg, "vocab_size", 0)
                * getattr(cfg, "d_model", 0)
            ),
            expert_ffn_params=expert_ffn,
            param_dtype_bytes=pd,
            state_bytes_per_param=sbpp,
            flops_per_token=(
                float(cfg.flops_per_token())
                if hasattr(cfg, "flops_per_token") else 6.0 * count
            ),
        )

    @staticmethod
    def from_params(param_count: int) -> "ModelProfile":
        return ModelProfile(param_count=param_count,
                            flops_per_token=6.0 * param_count)


@dataclass(frozen=True)
class CostEstimate:
    """Per-device memory + estimated step time for one candidate."""

    state_bytes: float       # params + opt state + step (exact when
                             # computed from the abstract tree)
    grad_bytes: float        # transient fp32 grads (peak during bwd)
    act_bytes: float         # saved activations + loss-path logits
    compute_s: float
    comm_overlap_s: float    # FSDP gathers / DP grad sync: prefetchable,
                             # XLA hides most of it behind compute
    comm_critical_s: float   # TP all-reduces, ring passes, EP all-to-all,
                             # stage transfers: on the activation critical
                             # path, largely exposed
    bubble: float            # pipeline multiplier on compute, >= 1
    hbm_s: float = 0.0       # HBM weight-traffic floor (pipeline ticks
                             # re-read resident stage weights)

    @property
    def total_bytes(self) -> float:
        return self.state_bytes + self.grad_bytes + self.act_bytes

    @property
    def comm_s(self) -> float:
        return self.comm_overlap_s + self.comm_critical_s

    @property
    def step_s(self) -> float:
        # Roofline: the pipelined compute cannot beat its weight-traffic
        # floor (hbm_s is 0 for non-pipeline specs, where the single
        # fwd+bwd weight pass is inside _MFU_DERATE).
        return (max(self.compute_s * self.bubble, self.hbm_s)
                + 0.15 * self.comm_overlap_s
                + 0.5 * self.comm_critical_s)

    def fits(self, hbm: float, headroom: float = 0.9) -> bool:
        return self.total_bytes <= hbm * headroom


def _axis_sizes(spec) -> dict:
    return {
        "data": spec.data, "fsdp": spec.fsdp, "tensor": spec.tensor,
        "seq": spec.seq, "expert": spec.expert, "pipe": spec.pipe,
    }


def state_bytes_per_device(abstract_state, spec) -> int:
    """Exact per-device train-state bytes for a candidate spec.

    Walks the abstract boxed pytree; each leaf's logical names map
    through ``spec.rules()`` to mesh axes, and every sharded dim is
    ceil-divided by the product of its mesh-axis sizes — the same
    arithmetic GSPMD performs, without building a mesh or compiling.
    ``zero`` specs first re-annotate the opt subtree exactly the way
    ``build`` will, so the memory model prices the sharded slices.
    """
    import jax

    rules_seq = spec.rules()
    if getattr(spec, "zero", False) and getattr(spec, "data", 1) > 1:
        from dlrover_tpu.accel.zero import apply_zero

        abstract_state = apply_zero(
            abstract_state, spec, rules_seq, warn=False
        )
    rules = dict(rules_seq)
    sizes = _axis_sizes(spec)

    def leaf_bytes(leaf):
        names = getattr(leaf, "names", None)
        inner = getattr(leaf, "value", leaf)
        shape = getattr(inner, "shape", ())
        dtype = getattr(inner, "dtype", None)
        itemsize = dtype.itemsize if dtype is not None else 4
        n = 1
        for i, dim in enumerate(shape):
            div = 1
            if names is not None and i < len(names) and names[i]:
                mesh_axes = rules.get(names[i])
                if mesh_axes is not None:
                    if isinstance(mesh_axes, str):
                        mesh_axes = (mesh_axes,)
                    for ax in mesh_axes:
                        div *= sizes.get(ax, 1)
            n *= math.ceil(dim / div)
        return n * itemsize

    total = 0
    for leaf in jax.tree_util.tree_leaves(
        abstract_state, is_leaf=lambda x: hasattr(x, "names")
    ):
        total += leaf_bytes(leaf)
    return total


def _act_floats_per_token_layer(p: ModelProfile) -> float:
    """Saved-activation floats per token per layer under the remat
    policy. Rough by design — the constant only needs to rank policies
    and scale with d_model/ff (flash attention: no [S,S] term)."""
    d, f = max(p.d_model, 1), max(p.ff_dim, 4 * max(p.d_model, 1))
    if p.remat and p.remat_policy == "nothing":
        return 2.0 * d                    # residual-stream boundary
    if p.remat:                           # "dots": matmul outputs saved
        return 5.0 * d + f
    return 10.0 * d + 2.0 * f             # no remat: everything


def estimate(
    profile: ModelProfile,
    spec,
    batch_size: int,
    hbm: float,
    abstract_state=None,
    peak_flops: float = _PEAK_FLOPS_DEFAULT,
    ici_bw: float = _ICI_BW,
    microbatches: int = 0,
    devices_per_host: int = 0,
    dcn_bw: float = _DCN_BW,
    hbm_bw: float = _HBM_BW,
    link_profile: Optional[dict] = None,
) -> CostEstimate:
    """Analytic memory + roofline cost for one candidate spec.

    ``devices_per_host > 0`` makes the comm terms hierarchy-aware: a
    mesh axis whose collective block spans hosts (canonical layout,
    outer axes first) is priced at ``dcn_bw`` with DCN latency — the
    model that makes hierarchical placements (fsdp inside a host, dp or
    pp across) beat host-crossing gathers.

    ``link_profile`` swaps the analytic link constants for *measured*
    figures (the master LinkProfileAggregator's per-axis fold,
    ``axis -> {bw_bytes_s, lat_s, saturated}``); axes the profile has no
    measurement for (``bw_bytes_s`` null — host-local links the agent
    probe cannot see) keep the analytic constants. The spec's
    ``collectives`` map then selects per-axis *algorithm* pricing:
    ``"bw"`` (default) is the flat ring reduce-scatter+all-gather —
    maximal wire volume, overlappable behind backward; ``"lat"`` is the
    hierarchical/fused all-reduce — reduces within a host first, so the
    slow-link wire volume divides by the host width and the launch count
    halves, but the fused collective sits on the critical path. The
    ranking therefore picks ``"bw"`` exactly where measured bandwidth
    justifies paying full volume for overlap (fast/host-local axes) and
    ``"lat"`` where a thin measured link makes volume the enemy."""
    p = profile
    dp = spec.data * spec.fsdp                      # batch shards
    tokens_dev = batch_size * max(p.seq_len, 1) / (dp * spec.seq)
    dtype_b = p.dtype_bytes

    # --- memory ---
    zero_shard = (
        spec.data if getattr(spec, "zero", False) and spec.data > 1 else 1
    )
    if abstract_state is not None:
        # Exact walk (zero specs re-slice the opt subtree inside);
        # transient grads are priced at the *param* dtype — a bf16 model
        # backprops bf16 grads, not fp32 (the old 4.0 double-counted).
        state_b = float(state_bytes_per_device(abstract_state, spec))
        param_shard = spec.fsdp * spec.tensor * spec.expert * spec.pipe
        grad_b = float(p.param_dtype_bytes) * p.param_count / param_shard
    else:
        param_shard = spec.fsdp * spec.tensor * spec.expert * spec.pipe
        # Split state_bytes_per_param into the param+grad share (stays
        # with the params) and the widened optimizer share (fp32 m/v +
        # master) — only the latter divides by the zero degree.
        opt_pp = max(
            p.state_bytes_per_param - 2.0 * p.param_dtype_bytes, 0.0
        )
        state_b = (
            (p.state_bytes_per_param - opt_pp) * p.param_count / param_shard
            + opt_pp * p.param_count / (param_shard * zero_shard)
        )
        grad_b = 0.0
    layers_dev = max(p.num_layers, 1) / spec.pipe
    act_b = (
        layers_dev * _act_floats_per_token_layer(p) * tokens_dev * dtype_b
    )
    # fp32 loss path: logits + logsumexp live once, sharded over the
    # vocab axis (tensor x pipe — see logical_rules) — dominant for
    # small models, real for all.
    if p.vocab_size:
        act_b += (tokens_dev * p.vocab_size / (spec.tensor * spec.pipe)
                  * (4.0 + dtype_b))

    # --- compute ---
    flops_step = p.flops_per_token * batch_size * max(p.seq_len, 1)
    compute_s = flops_step / spec.total / (peak_flops * _MFU_DERATE)
    if spec.tensor > 1 and p.ff_dim:
        # Narrow per-shard matmuls under-fill the MXU: derate compute
        # once the sharded ff width drops below ~2k lanes. This is what
        # makes EP beat TP on MoE models (EP keeps full-width experts)
        # and keeps TP off small models.
        eff = min(1.0, max(0.1, (p.ff_dim / spec.tensor) / 2048.0))
        compute_s /= eff
    if p.mlp_int8:
        # AQT int8 MLP matmuls: measured ~0.93x on v5e via this XLA
        # build (no double-rate int8 MXU engagement; ops/quantized.py).
        # Priced as a mild penalty so the search never *prefers* a spec
        # because int8 is on; re-fit this constant when the backend
        # exposes the 2x int8 rate.
        compute_s /= 0.93
    # Microbatching amortizes the pipeline bubble; assume the runtime
    # uses up to 4*P microbatches when the per-shard batch allows
    # (reconfigure_module applies the same rule).
    m = microbatches or _pipe_microbatches(
        spec.pipe, batch_size, dp
    )
    bubble = (m + spec.pipe - 1) / m if spec.pipe > 1 else 1.0

    # --- communication (per-axis bandwidth + per-collective α) ---
    # Each term is priced at its own axis's link: ICI within a host,
    # DCN when the axis's collective block spans hosts; a measured
    # link_profile entry overrides either constant.
    crossing = _axis_links(spec, devices_per_host)

    def bw(axis):
        measured = ((link_profile or {}).get(axis) or {}).get("bw_bytes_s")
        if measured:
            return float(measured)
        return dcn_bw if crossing.get(axis) else ici_bw

    def lat(axis):
        measured = ((link_profile or {}).get(axis) or {}).get("lat_s")
        if measured:
            return float(measured)
        return _DCN_LAT if crossing.get(axis) else _COLL_LAT

    def hier(axis):
        # Host width the "lat" algorithm's intra-host reduce collapses
        # over before touching the axis's slow link; a host-local axis
        # has no second tier, so its fused all-reduce still ships full
        # volume (and "lat" can only win there on pure launch count).
        return max(2, devices_per_host) if crossing.get(axis) else 1

    def lat_volume_s(axis, vol):
        # The hierarchical algorithm's wire time: reduce+broadcast the
        # full volume inside each host at ICI speed, then move vol/h
        # over the axis's (measured or analytic) link. Both legs are
        # fused into the step boundary — critical path. Charging the
        # intra-host leg is what keeps the trade bandwidth-sensitive:
        # on a fast axis the ring's overlap discount beats the volume
        # division, on a thin measured link it cannot.
        h = hier(axis)
        t = vol / h / bw(axis)
        if h > 1:
            t += vol / ici_bw
        return t

    strat = dict(getattr(spec, "collectives", ()) or ())
    comm_ov_s = 0.0  # prefetchable: FSDP gathers, DP grad sync
    comm_cp_s = 0.0  # critical path: TP/ring/EP/stage transfers
    pbytes_tp = 2.0 * p.param_count / (spec.tensor * spec.expert * spec.pipe)
    if spec.fsdp > 1:
        # all-gather params fwd + bwd, reduce-scatter grads (bf16 wire);
        # one collective per layer per direction.
        vol = 3.0 * pbytes_tp * (spec.fsdp - 1) / spec.fsdp
        if strat.get("fsdp") == "lat":
            comm_cp_s += lat_volume_s("fsdp", vol)
            comm_cp_s += 1.5 * layers_dev * lat("fsdp")
        else:
            comm_ov_s += vol / bw("fsdp")
            comm_cp_s += 3.0 * layers_dev * lat("fsdp")
    if spec.data > 1:
        # grad all-reduce over the pure-DP axis (on the fsdp-sharded rest).
        vol = (2.0 * (pbytes_tp / spec.fsdp)
               * (spec.data - 1) / spec.data)
        if strat.get("data") == "lat":
            comm_cp_s += lat_volume_s("data", vol)
            comm_cp_s += 0.5 * lat("data")
        else:
            comm_ov_s += vol / bw("data")
            comm_cp_s += lat("data")
    if zero_shard > 1:
        # ZeRO-1 swaps the grad all-reduce for reduce-scatter + an
        # all-gather of the updated params — the same wire volume (the
        # overlap term above already covers it), but the gather sits at
        # the step boundary where the backward pass can no longer hide
        # it: price a quarter of it exposed plus one extra collective
        # launch. This keeps replicated Adam winning ties when both
        # fit; when it doesn't fit, the memory column decides.
        ag = ((pbytes_tp / spec.fsdp) * (spec.data - 1) / spec.data
              / bw("data"))
        comm_cp_s += 0.25 * ag + lat("data")
    if spec.tensor > 1:
        # Megatron semantics: 2 activation all-reduces fwd + 2 bwd per
        # layer of [tokens, d_model]; an all-reduce moves 2x the payload
        # (reduce-scatter + all-gather).
        comm_cp_s += (8.0 * layers_dev * tokens_dev * p.d_model * dtype_b
                      * (spec.tensor - 1) / spec.tensor / bw("tensor"))
        comm_cp_s += 4.0 * layers_dev * lat("tensor")
    if spec.seq > 1:
        # ring attention: each device's K and V blocks make (seq-1) hops
        # around the ring per layer (full KV visits every shard); the
        # backward ring doubles it.
        comm_cp_s += (3.0 * 2.0 * layers_dev * tokens_dev * p.d_model
                      * dtype_b * (spec.seq - 1) / bw("seq"))
        comm_cp_s += 3.0 * layers_dev * spec.seq * lat("seq")
    if spec.expert > 1:
        # dispatch + combine all-to-all, fwd + bwd, top_k routed copies.
        comm_cp_s += (4.0 * layers_dev * tokens_dev * p.d_model * dtype_b
                      * p.moe_top_k * (spec.expert - 1) / spec.expert
                      / bw("expert"))
        comm_cp_s += 4.0 * layers_dev * lat("expert")
    hbm_s = 0.0
    if spec.pipe > 1:
        # stage-boundary activation transfers: m microbatches cross each
        # boundary fwd + bwd (one permute per schedule tick each way) —
        # the tiny traffic that makes PP the right axis to place across
        # DCN.
        comm_cp_s += 2.0 * tokens_dev * p.d_model * dtype_b / bw("pipe")
        comm_cp_s += 2.0 * (m + spec.pipe - 1) * lat("pipe")
        # Weight-traffic floor: every tick each device re-reads its
        # resident stage weights (fwd scan), and the backward replay
        # reads them again plus the grad-bank read-modify-write — ~3
        # resident passes per tick over (M+P-1) ticks. A non-pipelined
        # step reads weights once fwd + twice bwd regardless of batch,
        # so the pipeline's *extra* traffic scales with the microbatch
        # count — this is what sinks deep pipelines at small batch.
        # Only the stage-bank layers re-read per tick; the vocab-side
        # params (embedding, position table, untied LM head — exact
        # count from the config's vocab_param_count, which knows about
        # head tying) run once per step outside the pipe.
        # Only the expert-sharded FFN weights divide by the expert
        # degree; attention / norms / router are expert-replicated, so
        # dividing the WHOLE stack by spec.expert undercounted the
        # floor and made deep-pipe + high-EP specs look free.
        layer_params = max(p.param_count - p.vocab_params, 0.0)
        expert_ffn = min(float(p.expert_ffn_params), layer_params)
        dense_params = layer_params - expert_ffn
        resident_b = dtype_b * (
            dense_params / (spec.pipe * spec.tensor)
            + expert_ffn / (spec.pipe * spec.tensor * spec.expert)
        )
        hbm_s = 3.0 * (m + spec.pipe - 1) * resident_b / hbm_bw

    return CostEstimate(
        state_bytes=state_b, grad_bytes=grad_b, act_bytes=act_b,
        compute_s=compute_s, comm_overlap_s=comm_ov_s,
        comm_critical_s=comm_cp_s, bubble=bubble, hbm_s=hbm_s,
    )


def _pipe_microbatches(pipe: int, batch_size: int, dp: int) -> int:
    """Microbatch count the runtime will use for a pipe degree: up to
    4*P (bubble <= (P-1)/4P) as long as each microbatch still shards
    over the dp axis and divides the global batch."""
    if pipe <= 1:
        return 1
    for k in (4, 3, 2):
        if batch_size % (k * pipe * max(dp, 1)) == 0:
            return k * pipe
    return pipe


def _factorizations(n: int, k: int):
    """All k-tuples of positive ints whose product is n."""
    if k == 1:
        yield (n,)
        return
    for d in range(1, n + 1):
        if n % d == 0:
            for rest in _factorizations(n // d, k - 1):
                yield (d,) + rest


#: Axes whose collective algorithm is a searched dimension. Only the
#: param-sync axes: TP/ring/EP traffic is activation-shaped and its
#: algorithm is fixed by the layer semantics, but the fsdp gathers and
#: the dp grad sync genuinely admit both the flat ring (full volume,
#: overlappable) and the hierarchical fused form (reduced slow-link
#: volume, critical-path).
_STRATEGY_AXES = ("data", "fsdp")


def enumerate_specs(
    profile: ModelProfile, n_devices: int, batch_size: int,
    strategies: bool = False,
) -> List[Any]:
    """Every ParallelSpec the model can legally run on n_devices.

    ``strategies=True`` widens the space with per-axis collective
    algorithm choices on :data:`_STRATEGY_AXES` (``"lat"`` variants —
    the absent entry is the default ``"bw"`` ring), at most 3 extra
    variants per spec. Off by default: without a measured link profile
    the analytic constants price every variant identically enough that
    the extra candidates are pure search cost."""
    from dlrover_tpu.accel.accelerate import ParallelSpec

    p = profile
    out = []
    for data, fsdp, tensor, seq, expert, pipe in _factorizations(
        n_devices, 6
    ):
        if tensor > 1:
            if not p.num_heads or p.num_heads % tensor:
                continue
            if p.ff_dim and p.ff_dim % tensor:
                continue
        if tensor * pipe > 1 and p.vocab_size:
            # vocab shards over tensor x pipe (logical_rules): the dim
            # must divide evenly or materialization fails. Models with
            # awkward vocabs should pad (the standard TPU practice).
            if p.vocab_size % (tensor * pipe):
                continue
        if seq > 1:
            if not p.supports_ring or not p.seq_len:
                continue
            if p.seq_len % seq:
                continue
            if p.seq_len // seq < 1024:
                continue  # ring blocks below the kernel tile size are
                          # latency-bound, never a win
            if p.num_experts:   # ring + MoE dispatch not composed yet
                continue
        if expert > 1 and (not p.num_experts or p.num_experts % expert):
            continue
        if pipe > 1:
            if not p.supports_pipeline or not p.num_layers:
                continue
            if p.num_layers % pipe:
                continue
        if batch_size % (data * fsdp):
            continue
        if pipe > 1 and (batch_size // (data * fsdp)) % pipe:
            continue            # microbatching needs divisibility
        out.append(ParallelSpec(data=data, fsdp=fsdp, tensor=tensor,
                                seq=seq, expert=expert, pipe=pipe))
    # ZeRO-1 weight-update sharding (accel/zero.py) composes with any
    # spec that has a data axis. The estimator prices its memory cut and
    # its exposed param all-gather, so a zero variant only wins when the
    # replicated optimizer state is the binding constraint.
    out += [
        dataclasses.replace(s, zero=True) for s in out if s.data > 1
    ]
    if strategies:
        variants = []
        for s in out:
            live = [a for a in _STRATEGY_AXES if getattr(s, a) > 1]
            for mask in range(1, 1 << len(live)):
                combo = tuple(
                    (axis, "lat") for i, axis in enumerate(live)
                    if mask & (1 << i)
                )
                variants.append(dataclasses.replace(s, collectives=combo))
        out += variants
    return out


def search_spec(
    profile: ModelProfile,
    n_devices: int,
    batch_size: int,
    hbm: float,
    abstract_state=None,
    peak_flops: float = _PEAK_FLOPS_DEFAULT,
    top_k: int = 4,
    prefer: Sequence[str] = (),
    abstract_fn=None,
    ici_bw: float = _ICI_BW,
    devices_per_host: int = 0,
    dcn_bw: float = _DCN_BW,
    link_profile: Optional[dict] = None,
    strategies: bool = False,
) -> List[Tuple[Any, CostEstimate]]:
    """Rank the feasible strategy space; return the top-K (spec, cost).

    ``abstract_fn(spec) -> abstract_state`` supplies the per-candidate
    boxed tree when reconfiguration changes the param layout (pipeline
    stage axes); otherwise ``abstract_state`` is used for every
    candidate. If nothing fits in HBM, returns the least-oversubscribed
    candidates (the dry-run will be the judge — XLA sometimes fits what
    the model says won't). ``prefer`` breaks near-ties toward named
    degrees (used by tests and the MoE default).
    """
    cands = enumerate_specs(
        profile, n_devices, batch_size, strategies=strategies
    )
    if not cands:
        from dlrover_tpu.accel.accelerate import ParallelSpec

        fallback = ParallelSpec(data=1)
        ab = abstract_fn(fallback) if abstract_fn else abstract_state
        return [(fallback, estimate(
            profile, fallback, batch_size, hbm, ab, peak_flops,
            ici_bw=ici_bw, devices_per_host=devices_per_host,
            dcn_bw=dcn_bw, link_profile=link_profile))]
    scored = []
    for spec in cands:
        ab = abstract_fn(spec) if abstract_fn else abstract_state
        est = estimate(profile, spec, batch_size, hbm, ab, peak_flops,
                       ici_bw=ici_bw, devices_per_host=devices_per_host,
                       dcn_bw=dcn_bw, link_profile=link_profile)
        scored.append((spec, est))
    fitting = [s for s in scored if s[1].fits(hbm)]
    if fitting:
        pool = fitting
    else:
        # Nothing fits: keep only the most-sharded end of the space so
        # ranking-by-time can't resurrect a hopeless low-memory loser.
        min_b = min(s[1].total_bytes for s in scored)
        pool = [s for s in scored if s[1].total_bytes <= 1.10 * min_b]
        logger.warning(
            "strategy search: no candidate fits %.1f GB HBM "
            "(best needs %.1f GB); dry-run will decide",
            hbm / 1e9, min_b / 1e9,
        )

    def key(item):
        spec, est = item
        t = est.step_s
        for name in prefer:
            if getattr(spec, name, 1) > 1:
                t *= 0.95
        return t

    ranked = sorted(pool, key=key)
    top = ranked[:top_k]
    for spec, est in top:
        logger.info(
            "strategy search: %s -> %.1f GB state + %.1f GB act, "
            "est %.1f ms/step (comm %.1f ms, bubble %.2f)",
            spec, est.state_bytes / 1e9, est.act_bytes / 1e9,
            est.step_s * 1e3, est.comm_s * 1e3, est.bubble,
        )
    return top


def reconfigure_module(module, spec, batch_size: int = 0):
    """Adapt a model to the chosen spec when its config dataclass exposes
    the knobs: ``seq > 1`` flips ``attn_impl`` to the ring kernel,
    ``pipe > 1`` sets ``pipeline_stages`` (+ the microbatch count the
    cost model assumed). Returns the module unchanged when it has no
    ``cfg`` or nothing needs to change."""
    cfg = getattr(module, "cfg", None)
    if cfg is None or not dataclasses.is_dataclass(cfg):
        return module
    fields = {f.name for f in dataclasses.fields(cfg)}
    changes = {}
    if spec.seq > 1 and "attn_impl" in fields and cfg.attn_impl != "ring":
        changes["attn_impl"] = "ring"
    if spec.seq == 1 and getattr(cfg, "attn_impl", None) == "ring":
        changes["attn_impl"] = "xla"
    if "pipeline_stages" in fields:
        want = spec.pipe if spec.pipe > 1 else 0
        if (cfg.pipeline_stages or 0) != want:
            changes["pipeline_stages"] = want
        if want and batch_size and "pipeline_microbatches" in fields:
            changes["pipeline_microbatches"] = _pipe_microbatches(
                spec.pipe, batch_size, spec.data * spec.fsdp
            )
    if not changes:
        return module
    new_cfg = dataclasses.replace(cfg, **changes)
    logger.info("strategy search: reconfigured model %s", changes)
    return type(module)(new_cfg)


# ---------------- elastic mesh reshape (PR-16) ----------------

#: Spec axes whose degree change forces param/optimizer bytes to move
#: (the data axis only re-partitions the batch; params are replicated
#: across it, so changing it moves nothing at rest).
_STATE_MOVING_AXES = ("fsdp", "tensor", "seq", "expert", "pipe")


def spec_from_dict(d: dict):
    """Rebuild a ``ParallelSpec`` from its ``dataclasses.asdict`` form
    (the RescalePlan wire/journal encoding). Unknown keys are dropped so
    old masters' journals replay against newer specs."""
    from dlrover_tpu.accel.accelerate import ParallelSpec

    fields = {f.name for f in dataclasses.fields(ParallelSpec)}
    return ParallelSpec(**{
        k: v for k, v in (d or {}).items() if k in fields
    })


def spec_diff(old, new) -> str:
    """Human-readable axis-by-axis diff, e.g. ``data 2->3, tensor 2->1``.

    ``old``/``new`` may be ParallelSpecs or their asdict dicts; the
    string lands in plan logs, ``RescaleInfeasible`` nacks, timeline
    evidence lines and goodput incidents, so it names only what changed
    (``unchanged`` when nothing did)."""
    if isinstance(old, dict):
        old = spec_from_dict(old)
    if isinstance(new, dict):
        new = spec_from_dict(new)
    parts = []
    for name in ("data", "fsdp", "tensor", "seq", "expert", "pipe"):
        a, b = getattr(old, name), getattr(new, name)
        if a != b:
            parts.append(f"{name} {a}->{b}")
    if old.zero != new.zero:
        parts.append(f"zero {'on->off' if old.zero else 'off->on'}")
    oc = dict(getattr(old, "collectives", ()) or ())
    nc = dict(getattr(new, "collectives", ()) or ())
    if oc != nc:
        for axis in sorted(set(oc) | set(nc)):
            a, b = oc.get(axis, "bw"), nc.get(axis, "bw")
            if a != b:
                parts.append(f"{axis}-coll {a}->{b}")
    return ", ".join(parts) if parts else "unchanged"


def spec_move_distance(old, new) -> float:
    """How much state a transition moves, as a tie-break score: one
    point per state-moving axis whose degree changes, half a point for
    a zero flip (optimizer-state relayout only). The search uses it to
    prefer, among near-equal candidates, the spec that reshards the
    least."""
    d = 0.0
    for name in _STATE_MOVING_AXES:
        if getattr(old, name) != getattr(new, name):
            d += 1.0
    if old.zero != new.zero:
        d += 0.5
    return d


def search_reshape_spec(
    profile: ModelProfile,
    n_devices: int,
    batch_size: int,
    hbm: float,
    current_spec=None,
    abstract_state=None,
    peak_flops: float = _PEAK_FLOPS_DEFAULT,
    stickiness: float = 0.05,
    ici_bw: float = _ICI_BW,
    devices_per_host: int = 0,
    dcn_bw: float = _DCN_BW,
    link_profile: Optional[dict] = None,
) -> Optional[Tuple[Any, CostEstimate]]:
    """Constrained-world search: the best spec for ≤ ``n_devices``.

    The elastic difference from :func:`search_spec`: a membership change
    rarely lands on a friendly device count (4 → 3 with 2 heads), so the
    searched spec may deliberately *idle* devices — every total
    ``m ≤ n_devices`` is enumerated and candidates compete across
    totals, with the cost model pricing the extra accumulation a
    smaller world pays (ElasWave's TP-for-accumulation trade falls out
    of the ranking, not a special case). ``stickiness`` biases the
    choice toward ``current_spec``'s layout: among candidates within
    that fraction of the best step time, the one moving the least state
    (:func:`spec_move_distance`) wins, so a transition that *can* keep
    the mesh shape does. Returns None when nothing is feasible (callers
    fall back to the DP-only plan path)."""
    if n_devices < 1:
        return None
    # A measured profile unlocks the collective-strategy dimension: only
    # with live per-axis bandwidth can the ranking tell where the "lat"
    # variant's reduced wire volume beats the ring's overlap.
    strategies = bool(link_profile)
    cands = []
    for m in range(n_devices, 0, -1):
        cands.extend(enumerate_specs(
            profile, m, batch_size, strategies=strategies
        ))
    if not cands:
        return None
    scored = []
    for spec in cands:
        est = estimate(
            profile, spec, batch_size, hbm, abstract_state, peak_flops,
            ici_bw=ici_bw, devices_per_host=devices_per_host,
            dcn_bw=dcn_bw, link_profile=link_profile,
        )
        scored.append((spec, est))
    fitting = [s for s in scored if s[1].fits(hbm)]
    pool = fitting or scored
    pool = sorted(pool, key=lambda s: s[1].step_s)
    best_t = pool[0][1].step_s
    near = [s for s in pool if s[1].step_s <= best_t * (1.0 + stickiness)]
    if current_spec is not None:
        near.sort(key=lambda s: (
            spec_move_distance(current_spec, s[0]), s[1].step_s,
        ))
    chosen, est = near[0]
    logger.info(
        "reshape search: %d candidates for <=%d devices -> %s "
        "(est %.1f ms/step, move distance %s)",
        len(cands), n_devices, chosen, est.step_s * 1e3,
        "n/a" if current_spec is None
        else spec_move_distance(current_spec, chosen),
    )
    return chosen, est
