"""``auto_accelerate`` — strategy selection + sharded train-step assembly.

Parity: reference ``atorch/atorch/auto/accelerate.py:619`` (analyze model →
pick/search a Strategy → apply optimization wrappers → return wrapped
model/optim/dataloader). The TPU version is leaner because XLA does the
heavy lifting: a "strategy" is just a ``ParallelSpec`` (mesh degrees) plus
rules, and "applying" it is building one jitted train step with in/out
shardings. The dry-run profiler (reference ``auto/dry_runner/``) survives as
``profile=True``: compile and time each candidate spec, keep the fastest.
"""

import dataclasses
import time
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence

from dlrover_tpu.accel.mesh import create_mesh
from dlrover_tpu.accel.sharding import logical_rules, state_shardings, unbox
from dlrover_tpu.common.log import logger

# Training-state bytes per parameter: fp32 master + adam mu/nu + bf16 grad.
_BYTES_PER_PARAM = 16
_DEFAULT_HBM = 16e9  # v5e-class chip; overridable via device memory stats


@dataclass(frozen=True)
class ParallelSpec:
    """Mesh degrees — the Strategy object (parity: accelerate.py Strategy +
    parallel_mode, condensed). ``zero`` is not a mesh axis: it flags
    ZeRO-1 weight-update sharding of the optimizer state over the
    existing ``data`` axis (``accel/zero.py``), composable with any of
    the degrees."""

    data: int = 1
    fsdp: int = 1
    tensor: int = 1
    seq: int = 1
    expert: int = 1
    pipe: int = 1
    zero: bool = False
    #: Per-axis collective algorithm, e.g. ``(("data", "lat"),)``: an
    #: absent axis defaults to ``"bw"`` (flat ring reduce-scatter +
    #: all-gather — full wire volume, overlappable behind backward);
    #: ``"lat"`` is the hierarchical/fused all-reduce (slow-link volume
    #: divided by the host width, fewer launches, critical-path). Chosen
    #: per axis by the measured-bandwidth search (``accel/search.py``);
    #: stored as a sorted tuple of pairs so the frozen spec stays
    #: hashable (a dict or pair-list normalizes in ``__post_init__``).
    collectives: tuple = ()

    def __post_init__(self):
        for name in ("data", "fsdp", "tensor", "seq", "expert", "pipe"):
            if getattr(self, name) < 1:
                raise ValueError(f"{name} degree must be >= 1")
        coll = self.collectives
        if isinstance(coll, dict):
            coll = coll.items()
        norm = tuple(sorted(
            (str(axis), str(strategy)) for axis, strategy in (coll or ())
        ))
        for axis, strategy in norm:
            if strategy not in ("bw", "lat"):
                raise ValueError(
                    f"unknown collective strategy {strategy!r} for axis "
                    f"{axis!r} (want 'bw' or 'lat')"
                )
        object.__setattr__(self, "collectives", norm)

    @property
    def total(self) -> int:
        return (self.data * self.fsdp * self.tensor * self.seq
                * self.expert * self.pipe)

    def axes(self):
        return [
            (name, getattr(self, name))
            for name in ("data", "fsdp", "pipe", "seq", "expert", "tensor")
            if getattr(self, name) > 1
        ]

    def rules(self, vocab_size: int = 0):
        d = dataclasses.asdict(self)
        # Algorithm choice, not a mesh degree — no logical-axis rule.
        d.pop("collectives", None)
        return logical_rules(**d, vocab_size=vocab_size)


@dataclass
class AccelerateResult:
    spec: ParallelSpec
    mesh: Any
    rules: Any
    state: Any                   # materialized, sharded train state
    shardings: Any               # pytree of NamedSharding matching state
    batch_sharding: Any
    train_step: Callable         # (state, batch) -> (state, metrics)
    init_fn: Callable            # (rng) -> sharded state (for re-init)
    search_ranking: Any = None   # [(ParallelSpec, CostEstimate)] from the
                                 # strategy search (None for explicit specs)
    module: Any = None           # the (possibly reconfigured) flax module
                                 # the step was built for


def _device_hbm(devices) -> float:
    try:
        stats = devices[0].memory_stats()
        if stats and "bytes_limit" in stats:
            return float(stats["bytes_limit"])
    except Exception:
        logger.debug("device memory_stats probe failed", exc_info=True)
    return _DEFAULT_HBM


def _divisors_leq(n: int, cap: int) -> List[int]:
    return [d for d in range(1, min(n, cap) + 1) if n % d == 0]


def choose_spec(param_count: int, n_devices: int, hbm: float,
                allow_tensor: bool = False) -> ParallelSpec:
    """Memory-driven heuristic (parity: the reference's local strategy
    generation, ``auto/engine/planner.py`` semantics): pure DP while the
    train state fits comfortably; otherwise shard params over an fsdp axis
    just large enough; TP only on explicit opt-in (the reference calls TP
    semi-auto too, ``optimization_library.py:14``)."""
    state_bytes = param_count * _BYTES_PER_PARAM
    budget = 0.4 * hbm  # leave room for activations + workspace
    if state_bytes <= budget:
        return ParallelSpec(data=n_devices)
    need = int(state_bytes // budget) + 1
    for f in _divisors_leq(n_devices, n_devices):
        if f >= need:
            return ParallelSpec(data=n_devices // f, fsdp=f)
    return ParallelSpec(fsdp=n_devices)


def _check_spec_axes_used(spec, abstract_state):
    """Refuse degrees the model can't use: a ``pipe``/``expert`` degree
    with no parameter carrying the matching logical axis would silently
    replicate over those devices (round-2 weak #7 — phantom axes)."""
    import jax

    # Boxed leaves (nn.Partitioned / nn.LogicallyPartitioned) carry the
    # logical axis names in a `.names` tuple.
    names = set()
    for leaf in jax.tree_util.tree_leaves(
        abstract_state, is_leaf=lambda x: hasattr(x, "names")
    ):
        if hasattr(leaf, "names"):
            names.update(n for n in leaf.names if n)
    for degree, logical in (
        (spec.pipe, "stage"), (spec.expert, "expert")
    ):
        if degree > 1 and logical not in names:
            raise ValueError(
                f"ParallelSpec has {logical!r}-axis degree {degree} but no "
                f"model parameter carries the {logical!r} logical axis — "
                "those devices would be silently wasted. Configure the "
                "model for it (e.g. GPTConfig.pipeline_stages / "
                "num_experts) or drop the degree."
            )


def make_train_step(module, optimizer, loss, mesh, rules,
                    shardings, batch_sharding, donate: bool = True,
                    grad_accum: int = 1, collectives=()):
    """Assemble the jitted SPMD train step for a given strategy.

    ``grad_accum > 1`` splits the leading batch dim into that many
    microbatches and accumulates gradients over a ``lax.scan`` before the
    optimizer update — one compiled computation, activation memory of a
    single microbatch (the ElasticTrainer's world-size-change lever).

    ``collectives`` is the spec's per-axis algorithm map. With the data
    axis on the ``"bw"`` (ring) strategy and ``DLROVER_TPU_COMMS_OVERLAP``
    on, the accumulated gradient tree's *replicated* leaves are pinned
    to their final placement per leaf after the scan: GSPMD lowers one
    bucketed cross-replica reduction per leaf instead of a single fused
    all-reduce over the whole tree, so early buckets' reductions
    overlap the remaining buckets' and the per-leaf optimizer update's
    compute — only the last bucket stays exposed. Crucially the hint
    sits *after* the microbatch accumulation, where the baseline's
    reduction also runs: every gradient element still sums the same
    addends in the same order (a bucket split of an elementwise
    all-reduce touches disjoint elements), so the loss trajectory is
    bitwise that of the serialized step — ``tests/test_comms.py`` and
    the bench's comms arm assert exact equality. (Constraining the
    running sum *inside* the scan would start reductions a microbatch
    earlier but turns sum-then-reduce into reduce-then-sum, and pinning
    fsdp-sharded leaves repartitions the backward — both are real FP
    reassociations, observed non-identical at data=4/fsdp=2.)
    """
    import jax
    import flax.linen as nn

    from dlrover_tpu.common import env_utils

    overlap = (
        grad_accum > 1
        and dict(collectives or ()).get("data", "bw") == "bw"
        and env_utils.COMMS_OVERLAP.get()
    )

    def grads_of(params, batch):
        def scalar_loss(p):
            return loss(module, p, batch)

        return jax.value_and_grad(scalar_loss)(params)

    def step(state, batch):
        # The mesh context makes the mesh discoverable at trace time
        # (thread_resources) — ops like ring attention shard_map over it.
        with mesh, nn.logical_axis_rules(list(rules)):
            import optax

            if grad_accum > 1:
                import jax.numpy as jnp

                b = batch.shape[0]
                if b % grad_accum:
                    raise ValueError(
                        f"batch {b} not divisible by grad_accum "
                        f"{grad_accum}"
                    )
                micro = batch.reshape(
                    grad_accum, b // grad_accum, *batch.shape[1:]
                )

                def body(carry, mb):
                    loss_sum, g_sum = carry
                    lv, g = grads_of(state["params"], mb)
                    g_sum = jax.tree_util.tree_map(
                        lambda a, c: a + c, g_sum, g
                    )
                    return (loss_sum + lv, g_sum), None

                zero = jax.tree_util.tree_map(
                    jnp.zeros_like, state["params"]
                )
                (loss_sum, g_sum), _ = jax.lax.scan(
                    body, (jnp.zeros(()), zero), micro
                )
                lv = loss_sum / grad_accum
                grads = jax.tree_util.tree_map(
                    lambda g: g / grad_accum, g_sum
                )
                if overlap:
                    # Bucketed DP reduction: pin each *replicated* leaf
                    # to its final placement individually so GSPMD
                    # emits one cross-replica reduction per leaf
                    # (interleavable with the next leaves' reduce + the
                    # update sweep) instead of one fused tree-wide
                    # sync. Same graph position as the baseline's
                    # reduction → bit-identical values. Sharded (fsdp/
                    # tensor) leaves are left alone: they already
                    # reduce-scatter per leaf, and forcing a layout
                    # there repartitions the backward (observed FP
                    # reassociation at data=4/fsdp=2).
                    def _pin(g, s):
                        spec = getattr(s, "spec", None)
                        replicated = spec is not None and not any(
                            p is not None for p in spec
                        )
                        if not replicated:
                            return g
                        return jax.lax.with_sharding_constraint(g, s)

                    grads = jax.tree_util.tree_map(
                        _pin, grads, shardings["params"]
                    )
            else:
                lv, grads = grads_of(state["params"], batch)
            fused = getattr(optimizer, "update_and_apply", None)
            if fused is not None:
                # One kernel pass produces the new params (saves the
                # separate apply_updates HBM sweep; optim/low_bit.py).
                params, opt_state = fused(
                    grads, state["opt"], state["params"]
                )
            else:
                updates, opt_state = optimizer.update(
                    grads, state["opt"], state["params"]
                )
                params = optax.apply_updates(state["params"], updates)
            new_state = {
                "params": params, "opt": opt_state,
                "step": state["step"] + 1,
            }
            return new_state, {"loss": lv}

    return jax.jit(
        step,
        in_shardings=(shardings, batch_sharding),
        out_shardings=(shardings, None),
        donate_argnums=(0,) if donate else (),
    )


def transfer_state(state, shardings):
    """Move a LIVE train state onto new shardings (in-place rescale).

    ``jax.device_put`` with a sharding destination is a layout move, not
    a recompute: where the source and destination placements overlap the
    runtime routes device-to-device copies directly, and only leaves
    whose placement actually changed pay a transfer. Values are bitwise
    preserved — resharding never changes the numbers, which is what lets
    a rescale keep the loss trajectory exactly.
    """
    import jax

    return jax.tree_util.tree_map(
        lambda s, x: jax.device_put(x, s), shardings, state
    )


def auto_accelerate(
    module,
    optimizer,
    sample_batch,
    loss: Callable,
    spec: Any = "auto",
    devices: Optional[Sequence] = None,
    rng: Optional[Any] = None,
    profile: bool = False,
    profile_steps: int = 3,
    allow_tensor: Optional[bool] = None,
    grad_accum: int = 1,
    registry=None,
    search_top_k: int = 4,
    offload_optimizer: bool = False,
    precision: str = "bf16",
) -> AccelerateResult:
    """Analyze → choose strategy → build sharded state + train step.

    ``loss(module, params, batch) -> scalar``. ``spec`` may be a
    ``ParallelSpec``, "auto" (cost-model search over the full strategy
    space, ``accel/search.py``), or "auto" + ``profile=True`` (dry-run
    the top-K candidates and keep the fastest, parity:
    ``auto/dry_runner/dry_runner.py``). ``allow_tensor``: None (default)
    lets the search include tensor parallelism for framework models and
    excludes it for plain ones; True enables planner-driven TP for
    plain models; False forbids tensor candidates outright.
    ``offload_optimizer=True`` keeps optimizer state at rest in host
    memory (``optim/offload.py``). ``precision="int8"`` switches the
    model's MLP contractions to AQT-style quantized int8 matmuls
    (``ops/quantized.py``; the TPU analog of the reference's fp8
    training, ``amp_optimization.py:193``) — requires a model whose
    config carries ``mlp_precision``.
    """
    import jax

    devices = list(devices if devices is not None else jax.devices())
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    n = len(devices)

    if precision not in ("bf16", "int8"):
        raise ValueError(f"precision must be 'bf16' or 'int8', got "
                         f"{precision!r}")
    if precision == "int8":
        cfg_q = getattr(module, "cfg", None)
        if cfg_q is None or not hasattr(cfg_q, "mlp_precision"):
            raise ValueError(
                "precision='int8' needs a model config with "
                "mlp_precision (GPTConfig/LlamaConfig)"
            )
        if cfg_q.mlp_precision != "int8":
            # clone() keeps any other module attributes intact
            module = module.clone(
                cfg=dataclasses.replace(cfg_q, mlp_precision="int8")
            )
            logger.info("int8 MLP precision enabled (AQT-style)")

    def build(sp: ParallelSpec, mod=None) -> AccelerateResult:
        from jax.sharding import NamedSharding, PartitionSpec as P

        mod = mod if mod is not None else module
        if sp.total > n:
            raise ValueError(f"{sp} needs {sp.total} devices, have {n}")
        mesh = create_mesh(
            sp.axes() or [("data", 1)], devices=devices[: sp.total]
        )
        rules = sp.rules(
            vocab_size=getattr(
                getattr(mod, "cfg", None), "vocab_size", 0
            ) or 0
        )

        def init_fn(r):
            variables = mod.init(r, sample_batch)
            params = variables["params"]
            return {
                "params": params,
                "opt": optimizer.init(params),
                "step": 0,
            }

        abstract = jax.eval_shape(init_fn, rng)
        from dlrover_tpu.accel.registry import (
            default_registry,
            has_annotations,
        )

        if not has_annotations(abstract["params"]) and sp.total > 1:
            # Plain model (no logical-axis metadata): the registry's
            # path/shape rules make FSDP (and registered TP) work anyway.
            reg = registry
            if reg is None and (allow_tensor or sp.tensor > 1):
                # Automatic TP placement (parity: mip_tp_planner.py):
                # one abstract trace classifies every projection as
                # column-/row-parallel; no hand-written register() calls.
                from dlrover_tpu.accel.tp_planner import plan_tp

                logger.info(
                    "planning tensor-parallel placement automatically"
                )
                reg = plan_tp(mod, rng, sample_batch)
            logger.info(
                "model carries no logical axes; auto-annotating via the "
                "sharding registry"
            )
            abstract = (reg or default_registry).annotate_state(abstract)
        _check_spec_axes_used(sp, abstract)
        if sp.zero:
            # ZeRO-1: re-annotate opt-state leaves with the zero_dp axis
            # (rules already map it to "data" — sp.rules() saw zero=True).
            # Everything downstream is unchanged: the shardings computed
            # from the relabeled tree land in the jit in/out shardings
            # and GSPMD schedules the RS/AG. No optimizer wrapper.
            from dlrover_tpu.accel.zero import apply_zero

            abstract = apply_zero(abstract, sp, rules)
        shardings = state_shardings(mesh, abstract, rules)
        opt = optimizer
        if offload_optimizer:
            from dlrover_tpu.optim.offload import (
                host_memory_kind_supported,
                normalize_shardings,
                offload,
                offload_shardings,
            )

            if host_memory_kind_supported(devices[0]):
                abstract_opt = unbox(abstract["opt"])
                dev_opt = normalize_shardings(
                    shardings["opt"], abstract_opt
                )
                host_opt = offload_shardings(dev_opt, abstract_opt)
                shardings = dict(shardings)
                shardings["opt"] = host_opt
                opt = offload(
                    optimizer, device_shardings=dev_opt,
                    host_shardings=host_opt,
                )
            else:
                logger.warning(
                    "offload_optimizer requested but this backend has "
                    "no host memory space; keeping state in HBM"
                )
        batch_axes = dict(rules)["batch"]
        batch_sharding = NamedSharding(
            mesh, P(*([batch_axes] + [None] * (sample_batch.ndim - 1)))
        )
        # Materialize in default memory, then move the offloaded leaves
        # eagerly: compiling the whole init with host-kind outputs makes
        # XLA place init ops on the host, which not every runtime can
        # execute (the train step only ever *transfers* across spaces).
        init_shardings = shardings
        post_init_put = None
        if opt is not optimizer:  # offload active
            init_shardings = dict(shardings)
            init_shardings["opt"] = dev_opt

            def post_init_put(state):
                import jax as _jax

                state = dict(state)
                state["opt"] = jax.tree_util.tree_map(
                    lambda s, x: _jax.device_put(x, s),
                    shardings["opt"], state["opt"],
                )
                return state

        materialize = jax.jit(
            lambda r: unbox(init_fn(r)), out_shardings=init_shardings
        )
        state = materialize(rng)
        if post_init_put is not None:
            state = post_init_put(state)
            _materialize_base = materialize

            def materialize(r):
                return post_init_put(_materialize_base(r))
        train_step = make_train_step(
            mod, opt, loss, mesh, rules, shardings,
            batch_sharding, grad_accum=grad_accum,
            collectives=sp.collectives,
        )
        return AccelerateResult(
            spec=sp, mesh=mesh, rules=rules, state=state,
            shardings=shardings, batch_sharding=batch_sharding,
            train_step=train_step, init_fn=materialize, module=mod,
        )

    if isinstance(spec, ParallelSpec):
        return build(spec)

    # ---- auto: cost-model search over the full strategy space ----
    import dataclasses as _dc

    import numpy as np

    from dlrover_tpu.accel.search import (
        ModelProfile,
        reconfigure_module,
        search_spec,
    )

    def count_params(mod) -> int:
        abstract = jax.eval_shape(
            lambda r: mod.init(r, sample_batch), rng
        )
        return sum(
            int(np.prod(l.shape))
            for l in jax.tree_util.tree_leaves(unbox(abstract))
        )

    params = count_params(module)
    hbm = _device_hbm(devices)
    cfg = getattr(module, "cfg", None)
    if cfg is not None and _dc.is_dataclass(cfg):
        mprofile = ModelProfile.from_config(cfg, param_count=params)
        if allow_tensor is False:
            # Explicit opt-out: strip the tensor capability from the
            # search space (the default None lets the search decide —
            # that IS the auto contract for framework models).
            mprofile = _dc.replace(mprofile, num_heads=0)
    else:
        mprofile = ModelProfile.from_params(params)
        if allow_tensor:
            # Registry-annotated plain models can TP; expose it to the
            # search by advertising a head count the degrees can divide.
            mprofile = _dc.replace(mprofile, num_heads=n)

    # Exact per-candidate state bytes need the abstract tree for the
    # *reconfigured* module (pipe adds a stage axis); cache per reshape.
    _abstract_cache = {}

    def abstract_for(sp: ParallelSpec):
        mod = reconfigure_module(module, sp, sample_batch.shape[0])
        key = (sp.pipe, getattr(getattr(mod, "cfg", None), "attn_impl", None))
        if key not in _abstract_cache:
            def init_fn(r):
                variables = mod.init(r, sample_batch)
                p = variables["params"]
                return {"params": p, "opt": optimizer.init(p), "step": 0}

            _abstract_cache[key] = jax.eval_shape(init_fn, rng)
        return _abstract_cache[key]

    # Hierarchy awareness: when the device set spans hosts, axes whose
    # collective block crosses the host boundary are priced at DCN.
    hosts = len({getattr(d, "process_index", 0) for d in devices})
    devices_per_host = (n + hosts - 1) // hosts if hosts > 1 else 0
    ranked = search_spec(
        mprofile, n, batch_size=sample_batch.shape[0], hbm=hbm,
        abstract_fn=abstract_for, top_k=max(1, search_top_k),
        devices_per_host=devices_per_host,
    )
    chosen, chosen_est = ranked[0]
    logger.info(
        "auto_accelerate: %.1fM params on %s devices -> search chose %s",
        params / 1e6, n, chosen,
    )
    if not chosen_est.fits(hbm) and not offload_optimizer:
        # The binding constraint is memory and most of it is optimizer
        # state at rest: say so instead of letting the compile OOM
        # mutely (parity: the reference engine's strategy feedback).
        logger.warning(
            "auto_accelerate: best strategy %s needs %.1f GB/device "
            "(%.1f GB HBM); the optimizer state is %.0f%% of it — "
            "consider offload_optimizer=True and/or the 8-bit adam",
            chosen, chosen_est.total_bytes / 1e9, hbm / 1e9,
            100 * max(
                0.0, 1 - 8.0 * params / max(chosen_est.state_bytes, 1)
            ),
        )
    if not profile or len(ranked) == 1:
        result = build(
            chosen,
            reconfigure_module(module, chosen, sample_batch.shape[0]),
        )
        result.search_ranking = ranked
        return result

    best, best_time = None, float("inf")
    for cand, _est in ranked:
        try:
            result = build(cand, reconfigure_module(module, cand, sample_batch.shape[0]))
            state, batch = result.state, jax.device_put(
                sample_batch, result.batch_sharding
            )
            state, _ = result.train_step(state, batch)  # compile + warm
            jax.block_until_ready(state)
            t0 = time.perf_counter()
            for _ in range(profile_steps):
                state, _ = result.train_step(state, batch)
            jax.block_until_ready(state)
            dt = (time.perf_counter() - t0) / profile_steps
            logger.info("dry-run %s: %.1f ms/step", cand, dt * 1e3)
            if dt < best_time:
                best, best_time = cand, dt
        except Exception as e:
            logger.warning("dry-run %s failed: %s", cand, e)
    if best is None:
        best = chosen
    result = build(
        best, reconfigure_module(module, best, sample_batch.shape[0])
    )
    result.search_ranking = ranked
    return result
