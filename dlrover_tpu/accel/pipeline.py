"""Pipeline parallelism — GPipe schedule as spatial SPMD over the mesh.

Capability parity with the reference's pipeline compiler
(``atorch/atorch/modules/distributed_modules/compilers/pipe_compiler/PipelineStage.py``:
graph-split stages, P2P send/recv between ranks, 1F1B/GPipe runtime). The
TPU-first design needs none of that machinery: stages are a *vmapped array
dimension* whose logical axis (``stage``) is sharded over the ``pipe``
mesh axis, and the schedule is a ``scan`` over ``M + P - 1`` ticks in
which every stage processes its current microbatch concurrently and
activations shift one stage forward via ``jnp.roll`` on the stage dim —
which XLA lowers to a ``collective-permute`` over ICI. No P2P plumbing,
no per-rank programs: one SPMD computation, differentiable end-to-end
(the roll's transpose is the reverse permute, so the backward pass is the
same pipeline run in reverse).

Bubble fraction is the GPipe ``(P-1)/(M+P-1)``; raise
``num_microbatches`` to amortize. The schedule is mathematically exact —
outputs are identical to running the stages sequentially (tested).
"""

from typing import Any, Callable, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax


class _StageWrap(nn.Module):
    """Adapter giving the user's stage module a stable param path
    (``.../stages/stage/...``) under the vmap."""

    make: Callable[[], nn.Module]

    @nn.compact
    def __call__(self, x):
        return self.make()(x)


class _PipeTick(nn.Module):
    """One schedule tick: feed, compute all stages, collect, shift."""

    make_stage: Callable[[], nn.Module]
    num_microbatches: int
    carry_axes: Tuple

    @nn.compact
    def __call__(self, carry, t):
        state, outs, xs = carry
        m = self.num_microbatches
        p = state.shape[0]

        # Feed microbatch t into stage 0 (slot 0 holds garbage rolled off
        # the last stage otherwise; it is always overwritten while fresh
        # microbatches remain).
        inp = jnp.take(xs, jnp.minimum(t, m - 1), axis=0)
        state = state.at[0].set(jnp.where(t < m, inp, state[0]))
        state = nn.with_logical_constraint(
            state, ("stage",) + self.carry_axes
        )

        stages = nn.vmap(
            _StageWrap,
            in_axes=0,
            out_axes=0,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            metadata_params={nn.PARTITION_NAME: "stage"},
        )(self.make_stage, name="stages")
        processed = stages(state)

        # The last stage finishes microbatch t-(P-1) at this tick.
        done = t - (p - 1)
        outs = jnp.where(
            done >= 0,
            lax.dynamic_update_index_in_dim(
                outs, processed[-1], jnp.maximum(done, 0), 0
            ),
            outs,
        )
        # Shift every activation one stage forward (collective-permute
        # when the stage dim is sharded over `pipe`).
        state = jnp.roll(processed, 1, axis=0)
        return (state, outs, xs), None


class Pipeline(nn.Module):
    """Run ``num_stages`` copies of ``make_stage()`` as a GPipe pipeline.

    ``make_stage`` must return a fresh flax module mapping a microbatch
    ``[mb, ...]`` to the same shape; its parameters get a leading
    ``stage`` logical axis (map it to the ``pipe`` mesh axis via the
    sharding rules). ``carry_axes`` are the logical axes of one
    microbatch (e.g. ``("batch", "seq", "embed")``) used to keep the
    in-flight activations sharded.
    """

    make_stage: Callable[[], nn.Module]
    num_stages: int
    num_microbatches: int = 0
    carry_axes: Tuple = ("batch", None, None)

    @nn.compact
    def __call__(self, x):
        p = self.num_stages
        m = self.num_microbatches or p
        b = x.shape[0]
        if b % m != 0:
            raise ValueError(
                f"batch {b} not divisible by {m} microbatches"
            )
        mb = b // m
        xs = x.reshape(m, mb, *x.shape[1:])
        xs = nn.with_logical_constraint(xs, (None,) + self.carry_axes)

        state = jnp.zeros((p, mb) + x.shape[1:], x.dtype)
        outs = jnp.zeros_like(xs)
        ticks = nn.scan(
            _PipeTick,
            variable_broadcast="params",
            split_rngs={"params": False},
            in_axes=0,
            length=m + p - 1,
        )(
            self.make_stage, m, self.carry_axes, name="ticks"
        )
        (state, outs, _), _ = ticks(
            (state, outs, xs), jnp.arange(m + p - 1)
        )
        return outs.reshape(b, *x.shape[1:])
