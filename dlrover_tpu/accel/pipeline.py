"""Pipeline parallelism — GPipe + circular (interleaved) schedules as
spatial SPMD over the mesh.

Capability parity with the reference's pipeline compiler
(``atorch/atorch/modules/distributed_modules/compilers/pipe_compiler/PipelineStage.py``:
graph-split stages, P2P send/recv between ranks, 1F1B/interleaved
runtime). The TPU-first design needs none of that machinery: stages are a
*vmapped array dimension* whose logical axis (``stage``) is sharded over
the ``pipe`` mesh axis, and a schedule is a ``scan`` over ticks in which
every stage processes its current microbatch concurrently and activations
shift one stage forward via ``jnp.roll`` on the stage dim — which XLA
lowers to a ``collective-permute`` over ICI. No P2P plumbing, no per-rank
programs: one SPMD computation, differentiable end-to-end (the roll's
transpose is the reverse permute, so the backward pass is the same
pipeline run in reverse — giving 1F1B's bounded-in-flight memory
property for free under the scan's rematerialization).

Two schedules:

- :class:`Pipeline` — GPipe. ``M + P - 1`` ticks, bubble ``(P-1)/(M+P-1)``.
- :class:`CircularPipeline` — the interleaved/"virtual stages" schedule
  (Megatron-LM interleaved 1F1B's bubble cut, praxis' circular layout):
  the layer stack is split into ``C*P`` chunks and device ``p`` owns
  chunks ``p, p+P, ..., p+(C-1)P`` (strided), so each microbatch makes
  ``C`` passes around the ring. Ticks: ``C*M + P - 1`` at ``1/C`` the
  per-tick work — the drain bubble shrinks from ``(P-1)`` full-stage
  ticks to ``(P-1)`` chunk ticks, cutting the bubble fraction ~``C``×.
  Per-tick chunk selection is a per-stage dynamic index (batched
  gather) into the local ``C`` dim of the weight bank, reading only
  the selected ``1/C`` of the resident layers each tick.

Both schedules carry an auxiliary scalar (MoE load-balance loss)
alongside the activations, so expert-parallel MoE composes with pipeline
parallelism: a stage may return ``(y, aux)`` and the pipeline returns
``(outs, aux_mean)``.

The schedules are mathematically exact — outputs are identical to
running the chunks sequentially (tested).
"""

import dataclasses
from typing import Any, Callable, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax


def gpipe_ticks(num_microbatches: int, num_stages: int) -> int:
    return num_microbatches + num_stages - 1


def circular_ticks(num_microbatches: int, num_stages: int,
                   num_repeats: int) -> int:
    return num_repeats * num_microbatches + num_stages - 1


def schedule_cost(num_microbatches: int, num_stages: int,
                  num_repeats: int = 1) -> float:
    """Wall-clock of one pipeline pass in units of one *full forward*
    (all layers, one microbatch): ticks x per-tick work. Lower is
    better; the ideal (bubble-free) value is ``M / P``."""
    if num_repeats <= 1:
        return gpipe_ticks(num_microbatches, num_stages) / num_stages
    return circular_ticks(num_microbatches, num_stages, num_repeats) / (
        num_repeats * num_stages
    )


def _split_out(out):
    """Normalize a stage output to (y, aux_scalar_per_stage)."""
    if isinstance(out, tuple):
        y, aux = out
        return y, jnp.asarray(aux, jnp.float32)
    return out, None


class _StageWrap(nn.Module):
    """Adapter giving the user's stage module a stable param path
    (``.../stages/stage/...``) under the vmap."""

    make: Callable[[], nn.Module]

    @nn.compact
    def __call__(self, x):
        return self.make()(x)


class _PipeTick(nn.Module):
    """One GPipe tick: feed, compute all stages, collect, shift."""

    make_stage: Callable[[], nn.Module]
    num_microbatches: int
    carry_axes: Tuple
    overlap_collectives: bool = True

    @nn.compact
    def __call__(self, carry, t):
        state, aux_state, outs, aux_outs, xs = carry
        m = self.num_microbatches
        p = state.shape[0]

        # Feed microbatch t into stage 0 (slot 0 holds garbage rolled off
        # the last stage otherwise; it is always overwritten while fresh
        # microbatches remain).
        inp = jnp.take(xs, jnp.minimum(t, m - 1), axis=0)
        state = state.at[0].set(jnp.where(t < m, inp, state[0]))
        aux_state = aux_state.at[0].set(
            jnp.where(t < m, 0.0, aux_state[0])
        )
        state = nn.with_logical_constraint(
            state, ("stage",) + self.carry_axes
        )

        stages = nn.vmap(
            _StageWrap,
            in_axes=0,
            out_axes=0,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            metadata_params={nn.PARTITION_NAME: "stage"},
        )(self.make_stage, name="stages")
        processed, chunk_aux = _split_out(stages(state))
        if chunk_aux is None:
            aux_proc = aux_state
        else:
            aux_proc = aux_state + chunk_aux

        # The last stage finishes microbatch t-(P-1) at this tick.
        done = t - (p - 1)
        outs = jnp.where(
            done >= 0,
            lax.dynamic_update_index_in_dim(
                outs, processed[-1], jnp.maximum(done, 0), 0
            ),
            outs,
        )
        aux_outs = jnp.where(
            done >= 0,
            lax.dynamic_update_index_in_dim(
                aux_outs, aux_proc[-1], jnp.maximum(done, 0), 0
            ),
            aux_outs,
        )
        if self.overlap_collectives:
            # Pin the collected outputs to their final placement every
            # tick: the last stage's finished microbatch moves to the
            # output shard *during* the next tick's compute (one small
            # per-tick transfer), instead of one bulk relayout after the
            # scan. Placement-only — values are bit-identical with the
            # constraint off.
            outs = nn.with_logical_constraint(
                outs, (None,) + self.carry_axes
            )
        # Shift every activation one stage forward (collective-permute
        # when the stage dim is sharded over `pipe`).
        state = jnp.roll(processed, 1, axis=0)
        aux_state = jnp.roll(aux_proc, 1, axis=0)
        return (state, aux_state, outs, aux_outs, xs), None


class Pipeline(nn.Module):
    """Run ``num_stages`` copies of ``make_stage()`` as a GPipe pipeline.

    ``make_stage`` must return a fresh flax module mapping a microbatch
    ``[mb, ...]`` to the same shape (optionally ``(y, aux_scalar)`` for
    MoE stages); its parameters get a leading ``stage`` logical axis
    (map it to the ``pipe`` mesh axis via the sharding rules).
    ``carry_axes`` are the logical axes of one microbatch (e.g.
    ``("batch", "seq", "embed")``) used to keep the in-flight
    activations sharded. Returns ``y`` or ``(y, aux_mean)`` matching the
    stage's own return shape.
    """

    make_stage: Callable[[], nn.Module]
    num_stages: int
    num_microbatches: int = 0
    carry_axes: Tuple = ("batch", None, None)
    has_aux: bool = False   # stage returns (y, aux) — e.g. MoE stages
    # Constrain finished-microbatch outputs to their final placement per
    # tick so the stage-boundary transfers interleave with compute (see
    # _PipeTick). Bit-identical either way; off = the serialized
    # baseline bench.py's comms section measures against.
    overlap_collectives: bool = True

    @nn.compact
    def __call__(self, x):
        p = self.num_stages
        m = self.num_microbatches or p
        b = x.shape[0]
        if b % m != 0:
            raise ValueError(
                f"batch {b} not divisible by {m} microbatches"
            )
        mb = b // m
        xs = x.reshape(m, mb, *x.shape[1:])
        xs = nn.with_logical_constraint(xs, (None,) + self.carry_axes)

        state = jnp.zeros((p, mb) + x.shape[1:], x.dtype)
        aux_state = jnp.zeros((p,), jnp.float32)
        outs = jnp.zeros_like(xs)
        aux_outs = jnp.zeros((m,), jnp.float32)
        ticks = nn.scan(
            _PipeTick,
            variable_broadcast="params",
            split_rngs={"params": False},
            in_axes=0,
            length=m + p - 1,
        )(
            self.make_stage, m, self.carry_axes,
            self.overlap_collectives, name="ticks",
        )
        (state, _, outs, aux_outs, _), _ = ticks(
            (state, aux_state, outs, aux_outs, xs),
            jnp.arange(m + p - 1),
        )
        y = outs.reshape(b, *x.shape[1:])
        if self.has_aux:
            # Each stage contributed its mean-over-own-layers; divide by
            # the stage count so the total equals the dense model's
            # mean-over-all-layers.
            return y, jnp.mean(aux_outs) / p
        return y


def _box_bank(tree, p_, c_):
    """Reshape each leaf [P*C, ...] -> [P, C, ...] and prefix the
    logical axes with ("stage", None) so the sharding rules put chunk
    banks on the ``pipe`` mesh axis (the C dim stays device-local).
    Leaves may arrive boxed (``nn.with_logical_partitioning`` inits) or
    plain; both end up LogicallyPartitioned."""
    from flax.linen.spmd import LogicallyPartitioned

    def fix(leaf):
        if isinstance(leaf, LogicallyPartitioned):
            v = leaf.unbox()
            v = v.reshape(p_, c_, *v.shape[1:])
            return dataclasses.replace(
                leaf, value=v, names=("stage", None) + tuple(leaf.names)
            )
        v = leaf.reshape(p_, c_, *leaf.shape[1:])
        return LogicallyPartitioned(
            v, names=("stage", None) + (None,) * (v.ndim - 2)
        )

    return jax.tree_util.tree_map(
        fix, tree,
        is_leaf=lambda l: isinstance(l, LogicallyPartitioned),
    )


class CircularPipeline(nn.Module):
    """Interleaved ("circular") pipeline: ``C*P`` chunks on ``P`` stages.

    Device ``p`` owns chunks ``p, p+P, ..., p+(C-1)P``; a microbatch
    travels the ring ``C`` times. Chunk ``(c, p)`` of microbatch ``m``
    runs at tick ``t = c*M + p + m`` — neighbouring chunks are one tick
    (one ``roll``) apart, and the ring-wrap edge ``(c, P-1) → (c+1, 0)``
    has latency ``D = M - P + 1`` ticks, carried by a ``D``-slot FIFO.
    Requires ``M >= P``.

    The per-tick weight for stage position ``p`` is chunk
    ``c = clip((t-p)//M, 0, C-1)``, selected from the ``[P, C, ...]``
    weight bank by a per-stage dynamic index (batched gather) — per
    tick each device reads ``1/C`` of its resident layers, so weight
    traffic per full pass is ``(C*M+P-1)/(C*(M+P-1))`` of GPipe's
    (slightly *below* 1 for C>1; measured on-chip — see the table in
    ``docs/pipeline_schedules.md``; ``tests/test_pipeline.py``
    pins per-tick FLOPs at 1/C and slice/onehot bit-exactness).
    Gradients scatter-add back into just the selected chunk.

    Parity: Megatron interleaved 1F1B / reference ``PipelineStage.py``
    virtual stages; the spatial-SPMD formulation follows the praxis
    circular schedule. Bubble: ``(P-1)`` chunk-ticks instead of GPipe's
    ``(P-1)`` full-stage ticks — a ~``C``x cut (see ``schedule_cost``).
    """

    make_stage: Callable[[], nn.Module]   # builds ONE chunk
    num_stages: int                        # P (pipe mesh degree)
    num_repeats: int                       # C (chunks per device)
    num_microbatches: int = 0              # M >= P
    carry_axes: Tuple = ("batch", None, None)
    # Chunk-selection lowering. "slice" (default) is the per-stage
    # dynamic index / gather: 1/C of the bank per tick. "onehot" is the
    # dense contraction kept ONLY as a measurement baseline — it reads
    # the entire resident bank every tick (C x the weight traffic; see
    # docs/pipeline_schedules.md for the on-chip numbers).
    chunk_select: str = "slice"
    # Same per-tick output-placement constraint as Pipeline: finished
    # microbatches migrate to the output shard tick by tick instead of
    # in one post-scan relayout. Bit-identical either way.
    overlap_collectives: bool = True

    @nn.compact
    def __call__(self, x):
        if self.chunk_select not in ("slice", "onehot"):
            raise ValueError(
                f"chunk_select must be 'slice' or 'onehot', got "
                f"{self.chunk_select!r}"
            )
        p_ = self.num_stages
        c_ = self.num_repeats
        m = self.num_microbatches or p_
        if m < p_:
            raise ValueError(
                f"circular schedule needs microbatches >= stages "
                f"(got M={m} < P={p_})"
            )
        b = x.shape[0]
        if b % m:
            raise ValueError(
                f"batch {b} not divisible by {m} microbatches"
            )
        mb = b // m
        d_ = m - p_ + 1  # ring-wrap FIFO depth
        xs = x.reshape(m, mb, *x.shape[1:])
        xs = nn.with_logical_constraint(xs, (None,) + self.carry_axes)

        template = self.make_stage()
        dummy = jnp.zeros((mb,) + x.shape[1:], x.dtype)

        def bank_init(rng):
            # Per-chunk independent init: one key per (p, c) chunk.
            keys = jax.random.split(rng, p_ * c_)
            banks = jax.vmap(
                lambda k: template.init(k, dummy)["params"]
            )(keys)
            return _box_bank(banks, p_, c_)

        bank = nn.meta.unbox(self.param("bank", bank_init))

        # Probe the chunk's return contract at trace time via eval_shape
        # (no FLOPs): MoE chunks return (y, aux).
        probe = jax.eval_shape(
            lambda w, d: template.apply({"params": w}, d),
            jax.tree_util.tree_map(lambda a: a[0, 0], bank), dummy,
        )
        has_aux = isinstance(probe, tuple)

        def apply_chunk(w, xp):
            out = template.apply({"params": w}, xp)
            y, aux = _split_out(out)
            return y, (aux if aux is not None
                       else jnp.zeros((), jnp.float32))

        iota_p = jnp.arange(p_)

        def tick(carry, t):
            state, aux_state, buf, aux_buf, outs, aux_outs = carry
            # --- feed stage 0 ---
            rel0 = t  # t - p for p=0
            m0 = jnp.mod(rel0, m)
            c0 = rel0 // m
            slot = jnp.mod(t, d_)
            fresh = jnp.take(xs, jnp.minimum(m0, m - 1), axis=0)
            wrapped = jnp.take(buf, slot, axis=0)
            aux_wrapped = jnp.take(aux_buf, slot, axis=0)
            use_fresh = c0 == 0
            active0 = rel0 < c_ * m
            inp = jnp.where(use_fresh, fresh, wrapped)
            state = state.at[0].set(jnp.where(active0, inp, state[0]))
            aux_in = jnp.where(use_fresh, 0.0, aux_wrapped)
            aux_state = aux_state.at[0].set(
                jnp.where(active0, aux_in, aux_state[0])
            )
            state = nn.with_logical_constraint(
                state, ("stage",) + self.carry_axes
            )

            # --- select chunk weights + compute all stages ---
            # Per-stage dynamic index into the local C dim: a batched
            # gather that reads ONLY the selected chunk — 1/C of the
            # resident bank per tick. (A one-hot contraction would be
            # numerically identical but touches every chunk every tick:
            # C x the HBM weight traffic, erasing the bubble win at
            # memory-bound microbatch sizes. Its transpose also writes
            # the full-bank gradient per tick; the gather's transpose is
            # a scatter-add into just the selected chunk.)
            c_per = jnp.clip((t - iota_p) // m, 0, c_ - 1)

            if self.chunk_select == "onehot":
                onehot = jax.nn.one_hot(c_per, c_, dtype=state.dtype)
                selected = jax.tree_util.tree_map(
                    lambda w: jnp.einsum(
                        "pc...,pc->p...", w, onehot.astype(w.dtype)
                    ),
                    bank,
                )
            else:
                selected = jax.tree_util.tree_map(
                    lambda w: jax.vmap(
                        lambda wp, cp: lax.dynamic_index_in_dim(
                            wp, cp, axis=0, keepdims=False
                        )
                    )(w, c_per),
                    bank,
                )
            y, chunk_aux = jax.vmap(apply_chunk)(selected, state)
            aux_y = aux_state + chunk_aux

            # --- last stage output: done, wrap, or garbage ---
            rel_last = t - (p_ - 1)
            m_last = jnp.mod(rel_last, m)
            c_last = rel_last // m
            is_done = (rel_last >= 0) & (c_last == c_ - 1)
            is_wrap = (rel_last >= 0) & (c_last < c_ - 1)
            outs = jnp.where(
                is_done,
                lax.dynamic_update_index_in_dim(
                    outs, y[-1], jnp.maximum(m_last, 0), 0
                ),
                outs,
            )
            aux_outs = jnp.where(
                is_done,
                lax.dynamic_update_index_in_dim(
                    aux_outs, aux_y[-1], jnp.maximum(m_last, 0), 0
                ),
                aux_outs,
            )
            if self.overlap_collectives:
                outs = nn.with_logical_constraint(
                    outs, (None,) + self.carry_axes
                )
            buf = jnp.where(
                is_wrap,
                lax.dynamic_update_index_in_dim(buf, y[-1], slot, 0),
                buf,
            )
            aux_buf = jnp.where(
                is_wrap,
                lax.dynamic_update_index_in_dim(
                    aux_buf, aux_y[-1], slot, 0
                ),
                aux_buf,
            )

            state = jnp.roll(y, 1, axis=0)
            aux_state = jnp.roll(aux_y, 1, axis=0)
            return (state, aux_state, buf, aux_buf, outs, aux_outs), None

        state = jnp.zeros((p_, mb) + x.shape[1:], x.dtype)
        aux_state = jnp.zeros((p_,), jnp.float32)
        buf = jnp.zeros((d_, mb) + x.shape[1:], x.dtype)
        aux_buf = jnp.zeros((d_,), jnp.float32)
        outs = jnp.zeros_like(xs)
        aux_outs = jnp.zeros((m,), jnp.float32)
        n_ticks = circular_ticks(m, p_, c_)
        (state, _, _, _, outs, aux_outs), _ = lax.scan(
            tick,
            (state, aux_state, buf, aux_buf, outs, aux_outs),
            jnp.arange(n_ticks),
        )
        y = outs.reshape(b, *x.shape[1:])
        if has_aux:
            # C*P chunks each contributed its mean-over-own-layers.
            return y, jnp.mean(aux_outs) / (p_ * c_)
        return y
