"""Acceleration layer — the ATorch analog, TPU-first.

Capability parity with ``atorch/atorch/distributed/distributed.py`` (named
parallel groups) and ``atorch/atorch/auto/accelerate.py`` (auto_accelerate),
re-designed for XLA's compilation model: instead of wrapping a model in
DDP/FSDP/TP modules over NCCL process groups, we build ONE
``jax.sharding.Mesh`` with named axes and express every parallelism as a
sharding rule GSPMD compiles into ICI/DCN collectives.
"""

from dlrover_tpu.accel.mesh import (  # noqa: F401
    MeshConfig,
    create_mesh,
    local_mesh,
)
from dlrover_tpu.accel.sharding import (  # noqa: F401
    ShardingRules,
    logical_rules,
    state_shardings,
)
from dlrover_tpu.accel.accelerate import (  # noqa: F401
    AccelerateResult,
    ParallelSpec,
    auto_accelerate,
)
from dlrover_tpu.accel.search import (  # noqa: F401
    CostEstimate,
    ModelProfile,
    search_spec,
)
from dlrover_tpu.accel.tp_planner import plan_tp  # noqa: F401
