"""Training profiler — per-step timing, model cost analysis, MFU, and
XLA trace capture.

Capability parity with the reference's AProfiler
(``atorch/atorch/utils/prof.py:39-464``: per-module forward hooks
collecting flops/macs/duration, timeline export, GPU-utilization
estimate). The torch version hooks every ``nn.Module`` because eager
execution is observable; under jit there is nothing to hook — XLA fuses
the graph — so the TPU-first design measures at the three boundaries
that exist:

- **step timing** (host wall-clock per step, categorized phases:
  ``with prof.phase("data")``),
- **model cost** via ``jax.jit(...).lower().cost_analysis()`` — the
  *compiler's* flops/bytes for the exact compiled computation (more
  truthful than per-module analytical counts),
- **device timeline** via ``jax.profiler`` trace capture on a step
  schedule (the TensorBoard-viewable analog of AProfiler's timeline).

``utilization()`` reports MFU against the device's peak flops —
AProfiler's ``compute_gpu_utilization`` analog.
"""

import contextlib
import os
import time
from collections import defaultdict, deque
from typing import Any, Dict, List, Optional

from dlrover_tpu.common import env_utils
from dlrover_tpu.common.log import logger

# Peak dense fp/bf16 FLOPs by TPU generation substring (public specs).
_PEAK_FLOPS = (
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v4", 275e12),
    ("v6", 918e12),
)


def device_peak_flops(device=None) -> float:
    import jax

    device = device or jax.devices()[0]
    kind = device.device_kind.lower()
    for key, peak in _PEAK_FLOPS:
        if key in kind:
            return peak
    return env_utils.PEAK_FLOPS.get()


class StepStats:
    """Bounded step-time accumulator.

    Samples live in a ring (``window`` newest) so a long run neither
    grows without bound nor pays an ever-larger full sort per
    ``percentile`` call — the sort cost is capped by the window.
    ``count`` stays the *total* number of observations (the report's
    step counter); ``mean``/``percentile`` describe the window.
    """

    def __init__(self, window: int = 1024):
        self.times: deque = deque(maxlen=window)
        self._total = 0
        self._window_sum = 0.0

    def add(self, dt: float):
        if len(self.times) == self.times.maxlen:
            self._window_sum -= self.times[0]
        self.times.append(dt)
        self._window_sum += dt
        self._total += 1

    @property
    def count(self) -> int:
        return self._total

    @property
    def mean(self) -> float:
        return self._window_sum / len(self.times) if self.times else 0.0

    def percentile(self, p: float) -> float:
        if not self.times:
            return 0.0
        xs = sorted(self.times)
        idx = min(len(xs) - 1, int(p / 100 * len(xs)))
        return xs[idx]


class PhaseBreakdown:
    """Per-step wall-time split into the four phases a host thread can
    actually see under async dispatch, with NO extra device syncs.

    The trainer hands over three raw host segments per step:

    - ``input_s``  — blocking on the input pipeline (``next(it)``),
    - ``dispatch_s`` — from input done to the jitted step's dispatch
      returning (host-side work; an injected host straggle lands here),
    - ``fence_s`` — blocking on the lag-1 metric fence (device-bound
      wait: the previous step's compute plus any exposed collective),
    - ``readback_s`` — converting the fenced metrics to host floats.

    The fence wall conflates compute with exposed-communication wait, so
    the split uses a rolling *best-case* fence (the window minimum) as
    the pure-compute estimate: ``collective_s`` is the excess over that
    floor — a degraded link inflates it while steady compute does not —
    and ``compute_s`` is ``dispatch_s`` plus the floor. A heuristic, but
    one whose failure direction is safe: host-side straggle can never
    masquerade as link straggle.

    Stats ride the same bounded :class:`StepStats` rings as step times.
    """

    KEYS = ("input_s", "compute_s", "collective_s", "readback_s")

    def __init__(self, window: int = 256, fence_window: int = 16):
        self._fences: deque = deque(maxlen=fence_window)
        self.stats: Dict[str, StepStats] = {
            k: StepStats(window) for k in self.KEYS
        }
        self.last: Dict[str, float] = {}

    def split(self, input_s: float, dispatch_s: float, fence_s: float,
              readback_s: float = 0.0) -> Dict[str, float]:
        self._fences.append(fence_s)
        base = min(self._fences)
        collective = max(0.0, fence_s - base)
        phases = {
            "input_s": input_s,
            "compute_s": dispatch_s + (fence_s - collective),
            "collective_s": collective,
            "readback_s": readback_s,
        }
        for k, v in phases.items():
            self.stats[k].add(v)
        self.last = phases
        return phases

    def report(self) -> Dict[str, Dict[str, float]]:
        return {
            k: {
                "mean_s": round(st.mean, 6),
                "p99_s": round(st.percentile(99), 6),
            }
            for k, st in self.stats.items()
        }


class Profiler:
    """Step/phase timing + cost analysis + trace capture.

    Usage::

        prof = Profiler(trace_dir="/tmp/trace", trace_steps=(10, 13))
        for step in range(steps):
            with prof.step():
                with prof.phase("data"):
                    batch = next(loader)
                state, metrics = train_step(state, batch)
                prof.fence(metrics["loss"])   # honored iff sync=True
        print(prof.report())

    Step-time honesty under async dispatch: a jitted step returns to the
    host in microseconds while the device still computes, so the plain
    wall clock measures *dispatch*, not the step. ``sync=True`` makes
    ``step()`` block on the value registered via :meth:`fence` (or on
    all devices when no fence was registered) before recording the
    time — true device-inclusive step times, at the cost of a full
    sync per step (use it for profiling runs, not the production
    pipelined loop). The default ``sync=False`` keeps the context
    non-blocking and the report labels its numbers
    ``timing: "dispatch"`` so nobody mistakes them for device time.
    """

    def __init__(self, trace_dir: str = "",
                 trace_steps: Optional[tuple] = None,
                 sync: bool = False):
        self._step_stats = StepStats()
        self._phase_stats: Dict[str, StepStats] = defaultdict(StepStats)
        self._trace_dir = trace_dir
        self._trace_steps = trace_steps or ()
        self._tracing = False
        self._step_index = 0
        self._cost: Optional[Dict] = None
        self._sync = bool(sync)
        self._fence = None

    # ------------- timing -------------
    def fence(self, value):
        """Register this step's output (array or pytree) as the sync
        point; in ``sync=True`` mode ``step()`` blocks on it before
        recording the step time. Returns ``value`` unchanged."""
        self._fence = value
        return value

    def _sync_now(self):
        import jax

        if self._fence is not None:
            jax.block_until_ready(self._fence)
            return
        # No fence registered: best-effort barrier on everything in
        # flight (not every backend exposes one — then dispatch time is
        # what gets recorded, same as sync=False).
        for d in jax.devices():
            try:
                d.synchronize_all_activity()
            except Exception:
                return

    @contextlib.contextmanager
    def step(self):
        self._maybe_start_trace()
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            if self._sync:
                self._sync_now()
            self._fence = None
            self._step_stats.add(time.perf_counter() - t0)
            self._step_index += 1
            self._maybe_stop_trace()

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._phase_stats[name].add(time.perf_counter() - t0)

    # ------------- XLA trace capture -------------
    def _maybe_start_trace(self):
        if (
            self._trace_dir
            and not self._tracing
            and self._trace_steps
            and self._step_index == self._trace_steps[0]
        ):
            import jax

            jax.profiler.start_trace(self._trace_dir)
            self._tracing = True
            logger.info("profiler: trace started at step %s -> %s",
                        self._step_index, self._trace_dir)

    def _maybe_stop_trace(self):
        if self._tracing and self._step_index >= self._trace_steps[1]:
            import jax

            jax.profiler.stop_trace()
            self._tracing = False
            logger.info("profiler: trace stopped at step %s",
                        self._step_index)

    # ------------- model cost -------------
    def analyze(self, jitted_fn, *example_args) -> Dict[str, Any]:
        """Compiler-reported cost of the jitted computation
        (flops / bytes accessed / output bytes), AProfiler's
        flops-profile analog but from XLA itself."""
        lowered = jitted_fn.lower(*example_args)
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, list):  # older jax returns [dict]
            cost = cost[0] if cost else {}
        self._cost = {
            "flops": float(cost.get("flops", 0.0)),
            "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
        }
        return dict(self._cost)

    def utilization(self, flops_per_step: Optional[float] = None,
                    device=None) -> float:
        """MFU in [0,1]: (flops/step) / (peak * mean step time)."""
        flops = flops_per_step or (self._cost or {}).get("flops", 0.0)
        peak = device_peak_flops(device)
        mean = self._step_stats.mean
        if not (flops and peak and mean):
            return -1.0
        return flops / mean / peak

    # ------------- per-module attribution -------------
    def module_costs(
        self,
        module,
        rng,
        *example_args,
        depth: int = 2,
        top_k: int = 0,
    ) -> List[Dict[str, Any]]:
        """Per-module FLOPs/bytes census — AProfiler's module table
        (``atorch/atorch/utils/prof.py:39-464``) rebuilt for jit: torch
        hooks every module because eager is observable; here a flax
        *method interceptor* records each submodule call (path + input
        shapes) during one abstract trace, then every recorded module is
        independently lowered and the **compiler's own** cost analysis
        (flops / bytes accessed) is attributed to its path.

        Rows are sorted by flops; ``share`` is relative to the root
        module's total. XLA's cost analysis counts a while-loop body
        ONCE, so a module lifted by ``nn.scan`` reports *per-iteration*
        cost — pass an unrolled config (``scan_layers=False``) for exact
        whole-stack accounting.
        """
        import jax
        import flax.linen as nn

        records = []
        seen = set()

        def interceptor(next_fn, args, kwargs, context):
            path = context.module.path
            if (
                context.method_name == "__call__"
                and 0 < len(path) <= depth
                and path not in seen
            ):
                seen.add(path)
                avals = jax.tree_util.tree_map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
                    if hasattr(a, "shape") else a,
                    (args, kwargs),
                )
                records.append(
                    (path, context.module.clone(parent=None), avals)
                )
            return next_fn(*args, **kwargs)

        def trace():
            with nn.intercept_methods(interceptor):
                return module.init(rng, *example_args)

        jax.eval_shape(trace)

        def cost_of(mod, avals):
            a_args, a_kwargs = avals

            def f(variables, *xs):
                return mod.apply(variables, *xs, **a_kwargs)

            abstract_vars = jax.eval_shape(
                lambda *xs: mod.init(rng, *xs), *a_args
            )
            lowered = jax.jit(f).lower(abstract_vars, *a_args)
            cost = lowered.compile().cost_analysis()
            if isinstance(cost, list):
                cost = cost[0] if cost else {}
            return (
                float(cost.get("flops", 0.0)),
                float(cost.get("bytes accessed", 0.0)),
            )

        rows = []
        for path, mod, avals in records:
            try:
                flops, bytes_ = cost_of(mod, avals)
            except Exception as e:  # non-callable aux modules etc.
                logger.debug("module_costs: skip %s (%s)", path, e)
                continue
            rows.append({
                "path": "/".join(path),
                "type": type(mod).__name__,
                "flops": flops,
                "bytes_accessed": bytes_,
            })
        total = sum(
            r["flops"] for r in rows if "/" not in r["path"]
        ) or max((r["flops"] for r in rows), default=0.0)
        for r in rows:
            r["share"] = round(r["flops"] / total, 4) if total else 0.0
        rows.sort(key=lambda r: -r["flops"])
        self._module_rows = rows
        return rows[:top_k] if top_k else rows

    # ------------- report -------------
    def report(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "steps": self._step_stats.count,
            # Under async dispatch only a synced profiler measures the
            # device; label the numbers so dashboards can't lie.
            "timing": "synced" if self._sync else "dispatch",
            "step_time_mean_s": round(self._step_stats.mean, 6),
            "step_time_p50_s": round(self._step_stats.percentile(50), 6),
            "step_time_p99_s": round(self._step_stats.percentile(99), 6),
            "phases": {
                name: {
                    "mean_s": round(st.mean, 6),
                    "share": round(
                        st.mean / self._step_stats.mean, 4
                    ) if self._step_stats.mean else 0.0,
                }
                for name, st in self._phase_stats.items()
            },
        }
        if self._cost:
            out["cost_analysis"] = dict(self._cost)
            mfu = self.utilization()
            if mfu >= 0:
                out["mfu"] = round(mfu, 4)
        return out


# Reference-compatible alias (AProfiler is the name users know).
AProfiler = Profiler
