"""Control-plane event tracing — Chrome-trace-format event log.

Parity with the reference's tracing/diagnosis data collection (SURVEY §5:
the master records node events and training phase transitions for
offline diagnosis). Events are recorded in-process (thread-safe ring
buffer) and exported as Chrome trace JSON (``chrome://tracing`` /
Perfetto-viewable), giving rendezvous, restart, checkpoint and eviction
timelines across one process.

Usage::

    from dlrover_tpu.utils.tracing import get_tracer
    tracer = get_tracer()
    with tracer.span("rendezvous", round=3):
        ...
    tracer.instant("worker-crash", rank=2)
    tracer.export("/tmp/trace.json")
"""

import atexit
import contextlib
import json
import os
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, Optional

from dlrover_tpu.common import env_utils

_TRACE_ENV = env_utils.TRACE_FILE.name


class Tracer:
    def __init__(self, capacity: int = 65536):
        self._events: Deque[Dict] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._pid = os.getpid()

    def _emit(self, event: Dict):
        with self._lock:
            self._events.append(event)

    @contextlib.contextmanager
    def span(self, name: str, **args):
        """A complete ('X') event covering the with-block."""
        t0 = time.time()
        try:
            yield
        finally:
            self._emit({
                "name": name, "ph": "X", "pid": self._pid,
                "tid": threading.get_ident() % 1_000_000,
                "ts": t0 * 1e6, "dur": (time.time() - t0) * 1e6,
                "args": args,
            })

    def instant(self, name: str, **args):
        self._emit({
            "name": name, "ph": "i", "s": "p", "pid": self._pid,
            "tid": threading.get_ident() % 1_000_000,
            "ts": time.time() * 1e6, "args": args,  # dtlint: disable=DT011 -- Chrome-trace wall stamp for profiling output, never journaled; replay-time traces carry replay-time clocks by design
        })

    def counter(self, name: str, **values):
        self._emit({
            "name": name, "ph": "C", "pid": self._pid,
            "ts": time.time() * 1e6, "args": values,
        })

    @property
    def events(self):
        with self._lock:
            return list(self._events)

    def export(self, path: Optional[str] = None) -> Optional[str]:
        """Write Chrome trace JSON; default path from the env contract.

        Atomic (tmp + ``os.replace``, the port-file contract): exports
        fire mid-run and at exit, and a reader — or a crash between
        truncate and write — must never see a torn file."""
        path = path or env_utils.TRACE_FILE.get()
        if not path:
            return None
        with self._lock:
            events = list(self._events)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"traceEvents": events}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path


_tracer: Optional[Tracer] = None
_tracer_lock = threading.Lock()


def _export_at_exit():
    try:
        tracer = _tracer
        if tracer is not None:
            tracer.export()
    except Exception:  # dtlint: disable=DT001 -- atexit path: exits must never fail on tracing
        pass


def get_tracer() -> Tracer:
    global _tracer
    with _tracer_lock:
        if _tracer is None:
            _tracer = Tracer()
            if env_utils.TRACE_FILE.get():
                # The env contract asked for a file: make sure orderly
                # exits export even if no code path calls export().
                atexit.register(_export_at_exit)
        return _tracer
