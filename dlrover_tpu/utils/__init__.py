"""Shared utilities: profiler, tracing."""
