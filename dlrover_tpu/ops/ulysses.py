"""Ulysses-style all-to-all sequence parallelism.

Capability parity with DeepSpeed-Ulysses (the reference integrates it as
the all-to-all alternative to its distributed attention,
``atorch/atorch/modules/distributed_transformer/``): instead of rotating
K/V blocks around a ring, ONE all-to-all re-shards the activations from
sequence-sharded to head-sharded, every device runs *full-sequence*
attention over its head group, and a second all-to-all restores the
sequence sharding.

Trade-offs vs the ring (``ops/ring_attention.py``):

- comm volume is 2 all-to-alls of the q/k/v/out activations —
  ``O(tokens*d)`` total, independent of the seq degree — versus the
  ring's ``(n-1)`` K/V hops; on all-to-all-friendly fabrics (ICI torus)
  Ulysses wins at high degrees;
- the head count must divide the seq degree's mesh axis (heads become
  the sharded dim during attention) — the ring has no such constraint;
- each device sees the FULL sequence during attention, so the inner
  kernel can be the Pallas flash kernel unchanged (``inner="pallas"``),
  while the ring needs its own online-softmax accumulation.

Both are exact; pick per topology. ``ulysses_attention`` falls back to
plain attention when the mesh has no ``seq`` axis, so model code can
enable it unconditionally (same contract as ``ring_attention``).
"""

import jax
import jax.numpy as jnp
from jax import lax

from dlrover_tpu.common.log import logger

__all__ = ["ulysses_attention", "ulysses_attention_shard"]


def ulysses_attention_shard(q, k, v, causal: bool = True,
                            axis_name: str = "seq",
                            inner: str = "xla"):
    """Per-device body (run under ``shard_map``).

    q, k, v: device-local seq blocks [B, S_local, H, D]; H must be
    divisible by the ``axis_name`` mesh size.
    """
    n = lax.psum(1, axis_name)
    b, s_loc, h, d = q.shape
    if h % n:
        raise ValueError(
            f"ulysses: heads {h} not divisible by seq degree {n}"
        )
    # seq-sharded -> head-sharded: split the head dim across the axis,
    # concatenate the sequence blocks. [B, S/n, H, D] -> [B, S, H/n, D]
    qg = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1,
                        tiled=True)
    kg = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1,
                        tiled=True)
    vg = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1,
                        tiled=True)
    if inner == "pallas":
        from dlrover_tpu.ops.attention import flash_attention

        out = flash_attention(qg, kg, vg, causal=causal)
    else:
        from dlrover_tpu.ops.attention import reference_attention

        out = reference_attention(qg, kg, vg, causal=causal)
    # head-sharded -> seq-sharded.
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention(q, k, v, causal: bool = True,
                      axis_name: str = "seq", inner: str = "xla",
                      mesh=None):
    """Sequence-parallel attention via two all-to-alls over the ambient
    mesh's ``seq`` axis. q, k, v: GLOBAL [B, S, H, D] (seq-sharded by
    GSPMD). Falls back to plain attention without a ``seq`` axis."""
    from dlrover_tpu.ops.ring_attention import _ambient_mesh, _attn_specs

    mesh = mesh if mesh is not None else _ambient_mesh()
    if (
        mesh is None
        or axis_name not in mesh.axis_names
        or mesh.shape[axis_name] <= 1
    ):
        from dlrover_tpu.ops.attention import reference_attention

        logger.debug(
            "ulysses_attention: no %r mesh axis; using plain attention",
            axis_name,
        )
        return reference_attention(q, k, v, causal=causal)
    spec = _attn_specs(mesh, axis_name)
    fn = jax.shard_map(
        lambda a, b_, c: ulysses_attention_shard(
            a, b_, c, causal=causal, axis_name=axis_name, inner=inner
        ),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
