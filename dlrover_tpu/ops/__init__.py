"""TPU-native kernels (the reference's native-ops layer, rebuilt).

Parity targets: ATorch's flash-attention module swaps
(``atorch/atorch/modules/transformer/layers.py:898``) and the
sequence-parallel attention
(``atorch/atorch/modules/distributed_transformer/distributed_attention.py``).
Here the hot op is a Pallas TPU kernel and sequence parallelism is a
``shard_map`` ring over the ICI torus — the TPU-first replacements, not
ports.
"""

from dlrover_tpu.ops.attention import flash_attention, reference_attention
from dlrover_tpu.ops.moe import MoEMLP, compute_dispatch, load_balance_loss
from dlrover_tpu.ops.ring_attention import ring_attention, ring_attention_shard
from dlrover_tpu.ops.quantized import (
    QuantizedWeight,
    dequantize_params,
    quantize_params,
)
from dlrover_tpu.ops.ulysses import ulysses_attention, ulysses_attention_shard

__all__ = [
    "QuantizedWeight",
    "quantize_params",
    "dequantize_params",
    "ulysses_attention",
    "ulysses_attention_shard",
    "flash_attention",
    "reference_attention",
    "ring_attention",
    "ring_attention_shard",
    "MoEMLP",
    "compute_dispatch",
    "load_balance_loss",
]
