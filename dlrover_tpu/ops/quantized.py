"""Int8 weight-only quantization for inference/serving.

Capability parity with the reference's quantized-compute support
(``atorch/atorch/amp/amp_optimization.py:193`` fp8 paths, CUDA-only).
v5e-class TPUs have no fp8 MXU, so the TPU-first cut is the serving
technique that actually maps to the hardware: **int8 weight-only**
quantization — kernels stored as per-output-channel int8 + fp32 absmax
scales (4x smaller than fp32, 2x smaller than bf16), dequantized to
bf16 at the point of use. Under jit, XLA fuses the dequant into each
consumer matmul, so the int8 buffers are what's HBM-resident; the
per-layer bf16 view is a transient the scheduler recycles. Activations
stay bf16 (the MXU's native rate), so accuracy loss is the weight
rounding only (~1e-2 relative on logits for transformer blocks).

Usage::

    qparams = quantize_params(params)           # int8 storage pytree
    logits = jit(lambda qp, x: model.apply(
        {"params": dequantize_params(qp)}, x))(qparams, tokens)
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

__all__ = [
    "QuantizedWeight",
    "quantize_params",
    "dequantize_params",
    "quantized_nbytes",
]

_MIN_QUANT_ELEMS = 1024  # tiny leaves (biases, norms) stay as-is


class QuantizedWeight(NamedTuple):
    q: jnp.ndarray        # int8, same shape as the original kernel
    scale: jnp.ndarray    # fp32 absmax per output channel (last dim)


def _quantizable(leaf) -> bool:
    return (
        hasattr(leaf, "ndim") and leaf.ndim >= 2
        and leaf.size >= _MIN_QUANT_ELEMS
        and jnp.issubdtype(leaf.dtype, jnp.floating)
    )


def quantize_params(params, min_elems: int = _MIN_QUANT_ELEMS):
    """Per-output-channel symmetric int8 quantization of every >=2D
    floating kernel; small leaves pass through unchanged."""

    def quant(leaf):
        if not _quantizable(leaf) or leaf.size < min_elems:
            return leaf
        x = leaf.astype(jnp.float32)
        scale = jnp.max(jnp.abs(x), axis=tuple(range(x.ndim - 1)))
        safe = jnp.where(scale == 0, 1.0, scale)
        q = jnp.clip(jnp.round(x / safe * 127.0), -127, 127).astype(
            jnp.int8
        )
        return QuantizedWeight(q=q, scale=scale.astype(jnp.float32))

    return jax.tree_util.tree_map(quant, params)


def dequantize_params(qparams, dtype=jnp.bfloat16):
    """bf16 view of a quantized pytree (fused into consumers under
    jit — the int8 storage stays resident, the view is transient)."""

    def dequant(leaf):
        if isinstance(leaf, QuantizedWeight):
            return (
                leaf.q.astype(jnp.float32) * (leaf.scale / 127.0)
            ).astype(dtype)
        return leaf

    return jax.tree_util.tree_map(
        dequant, qparams,
        is_leaf=lambda l: isinstance(l, QuantizedWeight),
    )


def quantized_nbytes(qparams) -> int:
    return sum(
        l.nbytes for l in jax.tree_util.tree_leaves(qparams)
        if hasattr(l, "nbytes")
    )
