"""Int8 quantized compute: weight-only serving + AQT-style training.

Capability parity with the reference's quantized-compute support
(``atorch/atorch/auto/opt_lib/amp_optimization.py:193`` fp8 via
TransformerEngine, ``atorch/atorch/ops/csrc/quantization/pt_binding.cpp``
CUDA kernels). v5e-class TPUs have no fp8 MXU but run **int8 at 2x the
bf16 MXU rate**, so the TPU-first analog of the reference's fp8
training is int8 quantized *training* matmuls, AQT-style:

- **Serving** (``quantize_params``/``dequantize_params``): kernels
  stored per-output-channel int8 + fp32 absmax scales; XLA fuses the
  dequant into consumers so int8 is what's HBM-resident.
- **Training** (``int8_dot`` / ``Int8Dense``): dynamic symmetric
  per-row (tokens) x per-column (features) quantization at each call;
  the contraction runs int8 x int8 -> int32 on the MXU and rescales to
  the activation dtype. The backward pass is straight-through: grads
  are computed in bf16 against the *unquantized* operands (the AQT
  recipe — quantization noise acts as a forward-only perturbation, so
  optimizer dynamics stay fp32-clean). Opt in per model via
  ``mlp_precision="int8"`` (GPTConfig/LlamaConfig) or
  ``auto_accelerate(precision="int8")``.

Measured (v5e single chip via this XLA build, 2026-07-30, interleaved
A/B/A): **no step-time win today** — 0.93x at 355M (224 vs 242 ms),
0.96x at 124M. A raw ``int8 x int8 -> int32`` dot microbenchmark runs
at the same rate as the bf16 dot (34.7 TOPS vs 36.2 TFLOP/s), i.e.
this XLA build does not engage the double-rate int8 MXU mode, and the
quantize chain + int32 output traffic add ~5%. The capability is kept
correct and opt-in: where the int8 MXU rate is exposed (other
XLA builds / TPU generations), the same code path is the 2x lever;
bench.py's medium section re-measures the ratio every run.
"""

from typing import Any, Callable, NamedTuple, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = [
    "QuantizedWeight",
    "quantize_params",
    "dequantize_params",
    "quantized_nbytes",
    "int8_dot",
    "Int8Dense",
]

_MIN_QUANT_ELEMS = 1024  # tiny leaves (biases, norms) stay as-is


class QuantizedWeight(NamedTuple):
    q: jnp.ndarray        # int8, same shape as the original kernel
    scale: jnp.ndarray    # fp32 absmax per output channel (last dim)


def _quantizable(leaf) -> bool:
    return (
        hasattr(leaf, "ndim") and leaf.ndim >= 2
        and leaf.size >= _MIN_QUANT_ELEMS
        and jnp.issubdtype(leaf.dtype, jnp.floating)
    )


def quantize_params(params, min_elems: int = _MIN_QUANT_ELEMS):
    """Per-output-channel symmetric int8 quantization of every >=2D
    floating kernel; small leaves pass through unchanged."""

    def quant(leaf):
        if not _quantizable(leaf) or leaf.size < min_elems:
            return leaf
        x = leaf.astype(jnp.float32)
        scale = jnp.max(jnp.abs(x), axis=tuple(range(x.ndim - 1)))
        safe = jnp.where(scale == 0, 1.0, scale)
        q = jnp.clip(jnp.round(x / safe * 127.0), -127, 127).astype(
            jnp.int8
        )
        return QuantizedWeight(q=q, scale=scale.astype(jnp.float32))

    return jax.tree_util.tree_map(quant, params)


def dequantize_params(qparams, dtype=jnp.bfloat16):
    """bf16 view of a quantized pytree (fused into consumers under
    jit — the int8 storage stays resident, the view is transient)."""

    def dequant(leaf):
        if isinstance(leaf, QuantizedWeight):
            return (
                leaf.q.astype(jnp.float32) * (leaf.scale / 127.0)
            ).astype(dtype)
        return leaf

    return jax.tree_util.tree_map(
        dequant, qparams,
        is_leaf=lambda l: isinstance(l, QuantizedWeight),
    )


def quantized_nbytes(qparams) -> int:
    return sum(
        l.nbytes for l in jax.tree_util.tree_leaves(qparams)
        if hasattr(l, "nbytes")
    )


# --------------------------------------------------------------------------
# AQT-style int8 training matmul
# --------------------------------------------------------------------------

def _row_scale(x):
    """Symmetric absmax scale over the last (contraction) dim."""
    s = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    return jnp.where(s == 0, 1.0, s).astype(jnp.float32)


def _col_scale(w):
    """Symmetric absmax scale over the first (contraction) dim -> [1, N].

    Reduces axis 0 directly instead of the old ``_row_scale(w.T).T``
    round-trip, so no transpose of the full kernel enters the graph."""
    s = jnp.max(jnp.abs(w), axis=0, keepdims=True)
    return jnp.where(s == 0, 1.0, s).astype(jnp.float32)


def _quant8(x, scale):
    return jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale * 127.0), -127, 127
    ).astype(jnp.int8)


@jax.custom_vjp
def int8_dot(x, w):
    """``x[..., K] @ w[K, N]`` with an int8 MXU contraction.

    Forward: dynamic symmetric quantization — per-row scales for ``x``
    (each token/position gets its own absmax over K), per-column scales
    for ``w`` — then ``int8 x int8 -> int32`` (``preferred_element_type``
    puts the accumulation on the MXU's int path at 2x bf16 rate) and a
    rank-1 rescale. Backward: straight-through in bf16 against the
    unquantized operands.
    """
    y, _ = _int8_dot_fwd(x, w)
    return y


def _int8_dot_fwd(x, w):
    sx = _row_scale(x)                      # [..., 1] per-row
    sw = _col_scale(w)                      # [1, N] per-column
    qx = _quant8(x, sx)
    qw = _quant8(w, sw)
    acc = jax.lax.dot_general(
        qx, qw,
        dimension_numbers=(((qx.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    y = acc.astype(jnp.float32) * (sx / 127.0) * (sw / 127.0)
    return y.astype(x.dtype), (x, w)


def _int8_dot_bwd(res, g):
    x, w = res
    gf = g.astype(x.dtype)
    dx = jax.lax.dot_general(
        gf, w,
        dimension_numbers=(((gf.ndim - 1,), (1,)), ((), ())),
    ).astype(x.dtype)
    x2 = x.reshape(-1, x.shape[-1])
    g2 = gf.reshape(-1, gf.shape[-1])
    dw = jax.lax.dot_general(
        x2, g2, dimension_numbers=(((0,), (0,)), ((), ())),
    ).astype(w.dtype)
    return dx, dw


int8_dot.defvjp(_int8_dot_fwd, _int8_dot_bwd)


class Int8Dense(nn.Module):
    """Drop-in for ``nn.Dense`` whose contraction runs ``int8_dot``.

    Same param structure (``kernel`` [+ ``bias``], same logical-axis
    boxing) as ``nn.Dense``, so sharding rules, the TP planner, FSDP and
    checkpoints all see an identical tree — precision is a pure compute
    swap, exactly like the reference flipping a linear to fp8 via
    TransformerEngine.
    """

    features: int
    use_bias: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    kernel_init: Optional[Callable] = None
    bias_init: Optional[Callable] = None

    @nn.compact
    def __call__(self, x):
        kernel_init = self.kernel_init or nn.initializers.lecun_normal()
        kernel = self.param(
            "kernel", kernel_init, (x.shape[-1], self.features),
            self.param_dtype,
        )
        y = int8_dot(x.astype(self.dtype), kernel.astype(self.dtype))
        if self.use_bias:
            bias_init = self.bias_init or nn.initializers.zeros_init()
            bias = self.param(
                "bias", bias_init, (self.features,), self.param_dtype
            )
            y = y + bias.astype(self.dtype)
        return y
