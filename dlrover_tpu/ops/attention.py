"""Pallas TPU flash attention (forward + backward).

Blockwise online-softmax attention that never materializes the [S, S] score
matrix: O(S) memory instead of O(S^2), f32 accumulation on the MXU, causal
block skipping. Capability parity with the reference's FlashAttention
integration (``atorch/atorch/modules/transformer/layers.py:898-1661``) —
built as a native TPU kernel rather than a CUDA-library wrapper.

Layout convention matches the models: ``[batch, seq, heads, head_dim]``.
Internally arrays are folded to ``[batch*heads, seq, head_dim]``; the grid
walks (bh, q_block, kv_block) with the kv dimension innermost so the f32
accumulators live in VMEM scratch across kv steps (TPU grids execute
sequentially — the canonical Pallas accumulation pattern).

On non-TPU backends the kernel runs in interpreter mode (tests) — the
public entry point auto-selects, so models can enable ``attn_impl="pallas"``
unconditionally.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

_NEG_INF = -1e30
_LANES = 128  # scratch rows are padded to a full lane tile


def reference_attention(q, k, v, causal: bool = True):
    """Einsum softmax attention — the numerics oracle for the kernels.

    q, k, v: [B, S, H, D]; returns [B, S, H, D].
    """
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        s_q, s_k = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), s_k - s_q)
        logits = jnp.where(mask, logits, _NEG_INF)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(q.dtype), v)


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pick_block(seq: int, want: int) -> int:
    """Largest block <= `want` that divides `seq` (power-of-two stepping)."""
    b = min(want, seq)
    while seq % b:
        b //= 2
    return max(b, 1)


# ---------------------------------------------------------------- forward


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_s, l_s, *,
                scale, causal, block_q, block_k, nk):
    from jax.experimental import pallas as pl

    qi, ki = pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, _NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    # Causal: a kv block strictly above the diagonal contributes nothing.
    if causal:
        run = ki * block_k <= qi * block_q + block_q - 1
    else:
        run = ki >= 0  # traced always-true (pl.when needs a traced pred)

    @pl.when(run)
    def _():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk]
        if causal:
            rows = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            ) + qi * block_q
            cols = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            ) + ki * block_k
            logits = jnp.where(rows >= cols, logits, _NEG_INF)
        m_prev = m_s[:, 0]
        chunk_m = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m_prev, chunk_m)
        p = jnp.exp(logits - m_new[:, None])
        if causal:
            p = jnp.where(logits <= _NEG_INF / 2, 0.0, p)
        corr = jnp.exp(m_prev - m_new)
        l_s[:, 0] = l_s[:, 0] * corr + jnp.sum(p, axis=-1)
        m_s[:, 0] = m_new
        pv = jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc[:] = acc[:] * corr[:, None] + pv

    @pl.when(ki == nk - 1)
    def _():
        l = l_s[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc[:] / l_safe[:, None]).astype(o_ref.dtype)
        # lse blocks span the full row (TPU tiling forbids a (1, block_q)
        # block over [B*H, S]); each qi writes its slice.
        lse_ref[0, 0, pl.dslice(qi * block_q, block_q)] = (
            m_s[:, 0] + jnp.log(l_safe)
        )


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    from jax.experimental import pallas as pl

    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    scale = 1.0 / np.sqrt(d)
    qf = jnp.moveaxis(q, 2, 1).reshape(b * h, sq, d)
    kf = jnp.moveaxis(k, 2, 1).reshape(b * h, sk, d)
    vf = jnp.moveaxis(v, 2, 1).reshape(b * h, sk, d)
    nq, nk = sq // block_q, sk // block_k

    try:
        from jax.experimental.pallas import tpu as pltpu

        scratch = [
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ]
    except ImportError:  # pragma: no cover - non-TPU jax builds
        scratch = [
            pl.MemoryRef((block_q, d), jnp.float32),
            pl.MemoryRef((block_q, _LANES), jnp.float32),
            pl.MemoryRef((block_q, _LANES), jnp.float32),
        ]

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, nk=nk,
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=(b * h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, 1, sq), lambda bh, qi, ki: (bh, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 1, sq), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(qf, kf, vf)
    return jnp.moveaxis(o.reshape(b, h, sq, d), 1, 2), lse


# ---------------------------------------------------------------- backward


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc, *, scale, causal, block_q, block_k, nk):
    from jax.experimental import pallas as pl

    qi, ki = pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)

    if causal:
        run = ki * block_k <= qi * block_q + block_q - 1
    else:
        run = ki >= 0  # traced always-true (pl.when needs a traced pred)

    @pl.when(run)
    def _():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            rows = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            ) + qi * block_q
            cols = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            ) + ki * block_k
            logits = jnp.where(rows >= cols, logits, _NEG_INF)
        lse = lse_ref[0, 0, pl.dslice(qi * block_q, block_q)]
        p = jnp.exp(logits - lse[:, None])
        if causal:
            p = jnp.where(logits <= _NEG_INF / 2, 0.0, p)
        dp = jax.lax.dot_general(
            do_ref[0].astype(jnp.float32), v_ref[0].astype(jnp.float32),
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
        )
        delta = delta_ref[0, 0, pl.dslice(qi * block_q, block_q)]
        ds = p * (dp - delta[:, None])
        acc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[0] = acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *,
                    scale, causal, block_q, block_k, nq):
    from jax.experimental import pallas as pl

    ki, qi = pl.program_id(1), pl.program_id(2)

    @pl.when(qi == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    if causal:
        run = ki * block_k <= qi * block_q + block_q - 1
    else:
        run = ki >= 0  # traced always-true (pl.when needs a traced pred)

    @pl.when(run)
    def _():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk]
        if causal:
            rows = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            ) + qi * block_q
            cols = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            ) + ki * block_k
            logits = jnp.where(rows >= cols, logits, _NEG_INF)
        lse = lse_ref[0, 0, pl.dslice(qi * block_q, block_q)]
        p = jnp.exp(logits - lse[:, None])
        if causal:
            p = jnp.where(logits <= _NEG_INF / 2, 0.0, p)
        do = do_ref[0].astype(jnp.float32)
        # dv += p^T @ do
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        delta = delta_ref[0, 0, pl.dslice(qi * block_q, block_q)]
        ds = p * (dp - delta[:, None])
        # dk += ds^T @ (q * scale)  — q already carries the scale
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    from jax.experimental import pallas as pl

    q, k, v, o, lse = res
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    scale = 1.0 / np.sqrt(d)
    qf = jnp.moveaxis(q, 2, 1).reshape(b * h, sq, d)
    kf = jnp.moveaxis(k, 2, 1).reshape(b * h, sk, d)
    vf = jnp.moveaxis(v, 2, 1).reshape(b * h, sk, d)
    dof = jnp.moveaxis(g, 2, 1).reshape(b * h, sq, d)
    of = jnp.moveaxis(o, 2, 1).reshape(b * h, sq, d)
    nq, nk = sq // block_q, sk // block_k
    # delta = rowsum(do * o): cheap elementwise — XLA fuses it fine.
    delta = jnp.sum(
        dof.astype(jnp.float32) * of.astype(jnp.float32), axis=-1
    )[:, None, :]  # [B*H, 1, S] — matches the lse layout

    try:
        from jax.experimental.pallas import tpu as pltpu

        vmem = pltpu.VMEM
    except ImportError:  # pragma: no cover
        vmem = pl.MemoryRef

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, nk=nk,
        ),
        grid=(b * h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, 1, sq), lambda bh, qi, ki: (bh, 0, 0)),
            pl.BlockSpec((1, 1, sq), lambda bh, qi, ki: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        scratch_shapes=[vmem((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, nq=nq,
        ),
        grid=(b * h, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, 1, sq), lambda bh, ki, qi: (bh, 0, 0)),
            pl.BlockSpec((1, 1, sq), lambda bh, ki, qi: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), v.dtype),
        ],
        scratch_shapes=[
            vmem((block_k, d), jnp.float32),
            vmem((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    unfold = lambda x, s: jnp.moveaxis(x.reshape(b, h, s, d), 1, 2)
    return unfold(dq, sq), unfold(dk, sk), unfold(dv, sk)


# ---------------------------------------------------------------- public


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention(q, k, v, causal, block_q, block_k, interpret):
    o, _ = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return o


def _flash_attention_fwd(q, k, v, causal, block_q, block_k, interpret):
    o, lse = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_attention_bwd(causal, block_q, block_k, interpret, res, g):
    return _flash_bwd(causal, block_q, block_k, interpret, res, g)


_flash_attention.defvjp(_flash_attention_fwd, _flash_attention_bwd)


def flash_attention(q, k, v, causal: bool = True, block_q: int = 512,
                    block_k: int = 512, interpret: Optional[bool] = None):
    """Flash attention over [B, S, H, D] inputs (differentiable).

    ``interpret=None`` auto-selects: compiled Pallas on TPU, interpreter
    elsewhere (so CPU tests validate the same kernel code path).
    """
    if interpret is None:
        interpret = _use_interpret()
    return _flash_attention(q, k, v, causal, block_q, block_k, interpret)
