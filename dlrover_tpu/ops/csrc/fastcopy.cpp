// Native copy engine for flash-checkpoint staging.
//
// The checkpoint hot loop is host-RAM memcpy (device fetch -> shm, shm ->
// numpy on restore). The Python-side thread pool (common/fastcopy.py)
// already parallelizes it, but each chunk still pays Python dispatch and
// the pool's queue locking; this engine takes the whole task list in one
// call and fans the chunks over raw std::threads with an atomic cursor —
// no GIL round-trips between chunks, memcpy at memory-bus speed.
//
// Capability parity: the reference leans on torch's native multithreaded
// Tensor.copy_ for the same copies (plus CUDA-side kernels under
// atorch/atorch/ops/csrc); this is the TPU-host equivalent, built as a
// plain shared library bound via ctypes (no pybind11 in the image).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

struct DtCopyTask {
  void* dst;
  const void* src;
  uint64_t size;
};

// Copy every task, chunked to `chunk` bytes, on up to `threads` threads.
void dt_copy_many(const DtCopyTask* tasks, int64_t n_tasks, int64_t chunk,
                  int32_t threads) {
  if (n_tasks <= 0) return;
  if (chunk <= 0) chunk = 64ll << 20;

  struct Chunk {
    char* d;
    const char* s;
    uint64_t n;
  };
  std::vector<Chunk> chunks;
  for (int64_t i = 0; i < n_tasks; ++i) {
    const DtCopyTask& t = tasks[i];
    for (uint64_t off = 0; off < t.size; off += (uint64_t)chunk) {
      chunks.push_back({(char*)t.dst + off, (const char*)t.src + off,
                        std::min<uint64_t>((uint64_t)chunk, t.size - off)});
    }
  }
  if (chunks.empty()) return;

  std::atomic<size_t> next{0};
  auto work = [&]() {
    size_t i;
    while ((i = next.fetch_add(1, std::memory_order_relaxed)) <
           chunks.size()) {
      std::memcpy(chunks[i].d, chunks[i].s, chunks[i].n);
    }
  };

  int nt = std::max(1, std::min<int32_t>(threads, (int32_t)chunks.size()));
  if (nt == 1) {
    work();
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(nt - 1);
  for (int i = 0; i < nt - 1; ++i) pool.emplace_back(work);
  work();  // the calling thread copies too
  for (auto& th : pool) th.join();
}

// Single-buffer convenience (bindings/tests).
void dt_copy(void* dst, const void* src, uint64_t size, int64_t chunk,
             int32_t threads) {
  DtCopyTask t{dst, src, size};
  dt_copy_many(&t, 1, chunk, threads);
}

}  // extern "C"
