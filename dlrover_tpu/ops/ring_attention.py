"""Ring attention — sequence/context parallelism over the ICI torus.

Capability parity with the reference's sequence-parallel attention
(``atorch/atorch/modules/distributed_transformer/distributed_attention.py:21-115``:
seq-sharded KV, micro-Q allgather + distributed softmax + reduce-scatter,
dual CUDA streams). The TPU-first design is a *ring*: every device keeps its
local Q block resident and rotates the K/V blocks around the ``seq`` mesh
axis with ``ppermute`` — XLA overlaps the collective-permute with the
attention compute of the current block, which is exactly the comm/compute
overlap the reference hand-builds with CUDA streams. Softmax is the online
(max/sum-carrying) form, so the result is exact, not approximate.

``ring_attention_shard`` is the per-device body (call it under
``shard_map``); ``ring_attention`` wraps it with ``shard_map`` over the
ambient mesh and falls back to plain attention when no ``seq`` axis exists,
so models can enable it unconditionally.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.common.log import logger

_NEG_INF = -1e30


def ring_attention_shard(q, k, v, causal: bool = True,
                         axis_name: str = "seq"):
    """Per-device ring attention body (run under ``shard_map``).

    q, k, v: the device-local blocks [B, S_local, H, D]; the global sequence
    is the concatenation over the ``axis_name`` mesh axis. Exact (online
    softmax) — numerics match full attention on the gathered sequence.
    """
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    scale = 1.0 / np.sqrt(d)

    q32 = q.astype(jnp.float32)
    m = jnp.full((b, h, s_loc), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s_loc), jnp.float32)
    acc = jnp.zeros((b, s_loc, h, d), jnp.float32)
    rows = jax.lax.broadcasted_iota(jnp.int32, (s_loc, s_loc), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (s_loc, s_loc), 1)

    perm = [(j, (j + 1) % n) for j in range(n)]
    k_cur, v_cur = k, v
    for step in range(n):
        # After `step` rotations we hold the block that originated on
        # device (my - step) mod n.
        src = (my - step) % n
        logits = jnp.einsum(
            "bqhd,bkhd->bhqk", q32, k_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            # Global positions: q row r lives at my*s_loc + r, k col c at
            # src*s_loc + c. src is traced, so the mask is data-dependent —
            # fine under jit (select, not control flow).
            mask = (my * s_loc + rows) >= (src * s_loc + cols)
            logits = jnp.where(mask[None, None], logits, _NEG_INF)
        chunk_m = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, chunk_m)
        p = jnp.exp(logits - m_new[..., None])
        if causal:
            # A fully-masked block must contribute nothing even when
            # m_new is itself _NEG_INF (exp(0)=1 otherwise).
            p = jnp.where(logits <= _NEG_INF / 2, 0.0, p)
        corr = jnp.exp(m - m_new)  # [b, h, s]
        l = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum(
            "bhqk,bkhd->bqhd", p, v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        acc = acc * jnp.moveaxis(corr, 1, 2)[..., None] + pv
        m = m_new
        if step != n - 1:
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = acc / jnp.moveaxis(l_safe, 1, 2)[..., None]
    return out.astype(q.dtype)


def _ambient_mesh():
    """The mesh active at trace time (set by ``with mesh:`` in the accel
    layer's train step), or None."""
    try:
        from jax._src import mesh as mesh_lib

        mesh = mesh_lib.thread_resources.env.physical_mesh
        if mesh is not None and not mesh.empty:
            return mesh
    except Exception:
        try:  # pre-0.8 fallback
            from jax.interpreters import pxla

            mesh = pxla.thread_resources.env.physical_mesh
            if mesh is not None and not mesh.empty:
                return mesh
        except Exception:  # dtlint: disable=DT001 -- JAX-version API probe: no mesh found either way, caller falls back to SPMD axis env
            pass
    return None


def _attn_specs(mesh, axis_name: str):
    from jax.sharding import PartitionSpec as P

    batch_axes = tuple(
        a for a in ("data", "fsdp") if a in mesh.axis_names
    )
    heads = "tensor" if "tensor" in mesh.axis_names else None
    return P(batch_axes or None, axis_name, heads, None)


def ring_attention(q, k, v, causal: bool = True, axis_name: str = "seq",
                   mesh=None):
    """Sequence-parallel attention over the ambient mesh's ``seq`` axis.

    q, k, v: GLOBAL [B, S, H, D] arrays (seq-sharded by GSPMD). Falls back
    to plain attention when the mesh has no ``seq`` axis (size > 1), so the
    same model code runs on any topology.
    """
    mesh = mesh if mesh is not None else _ambient_mesh()
    if (
        mesh is None
        or axis_name not in mesh.axis_names
        or mesh.shape[axis_name] <= 1
    ):
        from dlrover_tpu.ops.attention import reference_attention

        logger.debug(
            "ring_attention: no %r mesh axis; using plain attention",
            axis_name,
        )
        return reference_attention(q, k, v, causal=causal)
    spec = _attn_specs(mesh, axis_name)
    fn = jax.shard_map(
        lambda a, b_, c: ring_attention_shard(
            a, b_, c, causal=causal, axis_name=axis_name
        ),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
