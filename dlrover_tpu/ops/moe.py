"""Mixture-of-Experts with expert parallelism, TPU-first.

Capability parity with the reference's MoE stack
(``atorch/atorch/modules/moe/moe_layer.py:87-161``: top-k gate, alltoall
dispatch to experts over a process group, alltoall combine). The TPU-first
design is the GShard/Switch *einsum dispatch* formulation instead of
explicit alltoalls: routing builds dense dispatch/combine tensors and the
expert computation is a batched einsum over an ``expert``-sharded weight
stack — GSPMD lowers the contractions into exactly the all-to-all +
grouped-matmul schedule the reference hand-writes, and the MXU sees one
large batched matmul per projection instead of E small ones.

Everything is static-shape (capacity-factor truncation instead of
data-dependent gather), so the whole layer jits into a single XLA
computation with no host round-trips.

Components:
- ``compute_dispatch``: top-k routing -> combine [N,E,C] / dispatch masks
  (Switch-style position-by-cumsum, capacity-dropping, gate renorm).
- ``load_balance_loss``: Switch aux loss (E * sum(frac_routed * mean_gate)).
- ``MoEMLP``: drop-in flax replacement for the transformer FFN; returns
  ``(out, aux_loss)``. Expert weights carry the ``expert`` logical axis, so
  ``ParallelSpec(expert=K)`` shards them K-way (EP) with zero model changes.
"""

from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


def compute_dispatch(gates, top_k: int, capacity: int):
    """Top-k assignment with per-expert capacity.

    gates: [N, E] router probabilities (softmax output, fp32).
    Returns (combine [N, E, C] fp32, dispatch [N, E, C] bool). Positions
    within an expert are assigned in token order via cumsum (deterministic,
    jit-friendly); tokens overflowing ``capacity`` are dropped for that
    choice. Combine weights are renormalized over the token's selected
    gates (GShard top-2 convention), so kept routes of a token sum to <= 1.
    """
    n, e = gates.shape
    remaining = gates
    base = jnp.zeros((e,), jnp.float32)  # slots already used per expert
    combine = jnp.zeros((n, e, capacity), jnp.float32)
    selected_sum = jnp.zeros((n,), jnp.float32)
    for _ in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)                    # [N]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)      # [N, E]
        # Position this token would take in its expert's buffer.
        pos_all = jnp.cumsum(onehot, axis=0) - onehot + base[None, :]
        pos = jnp.sum(pos_all * onehot, axis=-1)                # [N]
        keep = (pos < capacity).astype(jnp.float32)
        gate_val = jnp.sum(remaining * onehot, axis=-1)         # [N]
        pos_oh = jax.nn.one_hot(
            pos.astype(jnp.int32), capacity, dtype=jnp.float32
        )
        combine = combine + (
            (gate_val * keep)[:, None, None]
            * onehot[:, :, None]
            * pos_oh[:, None, :]
        )
        selected_sum = selected_sum + gate_val
        base = base + jnp.sum(onehot * keep[:, None], axis=0)
        remaining = remaining * (1.0 - onehot)
    denom = jnp.where(selected_sum > 0, selected_sum, 1.0)
    combine = combine / denom[:, None, None]
    dispatch = combine > 0
    return combine, dispatch


def load_balance_loss(gates, top1_onehot):
    """Switch-Transformer auxiliary loss: E * sum_e(frac_e * prob_e).

    Minimized (=1) when routing is uniform. gates [N, E] fp32,
    top1_onehot [N, E] the first-choice assignment.
    """
    e = gates.shape[-1]
    frac = jnp.mean(top1_onehot, axis=0)   # fraction routed to each expert
    prob = jnp.mean(gates, axis=0)         # mean router probability
    return e * jnp.sum(frac * prob)


def expert_capacity(n_tokens: int, n_experts: int, top_k: int,
                    capacity_factor: float) -> int:
    """Static per-expert buffer size, rounded up to a multiple of 8 so the
    [E, C, D] expert batches tile the MXU/VPU lanes cleanly."""
    c = int(np.ceil(capacity_factor * top_k * n_tokens / n_experts))
    return max(8, ((c + 7) // 8) * 8)


class MoEMLP(nn.Module):
    """Expert-parallel FFN: ``[B,S,D] -> ([B,S,D], aux_loss)``.

    Expert weight stacks are [E, ...] with the ``expert`` logical axis
    first; under ``ParallelSpec(expert=K)`` each device group holds E/K
    experts and GSPMD inserts the dispatch/combine all-to-alls. With no
    ``expert`` mesh axis the same code runs replicated (pure MoE without
    EP), and numerics are identical either way.
    """

    num_experts: int
    ff_dim: int
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    # "gelu": 2-matrix GPT-style FFN experts; "swiglu": 3-matrix
    # gate/up/down LLaMA/Mixtral-style experts (no biases).
    mlp_type: str = "gelu"

    @nn.compact
    def __call__(self, x) -> Tuple[Any, Any]:
        b, s, d = x.shape
        n, e, f = b * s, self.num_experts, self.ff_dim
        xf = x.reshape(n, d)

        router = self.param(
            "router",
            nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("embed", "expert")
            ),
            (d, e),
            self.param_dtype,
        )
        # Routing in fp32: gate ordering must not depend on bf16 rounding.
        logits = jnp.einsum(
            "nd,de->ne", xf.astype(jnp.float32), router.astype(jnp.float32)
        )
        gates = jax.nn.softmax(logits, axis=-1)
        top1 = jax.nn.one_hot(
            jnp.argmax(gates, axis=-1), e, dtype=jnp.float32
        )
        aux = load_balance_loss(gates, top1)

        cap = expert_capacity(n, e, self.top_k, self.capacity_factor)
        combine, dispatch = compute_dispatch(gates, self.top_k, cap)

        # Dispatch: [N,E,C] x [N,D] -> [E,C,D]. Under EP the output is
        # expert-sharded; the contraction over (data-sharded) N becomes
        # the dispatch all-to-all + psum.
        expert_in = jnp.einsum(
            "nec,nd->ecd", dispatch.astype(self.dtype), xf
        )
        expert_in = nn.with_logical_constraint(
            expert_in, ("expert", None, "embed")
        )

        w_up = self.param(
            "w_up",
            nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("expert", "embed", "mlp")
            ),
            (e, d, f),
            self.param_dtype,
        )
        b_up = self.param(
            "b_up",
            nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ("expert", "mlp")
            ),
            (e, f),
            self.param_dtype,
        )
        w_down = self.param(
            "w_down",
            nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("expert", "mlp", "embed")
            ),
            (e, f, d),
            self.param_dtype,
        )
        b_down = self.param(
            "b_down",
            nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ("expert", "embed")
            ),
            (e, d),
            self.param_dtype,
        )

        h = jnp.einsum(
            "ecd,edf->ecf", expert_in, w_up.astype(self.dtype)
        ) + b_up[:, None, :].astype(self.dtype)
        if self.mlp_type == "swiglu":
            w_gate = self.param(
                "w_gate",
                nn.with_logical_partitioning(
                    nn.initializers.normal(0.02),
                    ("expert", "embed", "mlp"),
                ),
                (e, d, f),
                self.param_dtype,
            )
            g = jnp.einsum(
                "ecd,edf->ecf", expert_in, w_gate.astype(self.dtype)
            )
            h = nn.silu(g) * h
        else:
            h = nn.gelu(h)
        h = nn.with_logical_constraint(h, ("expert", None, "mlp"))
        out_e = jnp.einsum(
            "ecf,efd->ecd", h, w_down.astype(self.dtype)
        ) + b_down[:, None, :].astype(self.dtype)

        # Combine: weighted gather back to token order.
        out = jnp.einsum(
            "nec,ecd->nd", combine.astype(self.dtype), out_e
        )
        return out.reshape(b, s, d), aux
