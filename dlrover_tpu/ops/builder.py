"""Native op builder — build, cache, and load C++ extensions on use.

Parity: the reference's op_builder framework
(``atorch/atorch/ops/op_builder/`` — per-op builder classes that
compile CUDA/C++ sources on first use and dlopen the result, with
graceful degradation when no toolchain exists). The TPU runtime has no
CUDA to build, but the same need exists for host-side native pieces
(the checkpoint copy engine today, IO/codec helpers tomorrow):

- an :class:`OpBuilder` names its sources and compile flags;
- ``load()`` compiles on first use **and whenever a source is newer
  than the built library** (mtime staleness — editing the .cpp never
  ships a stale .so), then ``ctypes``-loads it;
- results are cached per builder; a missing/broken toolchain returns
  ``None`` so every native op keeps a pure-Python fallback;
- ``DLROVER_TPU_DISABLE_NATIVE`` turns every builder off (the
  reference's op-building kill switch).

Builders register by name (:func:`register_builder`) and load via
:func:`get_op` — the discovery surface the reference exposes through
``op_builder.ALL_OPS``.
"""

import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional, Sequence

from dlrover_tpu.common import env_utils
from dlrover_tpu.common.log import logger

__all__ = ["OpBuilder", "register_builder", "get_op", "all_ops"]

_LOCK = threading.Lock()
_BUILDERS: Dict[str, "OpBuilder"] = {}


class OpBuilder:
    """One native extension: sources -> shared library -> ctypes CDLL."""

    def __init__(self, name: str, sources: Sequence[str],
                 output: str = "", extra_flags: Sequence[str] = ()):
        self.name = name
        self.sources = [os.path.abspath(s) for s in sources]
        out_dir = os.path.dirname(self.sources[0])
        self.output = output or os.path.join(
            out_dir, f"lib{name}.so"
        )
        self.extra_flags = list(extra_flags)
        self._lib: Optional[ctypes.CDLL] = None
        self._tried = False

    # ------------- build -------------
    def stale(self) -> bool:
        if not os.path.exists(self.output):
            return True
        built = os.path.getmtime(self.output)
        return any(
            os.path.exists(s) and os.path.getmtime(s) > built
            for s in self.sources
        )

    def build_command(self) -> List[str]:
        cxx = os.getenv("CXX", "g++")
        return [
            cxx, "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
            *self.extra_flags, "-o", self.output, *self.sources,
        ]

    def build(self) -> bool:
        cmd = self.build_command()
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=300
            )
        except (OSError, subprocess.TimeoutExpired) as e:
            logger.warning("op %s: toolchain unavailable (%s)",
                           self.name, e)
            return False
        if proc.returncode != 0:
            logger.warning("op %s: build failed:\n%s", self.name,
                           proc.stderr[-2000:])
            return False
        logger.info("op %s: built %s", self.name, self.output)
        return True

    # ------------- load -------------
    def load(self) -> Optional[ctypes.CDLL]:
        """Build (if stale) and load; None = use the Python fallback."""
        with _LOCK:
            if self._tried:
                return self._lib
            self._tried = True
            if env_utils.DISABLE_NATIVE.get():
                return None
            if self.stale() and not self.build():
                return None
            try:
                self._lib = ctypes.CDLL(self.output)
            except OSError as e:
                logger.warning("op %s: load failed: %s", self.name, e)
                self._lib = None
            return self._lib


def register_builder(builder: OpBuilder) -> OpBuilder:
    _BUILDERS[builder.name] = builder
    return builder


def get_op(name: str) -> Optional[ctypes.CDLL]:
    """Load a registered op by name (None when unbuildable)."""
    builder = _BUILDERS.get(name)
    if builder is None:
        raise KeyError(
            f"no op builder named {name!r}; registered: "
            f"{sorted(_BUILDERS)}"
        )
    return builder.load()


def all_ops() -> Dict[str, "OpBuilder"]:
    return dict(_BUILDERS)


def _csrc(name: str) -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "csrc", name)


# ---- built-in ops ----
register_builder(OpBuilder(
    "dtfastcopy", sources=[_csrc("fastcopy.cpp")],
))
