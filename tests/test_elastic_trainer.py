"""ElasticTrainer + gradient-accumulation tests (SURVEY §2.4)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.accel import ParallelSpec, auto_accelerate
from dlrover_tpu.models.gpt import GPT, GPTConfig, loss_fn
from dlrover_tpu.train.elastic_trainer import ElasticTrainer


def tiny_cfg():
    return dataclasses.replace(GPTConfig.tiny(), dtype=jnp.float32)


def token_loss(module, params, batch):
    return loss_fn(module.apply({"params": params}, batch), batch)


class TestGradAccum:
    def test_accum_matches_full_batch(self):
        """grad_accum=4 over a 16-sample batch must train identically to
        one full-batch step (mean-of-means == full mean)."""
        cfg = tiny_cfg()
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (16, 16), 0, cfg.vocab_size
        )

        def run(accum):
            res = auto_accelerate(
                GPT(cfg), optax.adamw(1e-3), tokens, token_loss,
                spec=ParallelSpec(), grad_accum=accum,
            )
            state = res.state
            losses = []
            for _ in range(3):
                state, m = res.train_step(state, tokens)
                losses.append(float(m["loss"]))
            return losses

        np.testing.assert_allclose(run(1), run(4), rtol=2e-5, atol=2e-5)

    def test_bad_divisibility_raises(self):
        cfg = tiny_cfg()
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (6, 16), 0, cfg.vocab_size
        )
        res = auto_accelerate(
            GPT(cfg), optax.adamw(1e-3), tokens, token_loss,
            spec=ParallelSpec(), grad_accum=4,
        )
        with pytest.raises(Exception):
            jax.block_until_ready(res.train_step(res.state, tokens))


class TestElasticTrainer:
    def test_accum_retunes_with_world_size(self):
        """The invariant: global batch stays fixed across world sizes."""
        for world, expect_accum in ((1, 8), (2, 4), (4, 2), (8, 1)):
            t = ElasticTrainer(
                global_batch_size=64, micro_batch_size=8, world_size=world
            )
            assert t.accum_steps == expect_accum
            assert (
                t.local_batch_size * world == 64
            ), "global batch drifted on resize"

    def test_world_from_env(self, monkeypatch):
        from dlrover_tpu.common.constants import NodeEnv

        monkeypatch.setenv(NodeEnv.NUM_PROCESSES, "2")
        t = ElasticTrainer(global_batch_size=32, micro_batch_size=4)
        assert t.world_size == 2 and t.accum_steps == 4

    def test_awkward_configs_now_tune(self):
        """Configs the old contract rejected derive an effective micro
        batch instead: the global batch is preserved exactly."""
        t = ElasticTrainer(global_batch_size=10, micro_batch_size=3)
        assert t.micro_batch_size == 2 and t.schedule.counts == [5]
        t = ElasticTrainer(global_batch_size=16, micro_batch_size=3,
                           world_size=2)
        assert t.micro_batch_size == 2 and t.schedule.counts == [4, 4]
        assert sum(t.schedule.counts) * t.micro_batch_size == 16

    def test_invalid_configs_raise(self):
        """Only truly unsatisfiable configs reject: a rank would get
        zero samples, or non-positive inputs."""
        with pytest.raises(ValueError):
            ElasticTrainer(global_batch_size=2, micro_batch_size=1,
                           world_size=3)
        with pytest.raises(ValueError):
            ElasticTrainer(global_batch_size=0, micro_batch_size=1)
        with pytest.raises(ValueError):
            ElasticTrainer(global_batch_size=8, micro_batch_size=0)
        with pytest.raises(ValueError):
            ElasticTrainer(global_batch_size=8, micro_batch_size=2,
                           world_size=4, rank=7)

    def test_retune_preserves_global_batch(self):
        """4 -> 3 -> 4: the total microbatch count is world-independent
        and the remainder lands deterministically on the lowest ranks."""
        t = ElasticTrainer(global_batch_size=64, micro_batch_size=8,
                           world_size=4, rank=0)
        assert t.schedule.counts == [2, 2, 2, 2]
        sched3 = t.retune(3)
        assert sched3.counts == [3, 3, 2]
        assert sum(sched3.counts) * sched3.micro_batch == 64
        assert t.accum_steps == 3 and t.local_batch_size == 24
        sched4 = t.retune(4)
        assert sched4.counts == [2, 2, 2, 2]
        assert sum(sched4.counts) * sched4.micro_batch == 64
        # Deterministic remainder placement: re-deriving is identical.
        assert t.retune(3).counts == [3, 3, 2]

    def test_prepare_trains(self):
        cfg = tiny_cfg()
        micro = jax.random.randint(
            jax.random.PRNGKey(2), (4, 16), 0, cfg.vocab_size
        )
        trainer = ElasticTrainer(
            global_batch_size=16, micro_batch_size=4, world_size=1
        )
        assert trainer.accum_steps == 4
        res = trainer.prepare(
            GPT(cfg), optax.adamw(1e-3), micro, token_loss,
            spec=ParallelSpec(data=2),
        )
        batch = jax.random.randint(
            jax.random.PRNGKey(3), (trainer.local_batch_size, 16), 0,
            cfg.vocab_size,
        )
        state = res.state
        losses = []
        for _ in range(4):
            state, m = res.train_step(
                state, jax.device_put(batch, res.batch_sharding)
            )
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]
