"""MoE / expert-parallelism tests on the 8-device CPU mesh.

Same strategy as test_accel.py: EP numerics must match the 1-device
baseline exactly (the dispatch math is mesh-independent), and expert
weights must actually shard over the ``expert`` axis.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.accel import ParallelSpec, auto_accelerate
from dlrover_tpu.models.gpt import GPT, GPTConfig, moe_loss_fn
from dlrover_tpu.ops.moe import (
    compute_dispatch,
    expert_capacity,
    load_balance_loss,
)


def moe_cfg(**kw):
    return dataclasses.replace(
        GPTConfig.tiny(), dtype=jnp.float32, num_experts=4,
        moe_top_k=2, **kw
    )


def token_loss(module, params, batch):
    return moe_loss_fn(module.apply({"params": params}, batch), batch)


def run_training(spec, steps=3, cfg=None):
    cfg = cfg or moe_cfg()
    model = GPT(cfg)
    opt = optax.adamw(1e-3)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size
    )
    res = auto_accelerate(model, opt, tokens, token_loss, spec=spec)
    state = res.state
    batch = jax.device_put(tokens, res.batch_sharding)
    losses = []
    for _ in range(steps):
        state, m = res.train_step(state, batch)
        losses.append(float(m["loss"]))
    res.state = state
    return losses, res


class TestDispatch:
    def test_capacity_respected_and_weights_normalized(self):
        rng = np.random.default_rng(0)
        gates = jax.nn.softmax(
            jnp.asarray(rng.normal(size=(32, 4)), jnp.float32), -1
        )
        combine, dispatch = compute_dispatch(gates, top_k=2, capacity=8)
        # <= capacity tokens per expert, one token per (expert, slot)
        per_slot = np.asarray(dispatch).sum(axis=0)  # [E, C]
        assert per_slot.max() <= 1
        # each kept token's combine weights sum to <= 1 (renormalized)
        tok_sum = np.asarray(combine).sum(axis=(1, 2))
        assert tok_sum.max() <= 1.0 + 1e-5
        # with generous capacity nothing is dropped: all sums == 1
        combine2, _ = compute_dispatch(gates, top_k=2, capacity=64)
        np.testing.assert_allclose(
            np.asarray(combine2).sum(axis=(1, 2)), 1.0, rtol=1e-5
        )

    def test_overflow_drops_lowest_priority(self):
        # All tokens prefer expert 0; capacity 2 keeps exactly 2 first
        # choices there.
        gates = jnp.tile(
            jnp.asarray([[0.9, 0.1, 0.0, 0.0]], jnp.float32), (6, 1)
        )
        combine, dispatch = compute_dispatch(gates, top_k=1, capacity=2)
        assert int(np.asarray(dispatch)[:, 0, :].sum()) == 2

    def test_balance_loss_uniform_is_one(self):
        n, e = 64, 4
        gates = jnp.full((n, e), 1.0 / e, jnp.float32)
        top1 = jax.nn.one_hot(jnp.arange(n) % e, e, dtype=jnp.float32)
        assert float(load_balance_loss(gates, top1)) == pytest.approx(1.0)

    def test_capacity_mxu_aligned(self):
        assert expert_capacity(128, 4, 2, 1.25) % 8 == 0
        assert expert_capacity(2, 4, 1, 1.0) >= 8


class TestMoENumerics:
    @pytest.fixture(scope="class")
    def baseline(self):
        return run_training(ParallelSpec())[0]

    @pytest.mark.parametrize(
        "spec",
        [
            ParallelSpec(expert=4),
            ParallelSpec(data=2, expert=4),
            ParallelSpec(data=2, fsdp=2, expert=2),
        ],
        ids=["ep", "dp-ep", "dp-fsdp-ep"],
    )
    def test_matches_baseline(self, spec, baseline):
        losses, _ = run_training(spec)
        np.testing.assert_allclose(losses, baseline, rtol=2e-5, atol=2e-5)

    def test_expert_weights_sharded(self):
        _, res = run_training(ParallelSpec(expert=4), steps=1)
        w_up = res.state["params"]["blocks"]["moe"]["w_up"]
        shard = w_up.addressable_shards[0]
        # [L, E, D, F]: expert dim sharded 4-way
        assert shard.data.shape[1] == w_up.shape[1] // 4

    def test_loss_decreases(self):
        losses, _ = run_training(ParallelSpec(data=4, expert=2), steps=5)
        assert losses[-1] < losses[0]
