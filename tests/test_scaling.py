"""Scaling stack tests: scalers, watcher, auto-scaler, resource
optimizer (SURVEY §2.2 scalers/watchers/auto-scaler/optimizer)."""

import json
import sys
import time

import pytest

from dlrover_tpu.common.messages import NodeResourceStats
from dlrover_tpu.common.node import Node
from dlrover_tpu.master.node_manager import LocalJobManager, ScalePlan
from dlrover_tpu.master.scaling import (
    AllreduceAutoScaler,
    ElasticJobScaler,
    LocalResourceOptimizer,
    ProcessScaler,
    ProcessWatcher,
    ResourcePlan,
)
from dlrover_tpu.master.stats import JobMetricCollector


def sleep_cmd(node):
    return [sys.executable, "-c", "import time; time.sleep(60)"]


class TestProcessScaler:
    def test_launch_and_remove(self):
        scaler = ProcessScaler(sleep_cmd)
        try:
            scaler.scale(ScalePlan(launch_nodes=[Node("worker", 0),
                                                 Node("worker", 1)]))
            assert sorted(scaler.alive_nodes()) == [0, 1]
            scaler.scale(ScalePlan(remove_nodes=[Node("worker", 0)]))
            assert scaler.alive_nodes() == [1]
        finally:
            scaler.stop()
        assert scaler.alive_nodes() == []


class TestProcessWatcher:
    def test_death_reported_to_job_manager(self):
        scaler = ProcessScaler(
            lambda n: [sys.executable, "-c", "pass"]  # exits immediately
        )
        jm = LocalJobManager(node_num=1)
        watcher = ProcessWatcher(scaler, jm, interval=0.1)
        try:
            scaler.scale(ScalePlan(launch_nodes=[Node("worker", 0)]))
            watcher._poll()  # sees it alive (or already dead)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                watcher._poll()
                node = jm.get_node(0)
                if node is not None and node.status == "failed":
                    break
                time.sleep(0.05)
            assert jm.get_node(0).status == "failed"
        finally:
            watcher.stop()
            scaler.stop()


class RecordingScaler:
    def __init__(self):
        self.plans = []

    def scale(self, plan):
        self.plans.append(plan)


class TestAutoScaler:
    def test_relaunches_missing_workers(self):
        jm = LocalJobManager(node_num=3)
        jm.update_node_status(2, "failed", "oom")
        jm.get_node(2).relaunchable = False
        scaler = RecordingScaler()
        auto = AllreduceAutoScaler(jm, scaler, target_worker_num=3,
                                   interval=60)
        auto._reconcile()
        launch_plans = [p for p in scaler.plans if p.launch_nodes]
        assert launch_plans, "no relaunch plan produced"
        # A fresh id (not colliding with 0..2) is assigned.
        assert launch_plans[0].launch_nodes[0].id == 3

    def test_no_plan_when_at_target(self):
        jm = LocalJobManager(node_num=2)
        scaler = RecordingScaler()
        auto = AllreduceAutoScaler(jm, scaler, target_worker_num=2,
                                   interval=60)
        auto._reconcile()
        assert not [p for p in scaler.plans if p.launch_nodes]

    def test_resource_plan_executed(self):
        collector = JobMetricCollector()
        collector.collect_node_resource(
            NodeResourceStats(node_id=0, cpu_percent=200.0,
                              used_memory_mb=1000)
        )
        jm = LocalJobManager(node_num=1)
        scaler = RecordingScaler()
        auto = AllreduceAutoScaler(
            jm, scaler, resource_optimizer=LocalResourceOptimizer(collector),
            target_worker_num=1, interval=60,
        )
        auto._reconcile()
        res_plans = [p for p in scaler.plans if p.node_group_resources]
        assert res_plans
        group = res_plans[0].node_group_resources["worker"]
        assert group.node_resource.memory_mb == 1300  # peak * 1.3


class TestLocalResourceOptimizer:
    def test_empty_without_stats(self):
        opt = LocalResourceOptimizer(JobMetricCollector())
        assert opt.generate_plan(2).empty()

    def test_plan_from_stats(self):
        collector = JobMetricCollector()
        collector.collect_node_resource(
            NodeResourceStats(node_id=0, cpu_percent=150.0,
                              used_memory_mb=2048)
        )
        plan = LocalResourceOptimizer(collector).generate_plan(4)
        assert plan.worker_num == 4
        assert plan.worker_cpu == pytest.approx(2.25)  # 1.5 cores * 1.5
        assert plan.worker_memory_mb == int(2048 * 1.3)


class TestElasticJobScaler:
    def test_emits_crd_manifest(self):
        """The emitted body must be the vendored ScalePlan CRD schema
        (``scaleplan_types.go`` field names), not an ad-hoc dict."""

        class FakeClient:
            def __init__(self):
                self.bodies = []

            def patch(self, body):
                self.bodies.append(body)

        client = FakeClient()
        scaler = ElasticJobScaler(client, "job-x")
        from dlrover_tpu.common.node import NodeGroupResource, NodeResource

        scaler.scale(ScalePlan(
            node_group_resources={
                "worker": NodeGroupResource(
                    count=4,
                    node_resource=NodeResource(cpu=2.0, memory_mb=8192),
                )
            },
            launch_nodes=[Node("worker", 5)],
        ))
        body = client.bodies[0]
        assert body["kind"] == "ScalePlan"
        assert body["apiVersion"].endswith("v1alpha1")
        assert body["metadata"]["labels"]["elasticjob-name"] == "job-x"
        spec = body["spec"]
        assert spec["ownerJob"] == "job-x"
        rrs = spec["replicaResourceSpecs"]["worker"]
        assert rrs["replicas"] == 4
        assert rrs["resource"] == {"cpu": "2.0", "memory": "8192Mi"}
        (pod,) = spec["createPods"]
        assert pod["id"] == 5 and pod["type"] == "worker"
        assert pod["rankIndex"] == 5
        assert body["status"]["phase"] == "Pending"

    def test_manifest_round_trips(self):
        from dlrover_tpu.master.crd import ScalePlanCRD, scaleplan_from_plan

        crd = scaleplan_from_plan(
            ScalePlan(launch_nodes=[Node("worker", 1)],
                      remove_nodes=[Node("worker", 0)]),
            "job-y", seq=3,
        )
        doc = crd.to_manifest()
        back = ScalePlanCRD.from_manifest(doc)
        assert back.name == "job-y-scaleplan-3"
        assert [p.id for p in back.spec.create_pods] == [1]
        assert [p.id for p in back.spec.remove_pods] == [0]


class TestScalePlanReconciler:
    def test_round_trip_autoscaler_to_new_process(self):
        """VERDICT r3 #7 done-criterion: auto-scaler -> ScalePlan CRD ->
        reconciler -> the platform actually launches the node (the same
        watch->realize->status flow elasticjob_controller.go runs)."""
        from dlrover_tpu.master.crd import (
            PHASE_SUCCEEDED,
            ScalePlanReconciler,
            ScalePlanStore,
        )

        jm = LocalJobManager(node_num=2)
        jm.update_node_status(1, "failed", "killed")
        jm.get_node(1).relaunchable = False

        store = ScalePlanStore()
        process_scaler = ProcessScaler(sleep_cmd)
        reconciler = ScalePlanReconciler(store, process_scaler)
        auto = AllreduceAutoScaler(
            jm, ElasticJobScaler(store, "job-rt"),
            target_worker_num=2, interval=60,
        )
        try:
            auto._reconcile()          # emits the CRD into the store
            reconciler.start()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not store.applied:
                time.sleep(0.05)
            assert store.applied, "reconciler never applied the plan"
            applied = store.applied[0]
            assert applied.status.phase == PHASE_SUCCEEDED
            assert applied.status.finish_time is not None
            # the platform really launched the replacement node
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if process_scaler.alive_nodes():
                    break
                time.sleep(0.05)
            assert process_scaler.alive_nodes()
        finally:
            reconciler.stop()
            process_scaler.stop()

    def test_remove_flows_through(self):
        from dlrover_tpu.master.crd import (
            ScalePlanReconciler,
            ScalePlanStore,
        )

        store = ScalePlanStore()
        process_scaler = ProcessScaler(sleep_cmd)
        reconciler = ScalePlanReconciler(store, process_scaler)
        ej = ElasticJobScaler(store, "job-rm")
        try:
            process_scaler.scale(
                ScalePlan(launch_nodes=[Node("worker", 7)])
            )
            assert process_scaler.alive_nodes() == [7]
            ej.scale(ScalePlan(remove_nodes=[Node("worker", 7)]))
            reconciler.start()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not store.applied:
                time.sleep(0.05)
            assert store.applied
            assert process_scaler.alive_nodes() == []
        finally:
            reconciler.stop()
            process_scaler.stop()


class TestK8sClientContract:
    """The REST client must emit exactly the apiserver's custom-resource
    protocol (paths/verbs/bodies) — pinned here so a real cluster is a
    transport swap (parity: reference k8sClient/pod_scaler surface)."""

    def make(self):
        calls = []

        def transport(method, path, body):
            calls.append((method, path, body))
            if method == "GET" and path.endswith("scaleplans"):
                return 200, {"items": []}
            if method == "GET":
                from dlrover_tpu.master.crd import scaleplan_from_plan

                return 200, scaleplan_from_plan(
                    ScalePlan(), "job-k", 1
                ).to_manifest()
            return 201, {"ok": True}

        from dlrover_tpu.master.k8s import K8sElasticJobClient

        return K8sElasticJobClient(transport, namespace="ml"), calls

    def test_create_scaleplan_request_shape(self):
        from dlrover_tpu.master.crd import scaleplan_from_plan

        client, calls = self.make()
        crd = scaleplan_from_plan(
            ScalePlan(launch_nodes=[Node("worker", 2)]), "job-k", 7
        )
        client.create_scaleplan(crd)
        method, path, body = calls[0]
        assert method == "POST"
        assert path == (
            "/apis/elastic.iml.github.io/v1alpha1/namespaces/ml/"
            "scaleplans"
        )
        assert body["kind"] == "ScalePlan"
        assert body["metadata"]["name"] == "job-k-scaleplan-7"
        assert body["spec"]["createPods"][0]["id"] == 2

    def test_status_patch_subresource(self):
        client, calls = self.make()
        client.update_scaleplan_status("job-k-scaleplan-7", "Succeeded")
        method, path, body = calls[0]
        assert method == "PATCH"
        assert path.endswith("/scaleplans/job-k-scaleplan-7/status")
        assert body["status"]["phase"] == "Succeeded"

    def test_elasticjob_replica_patch(self):
        client, calls = self.make()
        client.patch_elasticjob_replicas("job-k", {"worker": 5})
        method, path, body = calls[0]
        assert method == "PATCH"
        assert path.endswith("/elasticjobs/job-k")
        assert body["spec"]["replicaSpecs"]["worker"]["replicas"] == 5

    def test_elasticjob_scaler_through_k8s_submitter(self):
        """ElasticJobScaler -> K8sScalePlanSubmitter -> apiserver create:
        the cluster path uses the same CRD emission as the local one."""
        from dlrover_tpu.master.k8s import K8sScalePlanSubmitter

        client, calls = self.make()
        scaler = ElasticJobScaler(
            K8sScalePlanSubmitter(client), "job-k"
        )
        scaler.scale(ScalePlan(launch_nodes=[Node("worker", 0)]))
        method, path, body = calls[0]
        assert method == "POST"
        assert path.endswith("/scaleplans")
        assert body["spec"]["ownerJob"] == "job-k"

    def test_error_status_raises(self):
        from dlrover_tpu.master.crd import scaleplan_from_plan
        from dlrover_tpu.master.k8s import K8sElasticJobClient

        client = K8sElasticJobClient(
            lambda m, p, b: (409, {"reason": "AlreadyExists"}),
            namespace="ml",
        )
        with pytest.raises(RuntimeError, match="409"):
            client.create_scaleplan(
                scaleplan_from_plan(ScalePlan(), "j", 1)
            )


class TestDefaultTransportLiveHTTP:
    """Exercise ``default_transport`` (the urllib path a real cluster
    uses) against a live in-test HTTP server: verbs, paths, auth header,
    and the CRD PATCH content-type (merge-patch, not application/json —
    a real apiserver 415s the latter on custom resources)."""

    @pytest.fixture()
    def server(self):
        import http.server
        import threading

        seen = []

        class Handler(http.server.BaseHTTPRequestHandler):
            def _respond(self):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                seen.append({
                    "method": self.command,
                    "path": self.path,
                    "content_type": self.headers.get("Content-Type"),
                    "auth": self.headers.get("Authorization"),
                    "body": json.loads(body) if body else None,
                })
                if "conflict" in self.path:
                    payload = json.dumps(
                        {"reason": "AlreadyExists", "code": 409}
                    ).encode()
                    self.send_response(409)
                    self.send_header(
                        "Content-Type", "application/json"
                    )
                    self.send_header(
                        "Content-Length", str(len(payload))
                    )
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                payload = json.dumps({"ok": True, "items": []}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            do_GET = do_POST = do_PATCH = _respond

            def log_message(self, *a):
                pass

        httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            yield f"http://127.0.0.1:{httpd.server_address[1]}", seen
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_post_and_patch_over_live_server(self, server):
        from dlrover_tpu.master.crd import scaleplan_from_plan
        from dlrover_tpu.master.k8s import (
            K8sElasticJobClient,
            default_transport,
        )

        url, seen = server
        client = K8sElasticJobClient(
            default_transport(url, token="sekrit"), namespace="ml"
        )
        client.create_scaleplan(
            scaleplan_from_plan(
                ScalePlan(launch_nodes=[Node("worker", 1)]), "job-h", 3
            )
        )
        client.update_scaleplan_status("job-h-scaleplan-3", "Succeeded")
        client.patch_elasticjob_replicas("job-h", {"worker": 2})
        client.list_scaleplans()

        post, patch_status, patch_job, listed = seen
        assert post["method"] == "POST"
        assert post["content_type"] == "application/json"
        assert post["auth"] == "Bearer sekrit"
        assert post["body"]["kind"] == "ScalePlan"
        assert patch_status["method"] == "PATCH"
        assert patch_status["content_type"] == "application/merge-patch+json"
        assert patch_status["path"].endswith("/status")
        assert patch_job["content_type"] == "application/merge-patch+json"
        assert patch_job["body"]["spec"]["replicaSpecs"]["worker"][
            "replicas"] == 2
        assert listed["method"] == "GET"

    def test_non_2xx_surfaces_as_status_not_exception(self, server):
        """urlopen raises HTTPError on >=300; the transport must turn
        that back into (status, parsed apiserver Status body) so the
        client's error branches actually fire."""
        from dlrover_tpu.master.k8s import (
            K8sElasticJobClient,
            default_transport,
        )

        url, seen = server
        client = K8sElasticJobClient(default_transport(url))
        with pytest.raises(RuntimeError, match="409"):
            client.update_scaleplan_status("conflict-plan", "Succeeded")


class TestActorScaler:
    """Ray backend contract (parity: scaler/ray_scaler.py ActorScaler):
    actor naming, create/remove protocol, alive diffing."""

    class FakeRay:
        def __init__(self):
            self.actors = {}
            self.calls = []

        def create_actor(self, name, spec):
            self.calls.append(("create", name, spec))
            self.actors[name] = spec

        def remove_actor(self, name):
            self.calls.append(("remove", name))
            self.actors.pop(name, None)

        def list_actors(self):
            return list(self.actors)

    def test_scale_creates_and_removes_actors(self):
        from dlrover_tpu.common.node import NodeResource
        from dlrover_tpu.master.ray_scaler import ActorScaler

        ray = self.FakeRay()
        scaler = ActorScaler(ray, "job-r")
        n = Node("worker", 3)
        n.resource = NodeResource(cpu=2.0, memory_mb=4096)
        scaler.scale(ScalePlan(launch_nodes=[n]))
        assert "job-r-worker-3" in ray.actors
        spec = ray.actors["job-r-worker-3"]
        assert spec["num_cpus"] == 2.0
        assert spec["memory"] == 4096 << 20
        scaler.scale(ScalePlan(remove_nodes=[Node("worker", 3)]))
        assert ray.actors == {}

    def test_alive_nodes_ignores_foreign_actors(self):
        from dlrover_tpu.master.ray_scaler import ActorScaler

        ray = self.FakeRay()
        ray.actors = {
            "job-r-worker-0": {},
            "job-r-worker-2": {},
            "other-job-worker-5": {},
            "unrelated": {},
        }
        scaler = ActorScaler(ray, "job-r")
        assert sorted(scaler.alive_nodes()) == [
            ("worker", 0), ("worker", 2)
        ]

    def test_actor_name_round_trip(self):
        from dlrover_tpu.master.ray_scaler import (
            actor_name,
            parse_actor_name,
        )

        name = actor_name("j", Node("worker", 7))
        assert parse_actor_name(name) == ("worker", 7)
        assert parse_actor_name("garbage") is None


class TestClusterWatcher:
    def test_vanished_node_reported_once_and_rearms(self):
        from dlrover_tpu.master.ray_scaler import ClusterWatcher

        jm = LocalJobManager(node_num=2)
        failures = []
        jm.add_event_callback(
            lambda event: failures.append(
                (event.node.id, event.node.status)
            ) if event.node.status == "failed" else None
        )
        alive = {0, 1}
        watcher = ClusterWatcher(lambda: alive, jm, interval=60)
        watcher._poll()
        assert failures == []
        alive.discard(1)                # platform lost node 1
        watcher._poll()
        watcher._poll()                 # no duplicate report while down
        assert [f for f in failures if f[0] == 1] == [(1, "failed")]
        # relaunch: node 1 alive again, then vanishes again -> re-report
        jm.get_node(1).update_status("running")
        alive.add(1)
        watcher._poll()
        alive.discard(1)
        jm.get_node(1).update_status("running")
        watcher._poll()
        assert [f for f in failures if f[0] == 1] == [
            (1, "failed"), (1, "failed")
        ]


class TestK8sListWatch:
    """List+watch parity (k8s_watcher.py:151) against a LIVE chunked
    HTTP server: initial list seeds pending plans, watch events stream,
    EOF reconnects from the last resourceVersion, 410 re-lists, and the
    unchanged ScalePlanReconciler realizes plans + pushes status."""

    @pytest.fixture()
    def apiserver(self):
        import http.server
        import threading

        from dlrover_tpu.master.crd import scaleplan_from_plan

        def plan_doc(seq, rv, phase=""):
            crd = scaleplan_from_plan(
                ScalePlan(launch_nodes=[Node("worker", seq)]),
                "job-w", seq,
            )
            doc = crd.to_manifest()
            doc["metadata"]["resourceVersion"] = str(rv)
            doc["status"]["phase"] = phase
            return doc

        state = {
            "watch_calls": [], "status_patches": [],
            "expire_first_watch": False,
        }

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                if "watch=1" in self.path:
                    state["watch_calls"].append(self.path)
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "application/json"
                    )
                    self.end_headers()
                    if (state["expire_first_watch"]
                            and len(state["watch_calls"]) == 1):
                        self.wfile.write((json.dumps({
                            "type": "ERROR",
                            "object": {"code": 410,
                                       "reason": "Expired"},
                        }) + "\n").encode())
                        return
                    n = len(state["watch_calls"])
                    # two events per connection, then EOF
                    for i in range(2):
                        seq = 10 * n + i
                        self.wfile.write((json.dumps({
                            "type": "ADDED",
                            "object": plan_doc(seq, 100 * n + i),
                        }) + "\n").encode())
                        self.wfile.flush()
                    return
                body = json.dumps({
                    "metadata": {"resourceVersion": "5"},
                    "items": [plan_doc(1, 4),
                              plan_doc(2, 5, phase="Succeeded")],
                }).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_PATCH(self):
                length = int(self.headers.get("Content-Length") or 0)
                state["status_patches"].append(
                    (self.path,
                     json.loads(self.rfile.read(length)))
                )
                body = b"{}"
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            yield f"http://127.0.0.1:{httpd.server_address[1]}", state
        finally:
            httpd.shutdown()
            httpd.server_close()

    def make_client(self, url):
        from dlrover_tpu.master.k8s import (
            K8sElasticJobClient,
            default_stream_transport,
            default_transport,
        )

        return K8sElasticJobClient(
            default_transport(url),
            stream_transport=default_stream_transport(url, timeout=10),
        )

    def test_watch_streams_events(self, apiserver):
        url, _ = apiserver
        client = self.make_client(url)
        events = list(client.watch_scaleplans("5"))
        assert [e[0] for e in events] == ["ADDED", "ADDED"]
        assert events[0][1].spec.create_pods[0].id == 10

    def test_source_lists_then_watches_and_reconciler_realizes(
        self, apiserver
    ):
        from dlrover_tpu.master.crd import ScalePlanReconciler
        from dlrover_tpu.master.k8s import K8sScalePlanSource

        url, state = apiserver
        source = K8sScalePlanSource(self.make_client(url),
                                    reconnect_delay=0.05)
        realized = []

        class FakeScaler:
            def scale(self, plan):
                realized.append(
                    [n.id for n in plan.launch_nodes]
                )

        rec = ScalePlanReconciler(source, FakeScaler())
        source.start()
        rec.start()
        deadline = time.time() + 20
        # list seeds plan 1 (plan 2 already Succeeded -> skipped);
        # watch connections deliver 10, 11, then reconnect 20, 21...
        while time.time() < deadline and len(realized) < 3:
            time.sleep(0.05)
        rec.stop()
        source.stop()
        flat = [i for ids in realized for i in ids]
        assert 1 in flat           # from the initial list
        assert 10 in flat and 11 in flat  # from the first watch
        assert 2 not in flat       # already-realized plan skipped
        assert len(state["watch_calls"]) >= 2  # reconnected after EOF
        # resumed from the last seen resourceVersion
        assert "resourceVersion=101" in state["watch_calls"][1]
        # reconciler pushed phases back to the status subresource
        assert any(
            "/status" in path and body["status"]["phase"] == "Succeeded"
            for path, body in state["status_patches"]
        )

    def test_410_triggers_relist(self, apiserver):
        from dlrover_tpu.master.k8s import K8sScalePlanSource

        url, state = apiserver
        state["expire_first_watch"] = True
        source = K8sScalePlanSource(self.make_client(url),
                                    reconnect_delay=0.05)
        source.start()
        got = []
        deadline = time.time() + 20
        while time.time() < deadline and len(got) < 2:
            plan = source.watch(timeout=0.2)
            if plan is not None:
                got.append(plan)
        source.stop()
        # survived the 410: re-listed (plan 1 seen twice is fine) and
        # went on to receive watch events
        assert len(state["watch_calls"]) >= 2
        assert got


class TestWatchSourceScoping:
    def test_plans_queue_exactly_once(self):
        """A still-Pending plan arriving from list AND watch (or a 410
        re-list) must realize once, not twice."""
        from dlrover_tpu.master.crd import scaleplan_from_plan
        from dlrover_tpu.master.k8s import (
            K8sElasticJobClient,
            K8sScalePlanSource,
        )

        crd = scaleplan_from_plan(
            ScalePlan(launch_nodes=[Node("worker", 1)]), "job-d", 1
        )
        src = K8sScalePlanSource(
            K8sElasticJobClient(lambda m, p, b: (200, {}))
        )
        src._offer(crd)
        src._offer(crd)  # watch duplicate
        assert src.watch(timeout=0.1) is not None
        assert src.watch(timeout=0.1) is None

    def test_selector_scopes_to_job(self):
        """Two masters in one namespace: the source only lists/watches
        its own job's plans (elasticjob-name label selector)."""
        from dlrover_tpu.master.k8s import (
            K8sElasticJobClient,
            K8sScalePlanSource,
        )

        paths = []

        def transport(method, path, body):
            paths.append(path)
            return 200, {"metadata": {"resourceVersion": "1"},
                         "items": []}

        def stream(path):
            paths.append(path)
            return iter(())  # immediate EOF

        client = K8sElasticJobClient(
            transport, stream_transport=stream
        )
        source = K8sScalePlanSource(client, job_name="job-a",
                                    reconnect_delay=0.01)
        source.start()
        deadline = time.time() + 5
        while time.time() < deadline and len(paths) < 3:
            time.sleep(0.02)
        source.stop()
        assert any("labelSelector=elasticjob-name%3Djob-a" in p
                   or "labelSelector=elasticjob-name=job-a" in p
                   for p in paths if "watch" not in p)
        assert any("labelSelector" in p for p in paths
                   if "watch=1" in p)
