"""Scaling stack tests: scalers, watcher, auto-scaler, resource
optimizer (SURVEY §2.2 scalers/watchers/auto-scaler/optimizer)."""

import sys
import time

import pytest

from dlrover_tpu.common.messages import NodeResourceStats
from dlrover_tpu.common.node import Node
from dlrover_tpu.master.node_manager import LocalJobManager, ScalePlan
from dlrover_tpu.master.scaling import (
    AllreduceAutoScaler,
    ElasticJobScaler,
    LocalResourceOptimizer,
    ProcessScaler,
    ProcessWatcher,
    ResourcePlan,
)
from dlrover_tpu.master.stats import JobMetricCollector


def sleep_cmd(node):
    return [sys.executable, "-c", "import time; time.sleep(60)"]


class TestProcessScaler:
    def test_launch_and_remove(self):
        scaler = ProcessScaler(sleep_cmd)
        try:
            scaler.scale(ScalePlan(launch_nodes=[Node("worker", 0),
                                                 Node("worker", 1)]))
            assert sorted(scaler.alive_nodes()) == [0, 1]
            scaler.scale(ScalePlan(remove_nodes=[Node("worker", 0)]))
            assert scaler.alive_nodes() == [1]
        finally:
            scaler.stop()
        assert scaler.alive_nodes() == []


class TestProcessWatcher:
    def test_death_reported_to_job_manager(self):
        scaler = ProcessScaler(
            lambda n: [sys.executable, "-c", "pass"]  # exits immediately
        )
        jm = LocalJobManager(node_num=1)
        watcher = ProcessWatcher(scaler, jm, interval=0.1)
        try:
            scaler.scale(ScalePlan(launch_nodes=[Node("worker", 0)]))
            watcher._poll()  # sees it alive (or already dead)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                watcher._poll()
                node = jm.get_node(0)
                if node is not None and node.status == "failed":
                    break
                time.sleep(0.05)
            assert jm.get_node(0).status == "failed"
        finally:
            watcher.stop()
            scaler.stop()


class RecordingScaler:
    def __init__(self):
        self.plans = []

    def scale(self, plan):
        self.plans.append(plan)


class TestAutoScaler:
    def test_relaunches_missing_workers(self):
        jm = LocalJobManager(node_num=3)
        jm.update_node_status(2, "failed", "oom")
        jm.get_node(2).relaunchable = False
        scaler = RecordingScaler()
        auto = AllreduceAutoScaler(jm, scaler, target_worker_num=3,
                                   interval=60)
        auto._reconcile()
        launch_plans = [p for p in scaler.plans if p.launch_nodes]
        assert launch_plans, "no relaunch plan produced"
        # A fresh id (not colliding with 0..2) is assigned.
        assert launch_plans[0].launch_nodes[0].id == 3

    def test_no_plan_when_at_target(self):
        jm = LocalJobManager(node_num=2)
        scaler = RecordingScaler()
        auto = AllreduceAutoScaler(jm, scaler, target_worker_num=2,
                                   interval=60)
        auto._reconcile()
        assert not [p for p in scaler.plans if p.launch_nodes]

    def test_resource_plan_executed(self):
        collector = JobMetricCollector()
        collector.collect_node_resource(
            NodeResourceStats(node_id=0, cpu_percent=200.0,
                              used_memory_mb=1000)
        )
        jm = LocalJobManager(node_num=1)
        scaler = RecordingScaler()
        auto = AllreduceAutoScaler(
            jm, scaler, resource_optimizer=LocalResourceOptimizer(collector),
            target_worker_num=1, interval=60,
        )
        auto._reconcile()
        res_plans = [p for p in scaler.plans if p.node_group_resources]
        assert res_plans
        group = res_plans[0].node_group_resources["worker"]
        assert group.node_resource.memory_mb == 1300  # peak * 1.3


class TestLocalResourceOptimizer:
    def test_empty_without_stats(self):
        opt = LocalResourceOptimizer(JobMetricCollector())
        assert opt.generate_plan(2).empty()

    def test_plan_from_stats(self):
        collector = JobMetricCollector()
        collector.collect_node_resource(
            NodeResourceStats(node_id=0, cpu_percent=150.0,
                              used_memory_mb=2048)
        )
        plan = LocalResourceOptimizer(collector).generate_plan(4)
        assert plan.worker_num == 4
        assert plan.worker_cpu == pytest.approx(2.25)  # 1.5 cores * 1.5
        assert plan.worker_memory_mb == int(2048 * 1.3)


class TestElasticJobScaler:
    def test_patch_body(self):
        class FakeClient:
            def __init__(self):
                self.bodies = []

            def patch(self, body):
                self.bodies.append(body)

        client = FakeClient()
        scaler = ElasticJobScaler(client, "job-x")
        from dlrover_tpu.common.node import NodeGroupResource, NodeResource

        scaler.scale(ScalePlan(
            node_group_resources={
                "worker": NodeGroupResource(
                    count=4,
                    node_resource=NodeResource(cpu=2.0, memory_mb=8192),
                )
            },
            launch_nodes=[Node("worker", 5)],
        ))
        body = client.bodies[0]
        assert body["job"] == "job-x"
        assert body["replicas"]["worker"]["replicas"] == 4
        assert body["launch"] == [5]
