"""Profiler + tracing tests (SURVEY §2.5 profiler, §5 tracing)."""

import json
import time

import jax
import jax.numpy as jnp
import pytest

from dlrover_tpu.utils.profiler import Profiler, device_peak_flops
from dlrover_tpu.utils.tracing import Tracer


class TestProfiler:
    def test_step_and_phase_stats(self):
        prof = Profiler()
        for _ in range(5):
            with prof.step():
                with prof.phase("data"):
                    time.sleep(0.01)
                with prof.phase("compute"):
                    time.sleep(0.02)
        rep = prof.report()
        assert rep["steps"] == 5
        assert rep["step_time_mean_s"] >= 0.03
        assert rep["phases"]["data"]["mean_s"] >= 0.01
        assert rep["phases"]["compute"]["share"] > rep["phases"]["data"]["share"]

    def test_cost_analysis_flops(self):
        """Compiler-reported flops for a matmul must match 2*M*N*K."""
        prof = Profiler()
        m = 256

        @jax.jit
        def f(a, b):
            return a @ b

        a = jnp.ones((m, m), jnp.float32)
        cost = prof.analyze(f, a, a)
        assert cost["flops"] == pytest.approx(2 * m ** 3, rel=0.01)

    def test_utilization_needs_data(self):
        prof = Profiler()
        assert prof.utilization() == -1.0

    def test_mfu_computation(self):
        prof = Profiler()
        prof._cost = {"flops": 1e9, "bytes_accessed": 0}
        with prof.step():
            time.sleep(0.01)
        # On CPU device_peak_flops is 0 -> -1; force a peak.
        mfu = prof.utilization(device=None) if device_peak_flops() else None
        u = prof._cost["flops"] / prof._step_stats.mean / 1e12
        assert u > 0  # arithmetic sanity

    def test_trace_capture_writes_events(self, tmp_path):
        """jax.profiler trace capture on the step schedule produces
        profile artifacts."""
        import os

        prof = Profiler(trace_dir=str(tmp_path), trace_steps=(1, 2))

        @jax.jit
        def f(x):
            return x * 2

        x = jnp.ones(8)
        for _ in range(4):
            with prof.step():
                jax.block_until_ready(f(x))
        found = []
        for root, _, files in os.walk(tmp_path):
            found.extend(files)
        assert found, "no trace artifacts written"


class TestTracer:
    def test_span_and_instant(self):
        tracer = Tracer()
        with tracer.span("rendezvous", round=1):
            time.sleep(0.005)
        tracer.instant("crash", rank=2)
        events = tracer.events
        assert len(events) == 2
        span = next(e for e in events if e["ph"] == "X")
        assert span["name"] == "rendezvous"
        assert span["dur"] >= 5000  # microseconds
        assert span["args"]["round"] == 1

    def test_export_chrome_trace(self, tmp_path):
        tracer = Tracer()
        tracer.instant("e1")
        tracer.counter("mem", mb=512)
        path = str(tmp_path / "trace.json")
        tracer.export(path)
        with open(path) as f:
            doc = json.load(f)
        assert len(doc["traceEvents"]) == 2

    def test_export_without_path_is_noop(self, monkeypatch):
        monkeypatch.delenv("DLROVER_TPU_TRACE_FILE", raising=False)
        tracer = Tracer()
        tracer.instant("e")
        assert tracer.export() is None

    def test_capacity_bounded(self):
        tracer = Tracer(capacity=10)
        for i in range(100):
            tracer.instant(f"e{i}")
        assert len(tracer.events) == 10


class TestModuleCosts:
    """Per-module attribution (VERDICT r3 #9, parity with AProfiler's
    module table ``atorch/atorch/utils/prof.py:39-464``)."""

    def test_ranks_transformer_blocks_dominant(self):
        import dataclasses

        import jax
        import jax.numpy as jnp

        from dlrover_tpu.models.gpt import GPT, GPTConfig
        from dlrover_tpu.utils.profiler import Profiler

        cfg = dataclasses.replace(
            GPTConfig.tiny(), dtype=jnp.float32, scan_layers=False
        )
        prof = Profiler()
        tokens = jnp.zeros((2, 16), jnp.int32)
        rows = prof.module_costs(
            GPT(cfg), jax.random.PRNGKey(0), tokens, depth=2
        )
        assert rows, "no module rows recorded"
        by_path = {r["path"]: r for r in rows}
        # Transformer blocks must dominate the norms/embeddings...
        assert by_path["block_0"]["flops"] > by_path["ln_f"]["flops"]
        # ...and within a block the MLP up-projection (d->4d) must
        # outrank qkv (d->3d): the compiler's numbers, not guesses.
        assert (
            by_path["block_0/up"]["flops"]
            > by_path["block_0/qkv"]["flops"]
        )
        # shares are normalized against the root total
        top = rows[0]
        assert 0 < top["share"] <= 1.0

    def test_scan_module_reports_whole_stack(self):
        import dataclasses

        import jax
        import jax.numpy as jnp

        from dlrover_tpu.models.gpt import GPT, GPTConfig
        from dlrover_tpu.utils.profiler import Profiler

        unrolled = dataclasses.replace(
            GPTConfig.tiny(), dtype=jnp.float32, scan_layers=False
        )
        scanned = dataclasses.replace(unrolled, scan_layers=True)
        prof = Profiler()
        tokens = jnp.zeros((2, 16), jnp.int32)
        rows_u = prof.module_costs(
            GPT(unrolled), jax.random.PRNGKey(0), tokens, depth=1
        )
        rows_s = prof.module_costs(
            GPT(scanned), jax.random.PRNGKey(0), tokens, depth=1
        )
        flops_u = sum(
            r["flops"] for r in rows_u if r["path"].startswith("block_")
        )
        blocks = next(r for r in rows_s if r["path"] == "blocks")
        # XLA's cost analysis counts a while-loop body ONCE, so the
        # scanned row reports per-iteration cost: total / num_layers
        # (module_costs documents this; unrolled configs give totals).
        assert blocks["flops"] == pytest.approx(
            flops_u / unrolled.num_layers, rel=0.05
        )
