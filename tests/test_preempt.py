"""Preemption plane (ISSUE 13): known-ahead failures as planned moves.

Covers the master-side :class:`PreemptionCoordinator` (notice intake,
writer-lease pre-election, step-boundary proactive shrink, false-alarm
cancel through supersede semantics), the journaled RPC surface incl.
master failover mid-notice, the agent-side :class:`PreemptionWatcher`
notice sources and chaos variants, and the goodput ledger's distinct
``preempt:handled`` cause.
"""

import threading
import time

import pytest

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.preempt import PreemptionWatcher
from dlrover_tpu.chaos import FaultEvent, FaultInjector, FaultPlan
from dlrover_tpu.common import messages as m
from dlrover_tpu.common.constants import (
    NodeExitReason,
    NodeStatus,
    RendezvousName,
)
from dlrover_tpu.master.kv_store import KVStoreService
from dlrover_tpu.master.master import JobMaster
from dlrover_tpu.master.preempt import (
    NOTICE_ACTIVE,
    NOTICE_CANCELLED,
    NOTICE_HANDLED,
    PreemptionCoordinator,
)
from dlrover_tpu.master.rescale import PLAN_ABORTED, PLAN_ISSUED
from dlrover_tpu.observability.events import EventKind, JobEvent
from dlrover_tpu.observability.goodput import GoodputLedger

from tests.test_chaos import arm, chaos_clean  # noqa: F401  (fixture)
from tests.test_rescale import TRAIN, formed_world, make_coordinator
from tests.test_state_store import crash_master


class FakeJobManager:
    """Just the preempting-marker contract the coordinator drives."""

    def __init__(self):
        self.preempting = set()

    def mark_preempting(self, node_id):
        self.preempting.add(node_id)

    def clear_preempting(self, node_id):
        self.preempting.discard(node_id)


def make_preempt(mgr, rescale=None, kv=None, jm=None, store=None):
    return PreemptionCoordinator(
        rdzv_managers={TRAIN: mgr}, kv_store=kv, job_manager=jm,
        rescale_coordinator=rescale, state_store=store,
    )


def notice_req(victim=3, deadline=None, grace=30.0, source="file"):
    return m.PreemptionNotice(
        node_rank=victim,
        deadline_ts=deadline if deadline is not None else time.time() + 60,
        grace_s=grace, source=source, reason="test",
    )


# ---------------------------------------------------------------------------
# Master-side coordinator
# ---------------------------------------------------------------------------


class TestPreemptionCoordinator:
    def test_notice_dedup_first_deadline_wins(self):
        mgr, _, _ = formed_world(4)
        pre = make_preempt(mgr)
        first = notice_req(3, deadline=1000.0)
        assert pre.on_notice(first).success
        dup = pre.on_notice(notice_req(3, deadline=2000.0))
        assert dup.success and dup.reason == "duplicate"
        assert pre.pending() == [3]
        assert pre.notice_state(3)["deadline_ts"] == 1000.0

    def test_disabled_is_a_noop(self, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_PREEMPT", "0")
        mgr, _, _ = formed_world(4)
        pre = make_preempt(mgr)
        resp = pre.on_notice(notice_req(3))
        assert not resp.success
        assert pre.pending() == []

    def test_notice_preelects_writer_leases(self):
        """Every lease the victim owns moves to the lowest surviving
        rank before the victim dies — the next checkpoint epoch never
        blocks on a dead writer."""
        mgr, _, _ = formed_world(4)
        kv = KVStoreService()
        kv.setnx("ckpt_writer/0/ck:shard0", b"3")
        kv.setnx("ckpt_writer/0/ck:shard1", b"1")
        jm = FakeJobManager()
        pre = make_preempt(mgr, kv=kv, jm=jm)
        assert pre.on_notice(notice_req(3)).success
        # Victim-owned lease handed to rank 0; others untouched.
        assert kv.get("ckpt_writer/0/ck:shard0") == b"0"
        assert kv.get("ckpt_writer/0/ck:shard1") == b"1"
        assert pre.notice_state(3)["leases"] == [
            ["ckpt_writer/0/ck:shard0", 0, 3]
        ]
        assert 3 in jm.preempting

    def test_step_boundary_issues_proactive_shrink(self):
        mgr, round_, world = formed_world(4)
        coord = make_coordinator(mgr)
        pre = make_preempt(mgr, rescale=coord)
        assert pre.on_notice(notice_req(3)).success
        # Nothing happens until a step boundary arrives.
        assert mgr.current_world() == world
        pre.note_step(6)
        state = pre.notice_state(3)
        assert state["planned"] and state["plan_id"] >= 0
        plan = coord.get_plan(TRAIN, 0, round_)
        assert plan.exists and plan.status == PLAN_ISSUED
        assert sorted(plan.new_world) == [0, 1, 2]
        # The victim is already out of the world, pre-kill.
        assert 3 not in mgr.current_world()
        # A later step boundary does not re-plan.
        pre.note_step(7)
        assert pre.notice_state(3)["plan_id"] == plan.plan_id

    def test_eventual_kill_is_marked_handled(self):
        mgr, _, _ = formed_world(4)
        coord = make_coordinator(mgr)
        pre = make_preempt(mgr, rescale=coord)
        pre.on_notice(notice_req(3, deadline=time.time() - 100))
        pre.note_step(6)
        assert pre.on_node_removed(3) is True
        assert pre.notice_state(3)["status"] == NOTICE_HANDLED
        # The deadline is long past, but the node really died: tick must
        # NOT cancel a handled notice (no lease revert, no cancel event).
        pre.tick()
        assert pre.notice_state(3)["status"] == NOTICE_HANDLED
        # And a second removal report finds nothing left to do.
        assert pre.on_node_removed(3) is False

    def test_false_alarm_cancels_cleanly(self, monkeypatch):
        """Deadline passes, node still alive: leases revert, the shrink
        plan is superseded WITHOUT round invalidation, nothing restarts."""
        monkeypatch.setenv("DLROVER_TPU_PREEMPT_FALSE_ALARM_S", "0")
        mgr, round_, world = formed_world(4)
        coord = make_coordinator(mgr)
        kv = KVStoreService()
        kv.setnx("ckpt_writer/0/ck:shard0", b"3")
        jm = FakeJobManager()
        pre = make_preempt(mgr, rescale=coord, kv=kv, jm=jm)
        pre.on_notice(notice_req(3, deadline=time.time() - 1))
        pre.note_step(6)
        plan = coord.get_plan(TRAIN, 0, round_)
        assert plan.exists
        pre.tick()
        state = pre.notice_state(3)
        assert state["status"] == NOTICE_CANCELLED
        # Lease back with its prior owner, marker cleared, plan aborted.
        assert kv.get("ckpt_writer/0/ck:shard0") == b"3"
        assert 3 not in jm.preempting
        assert plan.status == PLAN_ABORTED
        # Supersede, not invalidation: the shrunk round stays live —
        # survivors keep training and the victim regrows normally.
        assert not mgr.world_stale(plan.new_round)
        # A node death long after the cancel is an ordinary crash.
        assert pre.on_node_removed(3) is False

    def test_false_alarm_before_any_step_reverts_without_plan(
        self, monkeypatch
    ):
        monkeypatch.setenv("DLROVER_TPU_PREEMPT_FALSE_ALARM_S", "0")
        mgr, _, world = formed_world(4)
        kv = KVStoreService()
        kv.setnx("ckpt_writer/0/ck:shard0", b"3")
        pre = make_preempt(mgr, kv=kv)
        pre.on_notice(notice_req(3, deadline=time.time() - 1))
        pre.tick()
        assert pre.notice_state(3)["status"] == NOTICE_CANCELLED
        assert kv.get("ckpt_writer/0/ck:shard0") == b"3"
        # No plan was ever issued and the world never shrank.
        assert mgr.current_world() == world


class TestKvScan:
    def test_scan_returns_sorted_prefix_slice(self):
        kv = KVStoreService()
        kv.set("ckpt_writer/0/a", b"1")
        kv.set("ckpt_writer/1/a", b"2")
        kv.set("other/x", b"3")
        got = kv.scan("ckpt_writer/")
        assert list(got) == ["ckpt_writer/0/a", "ckpt_writer/1/a"]
        assert kv.scan("nope/") == {}


# ---------------------------------------------------------------------------
# RPC surface + failover
# ---------------------------------------------------------------------------


class TestPreemptionRpc:
    def _join_world(self, master, clients):
        for r, c in enumerate(clients):
            c.join_rendezvous(TRAIN, r, 1)
        round_, _, world = clients[0].get_comm_world(TRAIN, 0)
        clients[0].report_model_info(
            0, 0.0, batch_size=16,
            extra={"global_batch": 16, "micro_batch": 4},
        )
        for r in (0, 1, 2):
            clients[r].report_model_info(
                0, 0.0, extra={"rescale_capable": True}
            )
        return round_, world

    def test_notice_to_shrink_to_nonevent_kill(self):
        master = JobMaster(port=0, node_num=4, job_name="preempt-rpc")
        master.prepare()
        clients = [MasterClient(master.addr, node_id=r) for r in range(4)]
        try:
            round_, world = self._join_world(master, clients)
            resp = clients[3].report_preemption_notice(
                node_rank=3, deadline_ts=time.time() + 60,
                grace_s=60.0, source="file",
            )
            assert resp.success
            assert master.preempt.pending() == [3]
            # Retry/duplicate report: absorbed, not re-run.
            dup = clients[3].report_preemption_notice(
                node_rank=3, deadline_ts=time.time() + 90,
                grace_s=90.0, source="env",
            )
            assert dup.success and dup.reason == "duplicate"
            # The next step boundary converts the notice into a plan
            # (the step report rides the bulk lane, so poll briefly).
            clients[0].report_global_step(7, time.time())
            deadline = time.monotonic() + 5
            got = m.RescalePlan()
            while time.monotonic() < deadline and not got.exists:
                got = clients[0].get_rescale_plan(TRAIN, 0, round_)
                time.sleep(0.05)
            assert got.exists and sorted(got.new_world) == [0, 1, 2]
            assert master.preempt.notice_state(3)["planned"]
            # The kill lands: the victim is already out of the world, so
            # the failure report must not issue a second plan.
            clients[3].report_failure("SIGTERM", level="node_error")
            assert master.preempt.notice_state(3)["status"] == NOTICE_HANDLED
            newer = clients[0].get_rescale_plan(TRAIN, 0, got.new_round)
            assert not newer.exists
        finally:
            for c in clients:
                c.close()
            master.stop()

    def test_failover_mid_notice_replays_exactly_once(self, tmp_path):
        """Master dies with a pending notice: WAL replay reproduces it —
        same deadline, same writer-lease handoff — exactly once."""
        state_dir = str(tmp_path / "mstate")
        deadline = time.time() + 3600
        m1 = JobMaster(
            port=0, node_num=4, job_name="preempt-fo", state_dir=state_dir
        )
        m1.prepare()
        clients = [MasterClient(m1.addr, node_id=r) for r in range(4)]
        try:
            self._join_world(m1, clients)
            # The victim owns a journaled writer lease before the notice.
            lease = clients[3].elect_ckpt_writer("ck:shard0", 0, 3)
            assert lease.exists and lease.owner_rank == 3
            resp = clients[3].report_preemption_notice(
                node_rank=3, deadline_ts=deadline, grace_s=60.0,
                source="metadata", reason="maintenance",
            )
            assert resp.success
            assert m1.kv_store.get("ckpt_writer/0/ck:shard0") == b"0"
        finally:
            for c in clients:
                c.close()
            crash_master(m1)

        m2 = JobMaster(
            port=0, node_num=4, job_name="preempt-fo", state_dir=state_dir
        )
        m2.prepare()
        try:
            # Exactly one pending notice, byte-for-byte the one reported.
            assert m2.preempt.pending() == [3]
            state = m2.preempt.notice_state(3)
            assert state["status"] == NOTICE_ACTIVE
            assert state["deadline_ts"] == pytest.approx(deadline)
            assert state["source"] == "metadata"
            # The replayed pre-election reproduces the identical handoff.
            assert m2.kv_store.get("ckpt_writer/0/ck:shard0") == b"0"
            assert state["leases"] == [["ckpt_writer/0/ck:shard0", 0, 3]]
            # And a client retry against the new master still dedupes.
            client = MasterClient(m2.addr, node_id=3)
            try:
                dup = client.report_preemption_notice(
                    node_rank=3, deadline_ts=deadline, grace_s=60.0,
                    source="metadata",
                )
                assert dup.success and dup.reason == "duplicate"
            finally:
                client.close()
        finally:
            m2.stop()


# ---------------------------------------------------------------------------
# Node bookkeeping: preempted exits never relaunch
# ---------------------------------------------------------------------------


class TestPreemptedNodeFlow:
    def test_process_error_during_notice_is_preempted_not_crash(self):
        from dlrover_tpu.master.node_manager import JobManager

        nm = JobManager(node_num=2)
        nm.mark_preempting(1)
        relaunch = nm.process_error(1, 0, "SIGTERM", "node_error")
        assert relaunch is False
        node = nm.get_node(1)
        assert node.status == NodeStatus.FAILED
        assert node.exit_reason == NodeExitReason.PREEMPTED
        # An unannounced failure on another node keeps the crash path.
        assert nm.is_preempting(0) is False

    def test_export_restore_round_trips_preempting_marker(self):
        from dlrover_tpu.master.node_manager import JobManager

        nm = JobManager(node_num=2)
        nm.mark_preempting(1)
        state = nm.export_nodes()
        nm2 = JobManager(node_num=2)
        nm2.restore_nodes(state)
        assert nm2.is_preempting(1) and not nm2.is_preempting(0)

    def test_should_relaunch_excludes_preempted(self):
        from dlrover_tpu.common.constants import NodeType
        from dlrover_tpu.common.node import Node
        from dlrover_tpu.master.status_flow import (
            get_node_state_flow,
            should_relaunch,
        )

        flow = get_node_state_flow(NodeStatus.RUNNING, NodeStatus.FAILED)
        assert flow.should_relaunch
        node = Node(NodeType.WORKER, 0, max_relaunch_count=3)
        node.exit_reason = NodeExitReason.PREEMPTED
        assert should_relaunch(node, flow) is False
        # Same flow without the preempted reason would relaunch.
        node.exit_reason = ""
        assert should_relaunch(node, flow) is True


# ---------------------------------------------------------------------------
# Agent-side watcher
# ---------------------------------------------------------------------------


class FakeClient:
    def __init__(self):
        self.reports = []

    def report_preemption_notice(self, **kw):
        self.reports.append(kw)
        return m.Response(success=True)


def make_watcher(metadata_fn=None):
    client = FakeClient()
    flushed = []
    killed = threading.Event()
    watcher = PreemptionWatcher(
        client=client, node_rank=2, metadata_fn=metadata_fn,
        flush_fn=lambda: flushed.append(True), kill_fn=killed.set,
    )
    return watcher, client, flushed, killed


class TestPreemptionWatcher:
    def test_file_source_arms_reports_and_flushes(
        self, tmp_path, monkeypatch
    ):
        path = tmp_path / "notice"
        deadline = time.time() + 45
        path.write_text(f"deadline={deadline}\n")
        monkeypatch.setenv("DLROVER_TPU_PREEMPT_NOTICE_FILE", str(path))
        watcher, client, flushed, killed = make_watcher()
        watcher.poll_once()
        assert watcher.active
        assert watcher.deadline_ts == pytest.approx(deadline)
        assert len(client.reports) == 1
        assert client.reports[0]["source"] == "file"
        assert flushed == [True]
        assert not killed.is_set()
        # Armed is a latch: further polls do not re-report.
        watcher.poll_once()
        assert len(client.reports) == 1

    def test_env_flip_source(self, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_PREEMPT_NOW", "1")
        monkeypatch.setenv("DLROVER_TPU_PREEMPT_GRACE_S", "40")
        watcher, client, flushed, _ = make_watcher()
        watcher.poll_once()
        assert watcher.active
        assert client.reports[0]["source"] == "env"
        assert client.reports[0]["grace_s"] == 40.0

    def test_metadata_shim_source(self):
        deadline = time.time() + 33
        watcher, client, flushed, _ = make_watcher(
            metadata_fn=lambda: {
                "deadline_ts": deadline, "grace_s": 33.0,
                "reason": "maintenance",
            }
        )
        watcher.poll_once()
        assert watcher.active
        assert client.reports[0]["source"] == "metadata"
        assert client.reports[0]["deadline_ts"] == pytest.approx(deadline)
        assert client.reports[0]["reason"] == "maintenance"

    def test_metadata_none_means_no_notice(self):
        watcher, client, _, _ = make_watcher(metadata_fn=lambda: None)
        watcher.poll_once()
        assert not watcher.active and client.reports == []

    def test_chaos_kill_after_window(self, monkeypatch, chaos_clean):
        arm(monkeypatch, FaultPlan(seed=1, events=[
            FaultEvent(site="preempt.notice", kind="notice", every=1,
                       max_fires=1, match="2",
                       args={"window_s": 5.0, "kill_after_s": 0.05}),
        ]))
        watcher, client, flushed, killed = make_watcher()
        watcher.poll_once()
        assert watcher.active
        assert client.reports[0]["source"] == "chaos"
        assert flushed == [True]
        assert killed.wait(2.0)
        watcher.stop()

    def test_chaos_kill_before_window_is_plain_crash(
        self, monkeypatch, chaos_clean
    ):
        """kill_after_s=0: the kill beats the notice — no report, no
        armed window, so nothing double-handles the ordinary crash."""
        arm(monkeypatch, FaultPlan(seed=1, events=[
            FaultEvent(site="preempt.notice", kind="notice", every=1,
                       max_fires=1,
                       args={"window_s": 5.0, "kill_after_s": 0}),
        ]))
        watcher, client, flushed, killed = make_watcher()
        watcher.poll_once()
        assert killed.is_set()
        assert not watcher.active
        assert client.reports == [] and flushed == []

    def test_expired_window_disarms(self, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_PREEMPT_FALSE_ALARM_S", "0")
        watcher, client, _, _ = make_watcher(
            metadata_fn=lambda: {"deadline_ts": time.time() - 1}
        )
        watcher.poll_once()
        assert len(client.reports) == 1
        # Deadline long gone with the workers alive: false alarm — a
        # later real crash must not be classified as preemption.
        assert not watcher.active

    def test_stale_evidence_does_not_rearm(self, tmp_path, monkeypatch):
        """A notice file that keeps sitting on disk after its window
        expired as a false alarm must not churn out a fresh
        notice/cancel cycle every window; deleting and re-creating it
        (a genuinely new notice) re-arms."""
        monkeypatch.setenv("DLROVER_TPU_PREEMPT_FALSE_ALARM_S", "0")
        path = tmp_path / "notice"
        path.write_text(f"deadline={time.time() - 1}\n")
        monkeypatch.setenv("DLROVER_TPU_PREEMPT_NOTICE_FILE", str(path))
        watcher, client, _, _ = make_watcher()
        watcher.poll_once()
        assert len(client.reports) == 1
        assert not watcher.active  # expired -> false alarm, source spent
        watcher.poll_once()
        watcher.poll_once()
        assert len(client.reports) == 1  # stale file stays latched
        # Evidence cleared, then a new notice lands: re-arm.
        path.unlink()
        watcher.poll_once()
        path.write_text(f"deadline={time.time() + 60}\n")
        watcher.poll_once()
        assert len(client.reports) == 2
        assert watcher.active

    def test_disabled_never_starts(self, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_PREEMPT", "0")
        watcher, _, _, _ = make_watcher()
        watcher.start()
        assert watcher._task is None


# ---------------------------------------------------------------------------
# Goodput: the distinct preempt:handled cause
# ---------------------------------------------------------------------------


def ev(kind, node=3, ts=0.0, **args):
    return JobEvent(kind=kind, node_id=node, ts=ts, args=args)


class TestPreemptGoodput:
    def test_handled_books_apart_from_crash(self):
        led = GoodputLedger(now=0.0)
        led.ingest(ev(EventKind.PREEMPT_NOTICE, ts=1.0))
        led.ingest(ev(EventKind.RESCALE_PLAN, ts=2.0, plan_id=1))
        led.ingest(ev(EventKind.PREEMPT_HANDLED, ts=2.0, plan_id=1))
        led.note_step(8, ts=2.5)
        led.ingest(ev(EventKind.WORKER_FAIL, node=1, ts=10.0,
                      cause="crash"))
        led.note_step(9, ts=12.0)
        s = led.summary(now=20.0)
        assert s["incidents_by_cause"]["preempt:handled"] == 1
        assert s["incidents_by_cause"]["worker-failure"] == 1
        assert "rescale" not in s["incidents_by_cause"]
        assert s["open_incidents"] == 0

    def test_announced_exit_lands_under_handled(self):
        """WORKER_FAIL / NODE_EVICT carrying cause="preempt" (the agent
        monitor's classification during an active window) open the
        handled incident, not a crash one."""
        led = GoodputLedger(now=0.0)
        led.ingest(ev(EventKind.WORKER_FAIL, ts=1.0, cause="preempt"))
        led.ingest(ev(EventKind.NODE_EVICT, ts=1.1, cause="preempt"))
        led.note_step(5, ts=2.0)
        s = led.summary(now=3.0)
        assert s["incidents_by_cause"] == {"preempt:handled": 1}

    def test_rescale_plan_never_stomps_handled(self):
        led = GoodputLedger(now=0.0)
        led.ingest(ev(EventKind.PREEMPT_HANDLED, ts=1.0))
        led.ingest(ev(EventKind.RESCALE_PLAN, ts=1.1, plan_id=1))
        (inc,) = led.incidents()
        assert inc.cause == "preempt:handled"

    def test_notice_without_kill_opens_nothing(self):
        """False alarm end-to-end in the ledger: notice + cancel are
        context, not faults — zero incidents, zero downtime."""
        led = GoodputLedger(now=0.0)
        led.note_step(1, ts=0.5)
        led.ingest(ev(EventKind.PREEMPT_NOTICE, ts=1.0))
        led.ingest(ev(EventKind.PREEMPT_CANCEL, ts=6.0))
        led.note_step(2, ts=6.5)
        s = led.summary(now=7.0)
        assert s["incidents"] == []
        assert s["open_incidents"] == 0
        assert s["downtime_s"] == 0.0
