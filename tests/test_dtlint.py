"""dtlint analyzer drills: per-rule fixtures (fire on the bad shape,
stay quiet on the good one), the suppression audit, the CLI contract,
the docs/env-table sync — and the tier-1 gate: the analyzer runs over
the whole ``dlrover_tpu`` package and must report zero unsuppressed
findings."""

import os
import subprocess
import sys
import textwrap

import pytest

from tools.dtlint.__main__ import build_env_table, main
from tools.dtlint.core import lint_source
from tools.dtlint.project import Project
from tools.dtlint.rules import ALL_RULES

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "dlrover_tpu")

PROJECT = Project(REPO)


def run_rule(rule_id, source, path="dlrover_tpu/somewhere/mod.py",
             project=PROJECT):
    rules = [r for r in ALL_RULES if r.id == rule_id]
    assert rules, f"unknown rule {rule_id}"
    return lint_source(textwrap.dedent(source), path, rules, project)


def rule_ids(findings):
    return [f.rule for f in findings]


class TestDT001SwallowedException:
    def test_fires_on_except_exception_pass(self):
        active, _ = run_rule("DT001", """\
            try:
                risky()
            except Exception:
                pass
        """)
        assert rule_ids(active) == ["DT001"]

    def test_fires_on_bare_except_without_reraise(self):
        active, _ = run_rule("DT001", """\
            try:
                risky()
            except:
                cleanup()
        """)
        assert rule_ids(active) == ["DT001"]

    def test_quiet_on_bare_except_with_reraise(self):
        active, _ = run_rule("DT001", """\
            try:
                risky()
            except:
                cleanup()
                raise
        """)
        assert active == []

    def test_quiet_when_logged_or_narrowed(self):
        active, _ = run_rule("DT001", """\
            try:
                risky()
            except Exception:
                logger.warning("boom", exc_info=True)
            try:
                risky()
            except (OSError, ValueError):
                pass
        """)
        assert active == []

    def test_suppression_with_reason_moves_to_suppressed(self):
        active, suppressed = run_rule("DT001", """\
            try:
                risky()
            except Exception:  # dtlint: disable=DT001 -- emit() never raises by contract
                pass
        """)
        assert active == []
        assert rule_ids(suppressed) == ["DT001"]


class TestDT002BlockingUnderLock:
    def test_fires_on_sleep_under_lock(self):
        active, _ = run_rule("DT002", """\
            import time

            def f(self):
                with self._lock:
                    time.sleep(1.0)
        """)
        assert rule_ids(active) == ["DT002"]

    def test_fires_on_emit_and_open_under_lock(self):
        active, _ = run_rule("DT002", """\
            def f(self):
                with self._state_lock:
                    emit("kind", step=1)
                    data = open("/tmp/x").read()
        """)
        assert rule_ids(active) == ["DT002", "DT002"]

    def test_quiet_outside_lock_and_in_nested_def(self):
        active, _ = run_rule("DT002", """\
            import time

            def f(self):
                with self._lock:
                    x = compute()

                    def later():
                        time.sleep(1.0)  # runs after release
                time.sleep(0.1)
        """)
        assert active == []

    def test_quiet_on_non_lock_context(self):
        active, _ = run_rule("DT002", """\
            import time

            def f(self):
                with open("/tmp/x") as fh:
                    time.sleep(0.1)
        """)
        assert active == []


class TestDT003BusyPoll:
    def test_fires_on_while_sleep(self):
        active, _ = run_rule("DT003", """\
            import time

            def f():
                while not done():
                    time.sleep(0.1)
        """)
        assert rule_ids(active) == ["DT003"]

    def test_quiet_on_backoff_and_event_wait(self):
        active, _ = run_rule("DT003", """\
            import time

            def f(backoff, stop):
                while not done():
                    backoff.sleep()
                while not stop.is_set():
                    stop.wait(0.5)
                time.sleep(1.0)  # not in a loop: a one-shot delay
        """)
        assert active == []

    def test_nested_function_in_loop_is_its_own_scope(self):
        active, _ = run_rule("DT003", """\
            import time

            def f():
                while True:
                    def cb():
                        time.sleep(0.1)  # runs elsewhere, not this loop
                    register(cb)
                    if done():
                        break
        """)
        assert active == []


class TestDT004Toctou:
    def test_fires_on_exists_then_open(self):
        active, _ = run_rule("DT004", """\
            import os

            def f(path):
                if os.path.exists(path):
                    with open(path) as fh:
                        return fh.read()
        """)
        assert rule_ids(active) == ["DT004"]

    def test_quiet_on_open_and_catch(self):
        active, _ = run_rule("DT004", """\
            def f(path):
                try:
                    with open(path) as fh:
                        return fh.read()
                except FileNotFoundError:
                    return None
        """)
        assert active == []

    def test_quiet_when_check_gates_no_open(self):
        active, _ = run_rule("DT004", """\
            import os

            def f(path, other):
                if os.path.exists(path):
                    os.unlink(path)
                with open(other) as fh:
                    return fh.read()
        """)
        assert active == []

    def test_scopes_are_independent(self):
        active, _ = run_rule("DT004", """\
            import os

            def check(path):
                return os.path.exists(path)

            def read(path):
                return open(path).read()
        """)
        assert active == []


class TestDT005AtomicWrite:
    DURABLE = "dlrover_tpu/master/state_store.py"

    def test_fires_on_write_open_in_durable_module(self):
        active, _ = run_rule("DT005", """\
            def save(path, data):
                with open(path, "wb") as fh:
                    fh.write(data)
        """, path=self.DURABLE)
        assert rule_ids(active) == ["DT005"]

    def test_quiet_on_tmp_then_replace_and_append(self):
        active, _ = run_rule("DT005", """\
            import os

            def save(path, data):
                tmp = path + ".tmp"
                with open(tmp, "wb") as fh:
                    fh.write(data)
                os.replace(tmp, path)

            def journal(path, rec):
                with open(path, "ab") as fh:
                    fh.write(rec)
        """, path=self.DURABLE)
        assert active == []

    def test_quiet_outside_durable_modules(self):
        active, _ = run_rule("DT005", """\
            def save(path, data):
                with open(path, "w") as fh:
                    fh.write(data)
        """, path="dlrover_tpu/utils/scratch.py")
        assert active == []


class TestDT006EnvRegistry:
    def test_declared_literal_is_a_bypass(self):
        active, _ = run_rule("DT006", """\
            import os

            flag = os.getenv("DLROVER_TPU_LOCKDEP")
        """)
        assert rule_ids(active) == ["DT006"]
        assert "env_utils" in active[0].message or "registry" in active[0].message

    def test_undeclared_literal_is_a_typo(self):
        active, _ = run_rule("DT006", """\
            import os

            flag = os.getenv("DLROVER_TPU_NO_SUCH_KNOB_EVER")
        """)
        assert rule_ids(active) == ["DT006"]

    def test_docstrings_and_registry_module_exempt(self):
        active, _ = run_rule("DT006", '''\
            """Set DLROVER_TPU_LOCKDEP=1 to arm lockdep."""
        ''')
        assert active == []
        active, _ = run_rule(
            "DT006",
            'LOCKDEP = _REG.bool("DLROVER_TPU_LOCKDEP", False, "doc")\n',
            path=PROJECT.env_registry_path,
        )
        assert active == []


class TestDT007ChaosSites:
    def test_registered_literal_is_a_bypass(self):
        active, _ = run_rule("DT007", """\
            chaos = fault_hit("trainer.step", detail="3")
        """)
        assert rule_ids(active) == ["DT007"]
        assert "ChaosSite" in active[0].message

    def test_unregistered_literal_is_a_typo(self):
        active, _ = run_rule("DT007", """\
            chaos = fault_hit("trainer.stpe")
        """)
        assert rule_ids(active) == ["DT007"]
        assert "not registered" in active[0].message

    def test_constant_reference_is_quiet(self):
        active, _ = run_rule("DT007", """\
            chaos = fault_hit(ChaosSite.TRAINER_STEP, detail="3")
        """)
        assert active == []


class TestDT008RpcContract:
    def _project(self, tmp_path, messages_src, servicer_src):
        messages = tmp_path / "messages.py"
        servicer = tmp_path / "servicer.py"
        messages.write_text(textwrap.dedent(messages_src))
        servicer.write_text(textwrap.dedent(servicer_src))
        return Project(
            REPO,
            messages_path=str(messages),
            servicer_path=str(servicer),
        ), str(messages), str(servicer)

    MESSAGES = """\
        class BaseRequest:
            pass

        class Covered(BaseRequest):
            journaled = True

        class Orphan(BaseRequest):
            pass
    """

    SERVICER = """\
        _HANDLERS = {m.Covered: 1}
        _JOURNALED = (m.Covered,)
        _APPLY_THEN_LOG = ()
    """

    def test_unhandled_request_flagged_in_messages(self, tmp_path):
        project, messages, _ = self._project(
            tmp_path, self.MESSAGES, self.SERVICER)
        active, _ = lint_source(
            open(messages).read(), messages,
            [r for r in ALL_RULES if r.id == "DT008"], project)
        assert ["DT008"] == rule_ids(active)
        assert "Orphan" in active[0].message

    def test_journal_tuple_mismatch_flagged_both_ways(self, tmp_path):
        project, messages, servicer = self._project(tmp_path, """\
            class BaseRequest:
                pass

            class Marked(BaseRequest):
                journaled = True
        """, """\
            _HANDLERS = {m.Marked: 1, m.Ghost: 2}
            _JOURNALED = (m.Ghost,)
            _APPLY_THEN_LOG = ()
        """)
        rule = [r for r in ALL_RULES if r.id == "DT008"]
        active, _ = lint_source(open(messages).read(), messages, rule, project)
        # Marked is journaled=True but missing from _JOURNALED.
        assert any("Marked" in f.message for f in active)
        active, _ = lint_source(open(servicer).read(), servicer, rule, project)
        # Ghost is handled+journaled but is not a declared request.
        assert any("Ghost" in f.message for f in active)

    def test_real_contract_is_clean(self):
        rule = [r for r in ALL_RULES if r.id == "DT008"]
        for path in (PROJECT.messages_path, PROJECT.servicer_path):
            active, _ = lint_source(open(path).read(), path, rule, PROJECT)
            assert active == [], [f.format() for f in active]


class TestSuppressionAudit:
    def test_reasonless_disable_is_dt000_and_does_not_suppress(self):
        active, suppressed = run_rule("DT001", """\
            try:
                risky()
            except Exception:  # dtlint: disable=DT001
                pass
        """)
        assert sorted(rule_ids(active)) == ["DT000", "DT001"]
        assert suppressed == []

    def test_unknown_rule_id_is_dt000(self):
        active, _ = run_rule("DT001", """\
            x = 1  # dtlint: disable=BOGUS -- because
        """)
        assert rule_ids(active) == ["DT000"]

    def test_dt000_cannot_be_suppressed(self):
        active, _ = run_rule("DT001", """\
            x = 1  # dtlint: disable=DT000 -- trying to silence the audit
        """)
        assert rule_ids(active) == ["DT000"]

    def test_malformed_directive_is_dt000(self):
        active, _ = run_rule("DT001", """\
            x = 1  # dtlint disable DT001 because reasons
        """)
        assert rule_ids(active) == ["DT000"]

    def test_multi_rule_disable_covers_both(self):
        active, suppressed = run_rule("DT003", """\
            import time

            def f():
                while not done():
                    time.sleep(0.5)  # dtlint: disable=DT002,DT003 -- scripted fixed cadence is the contract here
        """)
        assert active == []
        assert rule_ids(suppressed) == ["DT003"]


class TestCli:
    BAD = "try:\n    x()\nexcept Exception:\n    pass\n"

    def test_exit_one_on_findings_and_zero_on_clean(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text(self.BAD)
        assert main([str(bad)]) == 1
        out = capsys.readouterr().out
        assert "DT001" in out
        good = tmp_path / "good.py"
        good.write_text("x = 1\n")
        assert main([str(good)]) == 0

    def test_github_format(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text(self.BAD)
        assert main(["--format=github", str(bad)]) == 1
        assert "::error file=" in capsys.readouterr().out

    def test_syntax_error_is_reported_not_crashed(self, tmp_path, capsys):
        broken = tmp_path / "broken.py"
        broken.write_text("def f(:\n")
        assert main([str(broken)]) == 1
        assert "syntax error" in capsys.readouterr().err

    def test_list_rules_names_all_twelve(self, capsys):
        assert main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rid in ("DT001", "DT002", "DT003", "DT004",
                    "DT005", "DT006", "DT007", "DT008",
                    "DT009", "DT010", "DT011", "DT012"):
            assert rid in out

    def test_module_entrypoint(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(self.BAD)
        proc = subprocess.run(
            [sys.executable, "-m", "tools.dtlint", str(bad)],
            cwd=REPO, capture_output=True, text=True,
        )
        assert proc.returncode == 1
        assert "DT001" in proc.stdout


class TestTier1Gate:
    def test_package_has_zero_unsuppressed_findings(self, capsys):
        """THE gate: dlrover_tpu/ must lint clean. A new finding either
        gets fixed or carries a reasoned suppression — never lands raw."""
        rc = main([PKG])
        captured = capsys.readouterr()
        assert rc == 0, f"dtlint findings:\n{captured.out}\n{captured.err}"

    def test_env_table_matches_docs(self):
        """docs/configuration.md embeds the generated table verbatim
        (regenerate with `python -m tools.dtlint --env-table`)."""
        table = build_env_table(PROJECT.env_registry_path)
        doc_path = os.path.join(REPO, "docs", "configuration.md")
        doc = open(doc_path).read()
        begin, end = "<!-- env-table:begin -->", "<!-- env-table:end -->"
        assert begin in doc and end in doc
        embedded = doc.split(begin, 1)[1].split(end, 1)[0].strip()
        assert embedded == table.strip(), (
            "docs/configuration.md env table drifted from the registry; "
            "regenerate with: python -m tools.dtlint --env-table"
        )
