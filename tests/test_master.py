"""Master control-plane tests against an in-process master (SURVEY.md §4.1/4.3)."""

import time

import pytest

from dlrover_tpu.common.constants import NodeStatus, RendezvousName
from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.master.master import JobMaster
from dlrover_tpu.master.rendezvous import (
    DeviceCheckRendezvousManager,
    ElasticTrainingRendezvousManager,
)


@pytest.fixture
def master():
    master = JobMaster(port=0, node_num=2, job_name="test-job")
    master.prepare()
    yield master
    master.stop()


@pytest.fixture
def client(master):
    c = MasterClient(master.addr, node_id=0)
    yield c
    c.close()


class TestRendezvousManager:
    def test_training_rdzv_freeze_on_max(self):
        mgr = ElasticTrainingRendezvousManager("t")
        mgr.update_rdzv_params(2, 2, waiting_timeout=10)
        assert mgr.join_rendezvous(0, 4) == 0
        _, _, world = mgr.get_comm_world(0)
        assert world == {}  # only one of two nodes waiting
        mgr.join_rendezvous(1, 4)
        round_, group, world = mgr.get_comm_world(0)
        assert round_ == 1 and world == {0: 4, 1: 4}
        # Second node sees the same frozen world.
        _, _, world1 = mgr.get_comm_world(1)
        assert world1 == world
        assert mgr.num_nodes_waiting() == 0

    def test_training_rdzv_min_nodes_lastcall(self):
        mgr = ElasticTrainingRendezvousManager("t")
        mgr.update_rdzv_params(1, 4, waiting_timeout=0.2)
        mgr._lastcall_timeout = 0.1
        mgr.join_rendezvous(0, 8)
        time.sleep(0.25)
        round_, _, world = mgr.get_comm_world(0)
        assert world == {0: 8}

    def test_node_unit_alignment(self):
        mgr = ElasticTrainingRendezvousManager("t")
        mgr.update_rdzv_params(1, 4, waiting_timeout=0.1, node_unit=2)
        for r in range(3):
            mgr.join_rendezvous(r, 1)
        time.sleep(0.15)
        _, _, world = mgr.get_comm_world(0)
        # 3 waiting, unit 2 -> only 2 admitted.
        assert sorted(world) == [0, 1]
        assert mgr.num_nodes_waiting() == 1

    def test_membership_change_on_death(self):
        mgr = ElasticTrainingRendezvousManager("t")
        mgr.update_rdzv_params(2, 2, waiting_timeout=5)
        mgr.join_rendezvous(0, 1)
        mgr.join_rendezvous(1, 1)
        mgr.get_comm_world(0)
        mgr.remove_alive_node(1)
        # Node 1 respawns and rejoins -> waiting count observable by agents.
        mgr.join_rendezvous(1, 1)
        assert mgr.num_nodes_waiting() > 0


class TestFailureDetection:
    """Heartbeat death / training hang -> eviction -> stale world ->
    survivors re-form (SURVEY §5 failure detection; round-2 weak #5/#6)."""

    def _fast_master(self):
        from dlrover_tpu.common.global_context import get_context

        ctx = get_context()
        old = (ctx.heartbeat_timeout, ctx.node_monitor_interval,
               ctx.hang_detection_seconds)
        ctx.heartbeat_timeout = 0.6
        ctx.node_monitor_interval = 0.1
        master = JobMaster(port=0, node_num=2, job_name="test-failure")
        master.prepare()
        return master, ctx, old

    def _restore(self, ctx, old):
        (ctx.heartbeat_timeout, ctx.node_monitor_interval,
         ctx.hang_detection_seconds) = old

    def test_heartbeat_death_evicts_and_stales_world(self):
        master, ctx, old = self._fast_master()
        try:
            c0 = MasterClient(master.addr, node_id=0)
            c1 = MasterClient(master.addr, node_id=1)
            for rank, c in ((0, c0), (1, c1)):
                c.join_rendezvous(RendezvousName.TRAINING, rank, 1)
            round_, _, world = c0.get_comm_world(RendezvousName.TRAINING, 0)
            assert len(world) == 2
            c0.report_node_status(NodeStatus.RUNNING)
            c1.report_node_status(NodeStatus.RUNNING)
            # Both heartbeat, then node 1 goes silent.
            deadline = time.monotonic() + 5
            c1.report_heartbeat()
            while time.monotonic() < deadline:
                c0.report_heartbeat()
                if c0.world_stale(RendezvousName.TRAINING, round_):
                    break
                time.sleep(0.1)
            assert c0.world_stale(RendezvousName.TRAINING, round_), (
                "dead node never invalidated the world"
            )
            # Node 1 is gone from the job: the survivor alone can finish.
            assert master.job_manager.get_node(1) is None
            assert master.job_manager.get_node(0) is not None
            c0.close(), c1.close()
        finally:
            self._restore(ctx, old)
            master.stop()

    def test_hang_invalidates_round_without_eviction(self):
        """A synchronous-training hang stalls ALL nodes: the master must
        NOT evict anyone (that would abort the job) — it invalidates the
        round so every agent restarts in place."""
        master, ctx, old = self._fast_master()
        master.speed_monitor._hang_seconds = 0.5
        try:
            c0 = MasterClient(master.addr, node_id=0)
            c1 = MasterClient(master.addr, node_id=1)
            c0.join_rendezvous(RendezvousName.TRAINING, 0, 1)
            c1.join_rendezvous(RendezvousName.TRAINING, 1, 1)
            round_, _, world = c0.get_comm_world(RendezvousName.TRAINING, 0)
            assert len(world) == 2 and round_ >= 1
            c0.report_node_status(NodeStatus.RUNNING)
            c1.report_node_status(NodeStatus.RUNNING)
            c0.report_global_step(5, time.time())
            # Both keep heartbeating (agents alive) but no further steps
            # are reported (workers hung in a collective).
            deadline = time.monotonic() + 5
            stale = False
            while time.monotonic() < deadline:
                c0.report_heartbeat()
                c1.report_heartbeat()
                if c0.world_stale(RendezvousName.TRAINING, round_):
                    stale = True
                    break
                time.sleep(0.1)
            assert stale, "hang never invalidated the round"
            assert master.job_manager.get_node(0) is not None, (
                "hang recovery must not evict nodes"
            )
            assert master.job_manager.get_node(1) is not None
            c0.close(), c1.close()
        finally:
            self._restore(ctx, old)
            master.stop()


class TestDeviceCheckManager:
    def _form(self, mgr, n):
        mgr.update_rdzv_params(n, n, waiting_timeout=5)
        for r in range(n):
            mgr.join_rendezvous(r, 1)

    def test_pair_groups_and_fault_localization(self):
        mgr = DeviceCheckRendezvousManager("check")
        self._form(mgr, 4)
        groups = {}
        for r in range(4):
            _, g, world = mgr.get_comm_world(r)
            assert world, f"node {r} must be in a group"
            groups.setdefault(g, set()).update(world)
        assert sorted(len(v) for v in groups.values()) == [2, 2]

        # Round 1: node 3's pair fails -> suspects {2, 3}, not done.
        for r in range(4):
            ok = r not in (2, 3)
            mgr.report_check_result(r, ok, elapsed=1.0)
        fault, done = mgr.check_fault_node()
        assert set(fault) == {2, 3} and not done

        # Round 2: re-pair; only node 3 fails again -> confirmed fault.
        self._form(mgr, 4)
        for r in range(4):
            _, g, world = mgr.get_comm_world(r)
            assert world
        for r in range(4):
            mgr.report_check_result(r, r != 3, elapsed=1.0)
        fault, done = mgr.check_fault_node()
        assert fault == [3] and done

    def test_straggler_median_rule(self):
        mgr = DeviceCheckRendezvousManager("check")
        self._form(mgr, 4)
        for r in range(4):
            mgr.get_comm_world(r)
        times = {0: 1.0, 1: 1.1, 2: 0.9, 3: 5.0}
        for r, t in times.items():
            mgr.report_check_result(r, True, elapsed=t)
        stragglers, done = mgr.check_straggler()
        assert stragglers == [3] and done


class TestMasterEndToEnd:
    def test_kv_store(self, client):
        client.kv_store_set("a", b"1")
        assert client.kv_store_get("a") == b"1"
        assert client.kv_store_get("missing") is None
        assert client.kv_store_add("ctr", 2) == 2
        assert client.kv_store_add("ctr", 3) == 5
        got = client.kv_store_multi_get(["a", "ctr"])
        assert got == {"a": b"1", "ctr": b"5"}

    def test_rendezvous_rpc(self, master):
        c0 = MasterClient(master.addr, node_id=0)
        c1 = MasterClient(master.addr, node_id=1)
        c0.report_rdzv_params(2, 2, 10.0, 1)
        c0.join_rendezvous(RendezvousName.TRAINING, 0, 4)
        c1.join_rendezvous(RendezvousName.TRAINING, 1, 4)
        round_, group, world = c0.get_comm_world(RendezvousName.TRAINING)
        assert world == {0: 4, 1: 4}
        c0.close(), c1.close()

    def test_dynamic_sharding_with_worker_failure(self, master):
        c0 = MasterClient(master.addr, node_id=0)
        c1 = MasterClient(master.addr, node_id=1)
        c0.report_dataset_shard_params("ds", dataset_size=100, shard_size=10,
                                       num_epochs=1)
        t0 = c0.get_task("ds")
        t1 = c1.get_task("ds")
        assert t0.exists and t1.exists and t0.start != t1.start
        c0.report_task("ds", t0.task_id, success=True)
        # Worker 1 dies with its task in flight.
        c1.report_failure("worker died", level="node_error")
        # Its shard must come back; drain everything.
        seen = {(t0.start, t0.end)}
        while True:
            t = c0.get_task("ds")
            if not t.exists:
                break
            seen.add((t.start, t.end))
            c0.report_task("ds", t.task_id, success=True)
        assert (t1.start, t1.end) in seen
        assert len(seen) == 10
        c0.close(), c1.close()

    def test_metrics_sync_and_status(self, master, client):
        client.report_global_step(10)
        assert master.speed_monitor.global_step == 10
        client.report_heartbeat()
        assert client.join_sync("warmup", 0) in (True, False)
        client.barrier("b1", notify=True)
        assert client.barrier("b1") is True
        client.report_node_status(NodeStatus.RUNNING)
        node = master.job_manager.get_node(0)
        assert node.status == NodeStatus.RUNNING

    def test_job_exit(self, master, client):
        client.report_job_exit(success=True, reason="done")
        assert master.run(poll_interval=0.05) == 0


class TestShardCheckpoint:
    def test_checkpoint_restore_roundtrip(self, master, client):
        client.report_dataset_shard_params("ds2", dataset_size=40, shard_size=10)
        t = client.get_task("ds2")
        assert t.exists
        content = client.get_shard_checkpoint("ds2")
        assert "ds2" in content
        # Restore into a fresh task manager: the in-flight shard is back.
        from dlrover_tpu.master.shard.task_manager import TaskManager
        tm = TaskManager()
        tm.new_dataset("ds2", 40, 10)
        tm.restore(content)
        starts = set()
        while True:
            task = tm.get_task(0, "ds2")
            if not task.exists:
                break
            starts.add(task.start)
            tm.report_task("ds2", task.task_id, True)
        assert t.start in starts


class TestErrorMonitor:
    def test_word_boundary_classification(self):
        from dlrover_tpu.common.constants import NodeExitReason
        from dlrover_tpu.master.monitor.error_monitor import ErrorMonitor

        # Benign words must not trigger fatal classification.
        for benign in (
            "KeyError in policies lookup",
            "suspicious bloom filter mismatch",
            "assertion failed in hbm_viewer formatting",
        ):
            assert ErrorMonitor.classify(benign) == NodeExitReason.FATAL_ERROR
        assert (
            ErrorMonitor.classify("RESOURCE_EXHAUSTED: while allocating")
            == NodeExitReason.OOM
        )
        assert (
            ErrorMonitor.classify("jaxlib: out of memory allocating 2G")
            == NodeExitReason.OOM
        )
        assert (
            ErrorMonitor.classify("TPU halted unexpectedly")
            == NodeExitReason.HARDWARE_ERROR
        )
        assert (
            ErrorMonitor.classify("ICI link failure on port 3")
            == NodeExitReason.HARDWARE_ERROR
        )


class TestJobResource:
    """Per-role resource bookkeeping + OOM escalation (SURVEY §2.2
    JobResource row; parity: master/resource/job.py)."""

    def test_bookkeeping_round_trip(self):
        from dlrover_tpu.master.job_resource import JobResource

        jr = JobResource()
        jr.update_node_group_resource("worker", 4, 2.0, 8192)
        jr.update_node_group_resource("evaluator", 1, 1.0, 2048)
        assert jr.worker_num == 4
        assert jr.evaluator_num == 1
        assert sorted(jr.get_node_types()) == ["evaluator", "worker"]
        back = JobResource.from_dict(jr.to_dict())
        g = back.get_node_group_resource("worker")
        assert g.count == 4 and g.node_resource.memory_mb == 8192

    def test_oom_escalates_geometrically_then_gives_up(self):
        from dlrover_tpu.common.node import Node
        from dlrover_tpu.master.job_resource import (
            JobResourceManager,
            OomPolicy,
        )

        mgr = JobResourceManager(OomPolicy(factor=2.0, max_escalations=2))
        mgr.init_from_config(2, cpu=1.0, memory_mb=4096)
        node = Node("worker", 0)
        g1 = mgr.adjust_oom_resource(node)
        assert g1.node_resource.memory_mb == 8192
        g2 = mgr.adjust_oom_resource(node)
        assert g2.node_resource.memory_mb == 16384
        assert mgr.adjust_oom_resource(node) is None  # budget spent

    def test_oom_error_bumps_memory_and_exhaustion_is_fatal(self):
        """End-to-end through the job manager: an OOM report escalates
        the worker memory request; once the budget is spent the node
        becomes non-relaunchable instead of OOM-looping."""
        from dlrover_tpu.common.constants import TrainingExceptionLevel
        from dlrover_tpu.master.job_resource import (
            JobResourceManager,
            OomPolicy,
        )
        from dlrover_tpu.master.node_manager import LocalJobManager

        mgr = JobResourceManager(OomPolicy(factor=2.0, max_escalations=1))
        mgr.init_from_config(1, memory_mb=4096)
        jm = LocalJobManager(node_num=1, resource_manager=mgr)
        assert jm.process_error(
            0, 0, "RESOURCE_EXHAUSTED: out of memory",
            TrainingExceptionLevel.PROCESS_ERROR,
        )
        g = mgr.job_resource.get_node_group_resource("worker")
        assert g.node_resource.memory_mb == 8192
        # budget spent: second OOM marks the node non-relaunchable and
        # the API must report the actual decision (no relaunch).
        assert not jm.process_error(
            0, 1, "RESOURCE_EXHAUSTED: out of memory",
            TrainingExceptionLevel.PROCESS_ERROR,
        )
        assert jm.get_node(0).relaunchable is False

    def test_resource_plan_recorded(self):
        from dlrover_tpu.master.job_resource import JobResourceManager
        from dlrover_tpu.master.scaling import ResourcePlan

        mgr = JobResourceManager()
        assert not mgr.apply_resource_plan(ResourcePlan())
        assert mgr.apply_resource_plan(
            ResourcePlan(worker_cpu=2.0, worker_memory_mb=9000,
                         worker_num=3)
        )
        g = mgr.job_resource.get_node_group_resource("worker")
        assert g.count == 3 and g.node_resource.memory_mb == 9000
