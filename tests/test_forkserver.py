"""Preloaded fork-server tests (the spawn_s lever of the goodput
work; see dlrover_tpu/agent/forkserver.py)."""

import os
import subprocess
import sys
import time

import pytest

from dlrover_tpu.agent.forkserver import ForkServer


@pytest.fixture()
def server():
    fs = ForkServer()
    fs.start()
    yield fs
    fs.stop()


def test_spawn_runs_script_with_env(server, tmp_path):
    script = tmp_path / "w.py"
    script.write_text(
        "import os, sys\n"
        "print('hello from', os.environ['WHO'])\n"
        "sys.exit(int(os.environ.get('CODE', '0')))\n"
    )
    log = tmp_path / "w.log"
    env = {"WHO": "forked-worker", "PATH": os.environ.get("PATH", "")}
    w = server.spawn(str(script), [], env, log_path=str(log))
    assert w.wait(timeout=30) == 0
    assert "hello from forked-worker" in log.read_text()


def test_exit_codes_propagate(server, tmp_path):
    script = tmp_path / "f.py"
    script.write_text("import sys\nsys.exit(3)\n")
    w = server.spawn(str(script), [], {"PATH": os.environ.get("PATH", "")})
    assert w.wait(timeout=30) == 3


def test_spawn_is_fast_after_preload(server, tmp_path):
    """The point of the fork server: a worker that imports jax must
    start in a fraction of a cold python+jax start."""
    script = tmp_path / "j.py"
    script.write_text(
        "import time\nt0 = time.time()\n"
        "import jax\nimport optax\n"
        "print('imports took', time.time() - t0)\n"
    )
    log = tmp_path / "j.log"
    env = {k: v for k, v in os.environ.items()}
    env["JAX_PLATFORMS"] = "cpu"
    t0 = time.perf_counter()
    w = server.spawn(str(script), [], env, log_path=str(log))
    assert w.wait(timeout=60) == 0
    wall = time.perf_counter() - t0
    took = float(log.read_text().split()[-1])
    assert took < 0.3, f"imports not preloaded: {took:.2f}s"
    assert wall < 3.0, f"forked start too slow: {wall:.2f}s"


def test_workers_survive_parallel_spawns(server, tmp_path):
    script = tmp_path / "p.py"
    script.write_text(
        "import os, sys\nsys.exit(int(os.environ['RANK']) % 2)\n"
    )
    ws = [
        server.spawn(str(script), [], {"RANK": str(i),
                                       "PATH": os.environ.get("PATH", "")})
        for i in range(4)
    ]
    codes = [w.wait(timeout=90) for w in ws]
    assert codes == [0, 1, 0, 1]
    assert len({w.pid for w in ws}) == 4


def test_setsid_gives_own_process_group(server, tmp_path):
    script = tmp_path / "g.py"
    script.write_text(
        "import os, time\n"
        "assert os.getpgid(0) == os.getpid()\n"
    )
    w = server.spawn(str(script), [], {"PATH": os.environ.get("PATH", "")})
    assert w.wait(timeout=30) == 0


def test_opt_out_env(monkeypatch):
    monkeypatch.setenv("DLROVER_TPU_FORKSERVER", "0")
    assert not ForkServer.enabled()
    monkeypatch.delenv("DLROVER_TPU_FORKSERVER")
    assert ForkServer.enabled()
