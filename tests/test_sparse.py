"""KvVariable sparse embedding tests (SURVEY §2.6)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.sparse import KvVariable, SparseAdam


class TestKvVariable:
    def test_lookup_allocates_and_is_stable(self):
        var = KvVariable(dim=4, capacity=8, seed=1)
        ids = np.array([1001, 42, 1001])
        rows = np.asarray(var.lookup(ids))
        assert rows.shape == (3, 4)
        np.testing.assert_array_equal(rows[0], rows[2])  # same id, same row
        assert var.size == 2
        # A second lookup returns identical rows.
        np.testing.assert_array_equal(
            np.asarray(var.lookup(np.array([42]))), rows[1:2]
        )

    def test_growth_beyond_capacity(self):
        var = KvVariable(dim=2, capacity=4)
        var.lookup(np.arange(100))
        assert var.size == 100
        assert var.capacity >= 100
        assert var.table.shape[0] == var.capacity

    def test_unknown_id_without_allocate(self):
        var = KvVariable(dim=2, capacity=4)
        var.lookup(np.array([7]))
        before = var.size
        var.lookup(np.array([8, 9]), allocate=False)
        assert var.size == before  # inference never grows the table

    def test_batched_shape(self):
        var = KvVariable(dim=3, capacity=16)
        out = var.lookup(np.arange(6).reshape(2, 3))
        assert out.shape == (2, 3, 3)

    def test_row_grads_accumulate_duplicates(self):
        var = KvVariable(
            dim=2, capacity=4,
            initializer=lambda k, s, d: jnp.zeros(s, d),
        )
        ids = np.array([5, 5])
        grads = np.ones((2, 2))
        var.apply_row_grads(ids, grads, lr=0.1)
        row = np.asarray(var.lookup(np.array([5])))[0]
        np.testing.assert_allclose(row, -0.2 * np.ones(2), atol=1e-6)

    def test_export_import_roundtrip(self):
        var = KvVariable(dim=3, capacity=4, seed=3)
        var.lookup(np.array([10, 20, 30, 40, 50]))  # forces growth too
        ids, values = var.export()
        assert len(ids) == 5

        fresh = KvVariable(dim=3, capacity=2, seed=99)
        fresh.import_(ids, values)
        for i in ids:
            np.testing.assert_allclose(
                np.asarray(fresh.lookup(np.array([i]))),
                np.asarray(var.lookup(np.array([i]))),
                rtol=1e-6,
            )
        assert fresh.size == 5


class TestSparseAdam:
    def test_converges_per_key(self):
        """Each key's row converges to its own target; untouched keys
        never move."""
        var = KvVariable(
            dim=2, capacity=8,
            initializer=lambda k, s, d: jnp.zeros(s, d),
        )
        opt = SparseAdam(var, lr=0.05)
        targets = {7: np.array([1.0, -1.0]), 13: np.array([0.5, 2.0])}
        untouched = np.asarray(var.lookup(np.array([99])))  # allocate 99
        for _ in range(300):
            ids = np.array([7, 13])
            rows = np.asarray(var.lookup(ids))
            grads = 2 * (rows - np.stack([targets[7], targets[13]]))
            opt.update(ids, grads)
        for key, tgt in targets.items():
            got = np.asarray(var.lookup(np.array([key])))[0]
            np.testing.assert_allclose(got, tgt, atol=5e-2)
        np.testing.assert_array_equal(
            np.asarray(var.lookup(np.array([99]))), untouched
        )

    def test_state_grows_with_table(self):
        var = KvVariable(dim=2, capacity=2)
        opt = SparseAdam(var)
        opt.update(np.arange(10), np.ones((10, 2)))
        assert opt._m.shape[0] == var.capacity


class TestGrowMidTraining:
    """VERDICT r3 #10: growth during a jitted train loop must preserve
    optimizer slot values (the recompile-on-new-capacity path)."""

    def test_moments_survive_grow(self):
        var = KvVariable(dim=4, capacity=4, seed=1)
        adam = SparseAdam(var, lr=0.1)

        @jax.jit
        def fwd(table, slots):
            return jnp.take(table, slots, axis=0).sum()

        # Two Adam steps on key 0 BEFORE growth...
        g = np.ones((1, 4), np.float32)
        adam.update([0], g)
        adam.update([0], g)
        m_before = np.asarray(adam._m[var.to_slots([0])[0]]).copy()
        assert m_before.any()

        # ...touch enough new keys to force a capacity doubling, driving
        # the jitted gather through the recompile.
        for key in range(1, 9):
            slots = var.to_slots([key])
            fwd(var.table, jnp.asarray(slots))
            adam.update([key], g)
        assert var.capacity >= 16

        # key 0's moments and per-key step count survived intact.
        slot0 = var.to_slots([0], allocate=False)[0]
        np.testing.assert_allclose(
            np.asarray(adam._m[slot0]), m_before, rtol=1e-6
        )
        assert int(adam._counts[slot0]) == 2
        # a third step continues the same trajectory (bias correction
        # uses t=3, not t=1)
        adam.update([0], g)
        assert int(adam._counts[var.to_slots([0])[0]]) == 3


class TestHostSpillTier:
    """Tiered storage (parity: tfplus storage_table.h hybrid tables):
    cold rows spill to host RAM at max_capacity and restore on touch."""

    def test_capacity_capped_and_keys_preserved(self):
        var = KvVariable(dim=2, capacity=4, max_capacity=8, seed=0)
        written = {}
        for key in range(32):
            var.to_slots([key])
            row = np.full((1, 2), float(key), np.float32)
            var.scatter_update([key], row)
            written[key] = row[0]
        assert var.capacity == 8          # never grew past the cap
        assert var.resident_size == 8
        assert var.spilled_size == 24
        assert var.size == 32
        # every key's trained value is intact, wherever it lives
        for key, expect in written.items():
            np.testing.assert_allclose(
                np.asarray(var.lookup([key]))[0], expect
            )

    def test_lru_eviction_order(self):
        var = KvVariable(dim=2, capacity=2, max_capacity=2, seed=0)
        var.to_slots([1])
        var.to_slots([2])
        var.to_slots([1])          # 1 is now hottest
        var.to_slots([3])          # evicts 2 (coldest), not 1
        assert 1 in var._slots
        assert 3 in var._slots
        assert 2 in var._host_store

    def test_batch_larger_than_cap_raises(self):
        var = KvVariable(dim=2, capacity=2, max_capacity=2, seed=0)
        with pytest.raises(RuntimeError, match="max_capacity"):
            var.to_slots([1, 2, 3])

    def test_moments_survive_spill_and_restore(self):
        """An Adam trajectory split across an evict/restore must equal
        the uninterrupted one."""

        def train(max_capacity):
            var = KvVariable(dim=3, capacity=4, max_capacity=max_capacity,
                             seed=3)
            adam = SparseAdam(var, lr=0.05)
            g = np.ones((1, 3), np.float32) * 0.5
            adam.update([7], g)        # two steps on key 7
            adam.update([7], g)
            if max_capacity is not None:
                # flood with cold keys so 7 spills, moments included
                for key in range(100, 100 + max_capacity):
                    var.to_slots([key])
                assert 7 in var._host_store
            adam.update([7], g)        # third step after restore
            return np.asarray(var.lookup([7], allocate=False))[0]

        np.testing.assert_allclose(
            train(max_capacity=4), train(max_capacity=None), rtol=1e-6
        )

    def test_export_includes_spilled_rows(self):
        var = KvVariable(dim=2, capacity=2, max_capacity=2, seed=0)
        for key in range(6):
            var.scatter_update([key], np.full((1, 2), float(key)))
        ids, values = var.export()
        assert len(ids) == 6
        by_id = {int(k): v for k, v in zip(ids, values)}
        for key in range(6):
            np.testing.assert_allclose(by_id[key], [key, key])
        # round-trip through import_ on a fresh capped variable
        var2 = KvVariable(dim=2, capacity=2, max_capacity=4, seed=1)
        var2.import_(ids, values)
        assert var2.size == 6
        assert var2.capacity <= 4
        for key in range(6):
            np.testing.assert_allclose(
                np.asarray(var2.lookup([key], allocate=False))[0],
                [key, key],
            )


class TestImportSpillRestore:
    def test_import_seeded_restore_resets_stale_moments(self):
        """An import_()-seeded host-tier row (no optimizer payload)
        restoring onto a recycled slot must NOT inherit the evicted
        key's Adam moments (round-4 review finding)."""
        var = KvVariable(dim=2, capacity=2, max_capacity=2, seed=0)
        adam = SparseAdam(var, lr=0.1)
        # Seed 3 rows via import: 2 resident + 1 spilled (no payloads).
        ids = np.array([10, 11, 12], np.int64)
        values = np.array([[1, 1], [2, 2], [3, 3]], np.float32)
        var.import_(ids, values)
        assert var.spilled_size == 1
        # Build nonzero moments on a resident key...
        g = np.ones((1, 2), np.float32)
        adam.update([10], g)
        slot10 = var.to_slots([10], allocate=False)[0]
        assert np.asarray(adam._m[slot10]).any()
        # ...then touch key 12 (spilled, payload-less) and key 11 so the
        # hot key 10 gets evicted and 12 lands on its slot.
        var.to_slots([11])
        slots = var.to_slots([12])
        assert 10 in var._host_store
        # key 12's slot must carry ZERO moments, not key 10's.
        assert not np.asarray(adam._m[slots[0]]).any()
        assert int(adam._counts[slots[0]]) == 0
        # and key 10's moments survived the spill: restoring it brings
        # them back.
        slot10b = var.to_slots([10])[0]
        assert np.asarray(adam._m[slot10b]).any()


class TestGroupOptimizers:
    """Group-lasso sparse optimizers (SURVEY §2.6 group optimizers;
    parity: tfplus group_adam / group_adagrad)."""

    def test_group_lasso_zeroes_cold_rows(self):
        from dlrover_tpu.sparse.group_optimizers import SparseGroupLassoAdam

        var = KvVariable(dim=4, capacity=8, seed=0)
        opt = SparseGroupLassoAdam(var, lr=0.1, l21=5.0)
        # A strong regularizer against small gradients: rows shrink to 0.
        g = np.full((1, 4), 1e-3, np.float32)
        for _ in range(5):
            opt.update([7], g)
        assert 7 in set(opt.zero_rows([7, 8]))
        np.testing.assert_allclose(
            np.asarray(var.lookup([7], allocate=False))[0], 0.0,
            atol=1e-7,
        )

    def test_no_regularizer_matches_sparse_adam(self):
        from dlrover_tpu.sparse.group_optimizers import SparseGroupLassoAdam

        g = np.ones((1, 4), np.float32) * 0.3

        def train(cls, **kw):
            var = KvVariable(
                dim=4, capacity=8, seed=1,
                initializer=lambda k, s, d: jnp.zeros(s, d),
            )
            opt = cls(var, lr=0.05, **kw)
            for _ in range(3):
                opt.update([3], g)
            return np.asarray(var.lookup([3], allocate=False))[0]

        np.testing.assert_allclose(
            train(SparseGroupLassoAdam, l21=0.0),
            train(SparseAdam),
            rtol=1e-6,
        )

    def test_adagrad_converges_and_prox_applies(self):
        from dlrover_tpu.sparse.group_optimizers import SparseGroupAdagrad

        var = KvVariable(dim=2, capacity=4, seed=2)
        opt = SparseGroupAdagrad(var, lr=0.5)
        target = np.array([1.0, -2.0], np.float32)
        for _ in range(200):
            w = np.asarray(var.lookup([5]))[0]
            opt.update([5], (w - target)[None])
        np.testing.assert_allclose(
            np.asarray(var.lookup([5], allocate=False))[0], target,
            atol=0.05,
        )

    def test_adagrad_accumulator_survives_spill(self):
        from dlrover_tpu.sparse.group_optimizers import SparseGroupAdagrad

        def train(max_capacity):
            var = KvVariable(dim=2, capacity=4, max_capacity=max_capacity,
                             seed=3)
            opt = SparseGroupAdagrad(var, lr=0.2)
            g = np.ones((1, 2), np.float32)
            opt.update([9], g)
            opt.update([9], g)
            if max_capacity is not None:
                for key in range(100, 100 + max_capacity):
                    var.to_slots([key])
                assert 9 in var._host_store
            opt.update([9], g)
            return np.asarray(var.lookup([9], allocate=False))[0]

        np.testing.assert_allclose(
            train(4), train(None), rtol=1e-6
        )


class TestVocabChurnScale:
    """Realistic vocab churn (round-3 weak #9: 'no perf number for a
    realistic vocab churn'): tens of thousands of distinct ids stream
    through a capped table with an optimizer attached; the run must
    stay functional (exact spill/restore bookkeeping) and complete in
    bounded time thanks to the O(1)-victim LRU + batched tier moves."""

    # Promoted to slow: ~45s of pure churn volume; the same
    # spill/restore bookkeeping is asserted by the fast capped-table
    # tests above, this one only adds scale.
    @pytest.mark.slow
    def test_churn_through_capped_table(self):
        import time

        rng = np.random.default_rng(0)
        var = KvVariable(dim=8, capacity=1024, max_capacity=4096, seed=0)
        adam = SparseAdam(var, lr=0.01)
        n_steps, batch = 60, 256
        # Sentinel cold id: written once, then left to spill; its row
        # must come back byte-identical (the value-exactness check the
        # churn exists to exercise).
        sentinel = 999_999
        var.scatter_update([sentinel], np.full((1, 8), 7.5, np.float32))
        t0 = time.monotonic()
        seen = {sentinel}
        for step in range(n_steps):
            # zipf-ish skew: a hot head + a long cold tail, like vocab
            head = rng.integers(0, 2048, batch // 2)
            tail = rng.integers(2048, 20_000, batch // 2)
            ids = np.concatenate([head, tail])
            seen.update(int(i) for i in ids)
            g = rng.standard_normal((batch, 8)).astype(np.float32) * 0.01
            adam.update(ids, g)
        elapsed = time.monotonic() - t0
        assert var.capacity == 4096
        assert var.size == len(seen)
        assert var.resident_size <= 4096
        # the untouched sentinel genuinely went to the host tier...
        assert var.spilled_size > 0
        assert sentinel in var._host_store
        # ...and restores byte-exact through the batched tier moves
        np.testing.assert_array_equal(
            np.asarray(var.lookup([sentinel], allocate=False))[0],
            np.full(8, 7.5, np.float32),
        )
        # bounded wall time: generous ceiling (shared CI hosts run hot)
        # that an O(k*N) eviction regression still fails.
        assert elapsed < 300, f"churn took {elapsed:.1f}s"
        ids_, _ = var.export()
        assert len(ids_) == len(seen)


class TestDiskTier:
    """Third storage tier (parity: tfplus storage_table.h hybrid
    DRAM/SSD): device HBM > host RAM > disk, one lookup surface."""

    def test_three_tier_spill_and_restore(self, tmp_path):
        kv = KvVariable(dim=4, capacity=4, max_capacity=4,
                        host_capacity=3, disk_dir=str(tmp_path),
                        seed=1)
        # Touch 12 ids: 4 resident, 3 host, 5 on disk.
        first = {}
        for i in range(12):
            first[i] = np.asarray(kv.lookup([i]))[0].copy()
        assert kv.resident_size == 4
        assert kv.spilled_size == 8
        assert kv.disk_size == 5
        assert kv.size == 12
        # Every id restores bit-exact from whichever tier held it.
        for i in range(12):
            np.testing.assert_array_equal(
                np.asarray(kv.lookup([i]))[0], first[i]
            )

    def test_disk_rows_keep_their_values_through_updates(self, tmp_path):
        kv = KvVariable(dim=2, capacity=2, max_capacity=2,
                        host_capacity=1, disk_dir=str(tmp_path))
        kv.lookup([0, 1])
        kv.scatter_update([0, 1], np.array([[1., 1.], [2., 2.]]))
        kv.lookup([2, 3])   # 0,1 spill; one of them lands on disk
        kv.lookup([4, 5])   # deeper churn
        assert kv.disk_size >= 1
        np.testing.assert_array_equal(
            np.asarray(kv.lookup([0]))[0], [1., 1.]
        )
        np.testing.assert_array_equal(
            np.asarray(kv.lookup([1]))[0], [2., 2.]
        )

    def test_export_includes_disk_tier(self, tmp_path):
        kv = KvVariable(dim=2, capacity=2, max_capacity=2,
                        host_capacity=1, disk_dir=str(tmp_path))
        for i in range(8):
            kv.lookup([i])
        ids, values = kv.export()
        assert sorted(ids.tolist()) == list(range(8))
        kv2 = KvVariable(dim=2, capacity=2)
        kv2.import_(ids, values)
        for i, row in zip(ids, values):
            np.testing.assert_array_equal(
                np.asarray(kv2.lookup([int(i)]))[0], row
            )

    def test_optimizer_slots_survive_disk_trip(self, tmp_path):
        kv = KvVariable(dim=2, capacity=2, max_capacity=2,
                        host_capacity=1, disk_dir=str(tmp_path))
        opt = SparseAdam(kv, lr=0.1)
        ids = np.array([0, 1])
        kv.lookup(ids)
        opt.update(ids, np.ones((2, 2), np.float32))
        m_before = opt.extract_rows(kv.to_slots(ids))["m"].copy()
        # push 0 and 1 through host AND disk tiers
        kv.lookup([2, 3])
        kv.lookup([4, 5])
        assert kv.disk_size >= 1
        kv.lookup(ids)  # restore both
        m_after = opt.extract_rows(kv.to_slots(ids))["m"]
        np.testing.assert_allclose(m_after, m_before)

    def test_host_capacity_requires_disk_dir(self):
        with pytest.raises(ValueError, match="disk_dir"):
            KvVariable(dim=2, capacity=2, host_capacity=1)
