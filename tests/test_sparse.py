"""KvVariable sparse embedding tests (SURVEY §2.6)."""

import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.sparse import KvVariable, SparseAdam


class TestKvVariable:
    def test_lookup_allocates_and_is_stable(self):
        var = KvVariable(dim=4, capacity=8, seed=1)
        ids = np.array([1001, 42, 1001])
        rows = np.asarray(var.lookup(ids))
        assert rows.shape == (3, 4)
        np.testing.assert_array_equal(rows[0], rows[2])  # same id, same row
        assert var.size == 2
        # A second lookup returns identical rows.
        np.testing.assert_array_equal(
            np.asarray(var.lookup(np.array([42]))), rows[1:2]
        )

    def test_growth_beyond_capacity(self):
        var = KvVariable(dim=2, capacity=4)
        var.lookup(np.arange(100))
        assert var.size == 100
        assert var.capacity >= 100
        assert var.table.shape[0] == var.capacity

    def test_unknown_id_without_allocate(self):
        var = KvVariable(dim=2, capacity=4)
        var.lookup(np.array([7]))
        before = var.size
        var.lookup(np.array([8, 9]), allocate=False)
        assert var.size == before  # inference never grows the table

    def test_batched_shape(self):
        var = KvVariable(dim=3, capacity=16)
        out = var.lookup(np.arange(6).reshape(2, 3))
        assert out.shape == (2, 3, 3)

    def test_row_grads_accumulate_duplicates(self):
        var = KvVariable(
            dim=2, capacity=4,
            initializer=lambda k, s, d: jnp.zeros(s, d),
        )
        ids = np.array([5, 5])
        grads = np.ones((2, 2))
        var.apply_row_grads(ids, grads, lr=0.1)
        row = np.asarray(var.lookup(np.array([5])))[0]
        np.testing.assert_allclose(row, -0.2 * np.ones(2), atol=1e-6)

    def test_export_import_roundtrip(self):
        var = KvVariable(dim=3, capacity=4, seed=3)
        var.lookup(np.array([10, 20, 30, 40, 50]))  # forces growth too
        ids, values = var.export()
        assert len(ids) == 5

        fresh = KvVariable(dim=3, capacity=2, seed=99)
        fresh.import_(ids, values)
        for i in ids:
            np.testing.assert_allclose(
                np.asarray(fresh.lookup(np.array([i]))),
                np.asarray(var.lookup(np.array([i]))),
                rtol=1e-6,
            )
        assert fresh.size == 5


class TestSparseAdam:
    def test_converges_per_key(self):
        """Each key's row converges to its own target; untouched keys
        never move."""
        var = KvVariable(
            dim=2, capacity=8,
            initializer=lambda k, s, d: jnp.zeros(s, d),
        )
        opt = SparseAdam(var, lr=0.05)
        targets = {7: np.array([1.0, -1.0]), 13: np.array([0.5, 2.0])}
        untouched = np.asarray(var.lookup(np.array([99])))  # allocate 99
        for _ in range(300):
            ids = np.array([7, 13])
            rows = np.asarray(var.lookup(ids))
            grads = 2 * (rows - np.stack([targets[7], targets[13]]))
            opt.update(ids, grads)
        for key, tgt in targets.items():
            got = np.asarray(var.lookup(np.array([key])))[0]
            np.testing.assert_allclose(got, tgt, atol=5e-2)
        np.testing.assert_array_equal(
            np.asarray(var.lookup(np.array([99]))), untouched
        )

    def test_state_grows_with_table(self):
        var = KvVariable(dim=2, capacity=2)
        opt = SparseAdam(var)
        opt.update(np.arange(10), np.ones((10, 2)))
        assert opt._m.shape[0] == var.capacity
