"""Brain decision layer (ISSUE 19): goodput-driven auto-scaling on the
master.

Covers the :class:`BrainPolicy` signal table (drag/oversize shrink
hysteresis, detarget on a failed marginal test, uptarget while scaling
pays, release of parked capacity), the safety rails (min-world floor,
shared fleet cooldown, wholesale deference to remediation, plan-abort
revert), the servicer's brain join gate, WAL replay reproducing every
decision exactly once across a master crash (through the real
:class:`JobMaster`), chaos denial of the shrink action, the goodput
ledger's ``brain:shrink`` incidents, the exporter gauges — and the
end-to-end fleet drill: a wrong-sized fleet with a chronically
degraded node converges to the searched-best world with the degraded
node parked, and a relaunched master replays to the same decision
state.
"""

import time

import pytest

from dlrover_tpu.brain.policy import BrainPolicy
from dlrover_tpu.chaos.injector import (
    CHAOS_ENV,
    FaultEvent,
    FaultInjector,
    FaultPlan,
)
from dlrover_tpu.common import messages as m
from dlrover_tpu.master.master import JobMaster
from dlrover_tpu.master.rescale import PLAN_ISSUED
from dlrover_tpu.master.state_store import MasterStateStore
from dlrover_tpu.observability import events as events_mod
from dlrover_tpu.observability.events import EventKind, JobEvent
from dlrover_tpu.observability.goodput import GoodputLedger

from tests.test_rescale import TRAIN, formed_world, make_coordinator


@pytest.fixture(autouse=True)
def _clean_chaos_and_events(monkeypatch):
    monkeypatch.delenv(CHAOS_ENV, raising=False)
    FaultInjector.reset()
    events_mod.reset()
    yield
    events_mod.reset()
    FaultInjector.reset()


@pytest.fixture(autouse=True)
def brain_knobs(monkeypatch):
    """Deterministic policy timing: brain on, no cooldown, tight
    hysteresis. Each test overrides what it exercises."""
    monkeypatch.setenv("DLROVER_TPU_BRAIN", "1")
    monkeypatch.setenv("DLROVER_TPU_BRAIN_SUSTAIN_TICKS", "2")
    monkeypatch.setenv("DLROVER_TPU_BRAIN_COOLDOWN_S", "0")
    monkeypatch.setenv("DLROVER_TPU_BRAIN_MIN_WORLD", "2")


class FakeDrag:
    """Settable step-drag table, the shrink signal's input surface."""

    def __init__(self):
        self.drags = {}

    def step_drag(self, n=16):
        return dict(self.drags)


class FakeSpeed:
    def __init__(self):
        self.speed = 0.0

    def running_speed(self):
        return self.speed

    def remove_worker(self, worker_id):
        pass


class FakeRemediation:
    def __init__(self):
        self._acting = False
        self._last = 0.0
        self.noted = []

    def acting(self):
        return self._acting

    def last_action_ts(self):
        return self._last

    def note_fleet_action(self, ts):
        self.noted.append(ts)
        self._last = max(self._last, ts)


def make_policy(n=4, store=None, **coord_kw):
    mgr, _, _ = formed_world(n)
    coord = make_coordinator(mgr, **coord_kw)
    det, sm, rem = FakeDrag(), FakeSpeed(), FakeRemediation()
    policy = BrainPolicy(
        job_name="t",
        rdzv_managers={TRAIN: mgr},
        rescale_coordinator=coord,
        straggler_detector=det,
        speed_monitor=sm,
        remediation=rem,
        state_store=store,
    )
    return policy, det, sm, rem, coord, mgr


def shrink(policy, det, wid=3, drag=0.5, t0=100.0):
    """Drive wid through the drag-shrink hysteresis (sustain=2)."""
    det.drags = {wid: drag}
    policy.tick(now=t0)
    policy.tick(now=t0 + 1)
    assert wid in policy.parked()
    return t0 + 1


class TestShrinkHysteresis:
    def test_sustained_drag_shrinks_after_hysteresis(self):
        policy, det, sm, rem, coord, mgr = make_policy()
        det.drags = {3: 0.5}    # 50% drag > max(12.5%, 1/4) threshold
        policy.tick(now=100.0)
        # one tick: streak armed, world untouched
        assert policy.parked() == {} and len(mgr.current_world()) == 4
        policy.tick(now=101.0)
        # second sustained tick: shrunk in place, parked, plan pending
        world = mgr.current_world()
        assert 3 not in world and len(world) == 3
        rec = policy.parked()[3]
        assert rec["drag"] == 0.5 and "drag" in rec["reason"]
        plan_id = policy.status()["pending"]["plan_id"]
        assert coord.plan_status(plan_id) == PLAN_ISSUED
        # the shared fleet cooldown was armed on remediation's side too
        assert rem.noted == [101.0]

    def test_flapping_drag_clears_the_streak(self):
        policy, det, sm, rem, coord, mgr = make_policy()
        det.drags = {3: 0.5}
        policy.tick(now=100.0)
        det.drags = {}
        policy.tick(now=101.0)
        det.drags = {3: 0.5}
        policy.tick(now=102.0)  # streak restarted: still only 1 tick
        assert policy.parked() == {}
        assert len(mgr.current_world()) == 4

    def test_drag_below_threshold_never_acts(self):
        policy, det, sm, rem, coord, mgr = make_policy()
        det.drags = {3: 0.2}    # below the 1/world = 25% contribution bar
        for i in range(5):
            policy.tick(now=100.0 + i)
        assert policy.parked() == {}
        assert len(mgr.current_world()) == 4

    def test_min_world_floor_holds(self):
        policy, det, sm, rem, coord, mgr = make_policy(n=2)
        det.drags = {1: 0.9}
        for i in range(5):
            policy.tick(now=100.0 + i)
        assert policy.parked() == {}
        assert len(mgr.current_world()) == 2

    def test_oversize_shrink_picks_worst_drag_victim(self):
        policy, det, sm, rem, coord, mgr = make_policy()
        policy.restore({"target": 3})
        det.drags = {2: 0.1}    # below the shrink-drag bar on its own
        policy.tick(now=100.0)
        policy.tick(now=101.0)
        assert 2 in policy.parked()
        assert len(mgr.current_world()) == 3

    def test_oversize_shrink_defaults_to_max_rank(self):
        policy, det, sm, rem, coord, mgr = make_policy()
        policy.restore({"target": 3})
        policy.tick(now=100.0)
        policy.tick(now=101.0)
        assert 3 in policy.parked()


class TestDeference:
    def test_remediation_in_flight_defers_wholesale(self):
        policy, det, sm, rem, coord, mgr = make_policy()
        rem._acting = True
        det.drags = {3: 0.9}
        for i in range(5):
            policy.tick(now=100.0 + i)
        assert policy.parked() == {}
        assert policy.status()["deferrals"]["remediation"] == 5

    def test_shared_cooldown_rate_limits(self, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_BRAIN_COOLDOWN_S", "10")
        policy, det, sm, rem, coord, mgr = make_policy()
        rem._last = 95.0        # remediation moved the world at t=95
        det.drags = {3: 0.9}
        policy.tick(now=100.0)
        policy.tick(now=101.0)
        assert policy.parked() == {}
        assert policy.status()["deferrals"]["cooldown"] == 2
        # cooldown expired: the sustained signal acts
        policy.tick(now=106.0)
        policy.tick(now=107.0)
        assert 3 in policy.parked()

    def test_pending_plan_blocks_second_action(self):
        policy, det, sm, rem, coord, mgr = make_policy()
        shrink(policy, det, wid=3)
        det.drags = {2: 0.9}    # a second victim while plan 1 in flight
        policy.tick(now=102.0)
        policy.tick(now=103.0)
        assert 2 not in policy.parked()
        assert len(mgr.current_world()) == 3
        assert policy.status()["deferrals"]["plan-in-flight"] >= 1

    def test_disabled_brain_is_inert(self, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_BRAIN", "0")
        policy, det, sm, rem, coord, mgr = make_policy()
        det.drags = {3: 0.9}
        for i in range(5):
            policy.tick(now=100.0 + i)
        assert policy.parked() == {}
        assert not policy.gated_join(9, mgr.current_world())


class TestTargetSignals:
    def test_failed_marginal_test_pulls_target_in(self):
        policy, det, sm, rem, coord, mgr = make_policy()
        policy.restore({"target": 4})
        # white-box: settled throughput ledger says the 4th chip bought
        # ~2% of linear — far under the 50% efficiency bar
        policy._world_perf = {
            3: {"samples_per_s": 145.0, "n": 5.0},
            4: {"samples_per_s": 146.0, "n": 5.0},
        }
        policy._last_world = 4
        policy.tick(now=100.0)
        policy.tick(now=101.0)
        assert policy.target_world() == 3
        assert policy.status()["marginal"] < 0.5

    def test_uptarget_probes_while_scaling_pays(self):
        policy, det, sm, rem, coord, mgr = make_policy(n=3)
        policy.restore({"target": 3})
        mgr.join_rendezvous(3, 1)   # spare capacity waiting to join
        policy.tick(now=100.0)
        policy.tick(now=101.0)
        assert policy.target_world() == 4

    def test_release_longest_parked_when_short(self):
        policy, det, sm, rem, coord, mgr = make_policy()
        t = shrink(policy, det, wid=3)
        for r in sorted(mgr.current_world()):
            coord.apply_ack(policy.status()["pending"]["plan_id"], r,
                            ok=True)
        det.drags = {}
        policy.restore({"target": 4})   # fleet now short of target
        policy.tick(now=t + 1)          # settles the plan
        policy.tick(now=t + 2)
        policy.tick(now=t + 3)
        assert policy.parked() == {}    # gate lifted: next join regrows
        assert policy.status()["actions"]["release"] == 1


class TestJoinGate:
    def test_parked_node_is_gated_until_release(self):
        policy, det, sm, rem, coord, mgr = make_policy()
        shrink(policy, det, wid=3)
        world = mgr.current_world()
        assert policy.gated_join(3, world)          # parked: held out
        assert not policy.gated_join(0, world)      # member: never gated
        policy.on_node_evicted(3)                   # eviction landed
        assert not policy.gated_join(3, world)

    def test_overshooting_join_parks_at_target(self):
        policy, det, sm, rem, coord, mgr = make_policy()
        policy.restore({"target": 4})
        world = mgr.current_world()
        assert policy.gated_join(9, world)          # 4 >= target 4
        policy.restore({"target": 6})
        assert not policy.gated_join(9, world)      # below target: grow


class TestPlanAbort:
    def test_nacked_plan_reverts_the_park(self):
        policy, det, sm, rem, coord, mgr = make_policy()
        t = shrink(policy, det, wid=3)
        plan_id = policy.status()["pending"]["plan_id"]
        coord.apply_ack(plan_id, 1, ok=False, error="oom")
        policy.tick(now=t + 1)
        # unparked: the node may reform with the survivors
        assert policy.parked() == {}
        assert policy.status()["pending"]["plan_id"] == -1
        assert policy.status()["actions"]["revert"] == 1
        world = mgr.current_world()
        assert not policy.gated_join(3, world)

    def test_plan_timeout_reverts(self, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_RESCALE_APPLY_TIMEOUT_S", "0.05")
        policy, det, sm, rem, coord, mgr = make_policy()
        t = shrink(policy, det, wid=3)
        time.sleep(0.1)
        coord.tick()                    # deadline sweep aborts the plan
        policy.tick(now=t + 1)
        assert policy.parked() == {}
        assert policy.status()["actions"]["revert"] == 1

    def test_undeliverable_shrink_is_declined_not_applied(self):
        # only ranks 0..1 are rescale-capable: the pre-flight declines
        # and the world must NOT shrink (no half-applied park)
        policy, det, sm, rem, coord, mgr = make_policy(capable=range(2))
        det.drags = {3: 0.9}
        for i in range(4):
            policy.tick(now=100.0 + i)
        assert policy.parked() == {}
        assert len(mgr.current_world()) == 4
        assert policy.status()["actions"]["shrink_declined"] >= 1


class TestChaos:
    def test_chaos_deny_skips_the_shrink_tick(self, monkeypatch):
        monkeypatch.setenv(CHAOS_ENV, FaultPlan(seed=7, events=[
            FaultEvent(site="brain.act", kind="deny", every=1,
                       max_fires=1),
        ]).to_json())
        FaultInjector.reset()
        policy, det, sm, rem, coord, mgr = make_policy()
        det.drags = {3: 0.9}
        policy.tick(now=100.0)
        policy.tick(now=101.0)  # sustained, but chaos denies the act
        assert policy.parked() == {}
        assert len(mgr.current_world()) == 4
        policy.tick(now=102.0)  # chaos exhausted: the action lands
        assert 3 in policy.parked()


class TestWalReplay:
    def _journaled_policy(self, tmp_path, **kw):
        store = MasterStateStore(str(tmp_path))
        store.snapshot(lambda: {})      # open the generation's journal
        policy, det, sm, rem, coord, mgr = make_policy(store=store, **kw)
        return store, policy, det, sm, rem, coord, mgr

    def test_mid_shrink_failover_replays_exactly_once(self, tmp_path):
        store, policy, det, sm, rem, coord, mgr = self._journaled_policy(
            tmp_path
        )
        shrink(policy, det, wid=3)
        plan_id = policy.status()["pending"]["plan_id"]
        store.close()                   # crash: no graceful checkpoint

        # ---- failed-over master: fresh world, fresh coordinator ----
        mgr2, _, _ = formed_world(4)
        calls = []
        policy2, det2, _, _, coord2, _ = make_policy()
        coord2.on_node_removed = lambda *a, **k: calls.append(a)
        store2 = MasterStateStore(str(tmp_path))
        _, records = store2.recover()
        brain = [r for r in records if r[0] == "brain"]
        assert len(brain) == 1          # exactly one shrink decision
        store2.replaying = True
        try:
            for rec in brain:
                policy2.replay(rec[1])
        finally:
            store2.replaying = False
        # the pending shrink is reproduced...
        assert 3 in policy2.parked()
        assert policy2.status()["pending"]["plan_id"] == plan_id
        assert policy2.gated_join(3, mgr2.current_world())
        # ...exactly once: replay is pure bookkeeping, no re-shrink —
        # and the still-flagged drag cannot re-act while the replayed
        # plan is pending
        det2.drags = {3: 0.9}
        policy2.tick(now=500.0)
        assert calls == []
        store2.close()

    def test_tick_is_inert_while_replaying(self, tmp_path):
        store, policy, det, sm, rem, coord, mgr = self._journaled_policy(
            tmp_path
        )
        det.drags = {3: 0.9}
        store.replaying = True
        try:
            for i in range(5):
                policy.tick(now=100.0 + i)
        finally:
            store.replaying = False
        assert policy.parked() == {}
        assert len(mgr.current_world()) == 4
        store.close()

    def test_target_and_release_records_replay(self, tmp_path):
        store, policy, det, sm, rem, coord, mgr = self._journaled_policy(
            tmp_path
        )
        t = shrink(policy, det, wid=3)
        for r in sorted(mgr.current_world()):
            coord.apply_ack(policy.status()["pending"]["plan_id"], r,
                            ok=True)
        det.drags = {}
        policy.restore({"target": 4})
        policy.tick(now=t + 1)
        policy.tick(now=t + 2)
        policy.tick(now=t + 3)          # release record
        assert policy.parked() == {}
        store.close()

        policy2 = BrainPolicy()
        store2 = MasterStateStore(str(tmp_path))
        _, records = store2.recover()
        for rec in records:
            if rec[0] == "brain":
                policy2.replay(rec[1])
        # shrink then release: the parked set nets out empty
        assert policy2.parked() == {}
        assert policy2.status()["actions"]["shrink"] == 1
        store2.close()

    def test_master_crash_roundtrip(self, tmp_path, monkeypatch):
        """Through the real JobMaster: the brain table rides the
        snapshot and the ("brain", ...) journal records ride the
        dispatcher, so a SIGKILLed master's successor holds the same
        target and parked set."""
        master = JobMaster(port=0, node_num=4, state_dir=str(tmp_path))
        for r in range(4):
            master.rdzv_managers[TRAIN].join_rendezvous(r, 1)
        master.rdzv_managers[TRAIN].get_comm_world(0)
        master.rescale.set_batch_config(16, 4)
        for r in range(4):
            master.rescale.set_capable(r)
        det = FakeDrag()
        det.drags = {3: 0.5}
        master.brain._detector = det
        master.brain._retarget(3, "test", now=99.0)  # journaled path
        master.brain.tick(now=100.0)
        master.brain.tick(now=101.0)
        assert 3 in master.brain.parked()
        assert len(master.rdzv_managers[TRAIN].current_world()) == 3
        pre = master.brain.checkpoint()
        # crash: sever the server and the WAL, never the final snapshot
        master._stopped.set()
        master._server.stop()
        events_mod.uninstall_sink(master._event_sink_fn)
        master.state_store.close()

        master2 = JobMaster(port=0, node_num=4, state_dir=str(tmp_path))
        post = master2.brain.checkpoint()
        assert post["target"] == pre["target"] == 3
        assert post["parked"] == pre["parked"]
        assert master2.brain.gated_join(
            3, master2.rdzv_managers[TRAIN].current_world()
        )
        master2._stopped.set()
        master2._server.stop()
        events_mod.uninstall_sink(master2._event_sink_fn)
        master2.state_store.close()


class TestLedger:
    def test_brain_shrink_incident_books_act_and_release(self):
        led = GoodputLedger(now=0.0)
        led.ingest(JobEvent(
            kind=EventKind.BRAIN_SHRINK, ts=110.0, node_id=3,
            role="master", pid=1,
            args={"reason": "drag 50% > 25%", "plan_id": 7,
                  "old_world": [0, 1, 2, 3], "new_world": [0, 1, 2]},
        ))
        led.note_step(5, ts=112.0)
        s = led.summary(now=120.0)
        [inc] = s["incidents"]
        assert inc["cause"] == "brain:shrink"
        assert inc["persistent"] and inc["open"]
        assert "plan 7" in inc["evidence"]
        # degradation accounting, not downtime: survivors kept stepping
        assert s["downtime_s"] == 0.0 and s["goodput"] == 1.0
        led.ingest(JobEvent(
            kind=EventKind.BRAIN_RELEASE, ts=130.0, node_id=3,
            role="master", pid=1, args={"target": 4},
        ))
        [inc] = led.summary(now=140.0)["incidents"]
        assert not inc["open"]
        assert inc["recover_s"] == pytest.approx(20.0)

    def test_revert_closes_and_target_rides_the_trail(self):
        led = GoodputLedger(now=0.0)
        led.ingest(JobEvent(
            kind=EventKind.BRAIN_SHRINK, ts=10.0, node_id=1,
            role="master", pid=1, args={"plan_id": 3},
        ))
        led.ingest(JobEvent(
            kind=EventKind.BRAIN_TARGET, ts=11.0, node_id=1,
            role="master", pid=1, args={"target": 3},
        ))
        led.ingest(JobEvent(
            kind=EventKind.BRAIN_REVERT, ts=12.0, node_id=1,
            role="master", pid=1, args={"plan_id": 3},
        ))
        [inc] = led.incidents()
        assert EventKind.BRAIN_TARGET in inc.trail
        assert not inc.open and inc.recover_ts == 12.0


class TestMetrics:
    def test_gauges_and_action_counters(self):
        policy, det, sm, rem, coord, mgr = make_policy()
        policy.restore({"target": 3})
        shrink(policy, det, wid=3)
        metrics = {name: samples for name, _, _, samples
                   in policy.metrics()}
        assert metrics["dlrover_tpu_brain_target_world"] == [(None, 3.0)]
        assert metrics["dlrover_tpu_brain_parked_nodes"] == [(None, 1.0)]
        assert ({"action": "shrink"}, 1.0) in (
            metrics["dlrover_tpu_brain_actions_total"]
        )
        rem._acting = True
        policy.tick(now=200.0)
        metrics = {name: samples for name, _, _, samples
                   in policy.metrics()}
        assert ({"reason": "remediation"}, 1.0) in (
            metrics["dlrover_tpu_brain_deferrals_total"]
        )


class TestFleetDrill:
    """ISSUE 19 acceptance, end to end through tools.fleet_sim: wrong
    start world converges to the searched-best size, the chronically
    degraded node is autonomously cycled out, every decision journaled
    and WAL-replay-reproducible — and the brain arm beats both the
    static-wrong-world arm and the oracle-start-never-adapts arm."""

    def test_brain_drill_converges_and_replays(self):
        from tools.fleet_sim import run_brain_drill

        out = run_brain_drill(arm="brain", ticks=16)
        assert out["recommendation"] == {
            "world_size": 3, "source": "history-blended", "feasible": True,
        }
        assert out["target"] == 3 and out["world_end"] == 3
        assert out["degraded_parked"] and not out["degraded_in_world"]
        assert out["converged_at_tick"] >= 0
        assert out["actions"]["shrink"] == 1    # one decision, no flaps
        assert out["replay_match"]
        assert out["replay_pending_cleared"]

    def test_brain_arm_beats_static_and_oracle(self):
        from tools.fleet_sim import run_brain_drill

        brain = run_brain_drill(arm="brain", ticks=16)
        static_wrong = run_brain_drill(arm="static_wrong", ticks=16)
        oracle = run_brain_drill(arm="oracle_start", ticks=16)
        assert (
            brain["samples_per_s_avg"]
            > static_wrong["samples_per_s_avg"]
        )
        assert brain["samples_per_s_avg"] > oracle["samples_per_s_avg"]
        # the off arms never act and never park
        assert static_wrong["actions"] == {} and oracle["actions"] == {}
        assert static_wrong["degraded_in_world"]
        assert oracle["degraded_in_world"]
