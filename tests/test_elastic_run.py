"""End-to-end elastic launch tests: standalone run, crash-restart, 2-node world.

Mirrors the reference's agent e2e strategy (SURVEY.md §4.1): a real master,
real agents, real worker processes — all on localhost with CPU JAX.
"""

import os
import subprocess
import sys
import time
import uuid

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "examples", "train_tiny.py")


def _env(extra=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("DLROVER_TPU_MASTER_ADDR", None)
    # Drop any TPU-plugin site dir (its sitecustomize eagerly initializes a
    # PJRT backend, which breaks multi-process CPU jax.distributed).
    paths = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
             if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join([REPO, *paths])
    if extra:
        env.update(extra)
    return env


def _run_cli(cli_args, extra_env=None, timeout=180):
    cmd = [sys.executable, "-m", "dlrover_tpu.cli", *cli_args]
    return subprocess.run(
        cmd, env=_env(extra_env), timeout=timeout,
        capture_output=True, text=True,
    )


@pytest.mark.e2e
class TestElasticRun:
    def test_standalone_run_succeeds(self, tmp_path):
        job = f"e2e-{uuid.uuid4().hex[:6]}"
        result = _run_cli(
            [
                "--standalone", "--nproc_per_node=1", f"--job_name={job}",
                "--monitor_interval=0.2", SCRIPT, "--", "--steps", "5",
            ],
        )
        assert result.returncode == 0, result.stderr[-2000:]

    def test_standalone_with_network_check(self):
        """--network-check runs the device-check round before training."""
        job = f"e2e-{uuid.uuid4().hex[:6]}"
        result = _run_cli(
            [
                "--standalone", "--nproc_per_node=1", f"--job_name={job}",
                "--monitor_interval=0.2", "--network-check",
                SCRIPT, "--", "--steps", "3",
            ],
            extra_env={"DLROVER_TPU_CHECK_MATMUL_SIZE": "128"},
        )
        assert result.returncode == 0, result.stderr[-2000:]

    # Promoted to slow: ~123s of subprocess churn, the single largest
    # tier-1 cost after the two-node drill; the crash→flash-restore
    # chain stays covered in-process (test_checkpoint, test_state_store)
    # and by the shm-restore unit drills.
    @pytest.mark.slow
    def test_crash_restart_resumes_from_flash_checkpoint(self, tmp_path):
        """The core goodput scenario: every-step MEMORY snapshots, DISK
        persist every 10 steps, crash at step 7. The agent flushes the step-7
        memory snapshot to storage; the restarted worker resumes model +
        optimizer state from step 7 — NOT from the last disk persist and not
        from scratch. The trainer itself asserts its step counter reached
        --steps through the restart."""
        job = f"e2e-{uuid.uuid4().hex[:6]}"
        sentinel = str(tmp_path / "crash.sentinel")
        ckpt_dir = str(tmp_path / "ckpts")
        marker = str(tmp_path / "resumed_from.txt")
        result = _run_cli(
            [
                "--standalone", "--nproc_per_node=1", f"--job_name={job}",
                "--monitor_interval=0.2", "--max_restarts=2",
                SCRIPT, "--",
                "--steps", "12", "--crash-at", "7",
                "--crash-sentinel", sentinel,
                "--ckpt-dir", ckpt_dir, "--persist-every", "10",
                "--resume-marker", marker,
            ],
        )
        assert result.returncode == 0, result.stderr[-2000:]
        assert os.path.exists(f"{sentinel}.7"), "crash was never injected"
        assert os.path.exists(marker), "worker never resumed from checkpoint"
        with open(marker) as f:
            resumed = int(f.read())
        assert resumed == 7, f"resumed from {resumed}, expected 7"
        # The step-7 dir on disk proves the crash-FLUSH path specifically:
        # no periodic DISK save could have created it (persist-every=10),
        # and the memory-restore path alone would not touch storage.
        assert os.path.isdir(os.path.join(ckpt_dir, "checkpoint-7")), (
            "agent crash flush never persisted the step-7 memory snapshot"
        )

    def test_crash_restart_with_dataloader(self, tmp_path):
        """Same goodput scenario driven through the elastic data layer:
        the worker consumes master-dispatched shards via ElasticDataLoader;
        the crash leaves a shard in `doing`; the agent's failure report
        recovers it, and the restarted worker trains to completion (a
        blocking fetch would hang here if recovery were broken)."""
        job = f"e2e-{uuid.uuid4().hex[:6]}"
        sentinel = str(tmp_path / "crash.sentinel")
        ckpt_dir = str(tmp_path / "ckpts")
        marker = str(tmp_path / "resumed_from.txt")
        result = _run_cli(
            [
                "--standalone", "--nproc_per_node=1", f"--job_name={job}",
                "--monitor_interval=0.2", "--max_restarts=2",
                SCRIPT, "--",
                "--steps", "12", "--use-dataloader", "--crash-at", "7",
                "--crash-sentinel", sentinel,
                "--ckpt-dir", ckpt_dir, "--persist-every", "10",
                "--resume-marker", marker,
            ],
        )
        assert result.returncode == 0, result.stderr[-2000:]
        assert os.path.exists(f"{sentinel}.7"), "crash was never injected"
        with open(marker) as f:
            assert int(f.read()) == 7

    # Promoted to slow: at ~75s this was the single largest tier-1 cost
    # and the eviction/re-form path stays covered by the faster
    # in-process drills (test_rescale, test_reshape).
    @pytest.mark.slow
    def test_permanent_node_loss_survivor_reforms(self, tmp_path):
        """Kill one of two agents (and its worker) with NO failure report:
        the master's heartbeat monitor evicts the node, invalidates the
        round, and the survivor re-forms a 1-node world from the flash
        checkpoint and finishes the job."""
        import signal
        import subprocess as sp

        job = f"e2e-{uuid.uuid4().hex[:6]}"
        port_file = str(tmp_path / "port")
        ckpt_dir = str(tmp_path / "ckpts")
        marker = str(tmp_path / "resumed.txt")
        env = _env({
            "DLROVER_TPU_HEARTBEAT_TIMEOUT": "2",
            "DLROVER_TPU_NODE_MONITOR_INTERVAL": "0.3",
        })
        master = subprocess.Popen(
            [
                sys.executable, "-m", "dlrover_tpu.master.main",
                "--node_num", "2", "--job_name", job,
                "--port_file", port_file,
            ],
            env=env,
        )
        agents = []
        try:
            deadline = time.monotonic() + 30
            while not os.path.exists(port_file):
                assert time.monotonic() < deadline, "master never started"
                time.sleep(0.05)
            with open(port_file) as f:
                addr = f"127.0.0.1:{f.read().strip()}"

            for rank in range(2):
                agents.append(
                    subprocess.Popen(
                        [
                            sys.executable, "-m", "dlrover_tpu.cli",
                            "--nnodes=1:2", "--nproc_per_node=1",
                            f"--node_rank={rank}", f"--master_addr={addr}",
                            f"--job_name={job}", "--monitor_interval=0.2",
                            "--waiting_timeout=2", "--max_restarts=3",
                            SCRIPT, "--", "--steps", "40",
                            "--step-sleep", "0.25",
                            "--ckpt-dir", ckpt_dir, "--persist-every", "50",
                            "--resume-marker", marker,
                        ],
                        env=_env(), stdout=subprocess.PIPE,
                        stderr=subprocess.STDOUT, text=True,
                    )
                )
            # Wait until BOTH workers are actually training (their flash
            # ckpt shm appears after the first memory save) — a fixed
            # sleep is load-sensitive when the suite saturates the CPU —
            # then hard-kill agent 1 and its worker children (simulated
            # host loss — no report).
            import glob

            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if len(glob.glob(f"/dev/shm/ckpt_{job}_n*_rank0")) >= 2:
                    break
                time.sleep(0.5)
            assert len(glob.glob(f"/dev/shm/ckpt_{job}_n*_rank0")) >= 2, (
                "workers never started saving memory snapshots"
            )
            time.sleep(2)  # a few steps past the first snapshot
            victim = agents[1]
            kids = sp.run(
                ["pgrep", "-P", str(victim.pid)], capture_output=True,
                text=True,
            ).stdout.split()
            victim.kill()
            for pid in kids:
                try:
                    os.kill(int(pid), signal.SIGKILL)
                except (ProcessLookupError, ValueError):
                    pass
            out, _ = agents[0].communicate(timeout=240)
            assert agents[0].returncode == 0, out[-4000:]
            assert "re-forming" in out or "membership changed" in out, (
                out[-4000:]
            )
            assert os.path.exists(marker), (
                "survivor never resumed from the flash checkpoint\n"
                + out[-4000:]
            )
            master.wait(timeout=30)
            assert master.returncode == 0, "master did not exit success"
        finally:
            for a in agents:
                if a.poll() is None:
                    a.kill()
            if master.poll() is None:
                master.terminate()
                master.wait(timeout=10)

    # Promoted to slow: ~122s of subprocess churn, the largest tier-1
    # cost by 7x; two-node rendezvous coverage continues in the slow
    # lane alongside the other multi-process drills in this file.
    @pytest.mark.slow
    def test_two_node_world(self, tmp_path):
        """Two agents rendezvous through one master; workers form a
        2-process JAX world via jax.distributed."""
        job = f"e2e-{uuid.uuid4().hex[:6]}"
        port_file = str(tmp_path / "port")
        master = subprocess.Popen(
            [
                sys.executable, "-m", "dlrover_tpu.master.main",
                "--node_num", "2", "--job_name", job,
                "--port_file", port_file,
            ],
            env=_env(),
        )
        try:
            deadline = time.monotonic() + 30
            while not os.path.exists(port_file):
                assert time.monotonic() < deadline, "master never started"
                time.sleep(0.05)
            with open(port_file) as f:
                addr = f"127.0.0.1:{f.read().strip()}"

            agents = []
            for rank in range(2):
                agents.append(
                    subprocess.Popen(
                        [
                            sys.executable, "-m", "dlrover_tpu.cli",
                            "--nnodes=2", "--nproc_per_node=1",
                            f"--node_rank={rank}", f"--master_addr={addr}",
                            f"--job_name={job}", "--monitor_interval=0.2",
                            SCRIPT, "--", "--steps", "3",
                            "--expect-world", "2",
                        ],
                        env=_env(), stdout=subprocess.PIPE,
                        stderr=subprocess.STDOUT, text=True,
                    )
                )
            for a in agents:
                out, _ = a.communicate(timeout=180)
                assert a.returncode == 0, out[-3000:]
        finally:
            master.terminate()
            master.wait(timeout=10)

    # Promoted to slow: ~130s, the largest tier-1 cost; two-node
    # crash/restore coverage continues in the slow lane and the same
    # failover machinery is exercised in-process by the WAL-replay and
    # rescale drills.
    @pytest.mark.slow
    def test_two_node_flash_checkpoint_crash(self, tmp_path):
        """Multi-node flash checkpoint: both nodes snapshot to their shm
        every step; a crash on node 0 flushes, both agents restart their
        workers, and BOTH resume from the same flushed step (the
        step-consistency vote across nodes picks it). The step-7 dir must
        hold done-files/shards from both nodes under one tracker."""
        job = f"e2e-{uuid.uuid4().hex[:6]}"
        port_file = str(tmp_path / "port")
        ckpt_dir = str(tmp_path / "ckpts")
        sentinel = str(tmp_path / "crash.sentinel")
        markers = [str(tmp_path / f"resumed{r}.txt") for r in range(2)]
        master = subprocess.Popen(
            [
                sys.executable, "-m", "dlrover_tpu.master.main",
                "--node_num", "2", "--job_name", job,
                "--port_file", port_file,
            ],
            env=_env(),
        )
        try:
            deadline = time.monotonic() + 30
            while not os.path.exists(port_file):
                assert time.monotonic() < deadline, "master never started"
                time.sleep(0.05)
            with open(port_file) as f:
                addr = f"127.0.0.1:{f.read().strip()}"

            agents = []
            for rank in range(2):
                crash_args = (
                    ["--crash-at", "7", "--crash-sentinel", sentinel]
                    if rank == 0 else []
                )
                agents.append(
                    subprocess.Popen(
                        [
                            sys.executable, "-m", "dlrover_tpu.cli",
                            "--nnodes=2", "--nproc_per_node=1",
                            f"--node_rank={rank}", f"--master_addr={addr}",
                            f"--job_name={job}", "--monitor_interval=0.2",
                            "--max_restarts=2",
                            SCRIPT, "--", "--steps", "12", "--lockstep",
                            "--step-sleep", "0.1",
                            "--ckpt-dir", ckpt_dir, "--persist-every", "50",
                            "--resume-marker", markers[rank],
                            *crash_args,
                        ],
                        env=_env(), stdout=subprocess.PIPE,
                        stderr=subprocess.STDOUT, text=True,
                    )
                )
            outs = []
            for a in agents:
                out, _ = a.communicate(timeout=240)
                outs.append(out)
                assert a.returncode == 0, out[-4000:]
            assert os.path.exists(f"{sentinel}.7"), "crash was never injected"
            for r in range(2):
                assert os.path.exists(markers[r]), (
                    f"rank {r} never resumed\n" + outs[r][-3000:]
                )
                with open(markers[r]) as f:
                    resumed = int(f.read())
                assert resumed == 7, (
                    f"rank {r} resumed from {resumed}, expected the "
                    "crash-flushed step 7"
                )
            # The committed step-7 dir must hold BOTH nodes' shards and
            # done-files under one tracker (2-node commit).
            step7 = os.path.join(ckpt_dir, "checkpoint-7")
            for f in ("done_0", "done_1", "shard_0.bin", "shard_1.bin"):
                assert os.path.exists(os.path.join(step7, f)), (
                    f"missing {f} in the 2-node commit"
                )
        finally:
            for a in agents:
                if a.poll() is None:
                    a.kill()
            master.terminate()
            master.wait(timeout=10)


class TestMasterFailover:
    # Promoted to slow for tier-1 headroom (~16s of subprocess churn);
    # master-restart recovery itself is exercised in-process by the
    # state-store/WAL replay tests.
    @pytest.mark.slow
    def test_master_killed_and_relaunched_job_completes(self, tmp_path):
        """The master is the one per-job singleton: kill it mid-run and
        relaunch it at the same address (the reference's operator
        relaunching the master pod). Workers ride out the outage via
        the RPC client's retry window — the job must complete and the
        RELAUNCHED master must see the success report and exit 0."""
        job = f"mfail-{uuid.uuid4().hex[:6]}"
        port_file = str(tmp_path / "port")

        def start_master(port=0):
            args = [
                sys.executable, "-m", "dlrover_tpu.master.main",
                "--node_num", "1", "--job_name", job,
            ]
            if port:
                args += ["--port", str(port)]
            else:
                args += ["--port_file", port_file]
            return subprocess.Popen(args, env=_env())

        master = start_master()
        agent = None
        master2 = None
        try:
            deadline = time.monotonic() + 30
            while not os.path.exists(port_file):
                assert time.monotonic() < deadline, "master never started"
                time.sleep(0.05)
            with open(port_file) as f:
                port = int(f.read().strip())
            addr = f"127.0.0.1:{port}"

            agent = subprocess.Popen(
                [
                    sys.executable, "-m", "dlrover_tpu.cli",
                    "--nnodes=1", "--nproc_per_node=1",
                    "--node_rank=0", f"--master_addr={addr}",
                    f"--job_name={job}", "--monitor_interval=0.2",
                    "--max_restarts=2",
                    SCRIPT, "--", "--steps", "40",
                    "--step-sleep", "0.25",
                    "--ckpt-dir", str(tmp_path / "ckpts"),
                    "--persist-every", "50",
                ],
                env=_env(), stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
            )
            # Let the worker actually train (first flash snapshot lands),
            # then kill the master mid-job.
            import glob

            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if glob.glob(f"/dev/shm/ckpt_{job}_n*_rank0"):
                    break
                time.sleep(0.5)
            assert glob.glob(f"/dev/shm/ckpt_{job}_n*_rank0"), (
                "worker never started saving snapshots"
            )
            time.sleep(2)
            master.kill()
            master.wait(timeout=10)
            time.sleep(3)  # a real outage, not an instant flip
            master2 = start_master(port=port)

            out, _ = agent.communicate(timeout=240)
            assert agent.returncode == 0, out[-4000:]
            master2.wait(timeout=30)
            assert master2.returncode == 0, (
                "relaunched master did not exit success"
            )
        finally:
            for p in (agent, master, master2):
                if p is not None and p.poll() is None:
                    p.kill()
                    p.wait(timeout=10)
