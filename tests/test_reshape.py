"""Elastic mesh reshape (ISSUE 16): searched specs + d2d resharding.

Covers the whole reshape plane: the shard-cover algebra's exactness
(exhaustive {data×tp}→{data'×tp'} transitions, brute-force masks as the
oracle), the constrained-world spec search (TP-for-accumulation trade,
stickiness), the RescalePlan spec schema, the master coordinator's spec
selection / journal / failover, the checkpoint engine's targeted region
reader, and the worker engine's hybrid d2d+snapshot hydration with its
torn-mix guard. The full GPT bit-identity drills (SIGKILL a {data×tp}
member, preemption notice on a TP member) are slow-marked.
"""

import dataclasses
import itertools
from dataclasses import asdict
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.accel import ParallelSpec
from dlrover_tpu.accel.search import (
    ModelProfile,
    search_reshape_spec,
    spec_diff,
    spec_from_dict,
    spec_move_distance,
)
from dlrover_tpu.common import messages as m
from dlrover_tpu.common import shard_cover as sc
from dlrover_tpu.common.batching import derive_accum_schedule
from dlrover_tpu.common.constants import RendezvousName
from dlrover_tpu.master.rescale import PLAN_ABORTED, PLAN_ISSUED
from dlrover_tpu.train.checkpoint.engine import CheckpointEngine
from dlrover_tpu.train.rescale import RescaleEngine

from tests.test_rescale import (
    TRAIN,
    formed_world,
    make_coordinator,
)

P = jax.sharding.PartitionSpec


def region_mask(shape, region):
    """Boolean mask of a region — the brute-force oracle."""
    mask = np.zeros(shape, dtype=bool)
    mask[tuple(slice(s, e) for s, e in region)] = True
    return mask


def dt_mesh(data, tensor):
    devs = np.array(jax.devices()[: data * tensor]).reshape(data, tensor)
    return jax.sharding.Mesh(devs, ("data", "tensor"))


# ---------------------------------------------------------------------------
# Region algebra: subtraction/intersection exactness
# ---------------------------------------------------------------------------


class TestRegionAlgebra:
    def test_subtract_exhaustive_1d(self):
        """Every interval pair in a small universe: the pieces are
        disjoint and union to the set difference exactly."""
        ivals = [
            (a, b) for a in range(5) for b in range(a + 1, 6)
        ]
        for region, hole in itertools.product(ivals, ivals):
            pieces = sc.subtract_region((region,), (hole,))
            got = np.zeros(6, dtype=int)
            for p in pieces:
                got[p[0][0]:p[0][1]] += 1
            want = region_mask((6,), (region,)) & ~region_mask((6,), (hole,))
            assert (got <= 1).all(), "overlapping pieces"
            np.testing.assert_array_equal(got.astype(bool), want)

    def test_subtract_2d_slabs(self):
        ivals = [(0, 2), (1, 3), (0, 4), (2, 4), (1, 2)]
        for r0, r1, h0, h1 in itertools.product(ivals, repeat=4):
            region, hole = (r0, r1), (h0, h1)
            pieces = sc.subtract_region(region, hole)
            got = np.zeros((4, 4), dtype=int)
            for p in pieces:
                got[tuple(slice(s, e) for s, e in p)] += 1
            want = (
                region_mask((4, 4), region) & ~region_mask((4, 4), hole)
            )
            assert (got <= 1).all()
            np.testing.assert_array_equal(got.astype(bool), want)

    def test_split_cover_partitions_destination(self):
        """d2d pieces land inside their claimed source, snapshot pieces
        outside every source, and together they tile dst exactly."""
        dst = ((0, 8), (0, 4))
        sources = [((0, 3), (0, 4)), ((2, 5), (1, 3)), ((6, 8), (0, 2))]
        split = sc.split_cover(dst, sources)
        counts = np.zeros((8, 4), dtype=int)
        for region, si in split.d2d:
            counts[tuple(slice(s, e) for s, e in region)] += 1
            assert sc.intersect_regions(region, sources[si]) == region
        for region in split.snapshot:
            counts[tuple(slice(s, e) for s, e in region)] += 1
            for src in sources:
                assert sc.intersect_regions(region, src) is None
        np.testing.assert_array_equal(
            counts, region_mask((8, 4), dst).astype(int)
        )
        assert split.d2d_elems + split.snapshot_elems == sc.region_size(dst)

    def test_empty_and_full_covers(self):
        dst = ((0, 4),)
        none = sc.split_cover(dst, [])
        assert none.d2d == () and none.snapshot == (dst,)
        full = sc.split_cover(dst, [((0, 4),)])
        assert full.snapshot == () and full.d2d == ((dst, 0),)


# ---------------------------------------------------------------------------
# Exhaustive {data×tp} -> {data'×tp'} cover intersections
# ---------------------------------------------------------------------------

_DT = [(1, 1), (2, 1), (1, 2), (2, 2), (4, 1), (1, 4), (4, 2), (2, 4),
       (8, 1), (1, 8)]


class TestCoverIntersectionExhaustive:
    """Every {data×tp}→{data'×tp'} pair over the 8 virtual devices, for
    an activation-style leaf (sharded over both axes) and a param-style
    leaf (tp-sharded, data-replicated). Oracle: brute-force element
    masks; the assembled bytes must be bitwise identical to a full
    snapshot restore (the saved array itself)."""

    def check_split(self, arr_np, old_sharding, new_sharding, lost):
        old = jax.device_put(arr_np, old_sharding)
        splits = sc.leaf_transfer_split(old, new_sharding, lost)
        donors = sc.surviving_shards(old, lost)
        donor_regions = [
            sc.normalize_index(d.index, old.shape) for d in donors
        ]
        survivor_mask = np.zeros(arr_np.shape, dtype=bool)
        for r in donor_regions:
            survivor_mask |= region_mask(arr_np.shape, r)
        total_d2d = total_snap = 0
        for dst, split in splits.items():
            counts = np.zeros(arr_np.shape, dtype=int)
            for region, si in split.d2d:
                counts[tuple(slice(s, e) for s, e in region)] += 1
                # every d2d piece must lie inside its donor
                assert sc.intersect_regions(
                    region, donor_regions[si]
                ) == region
            snap_mask = np.zeros(arr_np.shape, dtype=bool)
            for region in split.snapshot:
                counts[tuple(slice(s, e) for s, e in region)] += 1
                snap_mask |= region_mask(arr_np.shape, region)
            # exact tiling of the destination region
            np.testing.assert_array_equal(
                counts.astype(bool), region_mask(arr_np.shape, dst)
            )
            assert (counts <= 1).all()
            # the snapshot remainder is EXACTLY what no survivor covers
            np.testing.assert_array_equal(
                snap_mask, region_mask(arr_np.shape, dst) & ~survivor_mask
            )
            # bitwise assembly: d2d from donor buffers, snapshot from the
            # saved-array oracle — must reproduce the original exactly
            out = np.full(
                tuple(e - s for s, e in dst), np.nan, dtype=arr_np.dtype
            )
            base = tuple(s for s, _ in dst)
            for region, si in split.d2d:
                dsl = tuple(
                    slice(s - b, e - b) for (s, e), b in zip(region, base)
                )
                dreg = donor_regions[si]
                ssl = tuple(
                    slice(s - ds, e - ds)
                    for (s, e), (ds, _) in zip(region, dreg)
                )
                out[dsl] = np.asarray(donors[si].data)[ssl]
            for region in split.snapshot:
                dsl = tuple(
                    slice(s - b, e - b) for (s, e), b in zip(region, base)
                )
                out[dsl] = arr_np[tuple(slice(s, e) for s, e in region)]
            np.testing.assert_array_equal(
                out, arr_np[tuple(slice(s, e) for s, e in dst)]
            )
            total_d2d += split.d2d_elems
            total_snap += split.snapshot_elems
        return total_d2d, total_snap

    @pytest.mark.parametrize("new_dt", _DT)
    @pytest.mark.parametrize("old_dt", _DT)
    def test_all_transitions(self, old_dt, new_dt):
        (od, ot), (nd, nt) = old_dt, new_dt
        old_mesh, new_mesh = dt_mesh(od, ot), dt_mesh(nd, nt)
        # the highest member dies (one device per member)
        lost = [jax.devices()[od * ot - 1]] if od * ot > 1 else []
        arr = np.arange(16 * 8, dtype=np.float32).reshape(16, 8)
        for spec_old, spec_new in [
            (P("data", "tensor"), P("data", "tensor")),   # activation
            (P(None, "tensor"), P(None, "tensor")),       # param (dp-repl)
        ]:
            d2d, snap = self.check_split(
                arr,
                jax.sharding.NamedSharding(old_mesh, spec_old),
                jax.sharding.NamedSharding(new_mesh, spec_new),
                lost,
            )
            if not lost:
                assert snap == 0
            # replicated-over-data params survive a single death whenever
            # another data row holds the same tp shard
            if spec_old == P(None, "tensor") and od > 1:
                assert snap == 0

    def test_full_loss_goes_to_snapshot(self):
        """Kill EVERY holder of a shard: its whole region must come from
        the snapshot, and nothing else may."""
        mesh = dt_mesh(2, 2)
        shd = jax.sharding.NamedSharding(mesh, P(None, "tensor"))
        arr = np.arange(32, dtype=np.float32).reshape(4, 8)
        # tensor column 1 lives on devices (0,1) and (1,1) = flat 1 and 3
        lost = [jax.devices()[1], jax.devices()[3]]
        d2d, snap = self.check_split(
            arr, shd, jax.sharding.NamedSharding(dt_mesh(1, 2), shd.spec),
            lost,
        )
        assert snap == 16 and d2d == 16


# ---------------------------------------------------------------------------
# Constrained-world spec search
# ---------------------------------------------------------------------------


def compute_bound_profile():
    """A profile whose arithmetic dominates collectives, so the search
    legitimately wants every device it can get."""
    return ModelProfile(
        param_count=4_000_000, num_layers=4, d_model=256, ff_dim=1024,
        seq_len=128, vocab_size=512, num_heads=4,
        flops_per_token=6.0 * 4_000_000,
    )


class TestSearchReshapeSpec:
    def test_trades_tp_for_accumulation_on_shrink(self):
        prof = compute_bound_profile()
        cur = ParallelSpec(data=2, tensor=2)
        found = search_reshape_spec(
            prof, 3, 16, 16e9, current_spec=cur, peak_flops=1e9,
        )
        assert found is not None
        spec, est = found
        assert spec.total <= 3
        # 4 devices do not fit in 3: the transition must give something
        # up relative to {data=2, tensor=2}.
        assert spec != cur
        assert est.step_s > 0

    def test_uses_all_devices_when_they_divide(self):
        prof = compute_bound_profile()
        found = search_reshape_spec(
            prof, 4, 16, 16e9,
            current_spec=ParallelSpec(data=2, tensor=2), peak_flops=1e9,
        )
        assert found is not None and found[0].total == 4

    def test_stickiness_prefers_current_layout(self):
        """Among near-equal candidates the one moving the least state
        wins — with a huge stickiness window, the current spec itself."""
        prof = compute_bound_profile()
        cur = ParallelSpec(data=2, tensor=2)
        found = search_reshape_spec(
            prof, 4, 16, 16e9, current_spec=cur, peak_flops=1e9,
            stickiness=1e9,
        )
        assert found is not None
        assert spec_move_distance(cur, found[0]) == 0.0

    def test_no_devices_returns_none(self):
        assert search_reshape_spec(
            compute_bound_profile(), 0, 16, 16e9
        ) is None

    def test_spec_diff_and_roundtrip(self):
        a = ParallelSpec(data=2, tensor=2)
        b = ParallelSpec(data=4)
        assert spec_diff(a, b) == "data 2->4, tensor 2->1"
        assert spec_diff(a, a) == "unchanged"
        assert spec_diff(asdict(a), asdict(b)) == "data 2->4, tensor 2->1"
        # asdict round-trip, unknown keys dropped (journal forward-compat)
        d = asdict(a)
        d["someday_axis"] = 7
        assert spec_from_dict(d) == a

    def test_move_distance_data_is_free(self):
        a, b = ParallelSpec(data=2), ParallelSpec(data=4)
        assert spec_move_distance(a, b) == 0.0
        assert spec_move_distance(
            ParallelSpec(data=2, fsdp=2), ParallelSpec(data=4, tensor=1)
        ) == 1.0


# ---------------------------------------------------------------------------
# Plan schema
# ---------------------------------------------------------------------------


class TestPlanSpecSchema:
    def test_defaults_do_not_reshape(self):
        plan = m.RescalePlan()
        assert plan.old_spec == {} and plan.new_spec == {}
        assert not plan.reshapes

    def test_reshapes_iff_new_differs(self):
        a, b = asdict(ParallelSpec(data=2)), asdict(ParallelSpec(fsdp=2))
        assert m.RescalePlan(old_spec=a, new_spec=b).reshapes
        assert not m.RescalePlan(old_spec=a, new_spec=dict(a)).reshapes
        # a plan that never searched stays a plain DP retune
        assert not m.RescalePlan(old_spec=a).reshapes

    def test_journal_roundtrip(self):
        plan = m.RescalePlan(
            plan_id=7, old_spec=asdict(ParallelSpec(data=2, tensor=2)),
            new_spec=asdict(ParallelSpec(data=2)),
        )
        back = m.RescalePlan(**dataclasses.asdict(plan))
        assert back.reshapes and back.new_spec == plan.new_spec


# ---------------------------------------------------------------------------
# Master coordinator: spec selection, journal, failover
# ---------------------------------------------------------------------------


def tiny_parallel_config():
    from dlrover_tpu.models.gpt import GPTConfig

    return (
        asdict(ParallelSpec(data=2, tensor=2)),
        asdict(ModelProfile.from_config(GPTConfig.tiny())),
        16e9,
    )


class TestCoordinatorReshape:
    def test_plan_carries_searched_spec(self):
        mgr, round_, world = formed_world(4)
        coord = make_coordinator(mgr)
        spec_d, prof_d, hbm = tiny_parallel_config()
        coord.set_parallel_config(spec_d, prof_d, hbm)
        plan = coord.on_node_removed(3, dict(world))
        assert plan is not None
        assert plan.old_spec == spec_d
        assert plan.new_spec, "coordinator should have searched a spec"
        new_sp = spec_from_dict(plan.new_spec)
        assert new_sp.total <= 3
        assert plan.reshapes

    def test_no_parallel_config_stays_dp_only(self):
        mgr, round_, world = formed_world(4)
        coord = make_coordinator(mgr)
        plan = coord.on_node_removed(3, dict(world))
        assert plan is not None
        assert plan.old_spec == {} and plan.new_spec == {}
        assert not plan.reshapes

    def test_non_integral_member_mapping_stays_dp_only(self):
        """5 devices over 4 members has no per-member device slice:
        nothing principled to search against."""
        mgr, round_, world = formed_world(4)
        coord = make_coordinator(mgr)
        spec_d, prof_d, hbm = tiny_parallel_config()
        spec_d = asdict(ParallelSpec(data=5))
        coord.set_parallel_config(spec_d, prof_d, hbm)
        plan = coord.on_node_removed(3, dict(world))
        assert plan is not None and not plan.reshapes

    def test_reshape_knob_off_stays_dp_only(self, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_RESCALE_RESHAPE", "0")
        mgr, round_, world = formed_world(4)
        coord = make_coordinator(mgr)
        spec_d, prof_d, hbm = tiny_parallel_config()
        coord.set_parallel_config(spec_d, prof_d, hbm)
        plan = coord.on_node_removed(3, dict(world))
        assert plan is not None and not plan.reshapes

    def test_config_replay_restores_search_inputs(self):
        """A failed-over master replays the ("reshape", config) record
        and can search the NEXT transition."""
        mgr, round_, world = formed_world(4)
        coord = make_coordinator(mgr)
        spec_d, prof_d, hbm = tiny_parallel_config()
        coord.replay_reshape({
            "rec": "config", "spec": spec_d, "profile": prof_d,
            "hbm": hbm,
        })
        plan = coord.on_node_removed(3, dict(world))
        assert plan is not None and plan.reshapes

    def test_checkpoint_restore_roundtrip(self):
        mgr, round_, world = formed_world(4)
        coord = make_coordinator(mgr)
        spec_d, prof_d, hbm = tiny_parallel_config()
        coord.set_parallel_config(spec_d, prof_d, hbm)
        snap = coord.checkpoint()
        assert snap["spec"] == spec_d

        mgr2, _, world2 = formed_world(4)
        coord2 = make_coordinator(mgr2)
        coord2.restore(snap)
        plan = coord2.on_node_removed(3, dict(world2))
        assert plan is not None and plan.reshapes

    def test_nack_aborts_and_remembers_diff(self):
        mgr, round_, world = formed_world(4)
        coord = make_coordinator(mgr)
        spec_d, prof_d, hbm = tiny_parallel_config()
        coord.set_parallel_config(spec_d, prof_d, hbm)
        plan = coord.on_node_removed(3, dict(world))
        assert plan.reshapes
        select = dict(coord._last_select)
        assert select["plan_id"] == plan.plan_id
        assert select["diff"] and select["diff"] != "unchanged"
        coord.apply_ack(
            plan.plan_id, 0,
            ok=False, error="plan 1 (round 2, data 2->1): boom",
        )
        got = coord.get_plan(TRAIN, 0, 0)
        assert not got.exists or got.status == PLAN_ABORTED


# ---------------------------------------------------------------------------
# Engine region reader
# ---------------------------------------------------------------------------


class TestMemoryRegionReader:
    def test_reads_exact_regions_across_blocks(self, job_name, tmp_path):
        from dlrover_tpu.common.ckpt_meta import ckpt_shm_name
        from dlrover_tpu.common.shared_memory import SharedMemory

        mesh = dt_mesh(4, 1)
        shd = jax.sharding.NamedSharding(mesh, P("data", None))
        w = np.arange(64, dtype=np.float32).reshape(8, 8)
        state = {"w": jax.device_put(w, shd), "step": np.int64(5)}
        eng = CheckpointEngine(str(tmp_path / "ck"), keep_latest=0)
        try:
            assert eng.save_to_memory(5, state, block=True)
            step, read = eng.memory_region_reader()
            assert step == 5 and read is not None
            # a region crossing two of the four saved blocks
            got = read("['w']", ((1, 5), (2, 7)))
            np.testing.assert_array_equal(got, w[1:5, 2:7])
            with pytest.raises(KeyError):
                read("['nope']", ((0, 1),))
        finally:
            eng.close()
            SharedMemory.remove(ckpt_shm_name(job_name, 0, 0))

    def test_no_snapshot_returns_none(self, job_name, tmp_path):
        eng = CheckpointEngine(str(tmp_path / "ck"), keep_latest=0)
        try:
            step, read = eng.memory_region_reader()
            assert step == -1 and read is None
        finally:
            eng.close()


# ---------------------------------------------------------------------------
# Worker engine: hybrid hydration
# ---------------------------------------------------------------------------


class FakeSpecHost:
    """The minimum `host` contract, with spec-aware retune: rebuilds an
    fsdp mesh + shardings + throwaway state for the requested spec."""

    def __init__(self, shape=(8, 4)):
        self.shape = shape
        self.result = None
        self.retunes = []

    def _build(self, spec):
        mesh = jax.sharding.Mesh(
            np.array(jax.devices()[: spec.total]), ("fsdp",)
        )
        shardings = {
            "w": jax.sharding.NamedSharding(mesh, P("fsdp", None)),
            "step": jax.sharding.NamedSharding(mesh, P()),
        }
        state = {
            "w": jax.device_put(
                np.zeros(self.shape, np.float32), shardings["w"]
            ),
            "step": jax.device_put(np.int64(0), shardings["step"]),
        }
        self.result = SimpleNamespace(
            spec=spec, mesh=mesh, state=state, shardings=shardings,
            batch_sharding=None,
        )

    def retune(self, world_size, rank=None, spec=None):
        self.retunes.append((world_size, rank, spec))
        if spec is not None:
            self._build(spec)


def reshape_plan(old_spec, new_spec, snapshot_step, new_nodes=3):
    sched = derive_accum_schedule(16, 4, new_nodes)
    return m.RescalePlan(
        plan_id=1, rdzv_name=RendezvousName.TRAINING, old_round=1,
        new_round=2, old_world={0: 1, 1: 1, 2: 1, 3: 1},
        new_world={r: 1 for r in range(new_nodes)}, global_batch=16,
        micro_batch=sched.micro_batch, accum_counts=list(sched.counts),
        snapshot_step=snapshot_step, status=PLAN_ISSUED,
        old_spec=asdict(old_spec), new_spec=asdict(new_spec),
    )


@pytest.fixture
def fsdp_world(job_name, tmp_path):
    """A live fsdp=4 state + warm shm snapshot + cleanup."""
    from dlrover_tpu.common.ckpt_meta import ckpt_shm_name
    from dlrover_tpu.common.shared_memory import SharedMemory

    host = FakeSpecHost()
    host._build(ParallelSpec(fsdp=4))
    w = np.arange(32, dtype=np.float32).reshape(8, 4)
    state = {
        "w": jax.device_put(w, host.result.shardings["w"]),
        "step": jax.device_put(np.int64(5), host.result.shardings["step"]),
    }
    host.result.state = state
    eng = CheckpointEngine(str(tmp_path / "ck"), keep_latest=0)
    assert eng.save_to_memory(5, state, block=True)
    try:
        yield host, state, w, eng
    finally:
        eng.close()
        SharedMemory.remove(ckpt_shm_name(job_name, 0, 0))


class TestEngineHybridHydration:
    def test_d2d_plus_snapshot_bitwise(self, fsdp_world):
        """fsdp 4->2, member 3 dead: rows 0..5 flow d2d from survivors,
        rows 6..7 (the dead member's shard) from the snapshot — and the
        split is byte-exact."""
        host, state, w, ck = fsdp_world
        eng = RescaleEngine(host, node_rank=0, checkpointer=ck)
        eng.round = 1
        plan = reshape_plan(
            ParallelSpec(fsdp=4), ParallelSpec(fsdp=2), snapshot_step=5
        )
        tr = eng.apply(plan, state=state)
        assert tr.ok, tr.error
        assert tr.source == "live+snapshot"
        assert tr.spec_diff == "fsdp 4->2"
        assert tr.spec == ParallelSpec(fsdp=2)
        # w is (8, 4) f32: dead member held rows 6..7 = 8 elems = 32B;
        # rows 0..5 (24 elems = 96B) move d2d. step is unsharded.
        assert tr.snapshot_bytes == 32
        assert tr.d2d_bytes == 96
        np.testing.assert_array_equal(np.asarray(tr.state["w"]), w)
        assert int(tr.state["step"]) == 5
        # the rebuilt leaf really is laid out for the new spec
        assert tr.state["w"].sharding.is_equivalent_to(
            host.result.shardings["w"], 2
        )

    def test_all_covered_needs_no_snapshot(self, fsdp_world):
        """fsdp 4->1 with NO dead member (pure spec change, e.g. a grow
        rebalance): pure transfer_state, zero snapshot bytes."""
        host, state, w, ck = fsdp_world
        eng = RescaleEngine(host, node_rank=0, checkpointer=ck)
        eng.round = 1
        plan = reshape_plan(
            ParallelSpec(fsdp=4), ParallelSpec(fsdp=2), snapshot_step=5,
            new_nodes=4,
        )
        plan.new_world = dict(plan.old_world)
        sched = derive_accum_schedule(16, 4, 4)
        plan.micro_batch, plan.accum_counts = (
            sched.micro_batch, list(sched.counts),
        )
        tr = eng.apply(plan, state=state)
        assert tr.ok, tr.error
        assert tr.source == "live" and tr.snapshot_bytes == 0
        np.testing.assert_array_equal(np.asarray(tr.state["w"]), w)

    def test_torn_mix_nacks_with_round_and_diff(self, fsdp_world):
        """Snapshot at step 5, live state at step 6: splicing them would
        tear the state — the nack names the plan round and the attempted
        spec transition."""
        host, state, w, ck = fsdp_world
        state = dict(state)
        state["step"] = jax.device_put(
            np.int64(6), host.result.shardings["step"]
        )
        host.result.state = state
        eng = RescaleEngine(host, node_rank=0, checkpointer=ck)
        eng.round = 1
        plan = reshape_plan(
            ParallelSpec(fsdp=4), ParallelSpec(fsdp=2), snapshot_step=6
        )
        tr = eng.apply(plan, state=state)
        assert not tr.ok
        assert tr.error.startswith("plan 1 (round 2, fsdp 4->2):")
        assert "snapshot step 5" in tr.error and "6" in tr.error

    def test_dead_member_without_snapshot_nacks(self, job_name):
        host = FakeSpecHost()
        host._build(ParallelSpec(fsdp=4))
        w = np.arange(32, dtype=np.float32).reshape(8, 4)
        state = {
            "w": jax.device_put(w, host.result.shardings["w"]),
            "step": jax.device_put(
                np.int64(5), host.result.shardings["step"]
            ),
        }
        eng = RescaleEngine(host, node_rank=0, checkpointer=None)
        eng.round = 1
        plan = reshape_plan(
            ParallelSpec(fsdp=4), ParallelSpec(fsdp=2), snapshot_step=5
        )
        tr = eng.apply(plan, state=state)
        assert not tr.ok
        assert "plan 1 (round 2, fsdp 4->2)" in tr.error
        assert "no flash checkpoint engine" in tr.error

    def test_worker_knob_off_keeps_old_spec(self, fsdp_world, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_RESCALE_RESHAPE", "0")
        host, state, w, ck = fsdp_world
        eng = RescaleEngine(host, node_rank=0, checkpointer=ck)
        eng.round = 1
        plan = reshape_plan(
            ParallelSpec(fsdp=4), ParallelSpec(fsdp=2), snapshot_step=5
        )
        tr = eng.apply(plan, state=state)
        assert tr.ok, tr.error
        # retune ran WITHOUT a spec: the old mesh layout stays
        assert host.retunes[-1][2] is None
        assert host.result.spec == ParallelSpec(fsdp=4)
        np.testing.assert_array_equal(np.asarray(tr.state["w"]), w)


# ---------------------------------------------------------------------------
# Goodput evidence
# ---------------------------------------------------------------------------


class TestReshapeGoodputEvidence:
    def test_complete_folds_bytes_into_incident(self):
        from dlrover_tpu.observability.events import EventKind, JobEvent
        from dlrover_tpu.observability.goodput import GoodputLedger

        led = GoodputLedger(now=0.0)
        led.ingest(JobEvent(
            kind=EventKind.RESCALE_PLAN, ts=1.0, node_id=3,
            role="master", pid=0,
            args={"plan_id": 1, "spec_diff": "tensor 2->1"},
        ))
        led.ingest(JobEvent(
            kind=EventKind.RESCALE_COMPLETE, ts=2.0, node_id=3,
            role="worker", pid=0,
            args={
                "plan_id": 1, "spec_diff": "tensor 2->1",
                "d2d_bytes": 4096, "snapshot_bytes": 512,
            },
        ))
        inc = led.summary(now=3.0)["incidents"][0]
        assert inc["evidence"] == (
            "reshape tensor 2->1: d2d 4096B, snapshot 512B"
        )

    def test_abort_folds_decline_reason(self):
        from dlrover_tpu.observability.events import EventKind, JobEvent
        from dlrover_tpu.observability.goodput import GoodputLedger

        led = GoodputLedger(now=0.0)
        led.ingest(JobEvent(
            kind=EventKind.RESCALE_PLAN, ts=1.0, node_id=3,
            role="master", pid=0, args={"plan_id": 1},
        ))
        led.ingest(JobEvent(
            kind=EventKind.RESCALE_ABORT, ts=2.0, node_id=3,
            role="master", pid=0,
            args={
                "plan_id": 1, "spec_diff": "fsdp 4->2",
                "reason": "snapshot stale",
            },
        ))
        inc = led.summary(now=3.0)["incidents"][0]
        assert inc["evidence"] == "reshape fsdp 4->2 declined: snapshot stale"


# ---------------------------------------------------------------------------
# Slow drills: the issue's acceptance chaos scenarios on a real GPT
# ---------------------------------------------------------------------------


def _gpt_world(world, spec, tmp_path):
    import optax

    from dlrover_tpu.models.gpt import GPT, GPTConfig, loss_fn
    from dlrover_tpu.train.elastic_trainer import ElasticTrainer

    cfg = dataclasses.replace(GPTConfig.tiny(), dtype=jnp.float32)

    def token_loss(module, params, batch):
        return loss_fn(module.apply({"params": params}, batch), batch)

    micro = jax.random.randint(
        jax.random.PRNGKey(2), (4, 16), 0, cfg.vocab_size
    )
    et = ElasticTrainer(global_batch_size=16, micro_batch_size=4,
                        world_size=world, rank=0)
    et.prepare(GPT(cfg), optax.adamw(1e-3), micro, token_loss, spec=spec)
    return et, cfg, micro, token_loss


def _train_steps(et, state, cfg, n, key=3):
    batch = jax.random.randint(
        jax.random.PRNGKey(key),
        (et.local_batch_size, 16), 0, cfg.vocab_size,
    )
    met = None
    for _ in range(n):
        state, met = et.result.train_step(
            state, jax.device_put(batch, et.result.batch_sharding)
        )
    return state, met


@pytest.mark.slow
@pytest.mark.chaos
class TestReshapeDrills:
    def test_sigkill_dt_member_reshapes_bit_identical(
        self, job_name, tmp_path
    ):
        """Acceptance drill 1: a {data=2 x tp=2} member dies; the master
        searches a spec for the 3 survivors, the engine reshapes in
        place, and one step later the loss is BIT-identical to the
        restart path hydrating from the same snapshot."""
        import optax

        from dlrover_tpu.accel.accelerate import transfer_state
        from dlrover_tpu.common.ckpt_meta import ckpt_shm_name
        from dlrover_tpu.common.shared_memory import SharedMemory
        from dlrover_tpu.models.gpt import GPT
        from dlrover_tpu.train.elastic_trainer import ElasticTrainer

        et, cfg, micro, token_loss = _gpt_world(
            4, ParallelSpec(data=2, tensor=2), tmp_path
        )
        state, _ = _train_steps(et, et.result.state, cfg, 2)
        et.result.state = state
        step0 = int(state["step"])
        saved = jax.tree_util.tree_map(
            lambda x: np.asarray(x).copy(), state
        )
        ck = CheckpointEngine(str(tmp_path / "ck"), keep_latest=0)
        try:
            assert ck.save_to_memory(step0, state, block=True)

            # Master side: the trainer's own reported config feeds the
            # search, exactly as _report_batch_config would.
            extras = et._parallel_config_extras()
            assert extras["parallel_spec"] == asdict(et.result.spec)
            mgr, round_, world = formed_world(4)
            coord = make_coordinator(mgr)
            coord.set_parallel_config(
                extras["parallel_spec"], extras["model_profile"],
                extras["hbm"],
            )
            plan = coord.on_node_removed(3, dict(world))  # SIGKILL'd
            assert plan is not None and plan.reshapes
            searched = spec_from_dict(plan.new_spec)
            assert searched.total <= 3

            eng = RescaleEngine(et, node_rank=0, checkpointer=ck)
            eng.round = round_
            tr = eng.apply(plan, state=state)
            assert tr.ok, tr.error
            assert tr.spec == searched and et.result.spec == searched
            # zero lost steps: the live step counter survived the move
            assert int(tr.state["step"]) == step0
            post = jax.tree_util.tree_leaves(tr.state)
            for x, y in zip(jax.tree_util.tree_leaves(saved), post):
                np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

            # Restart-path oracle under the SAME searched spec.
            et_r = ElasticTrainer(global_batch_size=16, micro_batch_size=4,
                                  world_size=3, rank=0)
            et_r.prepare(GPT(cfg), optax.adamw(1e-3), micro, token_loss,
                         spec=searched)
            rstate = transfer_state(saved, et_r.result.shardings)
            s_ip, m_ip = _train_steps(et, tr.state, cfg, 1, key=4)
            s_rs, m_rs = _train_steps(et_r, rstate, cfg, 1, key=4)
            assert float(m_ip["loss"]) == float(m_rs["loss"]), (
                "in-place reshape diverged from the restart path"
            )
            for x, y in zip(
                jax.tree_util.tree_leaves(s_ip),
                jax.tree_util.tree_leaves(s_rs),
            ):
                np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
        finally:
            ck.close()
            SharedMemory.remove(ckpt_shm_name(job_name, 0, 0))

    def test_preempt_notice_on_tp_member_zero_lost_steps(
        self, job_name, tmp_path
    ):
        """Acceptance drill 2: a preemption notice lands on a TP member;
        the proactive shrink plan carries a searched spec, the engine
        reshapes at the step boundary, and no step is lost."""
        from dlrover_tpu.common.ckpt_meta import ckpt_shm_name
        from dlrover_tpu.common.shared_memory import SharedMemory
        from tests.test_preempt import make_preempt, notice_req

        et, cfg, micro, token_loss = _gpt_world(
            4, ParallelSpec(data=2, tensor=2), tmp_path
        )
        state, _ = _train_steps(et, et.result.state, cfg, 2)
        et.result.state = state
        step0 = int(state["step"])
        ck = CheckpointEngine(str(tmp_path / "ck"), keep_latest=0)
        try:
            assert ck.save_to_memory(step0, state, block=True)
            extras = et._parallel_config_extras()
            mgr, round_, world = formed_world(4)
            coord = make_coordinator(mgr)
            coord.set_parallel_config(
                extras["parallel_spec"], extras["model_profile"],
                extras["hbm"],
            )
            pre = make_preempt(mgr, rescale=coord)
            assert pre.on_notice(notice_req(3)).success
            pre.note_step(step0)  # step boundary -> proactive shrink
            plan = coord.get_plan(TRAIN, 0, round_)
            assert plan.exists and plan.reshapes

            eng = RescaleEngine(et, node_rank=0, checkpointer=ck)
            eng.round = round_
            tr = eng.apply(plan, state=state)
            assert tr.ok, tr.error
            assert int(tr.state["step"]) == step0, "lost steps"
            # training continues under the searched spec immediately
            s1, m1 = _train_steps(et, tr.state, cfg, 1, key=5)
            assert int(s1["step"]) == step0 + 1
            assert np.isfinite(float(m1["loss"]))
        finally:
            ck.close()
            SharedMemory.remove(ckpt_shm_name(job_name, 0, 0))
