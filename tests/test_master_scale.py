"""Control-plane scale: coalesced beats, lanes, backpressure, fleet.

Tier-1 coverage of the 10k-agent master stack: the ``AgentBeat``
coalesced RPC end to end (heartbeat + step + probe in one dispatch),
the servicer's bulk/control lane split, event-shed backpressure on
both ends (master ``_report_events`` and the client-side
``EventReporter``), graceful ``RpcServer.stop()`` draining in-flight
handlers, the sharded mutation-lock order under lockdep, and a
~100-agent smoke of the synthetic fleet harness (``tools/fleet_sim``).
The full-scale run is the bench's ``master_scale`` section; a
mid-sized e2e rides here marked ``slow``.
"""

import threading
import time

import pytest

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common import env_utils, messages as m
from dlrover_tpu.common.rpc import RpcServer
from dlrover_tpu.master.master import JobMaster
from dlrover_tpu.master.mutation_locks import SHARDS, MutationLocks
from dlrover_tpu.master.servicer import message_priority
from dlrover_tpu.observability.events import JobEvent


# ---------------------------------------------------------------------------
# AgentBeat end to end
# ---------------------------------------------------------------------------


class TestAgentBeat:
    def test_beat_folds_heartbeat_step_and_probe(self):
        master = JobMaster(port=0, node_num=1, job_name="beat")
        master.prepare()
        client = MasterClient(master.addr, node_id=0)
        try:
            probe = {"h2d_mbps": 900.0, "d2h_mbps": 880.0, "rtt_ms": 1.1}
            client.report_beat(step=17, step_ts=time.time(), probe=probe)
            # Heartbeat registered...
            node = master.job_manager.get_node(0)
            assert node is not None and node.heartbeat_time > 0
            # ...step folded into the speed monitor...
            assert master.speed_monitor.global_step == 17
            # ...and the probe synthesized a ring-only probe.link event
            # for the straggler detector.
            probes = master.observability.event_log.events(
                kinds=("probe.link",)
            )
            assert len(probes) == 1
            assert probes[0].node_id == 0
            assert probes[0].args["h2d_mbps"] == 900.0
        finally:
            master.stop()
            client.close()

    def test_beat_without_step_or_probe_is_heartbeat_only(self):
        master = JobMaster(port=0, node_num=1, job_name="beat2")
        master.prepare()
        client = MasterClient(master.addr, node_id=0)
        try:
            client.report_beat()  # step=-1, empty probe
            node = master.job_manager.get_node(0)
            assert node is not None and node.heartbeat_time > 0
            assert master.speed_monitor.global_step == 0
            assert not master.observability.event_log.events(
                kinds=("probe.link",)
            )
        finally:
            master.stop()
            client.close()

    def test_beat_is_not_journaled(self, tmp_path):
        """Beats are pure soft state: 10k agents beating every second
        must not write the WAL at all."""
        master = JobMaster(port=0, node_num=1, job_name="beat3",
                           state_dir=str(tmp_path / "state"))
        master.prepare()
        client = MasterClient(master.addr, node_id=0)
        try:
            before = master.state_store.wal_status()["appended_records"]
            for s in range(3):
                client.report_beat(step=s, probe={"rtt_ms": 1.0})
            after = master.state_store.wal_status()["appended_records"]
            assert after == before
        finally:
            master.stop()
            client.close()


# ---------------------------------------------------------------------------
# Lane classification
# ---------------------------------------------------------------------------


class TestLanes:
    def test_telemetry_rides_bulk_control_rides_control(self):
        assert message_priority(m.AgentBeat()) == "bulk"
        assert message_priority(m.EventReport()) == "bulk"
        assert message_priority(m.GlobalStep()) == "bulk"
        assert message_priority(m.NodeHeartbeat()) == "bulk"
        # The latency-sensitive control plane stays off the bulk lane.
        assert message_priority(m.JoinRendezvous()) == "control"
        assert message_priority(m.TaskRequest()) == "control"
        assert message_priority(m.KVStoreSet()) == "control"
        assert message_priority(m.RescaleAck()) == "control"


# ---------------------------------------------------------------------------
# Backpressure: master-side shed + reporter-side shed
# ---------------------------------------------------------------------------


class TestBackpressure:
    def test_master_sheds_telemetry_under_bulk_backlog(self):
        master = JobMaster(port=0, node_num=1, job_name="shed")
        try:
            threshold = env_utils.EVENT_SHED_BACKLOG.get()
            master.servicer._bulk_backlog = lambda: threshold + 1
            events = [
                JobEvent(kind="metric.cpu_percent", ts=1.0, node_id=0,
                         role="agent", pid=0, args={"value": 1.0}),
                JobEvent(kind="probe.link", ts=1.0, node_id=0,
                         role="agent", pid=0, args={"rtt_ms": 9.0}),
                JobEvent(kind="worker.fail", ts=1.0, node_id=0,
                         role="agent", pid=0, args={}),
            ]
            master.servicer.handle(m.EventReport(node_id=0, events=events))
            log = master.observability.event_log
            # Lifecycle kept, telemetry shed and counted.
            assert log.events(kinds=("worker.fail",))
            assert not log.events(kinds=("metric.cpu_percent",))
            assert not log.events(kinds=("probe.link",))
            assert master.observability.shed_events == 2
        finally:
            master.stop()

    def test_master_keeps_telemetry_without_backlog(self):
        master = JobMaster(port=0, node_num=1, job_name="noshed")
        try:
            master.servicer._bulk_backlog = lambda: 0
            master.servicer.handle(m.EventReport(node_id=0, events=[
                JobEvent(kind="metric.cpu_percent", ts=1.0, node_id=0,
                         role="agent", pid=0, args={"value": 1.0}),
            ]))
            assert master.observability.event_log.events(
                kinds=("metric.cpu_percent",)
            )
            assert master.observability.shed_events == 0
        finally:
            master.stop()

    def test_reporter_sheds_telemetry_at_watermark(self):
        from dlrover_tpu.observability.reporter import EventReporter

        class _StuckClient:
            def report_events(self, events, timeout=None):
                raise ConnectionRefusedError("master down")

        reporter = EventReporter(
            client=_StuckClient(), flush_interval=999.0, max_buffer=10
        )
        try:
            # Fill to the 75% watermark with lifecycle events.
            for i in range(8):
                reporter.emit(JobEvent(kind="worker.fail", ts=1.0,
                                       node_id=0, role="agent", pid=0,
                                       args={"i": i}))
            shed_before = reporter.shed
            reporter.emit(JobEvent(kind="metric.cpu_percent", ts=1.0,
                                   node_id=0, role="agent", pid=0,
                                   args={}))
            assert reporter.shed == shed_before + 1
            # Lifecycle events still buffer past the watermark.
            reporter.emit(JobEvent(kind="worker.restart", ts=1.0,
                                   node_id=0, role="agent", pid=0,
                                   args={}))
            kinds = [e.kind for e in reporter._buffer]
            assert "metric.cpu_percent" not in kinds
            assert "worker.restart" in kinds
        finally:
            reporter.stop(flush=False)

    def test_reporter_buffers_telemetry_below_watermark(self):
        from dlrover_tpu.observability.reporter import EventReporter

        class _Sink:
            def report_events(self, events, timeout=None):
                return m.Response()

        reporter = EventReporter(
            client=_Sink(), flush_interval=999.0, max_buffer=100
        )
        try:
            reporter.emit(JobEvent(kind="metric.cpu_percent", ts=1.0,
                                   node_id=0, role="agent", pid=0,
                                   args={}))
            assert reporter.shed == 0
            assert reporter.pending() == 1
        finally:
            reporter.stop(flush=False)


# ---------------------------------------------------------------------------
# Graceful server stop: drain in-flight handlers
# ---------------------------------------------------------------------------


class TestServerDrain:
    def test_stop_drains_inflight_handler(self):
        release = threading.Event()

        def slow_handler(request):
            release.wait(5.0)
            return m.Response(reason="drained")

        server = RpcServer(0, slow_handler)
        server.start()
        from dlrover_tpu.common.rpc import RpcClient

        client = RpcClient(f"127.0.0.1:{server.port}",
                           timeout=10.0, retry_deadline=1.0)
        result = {}

        def call():
            result["resp"] = client.call(m.NodeHeartbeat(node_id=0))

        t = threading.Thread(target=call)
        t.start()
        # Let the request reach the handler, then stop concurrently.
        time.sleep(0.2)
        release.set()
        server.stop(drain=5.0)
        t.join(timeout=10.0)
        client.close()
        assert result["resp"].reason == "drained"

    def test_stop_without_drain_path_still_terminates(self):
        server = RpcServer(0, lambda req: m.Response())
        server.start()
        t0 = time.monotonic()
        server.stop(drain=0.5)  # nothing in flight: immediate
        assert time.monotonic() - t0 < 2.0


# ---------------------------------------------------------------------------
# Sharded mutation locks: order discipline under lockdep
# ---------------------------------------------------------------------------


class TestShardedLockOrder:
    @pytest.fixture(autouse=True)
    def clean_graph(self, monkeypatch):
        from dlrover_tpu.common import lockdep

        monkeypatch.delenv(env_utils.LOCKDEP.name, raising=False)
        lockdep.reset()
        yield
        lockdep.reset()

    def test_for_message_routes_to_declared_shards(self):
        locks = MutationLocks()
        assert locks.shards_for(m.KVStoreSet()) == ("kv",)
        assert locks.shards_for(m.NodeFailure()) == (
            "tasks", "nodes", "rdzv"
        )
        # Unknown mutating messages take every shard (safe default).
        assert locks.shards_for(object()) == SHARDS

    def test_sharded_order_is_cycle_free_under_real_traffic(
        self, monkeypatch, tmp_path
    ):
        """Arm lockdep and push journaled mutations + a snapshot (the
        quiesce path takes ALL shards) through a real master: the
        recorded shard/store/commit lock graph must be acyclic and must
        actually contain the sharded locks."""
        from dlrover_tpu.common import lockdep
        from dlrover_tpu.common.lockdep import lock_graph

        monkeypatch.setenv(env_utils.LOCKDEP.name, "1")
        lockdep.reset()
        master = JobMaster(port=0, node_num=1, job_name="lockshard",
                           state_dir=str(tmp_path / "state"))
        master.prepare()
        client = MasterClient(master.addr, node_id=0)
        try:
            client.kv_store_set("k", b"v")
            client.report_dataset_shard_params("ds", 20, 10)
            task = client.get_task("ds")
            client.report_task("ds", task.task_id, True)
            client.report_node_status("running")
            client.report_beat(step=1, probe={"rtt_ms": 1.0})
            master.servicer.handle(m.EventReport(node_id=0, events=[
                JobEvent(kind="drill", ts=1.0, node_id=0, role="agent",
                         pid=0, args={}),
            ]))
            master.state_store.snapshot(master._collect_state)
        finally:
            master.stop()
            client.close()
        graph = lock_graph()
        recorded = set(graph) | {b for bs in graph.values() for b in bs}
        assert any(
            name.startswith("master.mutation.") for name in recorded
        ), f"sharded locks never recorded: {sorted(recorded)}"
        lockdep.assert_acyclic()


# ---------------------------------------------------------------------------
# Fleet harness
# ---------------------------------------------------------------------------


class TestFleetSmoke:
    def test_hundred_agent_smoke(self):
        """Tier-1 smoke: the harness sustains a small fleet against the
        real server with zero RPC errors, and group commit batches
        fsyncs below one per mutation."""
        from tools.fleet_sim import run_fleet

        out = run_fleet(
            agents=100, duration_s=2.0, conns=8, wal_sync="group",
            kv_every=4, events_every=8, task_every=6,
        )
        assert out["agents_sustained"] == 100
        assert out["rpc_errors"] == 0
        assert out["rpcs"] > 200
        assert out["wal_mutations"] > 0
        assert out["fsyncs_per_mutation"] < 1.0
        # Generous CI bound; the real <50ms acceptance gate runs at 10k
        # agents in the bench's master_scale section.
        assert out["rpc_p99_ms"] < 1000.0

    @pytest.mark.slow
    def test_two_thousand_agent_e2e(self):
        """Mid-scale e2e (slow lane): a few thousand agents with the
        full traffic mix; both WAL arms, asserting the group-commit
        fsync cut that the bench measures at 10k."""
        from tools.fleet_sim import run_fleet

        # Same shape as the bench's group arm: a 25 ms accumulation
        # window and a control lane sized for the number of concurrently
        # journaling clients (each wait_durable parks a control worker
        # for ~the window; 4 default workers would serialize the lane).
        group = run_fleet(
            agents=2000, duration_s=8.0, conns=32, wal_sync="group",
            group_window_s=0.025, control_workers=32,
            kv_every=4, events_every=8, task_every=6,
        )
        always = run_fleet(
            agents=500, duration_s=3.0, conns=16, wal_sync="always",
            kv_every=4, events_every=8, task_every=6,
        )
        assert group["agents_sustained"] == 2000
        assert group["rpc_errors"] == 0
        assert always["fsyncs_per_mutation"] == 1.0
        assert group["fsyncs_per_mutation"] <= (
            always["fsyncs_per_mutation"] / 8.0
        )
        assert group["rpc_p99_ms"] < 250.0
