"""Runtime lockdep drills.

Unit level: an ABBA inversion is reported deterministically — with both
acquisition stacks — even though no actual deadlock occurred; disarmed,
``instrumented_lock`` hands back a plain threading primitive.

Integration level: the real master / client / reporter control plane is
run armed, with a worker-kill-shaped chaos drill on top (a dropped RPC
send plus a reported node failure re-dispatching in-flight shards), and
the recorded cross-domain lock-order graph must be cycle-free.
"""

import json
import threading

import pytest

from dlrover_tpu.chaos.injector import FaultEvent, FaultInjector, FaultPlan
from dlrover_tpu.chaos.sites import ChaosSite
from dlrover_tpu.common import env_utils, lockdep
from dlrover_tpu.common.lockdep import (
    LockOrderViolation,
    instrumented_lock,
    lock_graph,
)


@pytest.fixture(autouse=True)
def clean_graph(monkeypatch):
    """Each test starts disarmed with an empty process-global graph."""
    monkeypatch.delenv(env_utils.LOCKDEP.name, raising=False)
    lockdep.reset()
    yield
    lockdep.reset()


def arm(monkeypatch):
    monkeypatch.setenv(env_utils.LOCKDEP.name, "1")


class TestInstrumentedLock:
    def test_disarmed_returns_plain_primitives(self):
        assert type(instrumented_lock("x")) is type(threading.Lock())
        assert type(instrumented_lock("x", rlock=True)) is type(
            threading.RLock()
        )

    def test_armed_records_order_edges(self, monkeypatch):
        arm(monkeypatch)
        a = instrumented_lock("drill.a")
        b = instrumented_lock("drill.b")
        for _ in range(3):
            with a:
                with b:
                    pass
        assert lock_graph() == {"drill.a": ("drill.b",)}
        lockdep.assert_acyclic()

    def test_abba_inversion_raises_with_both_stacks(self, monkeypatch):
        arm(monkeypatch)
        a = instrumented_lock("drill.a")
        b = instrumented_lock("drill.b")
        with a:
            with b:
                pass
        with b:
            with pytest.raises(LockOrderViolation) as excinfo:
                a.acquire()
        err = excinfo.value
        assert err.cycle == ["drill.a", "drill.b"]
        # Both sides of the inversion carry a stack trace: where the
        # conflicting acquisition is happening now, and where the
        # original order was established.
        assert "test_lockdep" in err.this_stack
        assert len(err.prior_stacks) == 1
        edge, stack = err.prior_stacks[0]
        assert edge == "drill.a -> drill.b"
        assert "test_lockdep" in stack

    def test_violation_raises_before_blocking(self, monkeypatch):
        """The check runs BEFORE the inner acquire: the inversion is
        reported even while another thread holds the target lock (the
        interleaving that would otherwise be a real deadlock)."""
        arm(monkeypatch)
        a = instrumented_lock("drill.a")
        b = instrumented_lock("drill.b")
        with a:
            with b:
                pass
        holder_has_a = threading.Event()
        release_holder = threading.Event()

        def holder():
            with a:
                holder_has_a.set()
                release_holder.wait(5.0)

        t = threading.Thread(target=holder, daemon=True)
        t.start()
        assert holder_has_a.wait(5.0)
        with b:
            # A real deadlock shape: we hold b and want a; the holder
            # thread has a. Lockdep raises instead of hanging.
            with pytest.raises(LockOrderViolation):
                a.acquire()
        release_holder.set()
        t.join(5.0)

    def test_cross_thread_held_stacks_are_independent(self, monkeypatch):
        arm(monkeypatch)
        a = instrumented_lock("drill.a")
        b = instrumented_lock("drill.b")
        with a:
            with b:
                pass
        caught = []

        def inverted():
            try:
                with b:
                    with a:
                        pass
            except LockOrderViolation as e:
                caught.append(e)

        t = threading.Thread(target=inverted, daemon=True)
        t.start()
        t.join(5.0)
        assert len(caught) == 1

    def test_rlock_reentry_is_not_a_self_edge(self, monkeypatch):
        arm(monkeypatch)
        r = instrumented_lock("drill.r", rlock=True)
        with r:
            with r:
                pass
        assert lock_graph() == {}
        lockdep.assert_acyclic()

    def test_non_blocking_acquire_contract(self, monkeypatch):
        arm(monkeypatch)
        a = instrumented_lock("drill.a")
        assert a.acquire(blocking=False) is True
        assert a.acquire(blocking=False) is False
        a.release()


class TestExportGraph:
    def test_export_writes_dtlint_mergeable_artifact(
        self, monkeypatch, tmp_path
    ):
        arm(monkeypatch)
        a = instrumented_lock("drill.a")
        b = instrumented_lock("drill.b")
        with a:
            with b:
                pass
        out = tmp_path / "lockdep.json"
        data = lockdep.export_graph(str(out))
        assert data == {
            "version": 1,
            "armed": True,
            "edges": {"drill.a": ["drill.b"]},
        }
        assert json.loads(out.read_text()) == data
        assert not list(tmp_path.glob("*.tmp.*"))

    def test_export_disarmed_is_empty_but_valid(self):
        data = lockdep.export_graph()
        assert data["armed"] is False
        assert data["edges"] == {}


class TestControlPlaneLockGraph:
    def test_master_client_reporter_cycle_free_under_chaos(
        self, monkeypatch, tmp_path
    ):
        """Arm lockdep, run the real control plane through a worker-kill
        drill (dropped RPC send -> client retry; node-failure report ->
        in-flight shard re-dispatch; event reporter flushing into the
        master), and require the recorded lock graph to be acyclic. Any
        inversion raises LockOrderViolation right here, deterministically,
        instead of deadlocking one run in a thousand."""
        arm(monkeypatch)
        lockdep.reset()
        plan = FaultPlan(seed=3, events=[
            FaultEvent(site=ChaosSite.RPC_CLIENT_SEND, kind="drop", at=2),
        ])
        monkeypatch.setenv(env_utils.CHAOS.name, plan.to_json())
        FaultInjector.reset()

        from dlrover_tpu.agent.master_client import MasterClient
        from dlrover_tpu.common.constants import RendezvousName
        from dlrover_tpu.master.master import JobMaster
        from dlrover_tpu.observability.events import JobEvent
        from dlrover_tpu.observability.reporter import EventReporter

        # A state_dir makes the servicer hold the state-store mutation
        # lock across each mutating handler — the deepest real lock
        # nesting in the master; without it the drill records nothing.
        master = JobMaster(port=0, node_num=2, job_name="lockdep-drill",
                           state_dir=str(tmp_path / "state"))
        master.prepare()
        c0 = c1 = reporter = None
        try:
            c0 = MasterClient(master.addr, node_id=0)
            c1 = MasterClient(master.addr, node_id=1)
            reporter = EventReporter(client=c0, flush_interval=0.05)

            c0.kv_store_set("k", b"v")  # rides through the dropped send
            assert c0.kv_store_get("k") == b"v"
            c0.report_rdzv_params(2, 2, 10.0, 1)
            c0.join_rendezvous(RendezvousName.TRAINING, 0, 4)
            c1.join_rendezvous(RendezvousName.TRAINING, 1, 4)
            _, _, world = c0.get_comm_world(RendezvousName.TRAINING)
            assert world == {0: 4, 1: 4}

            c0.report_dataset_shard_params(
                "ds", dataset_size=40, shard_size=10, num_epochs=1
            )
            t1 = c1.get_task("ds")
            assert t1.exists
            c1.report_failure("worker killed", level="node_error")
            drained = 0
            while True:
                t = c0.get_task("ds")
                if not t.exists:
                    break
                c0.report_task("ds", t.task_id, success=True)
                drained += 1
            assert drained >= 4  # the killed worker's shard came back

            for step in range(8):
                c0.report_global_step(step)
                reporter.emit(JobEvent(kind="drill", node_id=0,
                                       role="worker", args={"step": step}))
            reporter.flush(timeout=5.0)
            assert reporter.sent >= 1
        finally:
            if reporter is not None:
                reporter.stop(flush=False)
            if c0 is not None:
                c0.close()
            if c1 is not None:
                c1.close()
            master.stop()
            FaultInjector.reset()

        graph = lock_graph()
        # The drill crossed real lock domains; an empty graph would mean
        # the drill tested nothing.
        assert graph, "no lock-order edges recorded by the drill"
        recorded = set(graph) | {b for bs in graph.values() for b in bs}
        assert any(name.startswith("rdzv.") for name in recorded)
        lockdep.assert_acyclic()
