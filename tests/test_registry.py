"""Sharding-registry tests: arbitrary un-annotated flax models shard
under auto_accelerate (SURVEY §2.5 — the modules-registry analog)."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.accel import ParallelSpec, auto_accelerate
from dlrover_tpu.accel.registry import ShardingRegistry, _default_axes


class PlainMLP(nn.Module):
    """Deliberately metadata-free: no logical axes anywhere."""

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(128, name="dense_in")(x)
        x = nn.relu(x)
        x = nn.Dense(256, name="dense_mid")(x)
        x = nn.relu(x)
        return nn.Dense(1, name="dense_out")(x)


def mse_loss(module, params, batch):
    pred = module.apply({"params": params}, batch)
    target = batch.sum(axis=1, keepdims=True)
    return jnp.mean((pred - target) ** 2)


def make_batch(n=64, d=16):
    rng = np.random.default_rng(0)
    return jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))


def run_training(spec, steps=3, registry=None):
    batch = make_batch()
    res = auto_accelerate(
        PlainMLP(), optax.adam(1e-2), batch, mse_loss, spec=spec,
        registry=registry,
    )
    state = res.state
    b = jax.device_put(batch, res.batch_sharding)
    losses = []
    for _ in range(steps):
        state, m = res.train_step(state, b)
        losses.append(float(m["loss"]))
    res.state = state
    return losses, res


class TestDefaultAxes:
    def test_kernel_largest_dim(self):
        assert _default_axes("layer/kernel", (16, 256)) == (None, "embed")
        assert _default_axes("layer/kernel", (256, 16)) == ("embed", None)
        assert _default_axes("b/bias", (64,)) == (None,)
        assert _default_axes("wte/embedding", (1000, 64)) == (
            "vocab", "embed",
        )


class TestAutoAnnotation:
    @pytest.fixture(scope="class")
    def baseline(self):
        return run_training(ParallelSpec())[0]

    def test_fsdp_shards_plain_model(self, baseline):
        losses, res = run_training(ParallelSpec(fsdp=8))
        np.testing.assert_allclose(losses, baseline, rtol=2e-5, atol=2e-6)
        kernel = res.state["params"]["dense_mid"]["kernel"]  # (128, 256)
        shard = kernel.addressable_shards[0]
        assert shard.data.shape[1] == kernel.shape[1] // 8

    def test_opt_state_inherits_sharding(self):
        _, res = run_training(ParallelSpec(fsdp=8), steps=1)
        mu = res.state["opt"][0].mu["dense_mid"]["kernel"]
        kernel = res.state["params"]["dense_mid"]["kernel"]
        assert mu.sharding == kernel.sharding  # ZeRO for free

    def test_registered_tp_pattern(self):
        reg = ShardingRegistry().register(
            r"dense_mid/kernel", ("embed", "mlp")
        )
        _, res = run_training(
            ParallelSpec(data=4, tensor=2), registry=reg
        )
        kernel = res.state["params"]["dense_mid"]["kernel"]
        shard = kernel.addressable_shards[0]
        assert shard.data.shape[1] == kernel.shape[1] // 2

    def test_rank_mismatch_rejected(self):
        # Axes LONGER than the param rank are user error; SHORTER axes
        # left-pad as unsharded leading dims (nn.scan layer stacks,
        # pipeline banks) — see test_short_axes_left_pad.
        reg = ShardingRegistry().register(
            r"kernel", ("layers", "embed", "mlp")
        )
        with pytest.raises(ValueError, match="rank-mismatch"):
            run_training(ParallelSpec(fsdp=2), registry=reg)

    def test_short_axes_left_pad(self):
        reg = ShardingRegistry().register(r"kernel", ("embed",))
        axes = reg.axes_for("dense/kernel", (3, 4, 8))
        assert axes == (None, None, "embed")

    def test_annotated_models_untouched(self):
        """Models WITH logical axes (the GPT flagship) keep their own
        annotations — the registry only fills a vacuum."""
        import dataclasses

        from dlrover_tpu.models.gpt import GPT, GPTConfig, loss_fn

        cfg = dataclasses.replace(GPTConfig.tiny(), dtype=jnp.float32)
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size
        )

        def token_loss(module, params, b):
            return loss_fn(module.apply({"params": params}, b), b)

        res = auto_accelerate(
            GPT(cfg), optax.adamw(1e-3), tokens, token_loss,
            spec=ParallelSpec(tensor=2),
        )
        # TP sharding comes from the model's own "mlp" axes, which the
        # default registry would never produce.
        kernel = res.state["params"]["blocks"]["up"]["kernel"]
        assert kernel.addressable_shards[0].data.shape[-1] == (
            kernel.shape[-1] // 2
        )
