"""Live rescale plane (ISSUE 8): scale change without the restart tax.

Covers the full path: accumulation-schedule math (the bit-identity
lever), the master-side :class:`RescaleCoordinator` plan lifecycle
(issue / deliver / ack / abort / journal replay), the RPC surface, the
worker-side :class:`RescaleEngine` in-place transition (live d2d
transfer, snapshot hydration, nack fallbacks), the agent's settle
protocol, and — slow-marked — the SIGKILL 4→3→4 drill from the issue's
acceptance criteria.
"""

import dataclasses
import subprocess
import sys
import threading
import time
from dataclasses import asdict
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.agent.agent import (
    ElasticLaunchConfig,
    ElasticTrainingAgent,
    RendezvousOutcome,
    WorkerSpec,
)
from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common import messages as m
from dlrover_tpu.common.batching import derive_accum_schedule
from dlrover_tpu.common.constants import NodeStatus, RendezvousName
from dlrover_tpu.master.master import JobMaster
from dlrover_tpu.master.rendezvous import ElasticTrainingRendezvousManager
from dlrover_tpu.master.rescale import (
    PLAN_ABORTED,
    PLAN_COMPLETE,
    PLAN_ISSUED,
    RescaleCoordinator,
    plan_survivors,
)
from dlrover_tpu.models.gpt import GPT, GPTConfig, loss_fn
from dlrover_tpu.train.elastic_trainer import ElasticTrainer
from dlrover_tpu.train.rescale import RescaleEngine

from tests.conftest import cpu_subprocess_env

TRAIN = RendezvousName.TRAINING


def tiny_cfg():
    return dataclasses.replace(GPTConfig.tiny(), dtype=jnp.float32)


def token_loss(module, params, batch):
    return loss_fn(module.apply({"params": params}, batch), batch)


def assert_leaves_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def formed_world(n=4):
    mgr = ElasticTrainingRendezvousManager(TRAIN)
    mgr.update_rdzv_params(n, n, waiting_timeout=10)
    for r in range(n):
        mgr.join_rendezvous(r, 1)
    round_, _, world = mgr.get_comm_world(0)
    assert len(world) == n
    return mgr, round_, world


def make_coordinator(mgr, global_batch=16, micro_batch=4, step=5,
                     capable=range(6)):
    coord = RescaleCoordinator(rdzv_managers={TRAIN: mgr})
    coord.set_batch_config(global_batch, micro_batch)
    coord.note_step(step)
    # Workers advertise a live RescaleEngine; without it the
    # coordinator declines and the restart path stays in charge.
    for r in capable:
        coord.set_capable(r)
    return coord


def make_plan(plan_id=1, old_world=None, new_world=None, old_round=1,
              new_round=2, global_batch=16, micro_batch=4,
              accum_counts=None, snapshot_step=2):
    old_world = old_world if old_world is not None else {0: 1, 1: 1, 2: 1, 3: 1}
    new_world = new_world if new_world is not None else {0: 1, 1: 1, 2: 1}
    if accum_counts is None:
        sched = derive_accum_schedule(
            global_batch, micro_batch, sum(new_world.values())
        )
        micro_batch, accum_counts = sched.micro_batch, list(sched.counts)
    return m.RescalePlan(
        plan_id=plan_id, rdzv_name=TRAIN, old_round=old_round,
        new_round=new_round, old_world=old_world, new_world=new_world,
        global_batch=global_batch, micro_batch=micro_batch,
        accum_counts=accum_counts, snapshot_step=snapshot_step,
        status=PLAN_ISSUED,
    )


class TestAccumSchedule:
    def test_total_micros_world_independent(self):
        """The bit-identity lever: every world partitions the same fixed
        microbatch sequence."""
        for world in range(1, 9):
            s = derive_accum_schedule(64, 8, world)
            assert s.total_micros == 8
            assert sum(s.counts) * s.micro_batch == 64
            assert len(s.counts) == world

    def test_shrink_regrow_partition_deterministic(self):
        assert derive_accum_schedule(64, 8, 4).counts == [2, 2, 2, 2]
        assert derive_accum_schedule(64, 8, 3).counts == [3, 3, 2]
        # Remainder lands on the lowest ranks, identically every time.
        assert derive_accum_schedule(64, 8, 3).counts == [3, 3, 2]
        assert derive_accum_schedule(16, 4, 3).counts == [2, 1, 1]
        assert derive_accum_schedule(64, 8, 4).counts == [2, 2, 2, 2]

    def test_awkward_config_derives_smaller_micro(self):
        s = derive_accum_schedule(10, 3, 1)
        assert s.micro_batch == 2 and s.counts == [5]

    def test_rejects_only_unsatisfiable(self):
        with pytest.raises(ValueError):
            derive_accum_schedule(2, 1, 3)  # a rank would get 0 samples
        with pytest.raises(ValueError):
            derive_accum_schedule(0, 1, 1)
        with pytest.raises(ValueError):
            derive_accum_schedule(8, 0, 1)


class TestRescaleCoordinator:
    def test_shrink_issues_plan_and_installs_world(self):
        mgr, round_, world = formed_world(4)
        coord = make_coordinator(mgr)
        mgr.remove_alive_node(3)
        plan = coord.on_node_removed(3, dict(world))
        assert plan is not None and plan.exists
        assert plan.status == PLAN_ISSUED
        assert sorted(plan.new_world) == [0, 1, 2]
        assert plan.micro_batch == 4 and plan.accum_counts == [2, 1, 1]
        assert plan.snapshot_step == 5
        assert plan.old_round == plan.new_round - 1
        assert plan_survivors(plan) == [0, 1, 2]
        # The new world is INSTALLED: old round stale, new round live.
        assert mgr.current_world() == plan.new_world
        assert mgr.world_stale(round_)
        assert not mgr.world_stale(plan.new_round)

    def test_quorum_decline(self, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_RESCALE_MIN_QUORUM", "0.9")
        mgr, round_, world = formed_world(4)
        coord = make_coordinator(mgr)
        assert coord.on_node_removed(3, dict(world)) is None

    def test_disabled_decline(self, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_RESCALE", "0")
        mgr, round_, world = formed_world(4)
        coord = make_coordinator(mgr)
        assert coord.on_node_removed(3, dict(world)) is None

    def test_no_batch_config_declines(self):
        mgr, round_, world = formed_world(4)
        coord = RescaleCoordinator(rdzv_managers={TRAIN: mgr})
        assert coord.on_node_removed(3, dict(world)) is None

    def test_survivors_without_engine_decline(self):
        """No plan unless EVERY survivor advertised a live engine —
        else an unappliable plan would hold the fleet for the apply
        timeout (training on the stale world) before the same restart
        the master could have taken immediately."""
        mgr, round_, world = formed_world(4)
        coord = make_coordinator(mgr, capable=())
        assert coord.on_node_removed(3, dict(world)) is None
        # Some-but-not-all survivors capable is still a decline.
        coord.set_capable(0)
        coord.set_capable(1)
        assert coord.on_node_removed(3, dict(world)) is None
        # All three survivors capable: plan issued. The dead node never
        # advertised and does not need to — it is not a survivor.
        coord.set_capable(2)
        plan = coord.on_node_removed(3, dict(world))
        assert plan is not None and sorted(plan.new_world) == [0, 1, 2]

    def test_unsatisfiable_schedule_declines(self):
        mgr, round_, world = formed_world(4)
        coord = make_coordinator(mgr, global_batch=2, micro_batch=1)
        # global_batch=2 cannot feed the 3 survivors -> full restart.
        assert coord.on_node_removed(3, dict(world)) is None

    def test_get_plan_visibility(self):
        mgr, round_, world = formed_world(4)
        coord = make_coordinator(mgr)
        plan = coord.on_node_removed(3, dict(world))
        # A covered survivor running the stale round sees the plan.
        got = coord.get_plan(TRAIN, 0, round_)
        assert got.exists and got.plan_id == plan.plan_id
        # The evicted node is not covered.
        assert not coord.get_plan(TRAIN, 3, round_).exists
        # A node already on the new round has nothing to apply.
        assert not coord.get_plan(TRAIN, 0, plan.new_round).exists

    def test_all_acks_complete_plan(self):
        mgr, round_, world = formed_world(4)
        coord = make_coordinator(mgr)
        plan = coord.on_node_removed(3, dict(world))
        for rank in (0, 1):
            assert coord.apply_ack(plan.plan_id, rank, True)
            assert plan.status == PLAN_ISSUED
        assert coord.apply_ack(plan.plan_id, 2, True)
        assert plan.status == PLAN_COMPLETE
        # Settled: no longer delivered; the new round stays live.
        assert not coord.get_plan(TRAIN, 0, round_).exists
        assert not mgr.world_stale(plan.new_round)

    def test_nack_aborts_and_invalidates_round(self):
        mgr, round_, world = formed_world(4)
        coord = make_coordinator(mgr)
        plan = coord.on_node_removed(3, dict(world))
        assert coord.apply_ack(plan.plan_id, 1, False, error="transfer oom")
        assert plan.status == PLAN_ABORTED
        # The round is invalidated -> survivors fall back to restart.
        assert mgr.world_stale(plan.new_round)

    def test_tick_aborts_on_apply_timeout(self, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_RESCALE_APPLY_TIMEOUT_S", "0")
        mgr, round_, world = formed_world(4)
        coord = make_coordinator(mgr)
        plan = coord.on_node_removed(3, dict(world))
        time.sleep(0.01)
        coord.tick()
        assert plan.status == PLAN_ABORTED
        assert mgr.world_stale(plan.new_round)

    def test_second_shrink_supersedes_in_flight_plan(self):
        """A membership change inside the apply window obsoletes the
        in-flight plan. It must abort as *superseded* — without fencing
        the newer plan's live round, which would force-restart a world
        that can still (or already did) transition in place."""
        mgr, round_, world = formed_world(4)
        coord = make_coordinator(mgr)
        plan1 = coord.on_node_removed(3, dict(world))
        assert plan1 is not None
        # A second death before plan1 collected all acks.
        mgr.remove_alive_node(2)
        plan2 = coord.on_node_removed(2, dict(plan1.new_world))
        assert plan2 is not None and sorted(plan2.new_world) == [0, 1]
        assert plan1.status == PLAN_ABORTED  # superseded at issue time
        assert not mgr.world_stale(plan2.new_round)
        # Survivors polling from any stale round see only the new plan.
        got = coord.get_plan(TRAIN, 0, round_)
        assert got.plan_id == plan2.plan_id
        # plan1 can never time out into an invalidation anymore.
        coord.tick()
        assert not mgr.world_stale(plan2.new_round)
        # A real failure of the LIVE plan still fences its round.
        coord.apply_ack(plan2.plan_id, 0, False, error="boom")
        assert mgr.world_stale(plan2.new_round)

    def test_obsolete_plan_timeout_keeps_live_round(self, monkeypatch):
        """An ISSUED plan targeting an older round (e.g. restored
        across a master relaunch after the world moved on) may abort on
        timeout, but must not invalidate the manager's current round."""
        monkeypatch.setenv("DLROVER_TPU_RESCALE_APPLY_TIMEOUT_S", "0")
        mgr, round_, world = formed_world(4)
        coord = make_coordinator(mgr)
        obsolete = make_plan(
            plan_id=5, old_round=round_ - 2, new_round=round_ - 1
        )
        coord.replay({"rec": "plan", "plan": asdict(obsolete)})
        time.sleep(0.01)
        coord.tick()
        assert coord.checkpoint()["plans"][0]["status"] == PLAN_ABORTED
        # The live round was untouched by the stale plan's abort.
        assert not mgr.world_stale(round_)

    def test_grow_on_join_one_transition_at_a_time(self):
        mgr, round_, world = formed_world(3)
        coord = make_coordinator(mgr)
        plan = coord.on_node_joined(3, 1, TRAIN)
        assert plan is not None and sorted(plan.new_world) == [0, 1, 2, 3]
        assert plan.accum_counts == [1, 1, 1, 1]
        # An existing member joining again is not a grow.
        assert coord.on_node_joined(0, 1, TRAIN) is None
        # One in-flight transition at a time.
        assert coord.on_node_joined(4, 1, TRAIN) is None
        for rank in plan_survivors(plan):
            coord.apply_ack(plan.plan_id, rank, True)
        assert plan.status == PLAN_COMPLETE
        assert coord.on_node_joined(4, 1, TRAIN) is not None

    def test_checkpoint_restore_roundtrip(self):
        mgr, round_, world = formed_world(4)
        coord = make_coordinator(mgr)
        plan = coord.on_node_removed(3, dict(world))
        coord.apply_ack(plan.plan_id, 0, True)
        snap = coord.checkpoint()

        coord2 = RescaleCoordinator(rdzv_managers={TRAIN: mgr})
        coord2.restore(snap)
        got = coord2.get_plan(TRAIN, 1, round_)
        assert got.exists and got.plan_id == plan.plan_id
        # The ack set survived: the two remaining acks complete it.
        coord2.apply_ack(plan.plan_id, 1, True)
        assert coord2.apply_ack(plan.plan_id, 2, True)
        assert coord2.get_plan(TRAIN, 1, round_).exists is False
        assert coord2.checkpoint()["next_plan_id"] == snap["next_plan_id"]
        # Capability advertisements survive the relaunch too.
        assert coord2.checkpoint()["capable"] == snap["capable"]

    def test_journal_replay_rebuilds_plans(self):
        mgr, round_, world = formed_world(4)
        plan = make_plan(plan_id=7, old_round=round_, new_round=round_ + 1)
        coord = RescaleCoordinator(rdzv_managers={TRAIN: mgr})
        coord.replay({"rec": "config", "global_batch": 16, "micro_batch": 4})
        coord.replay({"rec": "plan", "plan": asdict(plan)})
        got = coord.get_plan(TRAIN, 0, round_)
        assert got.exists and got.plan_id == 7
        # Replayed ids advance the counter past the journaled plan.
        assert coord.checkpoint()["next_plan_id"] == 8
        coord.replay({"rec": "abort", "plan_id": 7})
        assert not coord.get_plan(TRAIN, 0, round_).exists
        # Capability advertisements replay into the capable set.
        coord.replay({"rec": "capable", "node": 2})
        assert coord.checkpoint()["capable"] == [2]
        # Unknown records are skipped, not fatal.
        coord.replay({"rec": "???"})


class TestRescaleRpc:
    """The plan lifecycle through the real servicer + MasterClient."""

    @pytest.fixture
    def master(self):
        master = JobMaster(port=0, node_num=4, job_name="rescale-rpc")
        master.prepare()
        yield master
        master.stop()

    def test_plan_issue_deliver_ack_over_rpc(self, master):
        clients = [MasterClient(master.addr, node_id=r) for r in range(4)]
        try:
            for r, c in enumerate(clients):
                c.join_rendezvous(TRAIN, r, 1)
            round_, _, world = clients[0].get_comm_world(TRAIN, 0)
            assert len(world) == 4
            # The batch contract arrives the way ElasticTrainer.prepare
            # reports it; the step the way the trainer reports progress.
            clients[0].report_model_info(
                0, 0.0, batch_size=16,
                extra={"global_batch": 16, "micro_batch": 4},
            )
            # Each survivor's engine advertises that it can apply plans.
            for r in (0, 1, 2):
                clients[r].report_model_info(
                    0, 0.0, extra={"rescale_capable": True}
                )
            clients[0].report_global_step(7, time.time())
            plan = master.rescale.on_node_removed(3, dict(world))
            assert plan is not None and plan.snapshot_step == 7
            got = clients[0].get_rescale_plan(TRAIN, 0, round_)
            assert got.exists and got.accum_counts == [2, 1, 1]
            assert got.new_world == {0: 1, 1: 1, 2: 1}
            for r in (0, 1, 2):
                clients[r].report_rescale_ack(got.plan_id, r, True)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and plan.status != PLAN_COMPLETE:
                time.sleep(0.05)
            assert plan.status == PLAN_COMPLETE
            assert not clients[0].world_stale(TRAIN, plan.new_round)
        finally:
            for c in clients:
                c.close()

    def test_nack_over_rpc_aborts(self, master):
        clients = [MasterClient(master.addr, node_id=r) for r in range(4)]
        try:
            for r, c in enumerate(clients):
                c.join_rendezvous(TRAIN, r, 1)
            round_, _, world = clients[0].get_comm_world(TRAIN, 0)
            clients[0].report_model_info(
                0, 0.0, batch_size=16,
                extra={"global_batch": 16, "micro_batch": 4},
            )
            for r in (0, 1, 2):
                clients[r].report_model_info(
                    0, 0.0, extra={"rescale_capable": True}
                )
            plan = master.rescale.on_node_removed(3, dict(world))
            assert plan is not None
            clients[1].report_rescale_ack(
                plan.plan_id, 1, False, error="shm gone"
            )
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and plan.status != PLAN_ABORTED:
                time.sleep(0.05)
            assert plan.status == PLAN_ABORTED
            # Abort fences the new round: survivors fall back to restart.
            assert clients[0].world_stale(TRAIN, plan.new_round)
        finally:
            for c in clients:
                c.close()


# ---------------- worker-side engine ----------------


def replicated_shardings(state):
    dev = jax.devices()[0]
    sharding = jax.sharding.SingleDeviceSharding(dev)
    return jax.tree_util.tree_map(lambda _: sharding, state)


class FakeHost:
    """Minimal host contract: .retune(world, rank) + .result (+ .schedule)."""

    def __init__(self, state=None, counts=None):
        self.schedule = (
            SimpleNamespace(counts=list(counts)) if counts else None
        )
        self.result = SimpleNamespace(
            state=state,
            shardings=replicated_shardings(state) if state is not None else None,
            batch_sharding=None,
        )
        self.retuned = None

    def retune(self, world_size, rank=None):
        self.retuned = (world_size, rank)
        return self.schedule


class FakeClient:
    def __init__(self, plan=None):
        self.plan = plan
        self.acks = []
        self.polls = 0

    def get_rescale_plan(self, rdzv_name, node_rank, round_):
        self.polls += 1
        return self.plan if self.plan is not None else m.RescalePlan()

    def report_rescale_ack(self, plan_id, node_rank, ok, error=""):
        self.acks.append((plan_id, node_rank, ok, error))


class StubCheckpointer:
    def __init__(self, step, state, source="memory"):
        self.step, self.state = step, state
        self.last_restore_stats = {"source": source}

    def load(self, template):
        self.template = template
        return self.step, self.state


class TestRescaleEngine:
    def _state(self):
        return {"w": np.arange(6, dtype=np.float32), "step": np.int32(2)}

    def test_drift_nacks(self):
        host = FakeHost(state=self._state(), counts=(1, 1, 1))
        client = FakeClient()
        eng = RescaleEngine(host, client=client, node_rank=0)
        plan = make_plan(accum_counts=[2, 1, 1], micro_batch=4)
        tr = eng.apply(plan, state=self._state())
        assert not tr.ok and "drift" in tr.error
        assert client.acks == [(plan.plan_id, 0, False, tr.error)]
        assert eng.round == 0 and eng.applied_plans == 0

    def test_node_outside_new_world_nacks(self):
        host = FakeHost(state=self._state())
        client = FakeClient()
        eng = RescaleEngine(host, client=client, node_rank=9)
        tr = eng.apply(make_plan())
        assert not tr.ok and "not in the new world" in tr.error
        assert client.acks[-1][2] is False

    def test_live_transfer_preserves_bits_and_acks(self):
        state = self._state()
        host = FakeHost(state=state, counts=(2, 1, 1))
        client = FakeClient()
        eng = RescaleEngine(host, client=client, node_rank=0)
        plan = make_plan()
        tr = eng.apply(plan)  # no explicit state: falls back to live result
        assert tr.ok and tr.source == "live"
        assert host.retuned == (3, 0)
        assert_leaves_equal(tr.state, state)
        assert eng.round == plan.new_round and eng.applied_plans == 1
        assert client.acks == [(plan.plan_id, 0, True, "")]
        assert tr.world_size == 3 and tuple(tr.accum_counts) == (2, 1, 1)

    def test_rank_offset_from_node_local_sizes(self):
        host = FakeHost(state=self._state())
        eng = RescaleEngine(host, node_rank=2)
        plan = make_plan(
            old_world={0: 2, 1: 2, 2: 2, 3: 2},
            new_world={0: 2, 2: 2, 3: 2},
            global_batch=24, micro_batch=4,
        )
        tr = eng.apply(plan)
        assert tr.ok
        # Node 2 sits after node 0's two procs under the new world.
        assert host.retuned == (6, 2)

    def test_hydrate_from_snapshot(self):
        host = FakeHost(state=None)
        ck = StubCheckpointer(2, self._state(), source="memory")
        eng = RescaleEngine(host, node_rank=0, checkpointer=ck)
        tr = eng.apply(make_plan(snapshot_step=2))
        assert tr.ok and tr.source == "memory"
        assert_leaves_equal(tr.state, self._state())
        assert host.result.state is tr.state

    def test_hydrate_lag_gate_nacks(self):
        host = FakeHost(state=None)
        ck = StubCheckpointer(2, self._state())
        eng = RescaleEngine(host, node_rank=0, checkpointer=ck)
        tr = eng.apply(make_plan(snapshot_step=10))
        assert not tr.ok and "behind" in tr.error

    def test_no_state_no_checkpointer_nacks(self):
        host = FakeHost(state=None)
        eng = RescaleEngine(host, node_rank=0)
        tr = eng.apply(make_plan())
        assert not tr.ok and "no checkpointer" in tr.error

    def test_requeue_and_prefetch_swap(self):
        host = FakeHost(state=self._state())
        shards = SimpleNamespace(requeue_pending=lambda: 3)
        batches = [object()]
        swaps = []
        prefetch = SimpleNamespace(
            swap=lambda b, s=None: swaps.append((b, s)) or 0
        )
        eng = RescaleEngine(
            host, node_rank=0, sharding_client=shards,
            data_factory=lambda h: batches,
        )
        tr = eng.apply(make_plan(), prefetch=prefetch)
        assert tr.ok and tr.requeued_shards == 3
        assert tr.batches is batches
        assert swaps == [(batches, host.result.batch_sharding)]

    def test_stream_without_factory_nacks(self, monkeypatch):
        """A live loop's input stream is sized for the old schedule; if
        the local batch size changes and there is no data_factory to
        rebuild it, the plan must nack up front — not ack a transition
        the very next step would crash on."""
        monkeypatch.setenv("DLROVER_TPU_RESCALE_POLL_INTERVAL_S", "0")
        host = FakeHost(state=self._state(), counts=(2, 1, 1))
        host.local_batch_size = 4  # old world-4 schedule: one micro of 4
        client = FakeClient(plan=make_plan())  # world 3: rank 0 runs 8
        eng = RescaleEngine(host, client=client, node_rank=0)
        tr = eng.maybe_rescale()
        assert tr is not None and not tr.ok
        assert "data_factory" in tr.error
        assert client.acks[-1][2] is False
        assert host.retuned is None  # nacked before mutating the host

    def test_stream_with_factory_applies(self, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_RESCALE_POLL_INTERVAL_S", "0")
        host = FakeHost(state=self._state(), counts=(2, 1, 1))
        host.local_batch_size = 4
        client = FakeClient(plan=make_plan())
        batches = [object()]
        eng = RescaleEngine(host, client=client, node_rank=0,
                            data_factory=lambda h: batches)
        tr = eng.maybe_rescale()
        assert tr is not None and tr.ok
        assert tr.batches is batches

    def test_manual_apply_without_stream_still_allowed(self):
        """Callers that drive apply() directly (bench, the drill) feed
        batches themselves; a batch-size change without a data_factory
        is their business, not a nack."""
        host = FakeHost(state=self._state(), counts=(2, 1, 1))
        host.local_batch_size = 4
        eng = RescaleEngine(host, node_rank=0)
        tr = eng.apply(make_plan())
        assert tr.ok

    def test_engine_advertises_capability(self, monkeypatch):
        class AdvClient(FakeClient):
            def __init__(self):
                super().__init__()
                self.infos = []

            def report_model_info(self, params_count, flops_per_step,
                                  batch_size=0, seq_len=0, extra=None):
                self.infos.append(extra or {})

        client = AdvClient()
        RescaleEngine(FakeHost(state=None), client=client, node_rank=1)
        assert any(i.get("rescale_capable") for i in client.infos)
        # Killswitch: RESCALE off -> nothing advertised.
        monkeypatch.setenv("DLROVER_TPU_RESCALE", "0")
        client2 = AdvClient()
        RescaleEngine(FakeHost(state=None), client=client2, node_rank=1)
        assert client2.infos == []

    def test_maybe_rescale_poll_cycle(self, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_RESCALE_POLL_INTERVAL_S", "0")
        host = FakeHost(state=self._state())
        client = FakeClient(plan=make_plan())
        eng = RescaleEngine(host, client=client, node_rank=0)
        tr = eng.maybe_rescale()
        assert tr is not None and tr.ok
        # Plan consumed: an empty poll answer means nothing to do.
        client.plan = None
        assert eng.maybe_rescale() is None
        # Killswitch: RESCALE off -> no polling at all.
        monkeypatch.setenv("DLROVER_TPU_RESCALE", "0")
        polls = client.polls
        assert eng.maybe_rescale() is None
        assert client.polls == polls


class TestRescaleEngineLiveModel:
    """In-place 4→3→4 on a real prepared trainer: the jitted step is
    rebuilt per world, the live state moves bitwise, and the in-place
    path lands on the exact same math as the restart path."""

    @pytest.mark.slow  # ~16 s of real compiles; tier-1 budget headroom
    def test_shrink_regrow_live_state(self):
        from dlrover_tpu.accel import ParallelSpec

        cfg = tiny_cfg()
        micro = jax.random.randint(
            jax.random.PRNGKey(2), (4, 16), 0, cfg.vocab_size
        )
        et = ElasticTrainer(global_batch_size=16, micro_batch_size=4,
                            world_size=4, rank=0)
        et.prepare(GPT(cfg), optax.adamw(1e-3), micro, token_loss,
                   spec=ParallelSpec(data=1))
        assert et.schedule.counts == [1, 1, 1, 1]
        state = et.result.state
        batch4 = jax.random.randint(
            jax.random.PRNGKey(3), (4, 16), 0, cfg.vocab_size
        )
        for _ in range(2):
            state, met = et.result.train_step(
                state, jax.device_put(batch4, et.result.batch_sharding)
            )
        et.result.state = state
        step0 = int(state["step"])
        pre = [np.asarray(x).copy()
               for x in jax.tree_util.tree_leaves(state)]
        saved = jax.tree_util.tree_map(lambda x: np.asarray(x).copy(), state)

        eng = RescaleEngine(et, node_rank=0)
        eng.round = 1
        plan3 = make_plan(plan_id=1, old_round=1, new_round=2)
        tr = eng.apply(plan3, state=state)
        assert tr.ok and tr.source == "live"
        assert et.schedule.counts == [2, 1, 1]
        assert et.accum_steps == 2 and et.local_batch_size == 8
        assert int(tr.state["step"]) == step0
        # The transfer is layout-only: every leaf bitwise preserved.
        post = jax.tree_util.tree_leaves(tr.state)
        for x, y in zip(pre, post):
            np.testing.assert_array_equal(x, np.asarray(y))

        # Restart-path oracle: a fresh world-3 trainer hydrated from the
        # pre-shrink state must step to the exact same loss and weights.
        from dlrover_tpu.accel.accelerate import transfer_state

        et_r = ElasticTrainer(global_batch_size=16, micro_batch_size=4,
                              world_size=3, rank=0)
        et_r.prepare(GPT(cfg), optax.adamw(1e-3), micro, token_loss,
                     spec=ParallelSpec(data=1))
        rstate = transfer_state(saved, et_r.result.shardings)
        batch8 = jax.random.randint(
            jax.random.PRNGKey(4), (8, 16), 0, cfg.vocab_size
        )
        s_ip, m_ip = et.result.train_step(
            tr.state, jax.device_put(batch8, et.result.batch_sharding)
        )
        s_rs, m_rs = et_r.result.train_step(
            rstate, jax.device_put(batch8, et_r.result.batch_sharding)
        )
        assert float(m_ip["loss"]) == float(m_rs["loss"]), (
            "in-place rescale diverged from the restart path"
        )
        assert_leaves_equal(s_ip, s_rs)

        # Regrow back to 4: the original schedule returns exactly.
        plan4 = make_plan(
            plan_id=2, old_world={0: 1, 1: 1, 2: 1},
            new_world={0: 1, 1: 1, 2: 1, 3: 1}, old_round=2, new_round=3,
        )
        tr2 = eng.apply(plan4, state=s_ip)
        assert tr2.ok and tr2.source == "live"
        assert et.schedule.counts == [1, 1, 1, 1]
        assert et.local_batch_size == 4
        assert eng.applied_plans == 2 and eng.round == 3
        s_f, m_f = et.result.train_step(
            tr2.state, jax.device_put(batch4, et.result.batch_sharding)
        )
        assert int(s_f["step"]) == step0 + 2
        assert np.isfinite(float(m_f["loss"]))


class TestAgentSettle:
    """The agent's plan-settle protocol around _try_rescale_in_place."""

    def _agent(self, client, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_RESCALE_POLL_INTERVAL_S", "0.01")
        return ElasticTrainingAgent(
            ElasticLaunchConfig(node_rank=0), WorkerSpec("true", []), client
        )

    def _outcome(self):
        return RendezvousOutcome(
            1, {0: 1, 1: 1, 2: 1, 3: 1}, 0, "127.0.0.1:0"
        )

    def test_completed_plan_adopted(self, monkeypatch):
        plan = make_plan()

        class SettleClient(FakeClient):
            def __init__(self):
                super().__init__(plan)

            def get_rescale_plan(self, rdzv_name, node_rank, round_):
                self.polls += 1
                if self.polls >= 3:
                    return m.RescalePlan()  # settled: plan gone
                return plan

            def world_stale(self, rdzv_name, round_):
                return False  # new round stays live -> completed

        agent = self._agent(SettleClient(), monkeypatch)
        outcome = self._outcome()
        assert agent._try_rescale_in_place(outcome) is True
        assert outcome.round == plan.new_round
        assert outcome.world == plan.new_world
        assert outcome.world_size == 3 and outcome.num_nodes == 3

    def test_aborted_plan_falls_back(self, monkeypatch):
        plan = make_plan()

        class AbortClient(FakeClient):
            def __init__(self):
                super().__init__(plan)

            def world_stale(self, rdzv_name, round_):
                return True  # new round fenced -> plan aborted

        agent = self._agent(AbortClient(), monkeypatch)
        outcome = self._outcome()
        assert agent._try_rescale_in_place(outcome) is False
        assert outcome.round == 1  # nothing adopted

    def test_abort_landing_between_settle_reads_not_adopted(
        self, monkeypatch
    ):
        """The settle loop reads world_stale BEFORE get_rescale_plan;
        an abort landing between the two makes the plan vanish while
        the stale answer still says live. The agent must re-check
        before adopting, not treat "plan gone" as "completed"."""
        plan = make_plan()

        class RacyClient(FakeClient):
            def __init__(self):
                super().__init__(plan)
                self.stale_calls = 0

            def get_rescale_plan(self, rdzv_name, node_rank, round_):
                self.polls += 1
                if self.polls >= 2:
                    return m.RescalePlan()  # abort landed: plan gone
                return plan

            def world_stale(self, rdzv_name, round_):
                self.stale_calls += 1
                # First read races ahead of the abort; every later read
                # sees the invalidated round.
                return self.stale_calls >= 2

        agent = self._agent(RacyClient(), monkeypatch)
        outcome = self._outcome()
        assert agent._try_rescale_in_place(outcome) is False
        assert outcome.round == 1  # the aborted round was not adopted

    def test_unreachable_master_falls_back(self, monkeypatch):
        class DeadClient:
            def get_rescale_plan(self, *a, **k):
                raise ConnectionError("master gone")

        agent = self._agent(DeadClient(), monkeypatch)
        assert agent._try_rescale_in_place(self._outcome()) is False

    def test_disabled_falls_back(self, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_RESCALE", "0")
        agent = self._agent(FakeClient(make_plan()), monkeypatch)
        assert agent._try_rescale_in_place(self._outcome()) is False


# ---------------- the acceptance drill ----------------

_HEARTBEAT_SRC = """
import sys, time
from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common.constants import NodeStatus, RendezvousName

addr, rank = sys.argv[1], int(sys.argv[2])
c = MasterClient(addr, node_id=rank)
c.join_rendezvous(RendezvousName.TRAINING, rank, 1)
c.report_node_status(NodeStatus.RUNNING)
# Stand-in for this worker's RescaleEngine advertising itself.
c.report_model_info(0, 0.0, extra={"rescale_capable": True})
while True:
    c.report_heartbeat()
    time.sleep(0.1)
"""


def _spawn_heartbeater(addr, rank):
    return subprocess.Popen(
        [sys.executable, "-c", _HEARTBEAT_SRC, addr, str(rank)],
        env=cpu_subprocess_env(),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


@pytest.mark.chaos
@pytest.mark.e2e
@pytest.mark.slow
class TestShrinkRegrowDrill:
    """ISSUE 8 acceptance: SIGKILL 1 of 4 workers -> in-place shrink to
    3 -> regrow to 4, loss identical to the restart path, with no disk
    restore on the survivors."""

    def test_sigkill_shrink_then_regrow(self, tmp_path):
        from dlrover_tpu.accel import ParallelSpec
        from dlrover_tpu.common.global_context import get_context
        from dlrover_tpu.train.checkpoint import (
            FlashCheckpointer,
            StorageType,
        )

        ctx = get_context()
        old_ctx = (ctx.heartbeat_timeout, ctx.node_monitor_interval)
        ctx.heartbeat_timeout = 1.2
        ctx.node_monitor_interval = 0.1
        master = JobMaster(port=0, node_num=4, job_name="rescale-drill")
        master.prepare()
        procs = {}
        c0 = MasterClient(master.addr, node_id=0)
        stop_hb = threading.Event()

        def heartbeat():
            while not stop_hb.is_set():
                try:
                    c0.report_heartbeat()
                except Exception:
                    pass
                stop_hb.wait(0.2)

        hb = threading.Thread(target=heartbeat, daemon=True)
        try:
            # Node 0 is this process (the survivor whose trainer we
            # host); nodes 1-3 are real child processes.
            c0.join_rendezvous(TRAIN, 0, 1)
            c0.report_node_status(NodeStatus.RUNNING)
            for r in (1, 2, 3):
                procs[r] = _spawn_heartbeater(master.addr, r)
            deadline = time.monotonic() + 30
            world = {}
            while time.monotonic() < deadline and len(world) < 4:
                round_, _, world = c0.get_comm_world(TRAIN, 0)
                time.sleep(0.1)
            assert len(world) == 4, "fleet never formed"
            hb.start()

            # Batch contract + progress reach the coordinator the same
            # way a real trainer reports them.
            c0.report_model_info(
                0, 0.0, batch_size=16,
                extra={"global_batch": 16, "micro_batch": 4},
            )
            cfg = tiny_cfg()
            micro = jax.random.randint(
                jax.random.PRNGKey(2), (4, 16), 0, cfg.vocab_size
            )
            et = ElasticTrainer(global_batch_size=16, micro_batch_size=4,
                                world_size=4, rank=0)
            et.prepare(GPT(cfg), optax.adamw(1e-3), micro, token_loss,
                       spec=ParallelSpec(data=1))
            eng = RescaleEngine(et, client=c0, node_rank=0)
            eng.round = round_
            state = et.result.state
            batch4 = jax.random.randint(
                jax.random.PRNGKey(3), (4, 16), 0, cfg.vocab_size
            )
            for _ in range(2):
                state, met = et.result.train_step(
                    state, jax.device_put(batch4, et.result.batch_sharding)
                )
                c0.report_global_step(int(state["step"]), time.time())
            et.result.state = state
            step0 = int(state["step"])
            saved = jax.tree_util.tree_map(
                lambda x: np.asarray(x).copy(), state
            )
            # Persist the step-2 snapshot: the restart path's source.
            ck = FlashCheckpointer(str(tmp_path / "ckpts"))
            ck.save_checkpoint(step0, state, StorageType.DISK)
            assert ck.wait_persisted(step0, timeout=60)
            ck.close()

            # The fault: SIGKILL one of the four workers.
            procs[3].kill()
            procs[3].wait()

            # Heartbeat timeout -> eviction -> shrink plan. The survivor
            # polls it over the real RPC.
            plan = None
            deadline = time.monotonic() + 30
            while plan is None and time.monotonic() < deadline:
                plan = eng.poll()
                time.sleep(0.1)
            assert plan is not None, "no shrink plan issued"
            assert sorted(plan.new_world) == [0, 1, 2]
            assert plan.accum_counts == [2, 1, 1]

            tr = eng.apply(plan, state=state)
            assert tr.ok
            # No disk restore on the survivor: live d2d transfer only.
            assert tr.source == "live"
            # Stand-in acks for the other two survivors' trainers.
            for r in (1, 2):
                c = MasterClient(master.addr, node_id=r)
                c.report_rescale_ack(plan.plan_id, r, True)
                c.close()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if not c0.get_rescale_plan(TRAIN, 0, round_).exists:
                    break
                time.sleep(0.1)
            assert not c0.world_stale(TRAIN, plan.new_round), (
                "plan aborted instead of completing"
            )

            # Bit-identity, part 1: the transfer preserved every leaf.
            assert_leaves_equal(tr.state, saved)
            # Part 2: the in-place step equals the restart-path step — a
            # fresh world-3 trainer restored from disk, same batch.
            et_r = ElasticTrainer(global_batch_size=16, micro_batch_size=4,
                                  world_size=3, rank=0)
            et_r.prepare(GPT(cfg), optax.adamw(1e-3), micro, token_loss,
                         spec=ParallelSpec(data=1))
            ck2 = FlashCheckpointer(str(tmp_path / "ckpts"))
            rstep, rstate = ck2.load_checkpoint(et_r.result.state)
            ck2.close()
            assert rstep == step0
            batch8 = jax.random.randint(
                jax.random.PRNGKey(4), (8, 16), 0, cfg.vocab_size
            )
            s_ip, m_ip = et.result.train_step(
                tr.state, jax.device_put(batch8, et.result.batch_sharding)
            )
            s_rs, m_rs = et_r.result.train_step(
                rstate, jax.device_put(batch8, et_r.result.batch_sharding)
            )
            assert float(m_ip["loss"]) == float(m_rs["loss"]), (
                "in-place shrink diverged from the restart path"
            )
            assert_leaves_equal(s_ip, s_rs)
            c0.report_global_step(int(s_ip["step"]), time.time())

            # Regrow: the dead node comes back and is absorbed in place.
            procs[3] = _spawn_heartbeater(master.addr, 3)
            plan2 = None
            deadline = time.monotonic() + 30
            while plan2 is None and time.monotonic() < deadline:
                plan2 = eng.poll()
                time.sleep(0.1)
            assert plan2 is not None, "no grow plan issued"
            assert sorted(plan2.new_world) == [0, 1, 2, 3]
            assert plan2.accum_counts == [1, 1, 1, 1]
            tr2 = eng.apply(plan2, state=s_ip)
            assert tr2.ok and tr2.source == "live"
            for r in (1, 2):
                c = MasterClient(master.addr, node_id=r)
                c.report_rescale_ack(plan2.plan_id, r, True)
                c.close()
            # Back on the exact original schedule; training continues.
            assert et.schedule.counts == [1, 1, 1, 1]
            s_f, m_f = et.result.train_step(
                tr2.state, jax.device_put(batch4, et.result.batch_sharding)
            )
            assert int(s_f["step"]) == step0 + 2
            assert np.isfinite(float(m_f["loss"]))
        finally:
            stop_hb.set()
            if hb.is_alive():
                hb.join(timeout=2)
            for p in procs.values():
                try:
                    p.kill()
                    p.wait(timeout=5)
                except Exception:
                    pass
            c0.close()
            master.stop()
            (ctx.heartbeat_timeout, ctx.node_monitor_interval) = old_ctx
