"""Automatic straggler remediation (ISSUE 17): detect → quarantine →
in-place shrink → probation regrow, fully journaled.

Covers the :class:`RemediationPolicy` state machine table (hysteresis,
cooldown, min-world floor, concurrent cap, probation pass/fail/flap),
the nacked-plan → SUSPECT-with-backoff regression, WAL replay
reproducing a mid-quarantine failover exactly once, the servicer's
quarantine join gate, the goodput ledger's ``remediation:<kind>``
incidents with detect/act/recover stamps, the surfaced (no longer
swallowed) eviction-callback failure, and — slow-marked — the chaos
drill: a ``probe.link degrade`` on one node is autonomously
quarantined, the job shrinks in place, and regrows through the join
path when the probes recover, with goodput above the detect-only arm.
"""

import time

import pytest

from dlrover_tpu.agent.device_check import LinkProbe
from dlrover_tpu.chaos.injector import (
    CHAOS_ENV,
    FaultEvent,
    FaultInjector,
    FaultPlan,
)
from dlrover_tpu.common import messages as m
from dlrover_tpu.common.constants import RendezvousName
from dlrover_tpu.master.master import JobMaster
from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor
from dlrover_tpu.master.monitor.straggler import StragglerDetector
from dlrover_tpu.master.remediation import (
    STATE_EVICTED,
    STATE_PROBATION,
    STATE_QUARANTINED,
    STATE_SUSPECT,
    RemediationPolicy,
)
from dlrover_tpu.master.rescale import PLAN_ABORTED, PLAN_ISSUED
from dlrover_tpu.master.servicer import MasterServicer
from dlrover_tpu.master.state_store import MasterStateStore
from dlrover_tpu.observability import events as events_mod
from dlrover_tpu.observability.event_log import EventLog
from dlrover_tpu.observability.events import EventKind, JobEvent, emit
from dlrover_tpu.observability.goodput import GoodputLedger

from tests.test_rescale import TRAIN, formed_world, make_coordinator

PROBE_OK = {"h2d_mbps": 800.0, "d2h_mbps": 800.0, "rtt_ms": 1.0}


@pytest.fixture(autouse=True)
def _clean_routing_and_chaos(monkeypatch):
    monkeypatch.delenv(CHAOS_ENV, raising=False)
    FaultInjector.reset()
    events_mod.reset()
    yield
    events_mod.reset()
    FaultInjector.reset()


@pytest.fixture(autouse=True)
def fast_knobs(monkeypatch):
    """Deterministic policy timing: no cooldown, tight hysteresis. Each
    test overrides what it exercises."""
    monkeypatch.setenv("DLROVER_TPU_REMEDIATION_SUSTAIN_TICKS", "2")
    monkeypatch.setenv("DLROVER_TPU_REMEDIATION_COOLDOWN_S", "0")
    monkeypatch.setenv("DLROVER_TPU_REMEDIATION_PROBATION_S", "5")
    monkeypatch.setenv("DLROVER_TPU_REMEDIATION_BACKOFF_S", "10")


class FakeDetector:
    """Settable verdict table, the policy's whole input surface."""

    def __init__(self):
        self.flags = {}

    def flag(self, wid, kind="link", since_ts=0.0, detect_ts=0.0):
        self.flags[wid] = {
            "kind": kind, "since_ts": since_ts, "detect_ts": detect_ts,
        }

    def clear(self, wid):
        self.flags.pop(wid, None)

    def straggler_details(self):
        return {w: dict(d) for w, d in self.flags.items()}

    def stragglers(self):
        return {w: d["kind"] for w, d in self.flags.items()}


def make_policy(n=4, det=None, coord=None, mgr=None, store=None,
                evict_cb=None, **coord_kw):
    if mgr is None:
        mgr, _, _ = formed_world(n)
    det = det if det is not None else FakeDetector()
    if coord is None:
        coord = make_coordinator(mgr, **coord_kw)
    policy = RemediationPolicy(
        straggler_detector=det,
        rdzv_managers={TRAIN: mgr},
        rescale_coordinator=coord,
        state_store=store,
        evict_cb=evict_cb,
    )
    return policy, det, coord, mgr


def quarantine(policy, det, wid=0, kind="link", t0=100.0):
    """Drive wid through SUSPECT into QUARANTINED (sustain=2)."""
    det.flag(wid, kind=kind, since_ts=t0 - 5, detect_ts=t0)
    policy.tick(now=t0)
    policy.tick(now=t0 + 1)
    assert policy.state(wid) == STATE_QUARANTINED
    return t0 + 1


class TestStateMachine:
    def test_sustain_hysteresis_before_quarantine(self):
        policy, det, coord, mgr = make_policy()
        det.flag(0, "link", since_ts=95.0, detect_ts=100.0)
        policy.tick(now=100.0)
        # one tick: SUSPECT, world untouched
        assert policy.state(0) == STATE_SUSPECT
        assert len(mgr.current_world()) == 4
        assert not policy.gated(0)
        policy.tick(now=101.0)
        # second sustained tick: quarantined, world shrank in place
        assert policy.state(0) == STATE_QUARANTINED
        assert policy.gated(0)
        world = mgr.current_world()
        assert 0 not in world and len(world) == 3
        rec = policy.node_state(0)
        assert rec["plan_id"] >= 0
        assert coord.plan_status(rec["plan_id"]) == PLAN_ISSUED
        assert rec["detect_ts"] == 100.0 and rec["act_ts"] == 101.0

    def test_flap_clears_suspect_without_action(self):
        policy, det, coord, mgr = make_policy()
        det.flag(0)
        policy.tick(now=100.0)
        assert policy.state(0) == STATE_SUSPECT
        det.clear(0)
        policy.tick(now=101.0)
        # verdict flapped before the hysteresis ran out: record dropped
        assert policy.state(0) is None
        assert len(mgr.current_world()) == 4

    def test_cooldown_rate_limits_actions(self, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_REMEDIATION_COOLDOWN_S", "30")
        monkeypatch.setenv("DLROVER_TPU_REMEDIATION_MAX_CONCURRENT", "4")
        policy, det, coord, mgr = make_policy(n=6, capable=range(6))
        det.flag(0)
        det.flag(1)
        policy.tick(now=100.0)
        policy.tick(now=101.0)
        assert policy.state(0) == STATE_QUARANTINED
        # node 1 is equally sustained but the fleet-wide cooldown holds
        assert policy.state(1) == STATE_SUSPECT
        policy.tick(now=102.0)
        assert policy.state(1) == STATE_SUSPECT
        policy.tick(now=132.0)  # past the cooldown
        assert policy.state(1) == STATE_QUARANTINED

    def test_concurrent_cap_holds_second_quarantine(self):
        policy, det, coord, mgr = make_policy(n=6, capable=range(6))
        det.flag(0)
        det.flag(1)
        t = 100.0
        for i in range(6):
            policy.tick(now=t + i)
        # default cap is 1: one node parked, the other held SUSPECT
        assert policy.state(0) == STATE_QUARANTINED
        assert policy.state(1) == STATE_SUSPECT
        assert len(mgr.current_world()) == 5

    def test_min_world_floor_blocks_shrink(self, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_REMEDIATION_MIN_WORLD", "4")
        policy, det, coord, mgr = make_policy(n=4)
        det.flag(0)
        for i in range(5):
            policy.tick(now=100.0 + i)
        # 4 -> 3 would breach the floor: held in SUSPECT forever
        assert policy.state(0) == STATE_SUSPECT
        assert len(mgr.current_world()) == 4

    def test_preflight_decline_never_touches_world(self):
        # No batch config: the coordinator cannot plan any shrink.
        policy, det, coord, mgr = make_policy(global_batch=0)
        det.flag(0)
        for i in range(4):
            policy.tick(now=100.0 + i)
        assert policy.state(0) == STATE_SUSPECT
        # the node was NOT dropped from the rendezvous — an
        # issued-then-declined shrink would have forced a full restart
        assert len(mgr.current_world()) == 4

    def test_probation_pass_clears_to_healthy(self):
        policy, det, coord, mgr = make_policy()
        t = quarantine(policy, det)
        rec = policy.node_state(0)
        # survivors ack -> plan completes -> settle
        for r in (1, 2, 3):
            coord.apply_ack(rec["plan_id"], r, ok=True)
        policy.tick(now=t + 1)
        assert policy.node_state(0)["plan_id"] == -1
        # probes recover: the verdict clears -> probation, gate lifts
        det.clear(0)
        policy.tick(now=t + 2)
        assert policy.state(0) == STATE_PROBATION
        assert not policy.gated(0)
        # clean probation window -> HEALTHY (record dropped)
        policy.tick(now=t + 2 + 5.1)
        assert policy.state(0) is None

    def test_probation_fail_backs_off_then_requarantines(self):
        policy, det, coord, mgr = make_policy()
        t = quarantine(policy, det)
        rec = policy.node_state(0)
        for r in (1, 2, 3):
            coord.apply_ack(rec["plan_id"], r, ok=True)
        policy.tick(now=t + 1)
        det.clear(0)
        policy.tick(now=t + 2)
        assert policy.state(0) == STATE_PROBATION
        # the node regrows; simulate by re-joining the world
        mgr.join_rendezvous(0, 1)
        coord.on_node_joined(0, 1, TRAIN)
        # verdict returns during probation: first failure -> SUSPECT
        # with backoff, NOT an instant re-shrink
        det.flag(0)
        policy.tick(now=t + 3)
        rec = policy.node_state(0)
        assert rec["state"] == STATE_SUSPECT and rec["fails"] == 1
        assert rec["backoff_until"] == pytest.approx(t + 13)
        policy.tick(now=t + 4)
        assert policy.state(0) == STATE_SUSPECT  # backoff holds
        # past the backoff: fully sustained already, re-quarantines
        policy.tick(now=t + 14)
        assert policy.state(0) == STATE_QUARANTINED

    def test_second_probation_failure_evicts_permanently(self):
        evicted = []
        policy, det, coord, mgr = make_policy(
            evict_cb=lambda wid, reason: evicted.append((wid, reason))
        )
        t = quarantine(policy, det)
        rec = policy.node_state(0)
        for r in (1, 2, 3):
            coord.apply_ack(rec["plan_id"], r, ok=True)
        policy.tick(now=t + 1)
        det.clear(0)
        policy.tick(now=t + 2)         # probation #1
        mgr.join_rendezvous(0, 1)      # gate lifted: the node regrows
        coord.on_node_joined(0, 1, TRAIN)
        det.flag(0)
        policy.tick(now=t + 3)         # fail #1 -> suspect+backoff
        policy.tick(now=t + 14)        # re-quarantine
        assert policy.state(0) == STATE_QUARANTINED
        rec = policy.node_state(0)
        for r in (1, 2, 3):
            coord.apply_ack(rec["plan_id"], r, ok=True)
        policy.tick(now=t + 15)
        det.clear(0)
        policy.tick(now=t + 16)        # probation #2
        assert policy.state(0) == STATE_PROBATION
        det.flag(0)
        policy.tick(now=t + 17)        # fail #2 -> permanent eviction
        assert evicted and evicted[0][0] == 0
        assert "remediation:link" in evicted[0][1]
        assert policy.state(0) == STATE_EVICTED
        # the gate outlives the eviction: the node can never rejoin
        assert policy.gated(0)

    def test_unrelated_eviction_drops_record(self):
        policy, det, coord, mgr = make_policy()
        det.flag(0)
        policy.tick(now=100.0)
        assert policy.state(0) == STATE_SUSPECT
        # heartbeat-timeout eviction lands through the node manager:
        # the record must not linger (a returning node may rejoin)
        policy.on_node_evicted(0)
        assert policy.state(0) is None and not policy.gated(0)

    def test_disabled_policy_never_acts(self, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_REMEDIATION", "0")
        policy, det, coord, mgr = make_policy()
        det.flag(0)
        for i in range(5):
            policy.tick(now=100.0 + i)
        assert policy.state(0) is None
        assert len(mgr.current_world()) == 4


class TestNackedPlan:
    def test_nacked_plan_reverts_to_suspect_with_backoff(self):
        """Regression: a survivor nacking the shrink plan must revert
        the node to SUSPECT with backoff — never a crash, never a stuck
        QUARANTINED record pinning a gate nobody will lift."""
        policy, det, coord, mgr = make_policy()
        t = quarantine(policy, det)
        rec = policy.node_state(0)
        coord.apply_ack(rec["plan_id"], 1, ok=False, error="oom")
        assert coord.plan_status(rec["plan_id"]) == PLAN_ABORTED
        policy.tick(now=t + 1)
        rec = policy.node_state(0)
        assert rec["state"] == STATE_SUSPECT
        assert rec["plan_id"] == -1
        assert rec["backoff_until"] == pytest.approx(t + 11)
        assert not policy.gated(0)      # gate lifted: node may reform
        # backoff respected, then eligible again
        policy.tick(now=t + 2)
        assert policy.state(0) == STATE_SUSPECT

    def test_plan_timeout_reverts(self, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_RESCALE_APPLY_TIMEOUT_S", "0.05")
        policy, det, coord, mgr = make_policy()
        t = quarantine(policy, det)
        time.sleep(0.1)
        coord.tick()                    # deadline sweep aborts the plan
        policy.tick(now=t + 1)
        assert policy.state(0) == STATE_SUSPECT


class TestWalReplay:
    def _journaled_policy(self, tmp_path, **kw):
        store = MasterStateStore(str(tmp_path))
        store.snapshot(lambda: {})      # open the generation's journal
        policy, det, coord, mgr = make_policy(store=store, **kw)
        return store, policy, det, coord, mgr

    def test_mid_quarantine_failover_replays_exactly_once(self, tmp_path):
        store, policy, det, coord, mgr = self._journaled_policy(tmp_path)
        quarantine(policy, det)
        plan_id = policy.node_state(0)["plan_id"]
        store.close()                   # crash: no graceful checkpoint

        # ---- failed-over master: fresh world, fresh coordinator ----
        mgr2, _, _ = formed_world(4)
        calls = []
        policy2, det2, coord2, _ = make_policy(mgr=mgr2, det=det)
        coord2.on_node_removed = lambda *a, **k: calls.append(a)
        store2 = MasterStateStore(str(tmp_path))
        _, records = store2.recover()
        remediate = [r for r in records if r[0] == "remediate"]
        assert len(remediate) == 1      # exactly one quarantine record
        store2.replaying = True
        try:
            for rec in remediate:
                policy2.replay(rec[1])
        finally:
            store2.replaying = False
        # the pending quarantine is reproduced...
        rec = policy2.node_state(0)
        assert rec["state"] == STATE_QUARANTINED
        assert rec["plan_id"] == plan_id
        assert policy2.gated(0)
        # ...exactly once: replay is pure bookkeeping, no re-shrink
        assert calls == []
        # and the still-flagged verdict does not re-act on tick: the
        # node is already quarantined
        policy2.tick(now=500.0)
        assert calls == []
        store2.close()

    def test_probation_and_fail_records_replay(self, tmp_path):
        store, policy, det, coord, mgr = self._journaled_policy(tmp_path)
        t = quarantine(policy, det)
        rec = policy.node_state(0)
        for r in (1, 2, 3):
            coord.apply_ack(rec["plan_id"], r, ok=True)
        policy.tick(now=t + 1)
        det.clear(0)
        policy.tick(now=t + 2)          # probation record
        det.flag(0)
        policy.tick(now=t + 3)          # fail record
        expect = policy.node_state(0)
        store.close()

        policy2 = RemediationPolicy()
        store2 = MasterStateStore(str(tmp_path))
        _, records = store2.recover()
        for rec in records:
            if rec[0] == "remediate":
                policy2.replay(rec[1])
        got = policy2.node_state(0)
        assert got["state"] == expect["state"] == STATE_SUSPECT
        assert got["fails"] == expect["fails"] == 1
        assert got["backoff_until"] == expect["backoff_until"]
        store2.close()

    def test_tick_is_inert_while_replaying(self, tmp_path):
        store, policy, det, coord, mgr = self._journaled_policy(tmp_path)
        det.flag(0)
        store.replaying = True
        try:
            for i in range(5):
                policy.tick(now=100.0 + i)
        finally:
            store.replaying = False
        assert policy.state(0) is None
        assert len(mgr.current_world()) == 4
        store.close()

    def test_master_checkpoint_roundtrip(self, tmp_path):
        """Through the real JobMaster: the remediation table rides the
        snapshot and the ("remediate", ...) journal records ride the
        dispatcher, so a relaunched master holds the same gates."""
        master = JobMaster(port=0, node_num=4, state_dir=str(tmp_path))
        det = FakeDetector()
        master.remediation._detector = det
        for r in range(4):
            master.rdzv_managers[TRAIN].join_rendezvous(r, 1)
        master.rdzv_managers[TRAIN].get_comm_world(0)
        master.rescale.set_batch_config(16, 4)
        for r in range(4):
            master.rescale.set_capable(r)
        det.flag(3, "compute", since_ts=1.0, detect_ts=2.0)
        master.remediation.tick(now=100.0)
        master.remediation.tick(now=101.0)
        assert master.remediation.state(3) == STATE_QUARANTINED
        master._stopped.set()
        master._server.stop()
        master.state_store.close()

        master2 = JobMaster(port=0, node_num=4, state_dir=str(tmp_path))
        assert master2.remediation.state(3) == STATE_QUARANTINED
        assert master2.remediation.gated(3)
        master2._stopped.set()
        master2._server.stop()
        master2.state_store.close()


class TestJoinGate:
    def _servicer(self, mgr, policy):
        return MasterServicer(
            rdzv_managers={TRAIN: mgr},
            kv_store=None,
            task_manager=None,
            job_manager=None,
            speed_monitor=None,
            sync_service=None,
            shard_lease=object(),
            remediation_policy=policy,
        )

    def test_quarantined_join_parks_without_growing(self):
        policy, det, coord, mgr = make_policy()
        quarantine(policy, det)
        servicer = self._servicer(mgr, policy)
        world_before = mgr.current_world()
        round_ = servicer._join_rendezvous(m.JoinRendezvous(
            rdzv_name=TRAIN, node_rank=0, local_world_size=1,
        ))
        # parked: not admitted to the waiting set, no grow plan, but
        # told the current round so its poll loop keeps retrying
        assert mgr.current_world() == world_before
        assert mgr.num_nodes_waiting() == 0
        assert round_ == mgr.current_round()

    def test_probation_join_flows_to_grow_path(self):
        policy, det, coord, mgr = make_policy()
        t = quarantine(policy, det)
        rec = policy.node_state(0)
        for r in (1, 2, 3):
            coord.apply_ack(rec["plan_id"], r, ok=True)
        policy.tick(now=t + 1)
        det.clear(0)
        policy.tick(now=t + 2)
        assert policy.state(0) == STATE_PROBATION
        servicer = self._servicer(mgr, policy)
        servicer._rescale = coord
        servicer._join_rendezvous(m.JoinRendezvous(
            rdzv_name=TRAIN, node_rank=0, local_world_size=1,
        ))
        # the gate lifted, so the ordinary join path issued the regrow
        assert 0 in mgr.current_world()
        assert len(mgr.current_world()) == 4


class TestLedger:
    def test_remediation_incident_books_detect_act_recover(self):
        led = GoodputLedger(now=0.0)
        led.ingest(JobEvent(
            kind=EventKind.REMEDIATION_QUARANTINE, ts=110.0, node_id=2,
            role="master", pid=1,
            args={"kind": "link", "since_ts": 100.0, "detect_ts": 106.0,
                  "plan_id": 7, "old_world": [0, 1, 2, 3],
                  "new_world": [0, 1, 3]},
        ))
        led.note_step(5, ts=112.0)
        s = led.summary(now=120.0)
        [inc] = s["incidents"]
        assert inc["cause"] == "remediation:link"
        assert inc["persistent"] and inc["open"]
        assert inc["detect_s"] == pytest.approx(6.0)
        assert inc["act_s"] == pytest.approx(10.0)
        assert "plan 7" in inc["evidence"]
        # degradation, not downtime
        assert s["downtime_s"] == 0.0 and s["goodput"] == 1.0
        assert "remediation:link" in s["downtime_by_cause_s"]
        led.ingest(JobEvent(
            kind=EventKind.REMEDIATION_PROBATION, ts=130.0, node_id=2,
            role="master", pid=1, args={"kind": "link"},
        ))
        [inc] = led.summary(now=140.0)["incidents"]
        assert not inc["open"]
        assert inc["recover_s"] == pytest.approx(30.0)

    def test_straggler_recover_never_closes_remediation_incident(self):
        """A node carries BOTH lifecycles at once; each closes its own."""
        led = GoodputLedger(now=0.0)
        led.ingest(JobEvent(
            kind=EventKind.STRAGGLER_DETECT, ts=100.0, node_id=2,
            role="master", pid=1, args={"kind": "link"},
        ))
        led.ingest(JobEvent(
            kind=EventKind.REMEDIATION_QUARANTINE, ts=110.0, node_id=2,
            role="master", pid=1, args={"kind": "link"},
        ))
        led.ingest(JobEvent(
            kind=EventKind.STRAGGLER_RECOVER, ts=120.0, node_id=2,
            role="master", pid=1, args={"kind": "link"},
        ))
        by_cause = {
            i.cause: i for i in led.incidents()
        }
        assert not by_cause["straggler:link"].open
        assert by_cause["remediation:link"].open

    def test_evict_closes_and_revert_attaches(self):
        led = GoodputLedger(now=0.0)
        led.ingest(JobEvent(
            kind=EventKind.REMEDIATION_QUARANTINE, ts=10.0, node_id=1,
            role="master", pid=1, args={"kind": "compute"},
        ))
        led.ingest(JobEvent(
            kind=EventKind.REMEDIATION_REVERT, ts=12.0, node_id=1,
            role="master", pid=1,
            args={"kind": "compute", "reason": "plan-3-aborted"},
        ))
        led.ingest(JobEvent(
            kind=EventKind.REMEDIATION_EVICT, ts=20.0, node_id=1,
            role="master", pid=1, args={"kind": "compute", "fails": 2},
        ))
        [inc] = led.incidents()
        assert EventKind.REMEDIATION_REVERT in inc.trail
        assert not inc.open and inc.recover_ts == 20.0


class TestEvictFailureSurfaced:
    def test_failed_evict_cb_emits_remediation_failed(self):
        """Satellite of ISSUE 17: _evict_cb exceptions were logged and
        dropped — they must surface as a remediation.failed event and a
        goodput note."""
        log = EventLog()
        led = GoodputLedger()
        log.add_listener(led.ingest)
        events_mod.install_sink(log.append)
        sm = SpeedMonitor()

        def broken_evict(wid, reason):
            raise RuntimeError("scaler backend unreachable")

        det = StragglerDetector(
            speed_monitor=sm, window=16, ratio=2.0, sustain=2,
            evict_after=0.0, evict_enabled=True, evict_cb=broken_evict,
        )
        log.add_listener(det.observe)
        slow = {"input_s": 0.01, "compute_s": 0.50,
                "collective_s": 0.01, "readback_s": 0.01}
        normal = {"input_s": 0.01, "compute_s": 0.10,
                  "collective_s": 0.01, "readback_s": 0.01}
        for step in range(8):
            for w in range(3):
                det.note_phases(
                    w, dict(slow if w == 0 else normal), step=step
                )
            det.tick()
        assert det.stragglers() == {0: "compute"}
        failures = log.events(kinds=[EventKind.REMEDIATION_FAILED])
        assert failures and failures[0].node_id == 0
        assert "scaler backend unreachable" in failures[0].args["error"]
        # goodput note on the node's open straggler incident
        [inc] = [i for i in led.incidents()
                 if i.cause == "straggler:compute"]
        assert "failed" in inc.evidence
        assert EventKind.REMEDIATION_FAILED in inc.trail

    def test_policy_evict_failure_falls_back_to_suspect(self):
        """The policy's own permanent eviction failing must not leave an
        EVICTED-but-present node: it degrades to another quarantine
        round."""
        def broken_evict(wid, reason):
            raise RuntimeError("node manager down")

        policy, det, coord, mgr = make_policy(evict_cb=broken_evict)
        t = quarantine(policy, det)
        rec = policy.node_state(0)
        for r in (1, 2, 3):
            coord.apply_ack(rec["plan_id"], r, ok=True)
        policy.tick(now=t + 1)
        det.clear(0)
        policy.tick(now=t + 2)
        mgr.join_rendezvous(0, 1)
        coord.on_node_joined(0, 1, TRAIN)
        det.flag(0)
        policy.tick(now=t + 3)          # fail #1
        policy.tick(now=t + 14)         # re-quarantine
        rec = policy.node_state(0)
        for r in (1, 2, 3):
            coord.apply_ack(rec["plan_id"], r, ok=True)
        policy.tick(now=t + 15)
        det.clear(0)
        policy.tick(now=t + 16)
        det.flag(0)
        policy.tick(now=t + 17)         # fail #2 -> evict raises
        assert policy.state(0) == STATE_SUSPECT


class TestMetrics:
    def test_state_gauge_and_action_counter(self):
        policy, det, coord, mgr = make_policy()
        quarantine(policy, det, kind="link")
        metrics = {name: samples for name, _, _, samples
                   in policy.metrics()}
        assert ({"state": "quarantined", "kind": "link"}, 1.0) in (
            metrics["dlrover_tpu_remediation"]
        )
        assert ({"action": "quarantine"}, 1.0) in (
            metrics["dlrover_tpu_remediation_actions_total"]
        )


@pytest.mark.slow
class TestChaosDrill:
    """ISSUE 17 acceptance: ``probe.link degrade`` on one node →
    autonomous quarantine → in-place shrink (no restart) → probe
    recovery → probation regrow — every decision WAL-reproducible and
    goodput strictly above the detect-only arm."""

    DEGRADED_ROUNDS = 6

    def _run_arm(self, monkeypatch, tmp_path, remediate: bool):
        monkeypatch.setenv(
            "DLROVER_TPU_REMEDIATION", "1" if remediate else "0"
        )
        monkeypatch.setenv("DLROVER_TPU_REMEDIATION_SUSTAIN_TICKS", "2")
        monkeypatch.setenv("DLROVER_TPU_REMEDIATION_COOLDOWN_S", "0")
        monkeypatch.setenv("DLROVER_TPU_REMEDIATION_PROBATION_S", "0.1")
        log = EventLog()
        sm = SpeedMonitor()
        det = StragglerDetector(
            speed_monitor=sm, window=16, ratio=2.0, sustain=2,
            evict_after=1e9, evict_enabled=False,
        )
        led = GoodputLedger()
        log.add_listener(det.observe)
        log.add_listener(led.ingest)
        events_mod.install_sink(log.append)
        mgr, _, _ = formed_world(4)
        coord = make_coordinator(mgr)
        store = MasterStateStore(str(tmp_path / ("auto" if remediate
                                                 else "detect")))
        store.snapshot(lambda: {})
        policy = RemediationPolicy(
            straggler_detector=det,
            rdzv_managers={TRAIN: mgr},
            rescale_coordinator=coord,
            state_store=store,
        )
        events_mod.set_identity(0, "agent")
        probe = LinkProbe(interval=0, busy_fn=lambda: False,
                          sample_fn=lambda: dict(PROBE_OK))
        monkeypatch.setenv(CHAOS_ENV, FaultPlan(seed=11, events=[
            FaultEvent(site="probe.link", kind="degrade", every=1,
                       max_fires=self.DEGRADED_ROUNDS,
                       args={"factor": 0.05}),
        ]).to_json())
        FaultInjector.reset()

        # Throughput model for the goodput comparison: a round is slow
        # while a degraded node is in the training world, fast after
        # the shrink removes it (3 healthy chips beat 3 healthy + 1
        # that stalls every collective).
        FAST, SLOW = 0.1, 0.4
        sim_time, steps = 0.0, 0
        quarantined_at = None
        for round_ in range(14):
            probe.sample_once()           # node 0, through chaos
            for w in (1, 2, 3):
                emit(EventKind.PROBE_LINK, _node_id=w, _role="agent",
                     **PROBE_OK)
            det.tick()
            policy.tick()
            world = mgr.current_world()
            degraded_in_world = (
                0 in world and round_ < self.DEGRADED_ROUNDS
            )
            sim_time += SLOW if degraded_in_world else FAST
            steps += 1
            if quarantined_at is None and 0 not in world:
                quarantined_at = round_
                # in-place shrink, not a restart: a live round exists
                # and the plan's survivors keep their state
                assert mgr.current_world() == {1: 1, 2: 1, 3: 1}
                plan_id = policy.node_state(0)["plan_id"]
                for r in (1, 2, 3):
                    coord.apply_ack(plan_id, r, ok=True)
            if (
                remediate and policy.state(0) == STATE_PROBATION
                and 0 not in world
            ):
                # gate lifted: the node's next join poll regrows
                mgr.join_rendezvous(0, 1)
                coord.on_node_joined(0, 1, TRAIN)
            time.sleep(0.02)
        events_mod.reset()
        return {
            "throughput": steps / sim_time,
            "quarantined_at": quarantined_at,
            "policy": policy,
            "world": mgr.current_world(),
            "store": store,
            "log": log,
            "actions": dict(policy._actions),
        }

    def test_degraded_link_quarantine_shrink_regrow_beats_detect_only(
        self, monkeypatch, tmp_path
    ):
        auto = self._run_arm(monkeypatch, tmp_path, remediate=True)
        FaultInjector.reset()
        events_mod.reset()
        detect_only = self._run_arm(
            monkeypatch, tmp_path, remediate=False
        )

        # the detect-only arm never moved the world
        assert detect_only["quarantined_at"] is None
        assert len(detect_only["world"]) == 4
        # the auto arm quarantined while the link was degraded...
        assert auto["quarantined_at"] is not None
        assert auto["quarantined_at"] < self.DEGRADED_ROUNDS
        # ...and regrew to the full world after the probes recovered
        assert auto["world"] == {0: 1, 1: 1, 2: 1, 3: 1}
        assert auto["policy"].state(0) in (STATE_PROBATION, None)
        # zero flaps: exactly one quarantine action, no reverts
        assert auto["actions"].get("quarantine") == 1
        assert "revert" not in auto["actions"]
        # goodput strictly above the no-remediation arm
        assert auto["throughput"] > detect_only["throughput"]

        # every decision reproduces from WAL replay, exactly once
        store = auto["store"]
        store.close()
        store2 = MasterStateStore(store._root if hasattr(
            store, "_root") else str(tmp_path / "auto"))
        _, records = store2.recover()
        remediate_recs = [r[1] for r in records if r[0] == "remediate"]
        kinds = [p["rec"] for p in remediate_recs]
        assert kinds.count("quarantine") == 1
        assert kinds.count("probation") == 1
        replayed = RemediationPolicy()
        for payload in remediate_recs:
            replayed.replay(payload)
        assert replayed.state(0) == auto["policy"].state(0) or (
            replayed.state(0) == STATE_PROBATION
        )
        store2.close()
        detect_only["store"].close()
