"""Kernel numerics: Pallas flash attention + ring attention vs the einsum
oracle, standalone and end-to-end through the GPT model.

The Pallas kernels run in interpreter mode on CPU — same kernel code path
as the compiled TPU run (SURVEY.md §4's "multi-node logic without
multi-node" strategy applied to kernels).
"""

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.accel import ParallelSpec, auto_accelerate, create_mesh
from dlrover_tpu.models.gpt import GPT, GPTConfig, loss_fn
from dlrover_tpu.ops import (
    flash_attention,
    reference_attention,
    ring_attention,
)


def rand_qkv(key, b=2, s=128, h=2, d=32, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    mk = lambda k: jax.random.normal(k, (b, s, h, d), dtype)
    return mk(ks[0]), mk(ks[1]), mk(ks[2])


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_forward_matches_reference(self, causal):
        q, k, v = rand_qkv(jax.random.PRNGKey(0))
        out = flash_attention(
            q, k, v, causal=causal, block_q=64, block_k=64
        )
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_grads_match_reference(self):
        q, k, v = rand_qkv(jax.random.PRNGKey(1), s=64)

        def loss_flash(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, block_q=32, block_k=32) ** 2
            )

        def loss_ref(q, k, v):
            return jnp.sum(reference_attention(q, k, v) ** 2)

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for gf, gr in zip(g_flash, g_ref):
            np.testing.assert_allclose(gf, gr, rtol=1e-4, atol=1e-4)

    def test_uneven_blocks(self):
        """Sequence not divisible by the asked block size shrinks blocks."""
        q, k, v = rand_qkv(jax.random.PRNGKey(2), s=96)
        out = flash_attention(q, k, v, block_q=64, block_k=64)
        ref = reference_attention(q, k, v)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_bf16_inputs(self):
        q, k, v = rand_qkv(jax.random.PRNGKey(3), dtype=jnp.bfloat16)
        out = flash_attention(q, k, v, block_q=64, block_k=64)
        ref = reference_attention(q, k, v)
        np.testing.assert_allclose(
            out.astype(np.float32), ref.astype(np.float32),
            rtol=2e-2, atol=2e-2,
        )


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference_on_seq_mesh(self, causal):
        mesh = create_mesh([("seq", 8)])
        q, k, v = rand_qkv(jax.random.PRNGKey(4), s=64)
        out = ring_attention(q, k, v, causal=causal, mesh=mesh)
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_falls_back_without_seq_axis(self):
        q, k, v = rand_qkv(jax.random.PRNGKey(5), s=32)
        out = ring_attention(q, k, v, mesh=None)
        ref = reference_attention(q, k, v)
        np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)

    def test_mixed_mesh_batch_and_seq(self):
        mesh = create_mesh([("data", 2), ("seq", 4)])
        q, k, v = rand_qkv(jax.random.PRNGKey(6), b=4, s=64)
        out = ring_attention(q, k, v, mesh=mesh)
        ref = reference_attention(q, k, v)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def token_loss(module, params, batch):
    return loss_fn(module.apply({"params": params}, batch), batch)


def run_training(spec, cfg, steps=3):
    model = GPT(cfg)
    opt = optax.adamw(1e-3)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size
    )
    res = auto_accelerate(model, opt, tokens, token_loss, spec=spec)
    state = res.state
    batch = jax.device_put(tokens, res.batch_sharding)
    losses = []
    for _ in range(steps):
        state, m = res.train_step(state, batch)
        losses.append(float(m["loss"]))
    return losses


class TestModelIntegration:
    """attn_impl end-to-end: training losses must match the einsum path."""

    @pytest.fixture(scope="class")
    def baseline(self):
        cfg = dataclasses.replace(GPTConfig.tiny(), dtype=jnp.float32)
        return run_training(ParallelSpec(), cfg)

    def test_sp_ring_training_matches(self, baseline):
        cfg = dataclasses.replace(
            GPTConfig.tiny(), dtype=jnp.float32, attn_impl="ring"
        )
        losses = run_training(ParallelSpec(seq=8), cfg)
        np.testing.assert_allclose(losses, baseline, rtol=2e-5, atol=2e-5)

    def test_sp_composes_with_dp(self, baseline):
        cfg = dataclasses.replace(
            GPTConfig.tiny(), dtype=jnp.float32, attn_impl="ring"
        )
        losses = run_training(ParallelSpec(data=2, seq=4), cfg)
        np.testing.assert_allclose(losses, baseline, rtol=2e-5, atol=2e-5)

    def test_pallas_training_matches(self, baseline):
        cfg = dataclasses.replace(
            GPTConfig.tiny(), dtype=jnp.float32, attn_impl="pallas"
        )
        losses = run_training(ParallelSpec(), cfg)
        np.testing.assert_allclose(losses, baseline, rtol=1e-4, atol=1e-4)


class TestUlyssesAttention:
    """All-to-all sequence parallelism (optional SURVEY §2.8 row): exact
    numerics vs the einsum path, composed through training."""

    def test_shard_matches_reference(self):
        import flax.linen as nn
        from jax.sharding import Mesh

        from dlrover_tpu.ops.attention import reference_attention
        from dlrover_tpu.ops.ulysses import ulysses_attention

        b, s, h, d = 2, 32, 4, 8
        key = jax.random.PRNGKey(0)
        q, k, v = (
            jax.random.normal(kk, (b, s, h, d), jnp.float32)
            for kk in jax.random.split(key, 3)
        )
        devices = np.array(jax.devices()[:4]).reshape(4)
        mesh = Mesh(devices, ("seq",))
        out = jax.jit(
            lambda a, b_, c: ulysses_attention(a, b_, c, mesh=mesh)
        )(q, k, v)
        ref = reference_attention(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
        )

    def test_ulysses_training_matches(self):
        cfg0 = dataclasses.replace(GPTConfig.tiny(), dtype=jnp.float32)
        baseline = run_training(ParallelSpec(), cfg0)
        cfg = dataclasses.replace(
            GPTConfig.tiny(), dtype=jnp.float32, attn_impl="ulysses"
        )
        # heads=2 divides seq degree 2
        losses = run_training(ParallelSpec(data=4, seq=2), cfg)
        np.testing.assert_allclose(losses, baseline, rtol=2e-5, atol=2e-5)

    def test_head_divisibility_enforced(self):
        from jax.sharding import Mesh

        from dlrover_tpu.ops.ulysses import ulysses_attention

        b, s, h, d = 2, 32, 3, 8  # 3 heads, 4-way seq: invalid
        q = jnp.zeros((b, s, h, d))
        mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
        with pytest.raises(ValueError, match="divisible"):
            jax.jit(
                lambda a: ulysses_attention(a, a, a, mesh=mesh)
            )(q)


class TestInt8WeightOnly:
    """Int8 weight-only quantization (quantized-compute parity row; the
    TPU serving analog of the reference's fp8 paths)."""

    def test_logits_close_and_4x_smaller(self):
        import flax.linen as nn

        from dlrover_tpu.ops.quantized import (
            dequantize_params,
            quantize_params,
            quantized_nbytes,
        )

        cfg = dataclasses.replace(
            GPTConfig.tiny(), dtype=jnp.float32, d_model=64, num_heads=4
        )
        model = GPT(cfg)
        tokens = jax.random.randint(
            jax.random.PRNGKey(0), (2, 16), 0, cfg.vocab_size
        )
        params = nn.meta.unbox(
            model.init(jax.random.PRNGKey(1), tokens)["params"]
        )
        ref = model.apply({"params": params}, tokens)

        qparams = quantize_params(params, min_elems=256)
        out = jax.jit(
            lambda qp, t: model.apply(
                {"params": dequantize_params(qp, jnp.float32)}, t
            )
        )(qparams, tokens)
        # weight rounding only: logits track closely and rank identically
        err = float(jnp.abs(out - ref).max() / jnp.abs(ref).max())
        assert err < 0.05, f"relative error {err}"
        top_ref = jnp.argmax(ref, axis=-1)
        top_q = jnp.argmax(out, axis=-1)
        assert float((top_ref == top_q).mean()) > 0.95

        fp32_bytes = sum(
            l.nbytes for l in jax.tree_util.tree_leaves(params)
        )
        ratio = fp32_bytes / quantized_nbytes(qparams)
        assert ratio > 3.0, f"only {ratio:.2f}x smaller"

    def test_small_leaves_pass_through(self):
        from dlrover_tpu.ops.quantized import (
            QuantizedWeight,
            quantize_params,
        )

        params = {"norm": {"scale": jnp.ones((32,))},
                  "w": jnp.ones((64, 64))}
        q = quantize_params(params, min_elems=1024)
        assert not isinstance(q["norm"]["scale"], QuantizedWeight)
        assert isinstance(q["w"], QuantizedWeight) is False or True
        q2 = quantize_params(params, min_elems=256)
        assert isinstance(q2["w"], QuantizedWeight)


class TestInt8Training:
    """AQT-style int8 training matmuls (VERDICT r4 #3 — the TPU analog
    of the reference's fp8 training, amp_optimization.py:193)."""

    def test_int8_dot_close_to_exact(self):
        from dlrover_tpu.ops.quantized import int8_dot

        k = jax.random.PRNGKey(0)
        x = jax.random.normal(k, (4, 16, 64), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (64, 128)) * 0.05
        exact = x @ w
        q = int8_dot(x, w)
        err = jnp.abs(q - exact).max() / jnp.abs(exact).max()
        assert float(err) < 0.02, float(err)

    def test_backward_is_straight_through(self):
        """Grads equal the exact bf16 product grads (not quantized):
        quantization noise is a forward-only perturbation."""
        from dlrover_tpu.ops.quantized import int8_dot

        x = jax.random.normal(jax.random.PRNGKey(0), (8, 32), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (32, 16)) * 0.1

        gq = jax.grad(lambda x, w: int8_dot(x, w).sum(), argnums=(0, 1))
        ge = jax.grad(lambda x, w: (x @ w).sum(), argnums=(0, 1))
        for a, b in zip(gq(x, w), ge(x, w)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5
            )

    def test_int8_training_tracks_bf16(self):
        """Tiny GPT: 10 steps of int8-MLP training must track the bf16
        run (loss within a few percent — the AQT promise)."""
        import dataclasses
        import optax
        from dlrover_tpu.accel import auto_accelerate, ParallelSpec
        from dlrover_tpu.models.gpt import GPT, GPTConfig, loss_fn

        def run(precision):
            cfg = dataclasses.replace(GPTConfig.tiny(), dtype=jnp.float32)
            tokens = jax.random.randint(
                jax.random.PRNGKey(2), (8, 32), 0, cfg.vocab_size
            )
            res = auto_accelerate(
                GPT(cfg), optax.adamw(1e-2), tokens,
                lambda mod, p, b: loss_fn(
                    mod.apply({"params": p}, b), b
                ),
                spec=ParallelSpec(), precision=precision,
            )
            state = res.state
            batch = jax.device_put(tokens, res.batch_sharding)
            losses = []
            for _ in range(10):
                state, m = res.train_step(state, batch)
                losses.append(float(m["loss"]))
            return losses

        bf16 = run("bf16")
        int8 = run("int8")
        # same trajectory within a few percent at every step
        for a, b in zip(int8, bf16):
            assert abs(a - b) / b < 0.05, (int8, bf16)
        assert int8[-1] < int8[0] * 0.8  # actually learning

    def test_int8_param_tree_identical(self):
        """Precision is a pure compute swap: the param tree (names,
        shapes, logical axes) matches the bf16 model, so sharding
        rules, FSDP, TP and checkpoints are unaffected."""
        import dataclasses
        from dlrover_tpu.models.gpt import GPT, GPTConfig

        cfg = GPTConfig.tiny()
        qcfg = dataclasses.replace(cfg, mlp_precision="int8")
        tokens = jnp.zeros((2, 8), jnp.int32)
        a = jax.eval_shape(
            lambda: GPT(cfg).init(jax.random.PRNGKey(0), tokens)
        )
        b = jax.eval_shape(
            lambda: GPT(qcfg).init(jax.random.PRNGKey(0), tokens)
        )
        ta = jax.tree_util.tree_structure(a)
        tb = jax.tree_util.tree_structure(b)
        assert ta == tb
        for la, lb in zip(
            jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
        ):
            assert la.shape == lb.shape and la.dtype == lb.dtype

    def test_int8_composes_with_tp_fsdp(self):
        """int8 MLP under dp x fsdp x tp trains and the kernels stay
        sharded as planned."""
        import dataclasses
        import optax
        from dlrover_tpu.accel import auto_accelerate, ParallelSpec
        from dlrover_tpu.models.gpt import GPT, GPTConfig, loss_fn

        cfg = dataclasses.replace(
            GPTConfig.tiny(), dtype=jnp.float32, num_heads=4
        )
        tokens = jax.random.randint(
            jax.random.PRNGKey(2), (8, 32), 0, cfg.vocab_size
        )
        res = auto_accelerate(
            GPT(cfg), optax.adamw(1e-2), tokens,
            lambda mod, p, b: loss_fn(mod.apply({"params": p}, b), b),
            spec=ParallelSpec(data=2, fsdp=2, tensor=2),
            precision="int8",
        )
        state, m = res.train_step(
            res.state, jax.device_put(tokens, res.batch_sharding)
        )
        assert np.isfinite(float(m["loss"]))
        up = state["params"]["blocks"]["up"]["kernel"]
        assert (up.addressable_shards[0].data.shape[-1]
                == up.shape[-1] // 2)

    def test_plain_model_rejected(self):
        import flax.linen as nn
        import optax
        from dlrover_tpu.accel import auto_accelerate

        with pytest.raises(ValueError, match="mlp_precision"):
            auto_accelerate(
                nn.Dense(4), optax.sgd(0.1), jnp.zeros((2, 4)),
                lambda m, p, b: m.apply({"params": p}, b).sum(),
                precision="int8",
            )
