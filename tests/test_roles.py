"""Per-role manager tests (SURVEY §2.2 per-role managers)."""

import pytest

from dlrover_tpu.common.constants import NodeStatus
from dlrover_tpu.master.node_manager import LocalJobManager
from dlrover_tpu.master.role_manager import RoleAwareJobManager, RolePolicy


@pytest.fixture
def mgr():
    jm = LocalJobManager(node_num=2)
    return RoleAwareJobManager(jm, roles={
        "worker": RolePolicy(target=2, critical=True),
        "evaluator": RolePolicy(target=1, critical=False,
                                may_finish_early=True),
    })


class TestRoleAwareJobManager:
    def test_worker_role_delegates(self, mgr):
        assert len(mgr.nodes("worker")) == 2
        mgr.update_node_status("worker", 0, NodeStatus.RUNNING)
        assert len(mgr.alive("worker")) == 2

    def test_auxiliary_role_lifecycle(self, mgr):
        mgr.register_node("evaluator", 0)
        assert mgr.missing("evaluator") == 0
        mgr.update_node_status("evaluator", 0, NodeStatus.RUNNING)
        assert len(mgr.alive("evaluator")) == 1
        mgr.update_node_status("evaluator", 0, NodeStatus.SUCCEEDED)
        # finish-early role: a completed node still fills its slot (the
        # scaler must never relaunch a finished evaluator)
        assert mgr.missing("evaluator") == 0
        mgr.update_node_status("evaluator", 0, NodeStatus.FAILED, "oom")
        assert mgr.missing("evaluator") == 1  # failures DO leave a hole

    def test_workers_register_via_job_manager_only(self, mgr):
        with pytest.raises(ValueError):
            mgr.register_node("worker", 5)

    def test_success_gated_on_critical_roles_only(self, mgr):
        """Evaluator failure never fails the job; worker success
        completes it even with the evaluator still running."""
        mgr.register_node("evaluator", 0, NodeStatus.RUNNING)
        for wid in (0, 1):
            mgr.update_node_status("worker", wid, NodeStatus.RUNNING)
            mgr.update_node_status("worker", wid, NodeStatus.SUCCEEDED)
        assert mgr.job_finished()
        assert mgr.job_succeeded()
        mgr.update_node_status("evaluator", 0, NodeStatus.FAILED, "oom")
        assert mgr.job_succeeded()  # non-critical role can't gate

    def test_critical_unrecoverable_failure(self, mgr):
        mgr.update_node_status("worker", 0, NodeStatus.RUNNING)
        node = mgr.nodes("worker")[0]
        node.update_status(NodeStatus.FAILED)
        node.relaunchable = False
        assert mgr.job_failed()

    def test_scale_deficits_per_role(self, mgr):
        # Evaluator never launched: deficit 1. Workers present: 0.
        assert mgr.scale_deficits() == {"evaluator": 1}
